#!/usr/bin/env bash
# Pre-commit bar: the raylint repo gate + the static-analysis test
# suite + the runtime-lockdep-gated suites. CI runs the same thing —
# a commit that fails here fails tier-1.
#
#   tools/check.sh           # full bar (~2 min)
#   tools/check.sh --fast    # raylint gate + lint marker only (~30 s)
set -u -o pipefail

cd "$(dirname "$0")/.."

fast=0
[ "${1:-}" = "--fast" ] && fast=1

fail=0
step() {
    echo
    echo "==> $1"
    shift
    "$@" || { echo "FAILED: $1"; fail=1; }
}

# 1. raylint repo gate: per-module + whole-program checkers +
#    unused-suppression audit, against the committed (empty) baseline.
#    Exit-nonzero on any new finding.
step "raylint repo gate" python -m tools.raylint ray_tpu/ --root .

# 2. static-analysis tests: checker fixtures (known-bad detected,
#    known-good silent), call-graph units, CLI/baseline behavior, and
#    the lint-marked repo-gate tests.
step "raylint test suite" python -m pytest tests/test_raylint.py -q

if [ "$fast" -eq 0 ]; then
    # 3. runtime lockdep: the suites conftest gates under the
    #    lock-order validator (record-only, asserted clean at teardown).
    step "lockdep-gated suites" python -m pytest -q \
        tests/test_chaos.py tests/test_object_store.py \
        tests/test_rpc_batch.py tests/test_multitenant.py \
        tests/test_ownership.py tests/test_serve_llm_spec.py \
        tests/test_dispatch_ring.py tests/test_slo.py
fi

echo
if [ "$fail" -ne 0 ]; then
    echo "check.sh: FAILED"
    exit 1
fi
echo "check.sh: all gates green"
