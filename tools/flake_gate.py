#!/usr/bin/env python
"""Flake gate: prove a test is deterministic by running it N times solo.

The gang-durable commit turned `test_elastic_restore_bit_identical`'s
`resumed_from == 2` assertion from a ~50% race into a guarantee; this
gate keeps it that way. Any non-deterministic failure across the runs
fails the gate and leaves the failing run's full pytest output in the
log directory for replay.

Usage:
    python tools/flake_gate.py                      # default target, 20 runs
    python tools/flake_gate.py -n 5 tests/test_chaos.py::test_commit_kill_walks_back_to_gang_durable
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile
import time

DEFAULT_TARGET = (
    "tests/test_sharded_checkpoint.py::test_elastic_restore_bit_identical")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("target", nargs="?", default=DEFAULT_TARGET)
    parser.add_argument("-n", "--runs", type=int, default=20)
    parser.add_argument("--timeout", type=float, default=300.0,
                        help="per-run timeout in seconds")
    args = parser.parse_args()

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    log_dir = tempfile.mkdtemp(prefix="flake_gate_")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    failures = []
    for i in range(1, args.runs + 1):
        log_path = os.path.join(log_dir, f"run_{i:02d}.log")
        start = time.monotonic()
        with open(log_path, "wb") as log:
            try:
                proc = subprocess.run(
                    [sys.executable, "-m", "pytest", args.target, "-q",
                     "-p", "no:cacheprovider", "-p", "no:randomly"],
                    cwd=repo_root, env=env, stdout=log,
                    stderr=subprocess.STDOUT, timeout=args.timeout)
                rc = proc.returncode
            except subprocess.TimeoutExpired:
                rc = -1
        took = time.monotonic() - start
        status = "ok" if rc == 0 else f"FAIL rc={rc}"
        print(f"[flake-gate] run {i:2d}/{args.runs}: {status} "
              f"({took:.1f}s)", flush=True)
        if rc != 0:
            failures.append((i, log_path))
    if failures:
        print(f"[flake-gate] {len(failures)}/{args.runs} runs failed — "
              f"the test is non-deterministic. Failing logs:")
        for i, path in failures:
            print(f"  run {i}: {path}")
        return 1
    print(f"[flake-gate] {args.runs}/{args.runs} green — deterministic. "
          f"Logs: {log_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
