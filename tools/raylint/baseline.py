"""Baseline (burn-down) file handling.

The baseline freezes pre-existing findings so only *new* violations fail
the gate. Keys are line-number-free (`path::check::scope::detail`) so
unrelated edits don't churn the file; identical findings in one scope are
compared as a multiset (a second `ray_tpu.get` under the same lock in the
same method is a new finding). Fixing a violation leaves a stale entry —
the CLI reports it and `--write-baseline` burns it down.
"""

from __future__ import annotations

import collections
import os
from typing import Counter, Dict, List, Sequence, Tuple

from tools.raylint.core import Finding

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.txt")

_HEADER = """\
# raylint baseline — frozen pre-existing findings (one key per line).
# A finding listed here is tolerated; anything new fails the gate.
# Burn entries down by fixing the violation and running:
#   python -m tools.raylint ray_tpu/ --write-baseline
"""


def load(path: str = DEFAULT_BASELINE) -> Counter[str]:
    counts: Counter[str] = collections.Counter()
    if not os.path.exists(path):
        return counts
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line and not line.startswith("#"):
                counts[line] += 1
    return counts


def save(findings: Sequence[Finding], path: str = DEFAULT_BASELINE) -> None:
    keys = sorted(f.key() for f in findings)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(_HEADER)
        for key in keys:
            fh.write(key + "\n")


def compare(findings: Sequence[Finding], baseline: Counter[str]
            ) -> Tuple[List[Finding], List[str]]:
    """(new_findings, stale_keys): findings beyond the baselined count
    for their key, and baseline keys with no live finding left."""
    live: Counter[str] = collections.Counter(f.key() for f in findings)
    budget: Dict[str, int] = dict(baseline)
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            new.append(f)
    stale = sorted(k for k, n in baseline.items() if live.get(k, 0) < n)
    return new, stale
