"""Whole-program symbol table + call graph for raylint v2.

PR-2's checkers are per-module: each file is parsed, analyzed, and
forgotten. The three v2 checkers (``async-blocking``, ``rpc-surface``,
``surface-drift``) need facts that only exist across files — is this
sync helper reachable from an ``async def`` three modules away? does any
server register a handler for this string literal? does anything export
the metric this dashboard query reads? — so this module splits the
analysis RacerD-style into two phases:

1. **Per-module fact extraction** (`extract_module_facts`): one AST walk
   per file produces a plain-data `ModuleFacts` — functions with their
   async coloring, outgoing call sites (dotted names, unresolved),
   direct blocking operations, executor-hop shelter, RPC
   registrations/call literals, metric exports/consumptions, class
   shapes (bases, methods, ``self.attr = Ctor()`` types), import
   aliases, and suppression comments. Facts are pickle-stable and
   independent of every other file, which makes them **cacheable**: the
   repo gate persists them keyed by ``(mtime_ns, size)`` so a warm run
   re-parses only edited files (`FactsCache`).

2. **Whole-program resolution** (`Program`): the facts of every module
   are joined into a symbol table (``module.Class.method`` /
   ``module.func`` keys), call sites are resolved through import
   aliases, ``self.`` method dispatch (same-class, then cross-module
   base chain), ``self._attr.m()`` instance-attribute types, and local
   ``x = Ctor()`` bindings, and the checkers run over the resolved
   graph.

Resolution is deliberately *under*-approximate (an edge exists only
when the target is provably a repo function): the checkers built on it
flag what they can prove, and the baseline stays empty because every
edge they report is real.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import pickle
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# bump to invalidate cached facts when extraction logic changes
FACTS_VERSION = 8

_SUPPRESS_RE = re.compile(r"#\s*raylint:\s*disable=([\w,\-]+)")

# ---------------------------------------------------------------------------
# fact dataclasses (plain data — pickled by the facts cache)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CallFact:
    """One outgoing call site, unresolved: `callee` is the dotted name
    as written ('self._coal.send', 'mod.f', 'f', 'Cls().m')."""
    callee: str
    line: int


@dataclasses.dataclass
class FuncFact:
    name: str                 # module-local qual: 'Cls.m', 'f', 'f.<locals>.g'
    line: int
    is_async: bool
    calls: List[CallFact] = dataclasses.field(default_factory=list)
    # direct blocking operations: (reason, line)
    blocking: List[Tuple[str, int]] = dataclasses.field(default_factory=list)
    # local `x = Ctor(...)` bindings: var -> dotted ctor name as written
    local_types: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ClassFact:
    name: str
    line: int
    bases: List[str] = dataclasses.field(default_factory=list)
    methods: Dict[str, int] = dataclasses.field(default_factory=dict)
    async_methods: Set[str] = dataclasses.field(default_factory=set)
    # `self.attr = Ctor(...)` -> dotted ctor name as written
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class RpcRegistration:
    kind: str      # 'register' (literal) | 'register_all' (class sweep)
    name: str      # method literal, or module-local class name
    prefix: str    # register_all prefix ('' for literal registrations)
    line: int
    scope: str


@dataclasses.dataclass
class RpcCallSite:
    method: str
    verb: str      # 'call' | 'notify' | 'call_nowait'
    line: int
    scope: str


@dataclasses.dataclass
class MetricExport:
    name: str
    is_prefix: bool    # dynamic suffix ('rpc_' + formatted value)
    kind: str          # 'ctor' | 'text'
    line: int


@dataclasses.dataclass
class MetricUse:
    name: str
    is_prefix: bool    # prefix-filter consumption (DEFAULT_PREFIXES et al.)
    line: int
    scope: str


@dataclasses.dataclass
class ModuleFacts:
    relpath: str
    module: str                     # dotted ('ray_tpu._private.rpc')
    aux: bool = False               # consumer-only file (bench.py)
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    functions: Dict[str, FuncFact] = dataclasses.field(default_factory=dict)
    classes: Dict[str, ClassFact] = dataclasses.field(default_factory=dict)
    rpc_registrations: List[RpcRegistration] = \
        dataclasses.field(default_factory=list)
    rpc_calls: List[RpcCallSite] = dataclasses.field(default_factory=list)
    metric_exports: List[MetricExport] = \
        dataclasses.field(default_factory=list)
    metric_uses: List[MetricUse] = dataclasses.field(default_factory=list)
    # identifier-shaped string literals: [(value, line)] — dynamic
    # dispatch evidence for the rpc-surface dead-handler check (a
    # handler name mentioned anywhere outside its registration is
    # plausibly dispatched through a variable, so not provably dead)
    str_mentions: List[Tuple[str, int]] = \
        dataclasses.field(default_factory=list)
    # suppression comments: line -> set of check names (or {'all'})
    suppressions: Dict[int, Set[str]] = dataclasses.field(default_factory=dict)

    def suppressed(self, check: str, line: int) -> bool:
        return self.suppression_line(check, line) is not None

    def suppression_line(self, check: str, line: int) -> Optional[int]:
        """Line of the `# raylint: disable=` comment covering (check,
        line), or None. Matches the flagged line or the line above."""
        for ln in (line, line - 1):
            what = self.suppressions.get(ln)
            if what and ("all" in what or check in what):
                return ln
        return None


# ---------------------------------------------------------------------------
# blocking-operation classification (async-blocking sinks)
# ---------------------------------------------------------------------------

_SUBPROCESS_BLOCKING = {"run", "call", "check_call", "check_output",
                        "Popen", "getoutput", "getstatusoutput"}
_SOCKET_MODULE_BLOCKING = {"create_connection", "getaddrinfo",
                           "gethostbyname", "gethostbyaddr"}
_SOCKET_METHODS = {"recv", "recvfrom", "accept", "sendall", "connect"}
_FILE_METHODS = {"read_text", "write_text", "read_bytes", "write_bytes"}
_QUEUEISH = re.compile(r"queue|(^|[._])q$", re.IGNORECASE)
_LOCKISH = re.compile(r"lock|mutex|sem", re.IGNORECASE)

# executor/thread hops that shelter their function arguments from the
# event loop (the sanctioned way to run blocking work from async code)
_HOP_CALLS = {"run_in_executor", "to_thread", "start_new_thread"}

# asyncio combinators whose Call arguments are coroutines: an inner
# `q.get()` inside `await wait_for(q.get(), t)` is an awaitable, not a
# blocking queue read
_CORO_WRAPPERS = {"wait_for", "shield", "gather", "wait", "ensure_future",
                  "create_task", "as_completed",
                  "run_coroutine_threadsafe"}


def _dotted(node: ast.AST) -> Optional[str]:
    """Dotted name of an expression; `Cls(...).m` renders as 'Cls().m'
    so whole-program resolution can dispatch through the constructed
    type."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Call):
        inner = _dotted(node.func)
        return f"{inner}()" if inner else None
    if isinstance(node, ast.Await):
        return _dotted(node.value)
    return None


def classify_blocking(call: ast.Call) -> Optional[str]:
    """Reason string when `call` is a blocking primitive that would
    stall an event loop; None otherwise. Conservative: each pattern
    here is a known-synchronous operation."""
    name = _dotted(call.func) or ""
    parts = name.split(".")
    last = parts[-1] if parts else ""
    first = parts[0] if parts else ""

    if name.endswith("time.sleep") or name == "time.sleep":
        return "time.sleep"
    if first == "subprocess" and last in _SUBPROCESS_BLOCKING:
        return name
    if name in ("os.system", "os.waitpid", "os.popen"):
        return name
    if first == "socket" and last in _SOCKET_MODULE_BLOCKING:
        return name
    if first in ("ray_tpu", "ray") and len(parts) == 2 and \
            last in ("get", "wait"):
        return name
    if name == "open":
        return "open() [sync file I/O]"
    if name in ("os.read", "os.write", "os.fsync"):
        return name
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        recv = _dotted(call.func.value) or ""
        if attr in _FILE_METHODS:
            return f".{attr}() [sync file I/O]"
        if attr in _SOCKET_METHODS and "sock" in recv.lower():
            return f".{attr}() [sync socket]"
        if attr == "_run_sync":
            return "._run_sync() [sync RPC bridge]"
        if attr == "acquire" and _LOCKISH.search(recv.split(".")[-1]):
            # `lock.acquire(blocking=False)` polls, never parks
            for kw in call.keywords:
                if kw.arg == "blocking" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value is False:
                    return None
            return "Lock.acquire"
        if attr == "join" and not call.args and not call.keywords:
            return ".join()"
        if attr == "result" and (call.args or call.keywords):
            # a pending asyncio future's .result() raises immediately —
            # only the concurrent.futures form takes a timeout and parks
            return ".result(timeout) [concurrent future]"
        if attr == "get" and _QUEUEISH.search(recv):
            for kw in call.keywords:
                if kw.arg == "block" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value is False:
                    return None
            return ".get() [queue]"
    return None


def _is_hop_call(call: ast.Call) -> bool:
    """Calls that move their callable argument OFF the event loop:
    run_in_executor / to_thread / Thread(target=) / executor.submit."""
    name = _dotted(call.func) or ""
    last = name.split(".")[-1]
    if last in _HOP_CALLS:
        return True
    if last == "Thread":
        return True
    if last == "submit" and re.search(r"executor|pool",
                                      name.lower()):
        return True
    return False


# ---------------------------------------------------------------------------
# metric-name literal harvesting (surface-drift)
# ---------------------------------------------------------------------------

_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]{2,}$")
_IDENTIFIERISH_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]{2,39}$")
_ROW_HEAD_RE = re.compile(r"^([a-z][a-z0-9_]{2,})([{ ])")
_METRIC_TYPES = {"Counter", "Gauge", "Histogram"}
_PREFIXES_NAME_RE = re.compile(r"(?i)^_?(default_)?prefixes$")
_TSDB_QUERY_METHODS = {"rate", "latest", "points"}


def _exposition_lines(node: ast.AST) -> List[Tuple[str, bool]]:
    """Logical lines of a string/f-string literal: [(text, ends_in_
    dynamic)] where ends_in_dynamic marks a line whose tail is a
    FormattedValue (``f"name {value}"``). Adjacent implicit-concat
    literals arrive pre-merged by the parser, so a metrics_text body
    spanning several source lines is one node here."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        chunks: List = [node.value]
    elif isinstance(node, ast.JoinedStr):
        chunks = [v.value if isinstance(v, ast.Constant)
                  and isinstance(v.value, str) else None
                  for v in node.values]
    else:
        return []
    # each line is (head, has_dynamic): head stops at the line's first
    # dynamic piece — constants after it are value/label tail, not name
    lines: List[Tuple[str, bool]] = [("", False)]
    for chunk in chunks:
        if chunk is None:
            head, _ = lines[-1]
            lines[-1] = (head, True)
            continue
        parts = chunk.split("\n")
        head, dyn = lines[-1]
        if not dyn:
            lines[-1] = (head + parts[0], dyn)
        for part in parts[1:]:
            lines.append((part, False))
    return lines


def _exposition_exports(node: ast.AST) -> List[Tuple[str, bool]]:
    """Metric names exported by a string literal shaped like Prometheus
    exposition rows. Returns [(name, is_prefix)].

    - `'scheduler_queue_depth{job="x"} 3'` → exact
    - `f'serve_top_kv_pages_live{{deployment="{n}"}} {v}'` → exact
      (the AST constant chunk is 'serve_top_kv_pages_live{deployment="')
    - `f"rpc_{name} {value}"` → prefix 'rpc_' (dynamic suffix)
    - multi-row bodies (`"# TYPE x counter\\n" f"x {v}\\n"`) export
      every row — each logical line is matched independently
    """
    out: List[Tuple[str, bool]] = []
    for text, ends_dynamic in _exposition_lines(node):
        if not text or text.startswith("#"):
            continue  # comment/TYPE rows name the family elsewhere
        m = _ROW_HEAD_RE.match(text)
        if m:
            name, sep = m.group(1), m.group(2)
            rest = text[m.end():]
            if sep == "{" and '="' in text:
                out.append((name, False))
            elif sep == " " and (_looks_numeric(rest.split()[0])
                                 if rest.split()
                                 else ends_dynamic):
                out.append((name, False))
        elif ends_dynamic and text.endswith("_") and \
                _METRIC_NAME_RE.match(text):
            # 'rpc_' + {formatted}: a family of names sharing the prefix
            out.append((text, True))
    return out


def _looks_numeric(tok: str) -> bool:
    if not tok:
        return False
    try:
        float(tok)
        return True
    except ValueError:
        return False


# ---------------------------------------------------------------------------
# per-module extraction
# ---------------------------------------------------------------------------

def module_name_for(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".").replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class _FunctionExtractor:
    """Walks one function body (nested defs excluded — they become their
    own FuncFacts) collecting calls, blocking ops, and local types."""

    def __init__(self, fact: FuncFact, module_facts: ModuleFacts,
                 scope_class: Optional[str]):
        self.fact = fact
        self.mf = module_facts
        self.scope_class = scope_class
        self._awaited: Set[int] = set()

    def walk_body(self, stmts: Iterable[ast.stmt]) -> None:
        # prepass: awaited calls (and Call arguments of asyncio
        # combinators) produce coroutines — never blocking sinks
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Await) and \
                        isinstance(node.value, ast.Call):
                    self._awaited.add(id(node.value))
                if isinstance(node, ast.Call):
                    name = _dotted(node.func) or ""
                    if name.split(".")[-1] in _CORO_WRAPPERS:
                        for arg in node.args:
                            if isinstance(arg, ast.Call):
                                self._awaited.add(id(arg))
        for stmt in stmts:
            self._walk(stmt, sheltered=False)

    def _walk(self, node: ast.AST, sheltered: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate scope (handled by the module extractor)
        if isinstance(node, ast.Assign):
            value = node.value
            if isinstance(value, ast.Call):
                ctor = _dotted(value.func)
                if ctor:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.fact.local_types[t.id] = ctor
        if isinstance(node, ast.Call):
            self._on_call(node, sheltered)
            hop = _is_hop_call(node)
            for child in ast.iter_child_nodes(node):
                self._walk(child, sheltered or hop)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, sheltered)

    def _on_call(self, call: ast.Call, sheltered: bool) -> None:
        # RPC registration / call-site literals are harvested even in
        # sheltered positions — shelter only affects the event-loop edge
        self._harvest_rpc(call)
        self._harvest_metric_use(call)
        if sheltered:
            return
        if id(call) not in self._awaited:
            reason = classify_blocking(call)
            if reason is not None:
                self.fact.blocking.append((reason, call.lineno))
                return
        callee = _dotted(call.func)
        if callee:
            self.fact.calls.append(CallFact(callee, call.lineno))

    def _harvest_rpc(self, call: ast.Call) -> None:
        if not isinstance(call.func, ast.Attribute):
            return
        attr = call.func.attr
        if attr == "register" and call.args and \
                isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, str) and len(call.args) >= 2:
            self.mf.rpc_registrations.append(RpcRegistration(
                "register", call.args[0].value, "", call.lineno,
                self.fact.name))
        elif attr == "register_all" and call.args:
            target = _dotted(call.args[0])
            prefix = "rpc_"
            for kw in call.keywords:
                if kw.arg == "prefix" and \
                        isinstance(kw.value, ast.Constant):
                    prefix = kw.value.value
            if target == "self" and self.scope_class:
                target = self.scope_class
            if target:
                self.mf.rpc_registrations.append(RpcRegistration(
                    "register_all", target, prefix, call.lineno,
                    self.fact.name))
        elif attr in ("call", "notify", "call_nowait",
                      "_call", "_notify") and call.args and \
                isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, str):
            # `_call`/`_notify` are the conventional thin wrappers
            # around RpcClient (ray client's ClientContext._call) —
            # their method literals are call sites too
            self.mf.rpc_calls.append(RpcCallSite(
                call.args[0].value, attr.lstrip("_"), call.lineno,
                self.fact.name))

    def _harvest_metric_use(self, call: ast.Call) -> None:
        name = _dotted(call.func) or ""
        last = name.split(".")[-1]
        if last in _TSDB_QUERY_METHODS and "." in name and call.args and \
                isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, str) and \
                _METRIC_NAME_RE.match(call.args[0].value):
            self.mf.metric_uses.append(MetricUse(
                call.args[0].value, False, call.lineno, self.fact.name))
        elif last == "histogram_quantile" and len(call.args) >= 2 and \
                isinstance(call.args[1], ast.Constant) and \
                isinstance(call.args[1].value, str):
            self.mf.metric_uses.append(MetricUse(
                call.args[1].value + "_bucket", False, call.lineno,
                self.fact.name))


def extract_module_facts(source: str, relpath: str,
                         aux: bool = False) -> ModuleFacts:
    tree = ast.parse(source, filename=relpath)
    mf = ModuleFacts(relpath=relpath, module=module_name_for(relpath),
                     aux=aux)

    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            mf.suppressions[i] = {w.strip() for w in m.group(1).split(",")}

    _collect_imports(tree, mf)
    _collect_scopes(tree, mf)
    _collect_metric_surface(tree, mf)
    return mf


def _collect_imports(tree: ast.Module, mf: ModuleFacts) -> None:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                mf.imports[alias.asname or alias.name.split(".")[0]] = \
                    alias.name if alias.asname else alias.name.split(".")[0]
                if alias.asname:
                    mf.imports[alias.asname] = alias.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import: resolve against this package
                pkg_parts = mf.module.split(".")
                base_parts = pkg_parts[: len(pkg_parts) - node.level]
                base = ".".join(base_parts)
                target = f"{base}.{node.module}" if node.module else base
            else:
                target = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                mf.imports[alias.asname or alias.name] = \
                    f"{target}.{alias.name}" if target else alias.name


def _scope_name(stack: List[str], name: str) -> str:
    return ".<locals>.".join(stack + [name]) if stack else name


def _collect_scopes(tree: ast.Module, mf: ModuleFacts) -> None:
    def visit_func(fn: ast.AST, classname: Optional[str],
                   stack: List[str]) -> None:
        qual_base = f"{classname}.{fn.name}" if classname else fn.name
        qual = _scope_name(stack, qual_base)
        fact = FuncFact(name=qual, line=fn.lineno,
                        is_async=isinstance(fn, ast.AsyncFunctionDef))
        mf.functions[qual] = fact
        ex = _FunctionExtractor(fact, mf, classname)
        ex.fact = fact
        ex.walk_body(fn.body)
        # nested defs become their own facts under `qual.<locals>.`
        for stmt in _shallow(fn):
            visit_func(stmt, None, stack + [qual_base])

    def _shallow(fn):
        out = []
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(node)
                continue  # don't descend into nested scopes
            if isinstance(node, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(node))
        return out

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            visit_func(node, None, [])
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            cf = ClassFact(name=node.name, line=node.lineno,
                           bases=[b for b in (_dotted(base)
                                              for base in node.bases) if b])
            mf.classes[node.name] = cf
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    cf.methods[item.name] = item.lineno
                    if isinstance(item, ast.AsyncFunctionDef):
                        cf.async_methods.add(item.name)
                    visit_func(item, node.name, [])
                    for sub in ast.walk(item):
                        if isinstance(sub, ast.Assign) and \
                                isinstance(sub.value, ast.Call):
                            ctor = _dotted(sub.value.func)
                            if not ctor:
                                continue
                            for t in sub.targets:
                                if isinstance(t, ast.Attribute) and \
                                        isinstance(t.value, ast.Name) \
                                        and t.value.id == "self":
                                    cf.attr_types.setdefault(t.attr, ctor)


def _collect_metric_surface(tree: ast.Module, mf: ModuleFacts) -> None:
    # metric constructors: Counter("name", ...) / Gauge / Histogram —
    # exporters wherever they are constructed
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _dotted(node.func) or ""
            last = name.split(".")[-1]
            if last in _METRIC_TYPES and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str) and \
                    _METRIC_NAME_RE.match(node.args[0].value):
                base = node.args[0].value
                mf.metric_exports.append(MetricExport(
                    base, False, "ctor", node.lineno))
                if last == "Histogram":
                    for suffix in ("_bucket", "_sum", "_count"):
                        mf.metric_exports.append(MetricExport(
                            base + suffix, False, "ctor", node.lineno))
        # exposition-row literals (metrics_text builders, top's
        # self-ingested rows): any string that parses as `name{...} v`
        # or `name <value>` exports that name
        for name, is_prefix in _exposition_exports(node):
            mf.metric_exports.append(MetricExport(
                name, is_prefix, "text", node.lineno))
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                _IDENTIFIERISH_RE.match(node.value):
            mf.str_mentions.append((node.value, node.lineno))
        # prefix-filter consumption: `prefixes = ("serve_", ...)` /
        # DEFAULT_PREFIXES — each element must match some exporter
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            for t in node.targets:
                if isinstance(t, ast.Name) and \
                        _PREFIXES_NAME_RE.match(t.id):
                    for el in node.value.elts:
                        if isinstance(el, ast.Constant) and \
                                isinstance(el.value, str) and \
                                _METRIC_NAME_RE.match(el.value):
                            mf.metric_uses.append(MetricUse(
                                el.value, True, el.lineno, t.id))


# ---------------------------------------------------------------------------
# whole-program resolution
# ---------------------------------------------------------------------------

class Program:
    """Joined view over every module's facts with name resolution."""

    def __init__(self, modules: Sequence[ModuleFacts]):
        self.modules: Dict[str, ModuleFacts] = {m.module: m
                                                for m in modules}
        self.by_relpath: Dict[str, ModuleFacts] = {m.relpath: m
                                                   for m in modules}
        # global symbol table: 'mod::qual' -> (ModuleFacts, FuncFact)
        self.functions: Dict[str, Tuple[ModuleFacts, FuncFact]] = {}
        for m in modules:
            for qual, fact in m.functions.items():
                self.functions[f"{m.module}::{qual}"] = (m, fact)

    # -- symbol helpers ---------------------------------------------------

    def func_key(self, mf: ModuleFacts, qual: str) -> str:
        return f"{mf.module}::{qual}"

    def _class_in(self, dotted_cls: str,
                  home: ModuleFacts) -> Optional[Tuple[ModuleFacts,
                                                       ClassFact]]:
        """Resolve a dotted class name written inside `home` to its
        defining module (same module, imported symbol, or imported
        module attribute)."""
        if dotted_cls in home.classes:
            return home, home.classes[dotted_cls]
        parts = dotted_cls.split(".")
        target = home.imports.get(parts[0])
        if target is None:
            return None
        full = ".".join([target] + parts[1:])
        mod_name, _, cls_name = full.rpartition(".")
        mod = self.modules.get(mod_name)
        if mod and cls_name in mod.classes:
            return mod, mod.classes[cls_name]
        # `from pkg import mod` then `mod.Cls` → target may BE a module
        mod = self.modules.get(full)
        if mod is None and target in self.modules and len(parts) == 2:
            mod = self.modules.get(target)
            if mod and parts[1] in mod.classes:
                return mod, mod.classes[parts[1]]
        return None

    def class_mro(self, mf: ModuleFacts, classname: str
                  ) -> List[Tuple[ModuleFacts, ClassFact]]:
        """The class + its resolvable base chain, nearest first
        (cross-module bases followed through imports; cycles cut)."""
        out: List[Tuple[ModuleFacts, ClassFact]] = []
        seen: Set[Tuple[str, str]] = set()
        frontier: List[Tuple[ModuleFacts, str]] = [(mf, classname)]
        while frontier:
            home, name = frontier.pop(0)
            resolved = self._class_in(name, home)
            if resolved is None:
                continue
            rmod, rcls = resolved
            key = (rmod.module, rcls.name)
            if key in seen:
                continue
            seen.add(key)
            out.append((rmod, rcls))
            for base in rcls.bases:
                frontier.append((rmod, base))
        return out

    def find_method(self, mf: ModuleFacts, classname: str, meth: str
                    ) -> Optional[str]:
        """Key of `classname.meth` resolved through the base chain."""
        for rmod, rcls in self.class_mro(mf, classname):
            if meth in rcls.methods:
                key = f"{rmod.module}::{rcls.name}.{meth}"
                if key in self.functions:
                    return key
        return None

    # -- call resolution --------------------------------------------------

    def resolve_call(self, mf: ModuleFacts, caller: FuncFact,
                     callee: str) -> Optional[str]:
        """Resolve one call site's dotted name to a program function
        key, or None when the target is not provably a repo function."""
        caller_class = caller.name.split(".")[0] \
            if "." in caller.name and "<locals>" not in caller.name \
            else None
        parts = callee.split(".")

        # self.m() / self._attr.m()
        if parts[0] == "self" and caller_class:
            if len(parts) == 2:
                return self.find_method(mf, caller_class, parts[1])
            if len(parts) == 3:
                cf = mf.classes.get(caller_class)
                ctor = cf.attr_types.get(parts[1]) if cf else None
                if ctor:
                    ctor = ctor[:-2] if ctor.endswith("()") else ctor
                    resolved = self._class_in(ctor, mf)
                    if resolved:
                        rmod, rcls = resolved
                        return self.find_method(rmod, rcls.name, parts[2])
            return None

        # nested def called from its parent: parent.<locals>.name
        if len(parts) == 1:
            nested = f"{caller.name}.<locals>.{parts[0]}"
            key = self.func_key(mf, nested)
            if key in self.functions:
                return key
            if parts[0] in mf.functions:
                return self.func_key(mf, parts[0])
            target = mf.imports.get(parts[0])
            if target:
                mod_name, _, fn = target.rpartition(".")
                mod = self.modules.get(mod_name)
                if mod and fn in mod.functions:
                    return f"{mod.module}::{fn}"
                # imported class called = constructor
                resolved = self._class_in(parts[0], mf)
                if resolved:
                    rmod, rcls = resolved
                    return self.find_method(rmod, rcls.name, "__init__")
            if parts[0] in mf.classes:
                return self.find_method(mf, parts[0], "__init__")
            return None

        # Cls().m() — constructed-receiver dispatch
        if parts[0].endswith("()"):
            cls = parts[0][:-2]
            resolved = self._class_in(cls, mf)
            if resolved and len(parts) == 2:
                rmod, rcls = resolved
                return self.find_method(rmod, rcls.name, parts[1])
            return None

        # local `x = Ctor()` then `x.m()`
        if parts[0] in caller.local_types and len(parts) == 2:
            ctor = caller.local_types[parts[0]]
            ctor = ctor[:-2] if ctor.endswith("()") else ctor
            resolved = self._class_in(ctor, mf)
            if resolved:
                rmod, rcls = resolved
                return self.find_method(rmod, rcls.name, parts[1])
            # fall through: maybe a module alias shadowed by the binding

        # Cls.m() (unbound) or mod.f() / pkg.mod.f()
        if parts[0] in mf.classes and len(parts) == 2:
            return self.find_method(mf, parts[0], parts[1])
        target = mf.imports.get(parts[0])
        if target is not None:
            full = ".".join([target] + parts[1:])
            mod_name, _, fn = full.rpartition(".")
            mod = self.modules.get(mod_name)
            if mod:
                if fn in mod.functions:
                    return f"{mod.module}::{fn}"
                if fn in mod.classes:
                    return self.find_method(mod, fn, "__init__")
            # imported class: `rpc.RpcClient(...)` handled above via ();
            # `alias.Cls.method` (3 parts)
            if len(parts) == 3:
                mod = self.modules.get(target)
                if mod and parts[1] in mod.classes:
                    return self.find_method(mod, parts[1], parts[2])
        return None

    def edges_of(self, key: str) -> List[Tuple[str, int, str]]:
        """Resolved outgoing edges of one function:
        [(target_key, line, callee_as_written)]."""
        mf, fact = self.functions[key]
        out = []
        for call in fact.calls:
            target = self.resolve_call(mf, fact, call.callee)
            if target is not None and target != key:
                out.append((target, call.line, call.callee))
        return out


# ---------------------------------------------------------------------------
# facts cache
# ---------------------------------------------------------------------------

class FactsCache:
    """Pickle cache of per-file ModuleFacts keyed by (mtime_ns, size).
    Keeps the repo gate warm-run cost at parse-only-what-changed;
    disable with RAY_TPU_RAYLINT_CACHE=0."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or os.path.join(os.path.dirname(__file__),
                                         ".factscache.pkl")
        self.enabled = os.environ.get("RAY_TPU_RAYLINT_CACHE", "1") != "0"
        self._entries: Dict[str, Tuple[int, int, ModuleFacts]] = {}
        self._dirty = False
        if self.enabled:
            try:
                with open(self.path, "rb") as fh:
                    version, entries = pickle.load(fh)
                if version == FACTS_VERSION:
                    self._entries = entries
            except (OSError, pickle.PickleError, ValueError, EOFError):
                pass

    def get(self, abspath: str, relpath: str,
            aux: bool = False) -> ModuleFacts:
        st = os.stat(abspath)
        key = (st.st_mtime_ns, st.st_size)
        if self.enabled:
            hit = self._entries.get(abspath)
            if hit is not None and (hit[0], hit[1]) == key \
                    and hit[2].aux == aux:
                return hit[2]
        with open(abspath, encoding="utf-8") as fh:
            source = fh.read()
        facts = extract_module_facts(source, relpath, aux=aux)
        if self.enabled:
            self._entries[abspath] = (key[0], key[1], facts)
            self._dirty = True
        return facts

    def save(self) -> None:
        if not (self.enabled and self._dirty):
            return
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "wb") as fh:
                pickle.dump((FACTS_VERSION, self._entries), fh,
                            protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self.path)
        except OSError:
            pass


def build_program(paths: Sequence[str], root: str,
                  aux_paths: Sequence[str] = (),
                  cache: Optional[FactsCache] = None) -> Program:
    """Extract (or load cached) facts for every file and join them.
    `aux_paths` are consumer-only files (bench.py): their RPC call
    sites and metric uses/exports count, but per-module checkers and
    async-blocking sources skip them."""
    cache = cache or FactsCache()
    modules: List[ModuleFacts] = []
    seen: Set[str] = set()
    for path, aux in [(p, False) for p in paths] + \
                     [(p, True) for p in aux_paths]:
        abspath = os.path.abspath(path)
        if abspath in seen:
            continue
        seen.add(abspath)
        relpath = os.path.relpath(abspath, root).replace(os.sep, "/")
        try:
            modules.append(cache.get(abspath, relpath, aux=aux))
        except SyntaxError:
            continue  # reported by the per-module pass as parse-error
    cache.save()
    return Program(modules)
