"""CLI: ``python -m tools.raylint ray_tpu/``.

Exit codes: 0 — clean against the baseline; 1 — new findings; 2 — usage
error. ``--write-baseline`` refreshes the frozen set (burn-down commits
run it after fixing violations).
"""

from __future__ import annotations

import argparse
import os
import sys

from tools.raylint import baseline as baseline_mod
from tools.raylint.core import CHECKS, analyze_paths


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.raylint",
        description="concurrency + jit-boundary static analysis")
    parser.add_argument("paths", nargs="+", help="files or directories")
    parser.add_argument("--baseline", default=baseline_mod.DEFAULT_BASELINE,
                        help="baseline file (default: committed baseline)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignore the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="freeze the current findings as the baseline")
    parser.add_argument("--select", default=",".join(CHECKS),
                        help="comma-separated checks to run "
                             f"(default: all of {', '.join(CHECKS)})")
    parser.add_argument("--root", default=os.getcwd(),
                        help="path findings are reported relative to")
    args = parser.parse_args(argv)

    checks = tuple(c.strip() for c in args.select.split(",") if c.strip())
    unknown = [c for c in checks if c not in CHECKS]
    if unknown:
        parser.error(f"unknown checks: {', '.join(unknown)}")

    findings = analyze_paths(args.paths, root=args.root, checks=checks)

    if args.write_baseline:
        baseline_mod.save(findings, args.baseline)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    if args.no_baseline:
        for f in findings:
            print(f.render())
        print(f"{len(findings)} finding(s)")
        return 1 if findings else 0

    base = baseline_mod.load(args.baseline)
    new, stale = baseline_mod.compare(findings, base)
    for f in new:
        print(f.render())
    for key in stale:
        print(f"stale baseline entry (violation fixed — run "
              f"--write-baseline to burn down): {key}")
    if new:
        print(f"{len(new)} new finding(s) "
              f"({len(findings)} total, {sum(base.values())} baselined)")
        return 1
    print(f"clean: {len(findings)} finding(s), all baselined "
          f"({len(stale)} stale)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
