"""CLI: ``python -m tools.raylint ray_tpu/``.

Runs the per-module checkers (tools/raylint/core.py) plus the
whole-program pass (tools/raylint/whole_program.py — async-blocking,
rpc-surface, surface-drift over the repo-wide call graph) and, when the
full check set is selected, the unused-suppression audit: a
``# raylint: disable=`` comment whose check no longer fires anywhere on
its line is itself a finding, so suppressions cannot rot.

Exit codes: 0 — clean against the baseline; 1 — new findings; 2 — usage
error. ``--write-baseline`` refreshes the frozen set (burn-down commits
run it after fixing violations). ``--json`` emits machine-readable
findings for CI annotation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.raylint import baseline as baseline_mod
from tools.raylint.core import (CHECKS, Finding, analyze_paths,
                                collect_suppressions)
from tools.raylint.whole_program import WP_CHECKS, analyze_program_paths

ALL_CHECKS = CHECKS + WP_CHECKS + ("unused-suppression",)


def _finding_json(f: Finding) -> dict:
    return {"path": f.path, "line": f.line, "check": f.check,
            "scope": f.scope, "detail": f.detail, "message": f.message,
            "key": f.key()}


def run_checks(paths, root, checks, audit_suppressions=True):
    """All findings for `paths`: per-module + whole-program checkers,
    plus the unused-suppression audit when every check is enabled
    (a partial --select would otherwise flag suppressions whose check
    simply didn't run)."""
    hits = set()
    findings = []
    module_checks = tuple(c for c in checks if c in CHECKS)
    wp_checks = tuple(c for c in checks if c in WP_CHECKS)
    if module_checks:
        findings.extend(analyze_paths(paths, root=root,
                                      checks=module_checks,
                                      suppression_hits=hits))
    if wp_checks:
        findings.extend(analyze_program_paths(paths, root=root,
                                              checks=wp_checks,
                                              suppression_hits=hits))
    if audit_suppressions and "unused-suppression" in checks and \
            set(CHECKS + WP_CHECKS) <= set(checks):
        for relpath, line, raw in collect_suppressions(paths, root=root):
            if (relpath, line) not in hits:
                findings.append(Finding(
                    relpath, "unused-suppression", "<comment>",
                    f"disable={raw}", line,
                    f"suppression 'disable={raw}' matches no finding — "
                    f"the violation is gone; delete the comment"))
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.detail))
    return findings


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.raylint",
        description="concurrency + jit-boundary + whole-program "
                    "surface-consistency static analysis")
    parser.add_argument("paths", nargs="+", help="files or directories")
    parser.add_argument("--baseline", default=baseline_mod.DEFAULT_BASELINE,
                        help="baseline file (default: committed baseline)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignore the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="freeze the current findings as the baseline")
    parser.add_argument("--select", default=",".join(ALL_CHECKS),
                        help="comma-separated checks to run "
                             f"(default: all of {', '.join(ALL_CHECKS)})")
    parser.add_argument("--root", default=os.getcwd(),
                        help="path findings are reported relative to")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output (findings, new, "
                             "stale) for CI annotation")
    args = parser.parse_args(argv)

    checks = tuple(c.strip() for c in args.select.split(",") if c.strip())
    unknown = [c for c in checks if c not in ALL_CHECKS]
    if unknown:
        parser.error(f"unknown checks: {', '.join(unknown)}")

    findings = run_checks(args.paths, args.root, checks)

    if args.write_baseline:
        baseline_mod.save(findings, args.baseline)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    if args.no_baseline:
        if args.as_json:
            print(json.dumps({"findings": [_finding_json(f)
                                           for f in findings],
                              "new": [], "stale": []}, indent=2))
        else:
            for f in findings:
                print(f.render())
            print(f"{len(findings)} finding(s)")
        return 1 if findings else 0

    base = baseline_mod.load(args.baseline)
    new, stale = baseline_mod.compare(findings, base)
    if args.as_json:
        print(json.dumps({"findings": [_finding_json(f)
                                       for f in findings],
                          "new": [_finding_json(f) for f in new],
                          "stale": sorted(stale)}, indent=2))
        return 1 if new else 0
    for f in new:
        print(f.render())
    for key in stale:
        print(f"stale baseline entry (violation fixed — run "
              f"--write-baseline to burn down): {key}")
    if new:
        print(f"{len(new)} new finding(s) "
              f"({len(findings)} total, {sum(base.values())} baselined)")
        return 1
    print(f"clean: {len(findings)} finding(s), all baselined "
          f"({len(stale)} stale)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
