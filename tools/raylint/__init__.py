"""raylint — concurrency + jit-boundary static analysis for ray_tpu.

Usage: ``python -m tools.raylint ray_tpu/`` (see ``--help``). The four
checkers, the baseline-burndown workflow, and inline suppression are
documented in ``tools/raylint/core.py`` and README "Static analysis
gates".
"""

from tools.raylint.core import (  # noqa: F401
    CHECKS,
    Finding,
    analyze_file,
    analyze_paths,
    analyze_source,
)
