"""Whole-program checkers built on the raylint call graph.

Three interprocedural checkers over the `Program` view
(tools/raylint/callgraph.py) — see README "Static analysis gates":

``async-blocking``
    Flags blocking operations reachable from any ``async def`` through
    the transitive same-repo call chain with no intervening executor
    hop. The event-loop-stall class: a ``time.sleep`` backoff three
    sync helpers below an async RPC handler parks the entire loop, and
    shows up only as tail latency under load. Direct blocking ops in an
    async def are flagged at the op; a call from an async def into a
    sync chain that (transitively) blocks is flagged at the async→sync
    boundary call site, with the chain in the message. An async callee
    that blocks is that callee's own finding — the boundary rule keeps
    one finding per root cause instead of one per caller. Sanctioned
    escapes: ``loop.run_in_executor``, ``asyncio.to_thread``,
    ``Thread(target=)``, ``executor.submit`` — arguments of these calls
    run off-loop and are exempt.

``rpc-surface``
    Compile-time-style checking for the string-keyed RPC plane. Every
    ``server.register("name", fn)`` literal and ``register_all(self,
    prefix="rpc_")`` class sweep (base chain included) defines the
    handler surface; every ``client.call("name")`` /
    ``notify`` / ``call_nowait`` literal consumes it. A call site whose
    method no server registers is a latent ``RpcError("no handler for
    method ...")``; a handler no call site ever names is dead surface.
    Name-level matching (not per-server): the transport is shared, so a
    name registered by any server satisfies any caller.

``surface-drift``
    The same literal-matching discipline for the observability plane.
    Consumers — ``tsdb`` ``rate``/``latest``/``points`` query literals,
    ``histogram_quantile`` families (→ ``_bucket``), and prefix-filter
    tuples (``DEFAULT_PREFIXES``-shaped assignments, bench's attribution
    prefixes) — must resolve against an exporter: a ``Counter`` /
    ``Gauge`` / ``Histogram`` constructor literal (Histogram also
    exports ``_bucket``/``_sum``/``_count``) or an exposition-text row
    literal (``f"rpc_{n} {v}"``-style callbacks export the ``rpc_``
    prefix). A renamed metric otherwise silently zeroes the dashboard
    panel or bench REGRESSION gate that reads the old name.

Consumer-only aux files (bench.py) contribute rpc-surface call sites
and surface-drift uses, but are not async-blocking sources and their
string literals do not satisfy ``ray_tpu/`` exporters.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.raylint.callgraph import (FactsCache, ModuleFacts, Program,
                                     build_program)
from tools.raylint.core import Finding, iter_python_files

WP_CHECKS = ("async-blocking", "rpc-surface", "surface-drift")


# ---------------------------------------------------------------------------
# async-blocking
# ---------------------------------------------------------------------------

def _sync_blocking_summaries(program: Program,
                             suppression_hits: Optional[
                                 Set[Tuple[str, int]]] = None,
                             ) -> Dict[str, Tuple[str, List[str]]]:
    """Fixpoint over *sync* functions: key -> (reason, chain) where
    chain is the call path (function keys) from the function down to
    the primitive blocking op. Async functions are boundaries, never
    carriers — a sync fn calling an async fn gets a coroutine object
    back, it does not block."""
    summaries: Dict[str, Tuple[str, List[str]]] = {}
    sync_keys = [k for k, (_m, fact) in program.functions.items()
                 if not fact.is_async and not program.functions[k][0].aux]
    for key in sync_keys:
        mf, fact = program.functions[key]
        # a suppression at the primitive op (with its justification —
        # e.g. the one-time `make` in native.load_shm_store) sanctions
        # every chain through it, not just the sync caller's own line
        live = []
        for reason, line in fact.blocking:
            hit = mf.suppression_line("async-blocking", line)
            if hit is None:
                live.append((reason, line))
            elif suppression_hits is not None:
                # the comment sits on a real blocking op — it earns its
                # keep by sanctioning the chains through it
                suppression_hits.add((mf.relpath, hit))
        if live:
            reason, _line = live[0]
            summaries[key] = (reason, [])
    changed = True
    while changed:
        changed = False
        for key in sync_keys:
            if key in summaries:
                continue
            for target, _line, _callee in program.edges_of(key):
                _tm, tfact = program.functions[target]
                if tfact.is_async:
                    continue
                hit = summaries.get(target)
                if hit is not None:
                    reason, chain = hit
                    summaries[key] = (reason, [target] + chain)
                    changed = True
                    break
    return summaries


def _pretty_key(key: str) -> str:
    mod, _, qual = key.partition("::")
    return f"{mod}.{qual}"


def check_async_blocking(program: Program,
                         suppression_hits: Optional[
                             Set[Tuple[str, int]]] = None) -> List[Finding]:
    findings: List[Finding] = []
    summaries = _sync_blocking_summaries(program, suppression_hits)
    for key, (mf, fact) in sorted(program.functions.items()):
        if not fact.is_async or mf.aux:
            continue
        for reason, line in fact.blocking:
            findings.append(Finding(
                mf.relpath, "async-blocking", fact.name, reason, line,
                f"blocking op ({reason}) on the event loop — hand it to "
                f"run_in_executor/to_thread or use the async form"))
        for target, line, callee in program.edges_of(key):
            _tm, tfact = program.functions[target]
            if tfact.is_async:
                continue  # its own boundary — flagged there if dirty
            hit = summaries.get(target)
            if hit is None:
                continue
            reason, chain = hit
            path = " -> ".join(_pretty_key(k) for k in [target] + chain)
            findings.append(Finding(
                mf.relpath, "async-blocking", fact.name,
                f"{callee}->{reason}", line,
                f"call into blocking sync chain [{path} -> {reason}] "
                f"stalls the event loop — hop off-loop first "
                f"(run_in_executor/to_thread)"))
    return findings


# ---------------------------------------------------------------------------
# rpc-surface
# ---------------------------------------------------------------------------

def _registered_handlers(program: Program
                         ) -> Dict[str, List[Tuple[ModuleFacts, str, int]]]:
    """method name -> [(module, scope-for-report, def line)] from both
    literal register() calls and register_all() class sweeps."""
    out: Dict[str, List[Tuple[ModuleFacts, str, int]]] = {}
    for mf in program.modules.values():
        for reg in mf.rpc_registrations:
            if reg.kind == "register":
                out.setdefault(reg.name, []).append(
                    (mf, reg.scope, reg.line))
                continue
            # register_all(obj): sweep prefix-named methods of the
            # class (and its resolvable base chain)
            for rmod, rcls in program.class_mro(mf, reg.name):
                for meth, line in rcls.methods.items():
                    if meth.startswith(reg.prefix) and \
                            len(meth) > len(reg.prefix):
                        bare = meth[len(reg.prefix):]
                        out.setdefault(bare, []).append(
                            (rmod, f"{rcls.name}.{meth}", line))
    return out


def check_rpc_surface(program: Program) -> List[Finding]:
    findings: List[Finding] = []
    handlers = _registered_handlers(program)
    called: Set[str] = set()
    for mf in program.modules.values():
        for site in mf.rpc_calls:
            called.add(site.method)
            if site.method not in handlers:
                findings.append(Finding(
                    mf.relpath, "rpc-surface", site.scope,
                    f"call:{site.method}", site.line,
                    f"{site.verb}({site.method!r}) has no registered "
                    f"handler — a runtime RpcError('no handler for "
                    f"method') waiting to fire"))
    for name, sites in sorted(handlers.items()):
        if name in called:
            continue
        # dynamic-dispatch fallback: the name as a string literal
        # anywhere outside its own registration lines means some
        # variable-method path plausibly reaches it — not provably dead
        reg_lines = {(mf.relpath, line) for mf, _scope, line in sites}
        if any((m.relpath, line) not in reg_lines
               for m in program.modules.values()
               for value, line in m.str_mentions if value == name):
            continue
        for mf, scope, line in sites:
            if mf.aux:
                continue  # bench-local surface is bench's business
            findings.append(Finding(
                mf.relpath, "rpc-surface", scope,
                f"handler:{name}", line,
                f"handler {name!r} is registered but no call site "
                f"names it — dead RPC surface (delete it or add the "
                f"missing caller)"))
    return findings


# ---------------------------------------------------------------------------
# surface-drift
# ---------------------------------------------------------------------------

def check_surface_drift(program: Program) -> List[Finding]:
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    for mf in program.modules.values():
        if mf.aux:
            continue  # bench rows don't satisfy ray_tpu queries
        for exp in mf.metric_exports:
            (prefixes if exp.is_prefix else exact).add(exp.name)

    def resolves(use) -> bool:
        if use.is_prefix:
            # prefix-filter element: live if ANY exporter falls under it
            return any(n.startswith(use.name) for n in exact) or \
                any(p.startswith(use.name) or use.name.startswith(p)
                    for p in prefixes)
        if use.name in exact:
            return True
        return any(use.name.startswith(p) for p in prefixes)

    findings: List[Finding] = []
    for mf in program.modules.values():
        for use in mf.metric_uses:
            if resolves(use):
                continue
            kind = "prefix" if use.is_prefix else "metric"
            findings.append(Finding(
                mf.relpath, "surface-drift", use.scope,
                f"{kind}:{use.name}", use.line,
                f"{kind} {use.name!r} matches no registered or "
                f"callback-exported metric in ray_tpu/ — this query "
                f"silently reads zero"))
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

_WP_CHECKERS = {
    "async-blocking": check_async_blocking,
    "rpc-surface": check_rpc_surface,
    "surface-drift": check_surface_drift,
}


def find_aux_files(paths: Sequence[str], root: str) -> List[str]:
    """Consumer-only siblings of the analyzed tree: a ``bench.py``
    next to the repo root joins the program so its RPC call literals
    and metric value-keys are checked against the ray_tpu surface."""
    out: List[str] = []
    candidate = os.path.join(root, "bench.py")
    if os.path.isfile(candidate):
        analyzed = {os.path.abspath(p) for p in iter_python_files(paths)}
        if os.path.abspath(candidate) not in analyzed:
            out.append(candidate)
    return out


def analyze_program_paths(
        paths: Sequence[str], root: Optional[str] = None,
        checks: Sequence[str] = WP_CHECKS,
        aux_paths: Optional[Sequence[str]] = None,
        cache: Optional[FactsCache] = None,
        suppression_hits: Optional[Set[Tuple[str, int]]] = None,
) -> List[Finding]:
    """Run the whole-program checkers over `paths` (+ auto-discovered
    aux consumers). Suppressions are honored per finding line; matched
    suppression-comment lines are recorded into `suppression_hits`
    (for the unused-suppression audit)."""
    root = root or os.getcwd()
    files = iter_python_files(paths)
    if aux_paths is None:
        aux_paths = find_aux_files(paths, root)
    program = build_program(files, root, aux_paths=aux_paths, cache=cache)
    return analyze_program(program, checks, suppression_hits)


def analyze_program(program: Program,
                    checks: Sequence[str] = WP_CHECKS,
                    suppression_hits: Optional[Set[Tuple[str, int]]] = None,
                    ) -> List[Finding]:
    findings: List[Finding] = []
    for check in checks:
        if check == "async-blocking":
            raw = check_async_blocking(program, suppression_hits)
        else:
            raw = _WP_CHECKERS[check](program)
        for f in raw:
            mf = program.by_relpath.get(f.path)
            hit = mf.suppression_line(f.check, f.line) if mf else None
            if hit is not None:
                if suppression_hits is not None:
                    suppression_hits.add((f.path, hit))
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.detail))
    return findings


def analyze_program_sources(sources: Dict[str, str],
                            checks: Sequence[str] = WP_CHECKS,
                            aux: Sequence[str] = ()) -> List[Finding]:
    """Test helper: build a Program from in-memory {relpath: source}
    and run the whole-program checkers (paths in `aux` are
    consumer-only)."""
    from tools.raylint.callgraph import extract_module_facts
    modules = [extract_module_facts(src, rel, aux=rel in set(aux))
               for rel, src in sources.items()]
    return analyze_program(Program(modules), checks)
