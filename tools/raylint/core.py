"""raylint core — AST-based concurrency + jit-boundary analysis.

Four checkers over ``ray_tpu/`` source (see ISSUE/COVERAGE "Static
analysis gates"):

``lock-discipline``
    Compositional guard inference in the spirit of RacerD: per class,
    an instance attribute is *guarded* when some method writes it while
    holding a ``with self.<lock>:`` region. Any write to a guarded
    attribute outside a held-lock region is flagged. ``__init__`` writes
    are exempt up to the point where ``self`` escapes (is passed to a
    call — e.g. a registry publishing the half-built object to other
    threads); escape through ``super().__init__`` is resolved one level
    within the module. Methods named ``*_locked`` assert
    "caller holds the lock" and are exempt. The same inference runs at
    module level for globals written under a module-level lock.

``blocking-under-lock``
    Flags blocking operations inside a held-lock region: ``time.sleep``,
    ``subprocess.*``, ``.result()``, RPC sends (``.remote()``),
    ``ray_tpu.get/wait/kill`` and bare ``.join()``. Summaries are
    compositional: a call under a lock to a same-module function or
    same-class method that (transitively) blocks is flagged with the
    call chain. Calls on the held lock object itself (``cond.wait()``)
    are the condition-variable pattern and exempt.

``jit-purity``
    Finds functions staged by ``jax.jit`` / ``pjit`` / ``shard_map`` /
    ``lax.scan`` (decorator, ``functools.partial`` decorator, or direct
    call on a module/local function, lambda, or ``self.<method>``) and
    flags host side effects inside them: ``print``, ``logging``/logger
    calls, wall-clock reads (``time.time`` etc.), host RNG
    (``random.*``, ``np.random.*``), and tracer escape via ``self.<x> =``
    stores. ``jax.debug.print``/``jax.debug.callback`` are the
    sanctioned escape hatches and are not flagged.

``seeded-rng``
    In ``_private/`` runtime paths, bare ``random.*`` / ``np.random.*``
    calls are flagged: chaos schedules (``RAY_TPU_CHAOS``) are replayable
    only when every probabilistic decision routes through the FaultPlan's
    per-site seeded streams (``FaultPlan.rng_for``). Constructing a
    seeded ``random.Random(...)`` stream is the sanctioned form and is
    not flagged.

``jit-cache-stability``
    Flags jit-compiled callables constructed where they cannot be
    cached: ``jax.jit`` / ``pjit`` / ``shard_map`` construction inside a
    ``for``/``while`` loop body (a fresh wrapper per iteration discards
    the compilation cache — every step silently retraces), and the
    construct-and-call form ``jax.jit(f)(x)`` which builds and throws
    away the wrapper in one expression. The sanctioned forms are
    hoisting the jit out of the loop or routing the step through the
    AOT executable cache (``ray_tpu.parallel.compiled_step`` /
    ``fold_steps``).

``metric-in-hot-loop``
    Flags ``Counter`` / ``Gauge`` / ``Histogram`` (ray_tpu.util.metrics)
    constructed inside a loop or a per-call function: every
    construction registers a NEW metric object with the registry, so a
    metric built per task/request/iteration leaks registry entries
    without bound (and every /metrics scrape re-renders all of them).
    Sanctioned forms: module-scope construction, construction in
    ``__init__`` (one object per instance), one-time setup functions
    (names like ``init*``/``setup*``/``create*``/``build*``/
    ``register*``/``start*``/``main``), or a scrape-time text callback
    (``DEFAULT_REGISTRY.register_callback``) which constructs nothing.

``span-leak``
    Flags tracing spans opened manually — ``s = start_span(...)`` or
    ``s = span(...).__enter__()`` — whose close (``s.__exit__`` /
    ``s.end()`` / ``s.close()`` / ``s.finish()``) is not guaranteed on
    exception paths: an exception between open and close leaks the span
    (its end timestamp never lands, and a contextvar-parented span
    poisons every span opened after it on that thread). Sanctioned
    forms: ``with span(...)``, or closing in a ``finally:`` block.

``snapshot-read``
    Pins the dispatch-plane snapshot-read idiom (serve/dispatch.py):
    rows bound from a ``ring.snapshot()`` read are validated by the
    generation check *at read time only*. Re-using them — or anything
    derived from them — after a mutating call on the same receiver
    (``publish`` / ``mark_dead`` / ``done`` / ``release``) crosses a
    version or generation bump: the rows can describe replicas whose
    slot was already retired and re-issued, so a routing decision made
    from them sails past the ABA guard. Sanctioned forms: finish every
    use before the mutating call (single-hold read), or re-snapshot
    after it. Conservative: flags only a straight-line
    bind → same-receiver mutate → reuse sequence within one function.

Suppression: append ``# raylint: disable=<check>`` (or ``disable=all``)
to the flagged line, or put it on a comment line directly above.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

CHECKS = ("lock-discipline", "blocking-under-lock", "jit-purity",
          "seeded-rng", "jit-cache-stability", "metric-in-hot-loop",
          "span-leak", "snapshot-read", "watchdog-probe")

_LOCKISH_NAME = re.compile(r"lock|mutex|cond", re.IGNORECASE)
_LOCK_FACTORIES = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "allocate_lock",
}
# container/ordered-dict mutators that count as writes to the container
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "appendleft", "popleft",
    "move_to_end", "sort", "reverse",
}
_SUPPRESS_RE = re.compile(r"#\s*raylint:\s*disable=([\w,\-]+)")

# ray_tpu.util.metrics constructor names (metric-in-hot-loop)
_METRIC_TYPES = {"Counter", "Gauge", "Histogram"}
# one-time setup scopes where constructing a metric is sanctioned
_METRIC_SETUP_PREFIXES = ("init", "_init", "__init", "setup", "_setup",
                          "create", "_create", "build", "_build",
                          "register", "_register", "start", "_start",
                          "make", "_make", "main")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str      # repo-relative posix path
    check: str     # one of CHECKS
    scope: str     # Class.method, function name, or <module>
    detail: str    # stable detail, e.g. "attr:_queue" or "ray_tpu.get"
    line: int      # 1-based line (display only — not part of the key)
    message: str

    def key(self) -> str:
        """Line-number-free identity used for the baseline (stable
        across unrelated edits)."""
        return f"{self.path}::{self.check}::{self.scope}::{self.detail}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.check}] {self.scope}: "
                f"{self.message}")


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """Best-effort dotted name of an expression ('self._lock',
    'ray_tpu.get'). None for anything non-name-shaped."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """Attr name if node is ``self.<attr>``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _written_self_attrs(target: ast.AST) -> List[str]:
    """Self attrs written by an assignment target (incl. subscript
    stores — writing ``self.x[k]`` mutates the object behind ``x``)."""
    out: List[str] = []
    attr = _self_attr(target)
    if attr is not None:
        out.append(attr)
    elif isinstance(target, ast.Subscript):
        attr = _self_attr(target.value)
        if attr is not None:
            out.append(attr)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            out.extend(_written_self_attrs(el))
    elif isinstance(target, ast.Starred):
        out.extend(_written_self_attrs(target.value))
    return out


def _written_globals(target: ast.AST, global_names: Set[str]) -> List[str]:
    out: List[str] = []
    if isinstance(target, ast.Name) and target.id in global_names:
        out.append(target.id)
    elif (isinstance(target, ast.Subscript)
          and isinstance(target.value, ast.Name)
          and target.value.id in global_names):
        out.append(target.value.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            out.extend(_written_globals(el, global_names))
    return out


def _iter_func_nodes(tree: ast.Module):
    """Yield (classname_or_None, funcdef) for every module-level function
    and every method of every class (nested classes included)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield node.name, item


def _scan_held(nodes: Iterable[ast.stmt], held: Tuple[str, ...],
               nested: bool, lock_test):
    """Depth-first walk of statements yielding ``(node, held, nested)``
    for every AST node, where ``held`` is the tuple of lock names whose
    ``with`` region lexically encloses the node. Nested function/lambda
    bodies run at another time (often another thread): they are walked
    with an empty held set and ``nested=True``."""
    for node in nodes:
        yield from _scan_node(node, held, nested, lock_test)


def _scan_node(node: ast.AST, held: Tuple[str, ...], nested: bool,
               lock_test):
    yield node, held, nested
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for d in node.decorator_list:
            yield from _scan_node(d, held, nested, lock_test)
        yield from _scan_held(node.body, (), True, lock_test)
        return
    if isinstance(node, ast.Lambda):
        yield from _scan_node(node.body, (), True, lock_test)
        return
    if isinstance(node, (ast.With, ast.AsyncWith)):
        locks: List[str] = []
        for item in node.items:
            name = lock_test(item.context_expr)
            if name:
                locks.append(name)
            yield from _scan_node(item.context_expr, held, nested,
                                  lock_test)
        yield from _scan_held(node.body, held + tuple(locks), nested,
                              lock_test)
        return
    for child in ast.iter_child_nodes(node):
        yield from _scan_node(child, held, nested, lock_test)


# ---------------------------------------------------------------------------
# per-module context
# ---------------------------------------------------------------------------

class ModuleContext:
    """Parsed module plus the facts the checkers share: lock attrs per
    class, module-level lock globals, class bases, import aliases."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.classes: Dict[str, ast.ClassDef] = {}
        self.module_funcs: Dict[str, ast.AST] = {}
        self.class_bases: Dict[str, List[str]] = {}
        self.lock_attrs: Dict[str, Set[str]] = {}   # class -> lock attrs
        self.module_lock_globals: Set[str] = set()
        self.random_aliases: Set[str] = set()
        self.numpy_aliases: Set[str] = set()
        # names bound to ray_tpu.util.metrics constructors (so bare
        # `Counter(...)` is only a metric ctor when imported from the
        # metrics module — collections.Counter must not be flagged)
        self.metric_ctor_names: Set[str] = set()
        self._collect()

    # -- fact collection -------------------------------------------------

    def _collect(self) -> None:
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_funcs[node.name] = node
            elif isinstance(node, ast.Assign):
                if self._is_lock_factory_call(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.module_lock_globals.add(t.id)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                self.class_bases[node.name] = [
                    b for b in (dotted(base) for base in node.bases) if b]
                attrs: Set[str] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and \
                            self._is_lock_factory_call(sub.value):
                        for t in sub.targets:
                            a = _self_attr(t)
                            if a:
                                attrs.add(a)
                self.lock_attrs[node.name] = attrs
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name
                    if alias.name == "random":
                        self.random_aliases.add(bound)
                    elif alias.name in ("numpy", "numpy.random"):
                        self.numpy_aliases.add(bound.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                if node.module == "numpy":
                    for alias in node.names:
                        if alias.name == "random":
                            # `from numpy import random as npr` — treat the
                            # bound name as a numpy.random module ref
                            self.random_aliases.discard(
                                alias.asname or alias.name)
                            self.numpy_aliases.add("__from_numpy__")
                if node.module and (node.module.endswith("metrics")
                                    or node.module == "ray_tpu.util"):
                    for alias in node.names:
                        if alias.name in _METRIC_TYPES:
                            self.metric_ctor_names.add(
                                alias.asname or alias.name)

    @staticmethod
    def _is_lock_factory_call(value: ast.AST) -> bool:
        if not isinstance(value, ast.Call):
            return False
        name = dotted(value.func)
        if not name:
            return False
        return name.split(".")[-1] in _LOCK_FACTORIES

    # -- lock expression tests -------------------------------------------

    def lock_test_for_class(self, classname: Optional[str]):
        """Return lock_test(expr) -> canonical-name-or-None for with
        items, valid inside the given class (or module scope)."""
        lock_attrs = self.lock_attrs.get(classname or "", set())

        def test(expr: ast.AST) -> Optional[str]:
            name = dotted(expr)
            if not name:
                return None
            if name.startswith("self."):
                attr = name[5:]
                if attr in lock_attrs or _LOCKISH_NAME.search(attr):
                    return name
                return None
            if name in self.module_lock_globals:
                return name
            if "." not in name and _LOCKISH_NAME.search(name):
                # local variable holding a lock (e.g. key_lock)
                return name
            return None

        return test

    # -- misc -------------------------------------------------------------

    def suppressed(self, check: str, line: int) -> bool:
        """True when `# raylint: disable=<check>` is on the flagged line
        or the line directly above it."""
        return self.suppression_line(check, line) is not None

    def suppression_line(self, check: str, line: int) -> Optional[int]:
        """Line number of the suppression comment covering (check,
        line), or None — lets the CLI audit which suppressions still
        earn their keep."""
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = _SUPPRESS_RE.search(self.lines[ln - 1])
                if m:
                    what = {w.strip() for w in m.group(1).split(",")}
                    if "all" in what or check in what:
                        return ln
        return None

    def base_chain(self, classname: str) -> List[str]:
        """Same-module ancestor classes, nearest first (cycles cut)."""
        out: List[str] = []
        seen = {classname}
        frontier = [classname]
        while frontier:
            cur = frontier.pop(0)
            for base in self.class_bases.get(cur, []):
                base = base.split(".")[-1]
                if base in self.classes and base not in seen:
                    seen.add(base)
                    out.append(base)
                    frontier.append(base)
        return out


# ---------------------------------------------------------------------------
# checker 1: lock-discipline
# ---------------------------------------------------------------------------

def _writes_in(node: ast.AST) -> List[Tuple[str, int]]:
    """(attr, line) self-attr writes performed directly by `node`
    (assignment targets, aug-assign, del, container mutator calls)."""
    out: List[Tuple[str, int]] = []
    if isinstance(node, ast.Assign):
        for t in node.targets:
            for a in _written_self_attrs(t):
                out.append((a, node.lineno))
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if getattr(node, "value", None) is not None or \
                isinstance(node, ast.AugAssign):
            for a in _written_self_attrs(node.target):
                out.append((a, node.lineno))
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            for a in _written_self_attrs(t):
                out.append((a, node.lineno))
    elif isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            a = _self_attr(node.func.value)
            if a is not None:
                out.append((a, node.lineno))
    return out


def _escapes_self(call: ast.Call) -> bool:
    """Does this call receive `self` as an explicit argument?"""
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, ast.Name) and arg.id == "self":
            return True
        if isinstance(arg, ast.Starred) and \
                isinstance(arg.value, ast.Name) and arg.value.id == "self":
            return True
    return False


def _is_super_init(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "__init__"
            and isinstance(call.func.value, ast.Call)
            and isinstance(call.func.value.func, ast.Name)
            and call.func.value.func.id == "super")


def _init_escape_fact(ctx: ModuleContext, classname: str,
                      memo: Dict[str, bool]) -> bool:
    """Does `classname.__init__` leak self (directly or via a same-module
    base __init__)?"""
    if classname in memo:
        return memo[classname]
    memo[classname] = False  # cycle guard
    cls = ctx.classes.get(classname)
    if cls is None:
        return False
    init = next((n for n in cls.body
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                 and n.name == "__init__"), None)
    escaped = False
    if init is not None:
        for node in ast.walk(init):
            if isinstance(node, ast.Call):
                if _escapes_self(node):
                    escaped = True
                    break
                if _is_super_init(node):
                    for base in ctx.base_chain(classname):
                        if _init_escape_fact(ctx, base, memo):
                            escaped = True
                            break
                    if escaped:
                        break
    else:
        # no own __init__: inherits the base's behavior
        for base in ctx.base_chain(classname):
            if _init_escape_fact(ctx, base, memo):
                escaped = True
                break
    memo[classname] = escaped
    return escaped


def check_lock_discipline(ctx: ModuleContext) -> List[Finding]:
    findings: List[Finding] = []
    escape_memo: Dict[str, bool] = {}

    # ---- class-level inference ----
    # pass 1: guarded attrs per class (merged along same-module bases)
    own_guarded: Dict[str, Set[str]] = {}
    for classname, cls in ctx.classes.items():
        lock_test = ctx.lock_test_for_class(classname)
        guarded: Set[str] = set()
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue
            for node, held, _nested in _scan_held(item.body, (), False,
                                                  lock_test):
                if held and any(h.startswith("self.") for h in held):
                    for attr, _line in _writes_in(node):
                        guarded.add(attr)
        own_guarded[classname] = guarded

    for classname, cls in ctx.classes.items():
        guarded = set(own_guarded.get(classname, ()))
        for base in ctx.base_chain(classname):
            guarded |= own_guarded.get(base, set())
        if not guarded:
            continue
        lock_test = ctx.lock_test_for_class(classname)
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name.endswith("_locked"):
                continue  # contract: caller holds the lock
            scope = f"{classname}.{item.name}"
            if item.name == "__init__":
                # exempt until self escapes (publication point)
                escaped = False
                for stmt in item.body:
                    if escaped:
                        for node in ast.walk(stmt):
                            for attr, line in _writes_in(node):
                                if attr in guarded:
                                    findings.append(Finding(
                                        ctx.relpath, "lock-discipline",
                                        scope, f"attr:{attr}", line,
                                        f"write to lock-guarded `self."
                                        f"{attr}` after `self` escaped in "
                                        f"__init__ (object is visible to "
                                        f"other threads before its state "
                                        f"is complete)"))
                    else:
                        for node in ast.walk(stmt):
                            if isinstance(node, ast.Call) and (
                                    _escapes_self(node)
                                    or (_is_super_init(node) and any(
                                        _init_escape_fact(ctx, b,
                                                          escape_memo)
                                        for b in ctx.base_chain(
                                            classname)))):
                                escaped = True
                                break
                continue
            for node, held, nested in _scan_held(item.body, (), False,
                                                 lock_test):
                if held and any(h.startswith("self.") for h in held):
                    continue
                for attr, line in _writes_in(node):
                    if attr in guarded:
                        where = ("nested function in " if nested else "")
                        findings.append(Finding(
                            ctx.relpath, "lock-discipline", scope,
                            f"attr:{attr}", line,
                            f"write to `self.{attr}` outside the lock "
                            f"that guards it elsewhere ({where}{scope})"))

    # ---- module-level inference (globals under module locks) ----
    if ctx.module_lock_globals:
        lock_test = ctx.lock_test_for_class(None)
        global_names = _module_global_names(ctx)
        guarded_globals: Set[str] = set()
        fn_nodes = [(cname, fn) for cname, fn in _iter_func_nodes(ctx.tree)]
        for _cname, fn in fn_nodes:
            for node, held, _nested in _scan_held(fn.body, (), False,
                                                  lock_test):
                if not any(h in ctx.module_lock_globals for h in held):
                    continue
                for name, _line in _global_writes_in(node, global_names):
                    guarded_globals.add(name)
        if guarded_globals:
            for cname, fn in fn_nodes:
                if fn.name.endswith("_locked"):
                    continue
                scope = f"{cname}.{fn.name}" if cname else fn.name
                for node, held, _n in _scan_held(fn.body, (), False,
                                                 lock_test):
                    if any(h in ctx.module_lock_globals for h in held):
                        continue
                    for name, line in _global_writes_in(node, global_names):
                        if name in guarded_globals:
                            findings.append(Finding(
                                ctx.relpath, "lock-discipline", scope,
                                f"global:{name}", line,
                                f"write to module global `{name}` outside "
                                f"the module lock that guards it "
                                f"elsewhere"))
    return findings


def _module_global_names(ctx: ModuleContext) -> Set[str]:
    names: Set[str] = set()
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            names.add(node.target.id)
    return names


def _global_writes_in(node: ast.AST,
                      global_names: Set[str]) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    if isinstance(node, ast.Assign):
        for t in node.targets:
            for n in _written_globals(t, global_names):
                out.append((n, node.lineno))
    elif isinstance(node, ast.AugAssign):
        for n in _written_globals(node.target, global_names):
            out.append((n, node.lineno))
    return out


# ---------------------------------------------------------------------------
# checker 2: blocking-under-lock
# ---------------------------------------------------------------------------

_SUBPROCESS_BLOCKING = {"run", "call", "check_call", "check_output",
                        "Popen", "getoutput", "getstatusoutput"}
_RAY_BLOCKING = {"get", "wait", "kill"}


def _direct_block_reason(call: ast.Call) -> Optional[str]:
    """Reason string when `call` is a known blocking primitive."""
    name = dotted(call.func)
    if name:
        parts = name.split(".")
        if name == "time.sleep":
            return "time.sleep"
        if parts[0] == "subprocess" and parts[-1] in _SUBPROCESS_BLOCKING:
            return name
        if name in ("os.system", "os.waitpid"):
            return name
        if parts[0] in ("ray_tpu", "ray") and len(parts) == 2 and \
                parts[1] in _RAY_BLOCKING:
            return name
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr == "result":
            return ".result()"
        if attr == "remote":
            return ".remote() [RPC send]"
        if attr == "join" and not call.args:
            return ".join()"
    return None


def _build_block_summaries(ctx: ModuleContext):
    """qual -> (direct_reasons, callees). qual is 'Class.meth' or
    'func'. Callees resolved within the module (self.m → same class or
    same-module base; bare f() → module function)."""
    info: Dict[str, Tuple[List[str], Set[str]]] = {}
    for classname, fn in _iter_func_nodes(ctx.tree):
        qual = f"{classname}.{fn.name}" if classname else fn.name
        direct: List[str] = []
        callees: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            reason = _direct_block_reason(node)
            if reason:
                direct.append(reason)
                continue
            name = dotted(node.func)
            if not name:
                continue
            if name.startswith("self.") and classname:
                meth = name[5:]
                if "." not in meth:
                    for owner in [classname] + ctx.base_chain(classname):
                        if f"{owner}.{meth}" in info or _class_has_method(
                                ctx, owner, meth):
                            callees.add(f"{owner}.{meth}")
                            break
            elif "." not in name and name in ctx.module_funcs:
                callees.add(name)
        info[qual] = (direct, callees)
    return info


def _class_has_method(ctx: ModuleContext, classname: str,
                      meth: str) -> bool:
    cls = ctx.classes.get(classname)
    if cls is None:
        return False
    return any(isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
               and n.name == meth for n in cls.body)


def _block_chains(ctx: ModuleContext) -> Dict[str, str]:
    """Fixpoint: qual -> human chain like '_poll → ray_tpu.get' for every
    function that (transitively) blocks."""
    info = _build_block_summaries(ctx)
    chains: Dict[str, str] = {}
    changed = True
    while changed:
        changed = False
        for qual, (direct, callees) in info.items():
            if qual in chains:
                continue
            if direct:
                chains[qual] = direct[0]
                changed = True
                continue
            for callee in callees:
                if callee in chains:
                    chains[qual] = f"{callee} → {chains[callee]}"
                    changed = True
                    break
    return chains


def check_blocking_under_lock(ctx: ModuleContext) -> List[Finding]:
    findings: List[Finding] = []
    chains = _block_chains(ctx)
    for classname, fn in _iter_func_nodes(ctx.tree):
        scope = f"{classname}.{fn.name}" if classname else fn.name
        lock_test = ctx.lock_test_for_class(classname)
        for node, held, _nested in _scan_held(fn.body, (), False,
                                              lock_test):
            if not held or not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            # condition-variable pattern: calls on the held lock itself
            if any(name == h or name.startswith(h + ".") for h in held):
                continue
            reason = _direct_block_reason(node)
            if reason:
                findings.append(Finding(
                    ctx.relpath, "blocking-under-lock", scope, reason,
                    node.lineno,
                    f"blocking `{reason}` while holding "
                    f"{', '.join(held)}"))
                continue
            target = None
            if name.startswith("self.") and classname and \
                    "." not in name[5:]:
                meth = name[5:]
                for owner in [classname] + ctx.base_chain(classname):
                    if f"{owner}.{meth}" in chains:
                        target = f"{owner}.{meth}"
                        break
            elif "." not in name and name in chains:
                target = name
            if target is not None:
                findings.append(Finding(
                    ctx.relpath, "blocking-under-lock", scope,
                    f"call:{target}", node.lineno,
                    f"`{name}()` blocks ({target} → {chains[target]}) "
                    f"while holding {', '.join(held)}"))
    return findings


# ---------------------------------------------------------------------------
# checker 3: jit-purity
# ---------------------------------------------------------------------------

_JIT_ENTRY = {"jit", "pjit", "shard_map", "scan", "while_loop",
              "compiled_step", "fold_steps"}


def _jit_entry_name(name: Optional[str]) -> Optional[str]:
    """'jax.jit' / 'jit' / 'lax.scan' / 'shard_map' / 'compiled_step'
    → canonical entry. `compiled_step`/`fold_steps` are the AOT
    executable-cache stagers (ray_tpu.parallel.compile_cache): their
    bodies are staged exactly like a jit's, so jit-purity gates them
    too."""
    if not name:
        return None
    last = name.split(".")[-1]
    if last not in _JIT_ENTRY:
        return None
    # bare `scan`/`while_loop` could be anything; require a lax/jax
    # qualifier for those
    if last in ("scan", "while_loop") and "lax" not in name and \
            "jax" not in name:
        return None
    return last


def _collect_jit_targets(ctx: ModuleContext):
    """Yield (funcdef_or_lambda, classname_or_None, via) for every
    function staged by jit/pjit/shard_map/scan."""
    # name -> (node, classname) for resolution
    local_funcs: Dict[Tuple[Optional[str], str],
                      ast.AST] = {}
    for classname, fn in _iter_func_nodes(ctx.tree):
        local_funcs[(classname, fn.name)] = fn
        # nested defs too (scan bodies are usually local closures)
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not fn:
                local_funcs[(classname, sub.name)] = sub

    seen: Set[int] = set()

    def _resolve(arg: ast.AST, classname: Optional[str]):
        if isinstance(arg, ast.Lambda):
            return arg
        name = dotted(arg)
        if not name:
            return None
        if name.startswith("self."):
            return local_funcs.get((classname, name[5:]))
        if "." not in name:
            return (local_funcs.get((classname, name))
                    or local_funcs.get((None, name)))
        return None

    # decorators
    for classname, fn in _iter_func_nodes(ctx.tree):
        for nested_cls, node in [(classname, fn)] + [
                (classname, sub) for sub in ast.walk(fn)
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub is not fn]:
            for dec in node.decorator_list:
                entry = _jit_entry_name(dotted(dec))
                if entry is None and isinstance(dec, ast.Call):
                    dec_name = dotted(dec.func) or ""
                    entry = _jit_entry_name(dec_name)
                    if entry is None and \
                            dec_name.split(".")[-1] == "partial" and \
                            dec.args:
                        entry = _jit_entry_name(dotted(dec.args[0]))
                if entry and id(node) not in seen:
                    seen.add(id(node))
                    yield node, nested_cls, f"@{entry}"

    # call sites: jit(f), shard_map(f, ...), lax.scan(f, ...)
    for classname, fn in _iter_func_nodes(ctx.tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            entry = _jit_entry_name(dotted(node.func))
            if entry is None:
                name = dotted(node.func) or ""
                if name.split(".")[-1] == "partial" and node.args:
                    entry = _jit_entry_name(dotted(node.args[0]))
                    if entry and len(node.args) > 1:
                        target = _resolve(node.args[1], classname)
                        if target is not None and id(target) not in seen:
                            seen.add(id(target))
                            yield target, classname, entry
                    continue
                continue
            target = _resolve(node.args[0], classname)
            if target is not None and id(target) not in seen:
                seen.add(id(target))
                yield target, classname, entry


_TIME_IMPURE = {"time.time", "time.monotonic", "time.perf_counter",
                "time.sleep", "time.time_ns", "time.perf_counter_ns"}
_LOGGERISH = re.compile(r"^(logging|logger|log|_logger)\.")
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}


def check_jit_purity(ctx: ModuleContext) -> List[Finding]:
    findings: List[Finding] = []
    for target, classname, via in _collect_jit_targets(ctx):
        if isinstance(target, ast.Lambda):
            scope = (f"{classname}.<lambda>" if classname else "<lambda>")
            body_nodes: List[ast.AST] = [target.body]
        else:
            scope = (f"{classname}.{target.name}" if classname
                     else target.name)
            body_nodes = list(target.body)
        for root in body_nodes:
            for node in ast.walk(root):
                if isinstance(node, ast.Call):
                    name = dotted(node.func) or ""
                    if name.startswith("jax.debug."):
                        continue  # sanctioned host callback
                    if name == "print":
                        findings.append(Finding(
                            ctx.relpath, "jit-purity", scope, "print",
                            node.lineno,
                            f"`print` inside a {via}-staged function "
                            f"runs at trace time only (use "
                            f"jax.debug.print)"))
                    elif _LOGGERISH.match(name) and \
                            name.split(".")[-1] in _LOG_METHODS:
                        findings.append(Finding(
                            ctx.relpath, "jit-purity", scope, "logging",
                            node.lineno,
                            f"logging inside a {via}-staged function "
                            f"runs at trace time only"))
                    elif name in _TIME_IMPURE:
                        findings.append(Finding(
                            ctx.relpath, "jit-purity", scope, name,
                            node.lineno,
                            f"`{name}` inside a {via}-staged function is "
                            f"a host side effect (baked in at trace "
                            f"time)"))
                    elif _is_host_rng_call(ctx, node):
                        findings.append(Finding(
                            ctx.relpath, "jit-purity", scope,
                            dotted(node.func) or "host-rng", node.lineno,
                            f"host RNG inside a {via}-staged function "
                            f"(use jax.random with a threaded key)"))
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        for attr in _written_self_attrs(t):
                            findings.append(Finding(
                                ctx.relpath, "jit-purity", scope,
                                f"self-store:{attr}", node.lineno,
                                f"storing to `self.{attr}` inside a "
                                f"{via}-staged function leaks tracers "
                                f"into persistent state"))
    return findings


# ---------------------------------------------------------------------------
# checker 4: seeded-rng
# ---------------------------------------------------------------------------

def _expr_names(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_host_rng_call(ctx: ModuleContext, call: ast.Call) -> bool:
    """`random.<fn>(...)` (module ref, not Random construction) or
    `np.random.<fn>(...)`."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return False
    if func.attr in ("Random", "SystemRandom", "default_rng", "Generator"):
        return False  # constructing a dedicated (seedable) stream
    value = func.value
    # np.random.<fn>
    if isinstance(value, ast.Attribute) and value.attr == "random" and \
            isinstance(value.value, ast.Name) and \
            value.value.id in ctx.numpy_aliases:
        return True
    # random.<fn> — including `(rng or random).shuffle`
    names = _expr_names(value)
    if names & ctx.random_aliases:
        # exclude attribute chains where `random` is an attr of numpy
        # (already handled) or a local var named random-ish bound to a
        # Random instance — a bare Name ref to the module is the signal
        return True
    return False


def check_seeded_rng(ctx: ModuleContext) -> List[Finding]:
    if f"{os.sep}_private{os.sep}" not in ctx.path and \
            "/_private/" not in ctx.relpath:
        return []
    findings: List[Finding] = []
    if not ctx.random_aliases and not ctx.numpy_aliases:
        return findings
    for classname, fn in _iter_func_nodes(ctx.tree):
        scope = f"{classname}.{fn.name}" if classname else fn.name
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and _is_host_rng_call(ctx, node):
                name = dotted(node.func) or "random.*"
                findings.append(Finding(
                    ctx.relpath, "seeded-rng", scope, name, node.lineno,
                    f"bare `{name}` in a _private/ runtime path breaks "
                    f"RAY_TPU_CHAOS replay — draw from "
                    f"FaultPlan.rng_for(site) (fault_injection) or a "
                    f"seeded random.Random stream instead"))
    return findings


# ---------------------------------------------------------------------------
# checker 5: jit-cache-stability
# ---------------------------------------------------------------------------

_JIT_CONSTRUCTORS = {"jit", "pjit", "shard_map"}


def _jit_ctor_name(name: Optional[str]) -> Optional[str]:
    """jit-wrapper CONSTRUCTION sites only (not scan/while_loop, which
    execute rather than build a cached callable)."""
    if not name:
        return None
    last = name.split(".")[-1]
    return last if last in _JIT_CONSTRUCTORS else None


def check_jit_cache_stability(ctx: ModuleContext) -> List[Finding]:
    """Flag jit wrappers constructed where their compilation cache is
    discarded: inside a loop body (fresh wrapper per iteration — every
    step silently retraces) or constructed-and-called in one expression
    (``jax.jit(f)(x)``). Hoist the construction, or use the AOT
    executable cache (ray_tpu.parallel.compiled_step / fold_steps)."""
    findings: List[Finding] = []
    flagged: Set[int] = set()

    def flag(call: ast.Call, scope: str, entry: str, why: str,
             detail: str) -> None:
        if id(call) in flagged:
            return
        flagged.add(id(call))
        findings.append(Finding(
            ctx.relpath, "jit-cache-stability", scope,
            f"{detail}:{entry}", call.lineno, why))

    def visit(node: ast.AST, scope: str, classname: Optional[str],
              in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            c_scope, c_class, c_loop = scope, classname, in_loop
            if isinstance(child, ast.ClassDef):
                c_class = child.name
            elif isinstance(child,
                            (ast.FunctionDef, ast.AsyncFunctionDef)):
                c_scope = (f"{c_class}.{child.name}" if c_class
                           else child.name)
                # in_loop propagates INTO a def inside a loop: that def
                # is a fresh closure per iteration, so a jit built in
                # its body is rebuilt per step too
            elif isinstance(child, (ast.For, ast.While, ast.AsyncFor)):
                c_loop = True
            if isinstance(child, ast.Call):
                inner = child.func
                if isinstance(inner, ast.Call):
                    entry = _jit_ctor_name(dotted(inner.func))
                    if entry:
                        flag(inner, c_scope, entry,
                             f"`{entry}(...)(...)` constructs and "
                             f"discards the jitted callable in one "
                             f"expression — every call retraces; bind "
                             f"the wrapper once (or use "
                             f"ray_tpu.parallel.compiled_step)",
                             "construct-and-call")
                entry = _jit_ctor_name(dotted(child.func))
                if entry and c_loop and id(child) not in flagged:
                    flag(child, c_scope, entry,
                         f"`{entry}` constructed inside a loop builds a "
                         f"fresh wrapper per iteration — the compilation "
                         f"cache is discarded and every step silently "
                         f"retraces; hoist it out of the loop (or use "
                         f"ray_tpu.parallel.compiled_step / fold_steps)",
                         "in-loop")
            visit(child, c_scope, c_class, c_loop)

    visit(ctx.tree, "<module>", None, False)
    return findings


# ---------------------------------------------------------------------------
# checker 6: metric-in-hot-loop
# ---------------------------------------------------------------------------

def _is_metric_ctor(ctx: ModuleContext, call: ast.Call) -> Optional[str]:
    """The metric type name when `call` constructs a
    ray_tpu.util.metrics Counter/Gauge/Histogram, else None. Bare names
    must have been imported from a metrics module (collections.Counter
    is not a metric); dotted calls qualify when the holder looks like a
    metrics module (`metrics.Counter`, `_metrics.Histogram`)."""
    name = dotted(call.func)
    if not name:
        return None
    parts = name.split(".")
    last = parts[-1]
    if last not in _METRIC_TYPES:
        return None
    if len(parts) == 1:
        return last if name in ctx.metric_ctor_names else None
    return last if "metric" in parts[-2].lower() else None


def _is_setup_scope(func_name: str) -> bool:
    if func_name in ("__init__", "__new__", "__post_init__"):
        return True
    stripped = func_name.lstrip("_")
    return any(stripped.startswith(p.lstrip("_"))
               for p in _METRIC_SETUP_PREFIXES)


def check_metric_in_hot_loop(ctx: ModuleContext) -> List[Finding]:
    """Flag Counter/Gauge/Histogram constructed where the construction
    repeats: inside a loop body, or inside a per-call function (every
    construction registers a fresh metric — the registry leaks an entry
    per call). Module scope, __init__, and one-time setup scopes
    (init*/setup*/create*/build*/register*/start*/make*/main) are the
    sanctioned construction sites."""
    findings: List[Finding] = []

    def visit(node: ast.AST, scope: str, classname: Optional[str],
              in_loop: bool, exempt: bool) -> None:
        for child in ast.iter_child_nodes(node):
            c_scope, c_class = scope, classname
            c_loop, c_exempt = in_loop, exempt
            if isinstance(child, ast.ClassDef):
                c_class = child.name
            elif isinstance(child,
                            (ast.FunctionDef, ast.AsyncFunctionDef)):
                c_scope = (f"{c_class}.{child.name}" if c_class
                           else child.name)
                # entering a per-call function cancels a setup parent's
                # exemption; a def inside a loop stays in_loop (fresh
                # closure per iteration constructs per iteration)
                c_exempt = _is_setup_scope(child.name)
            elif isinstance(child, ast.Lambda):
                # a lambda body runs per call of the lambda
                c_exempt = False
            elif isinstance(child, (ast.For, ast.While, ast.AsyncFor)):
                c_loop = True
            if isinstance(child, ast.Call):
                mtype = _is_metric_ctor(ctx, child)
                if mtype and (c_loop or (
                        c_scope != "<module>" and not c_exempt)):
                    where = "in-loop" if c_loop else "per-call"
                    findings.append(Finding(
                        ctx.relpath, "metric-in-hot-loop", c_scope,
                        f"{where}:{mtype}", child.lineno,
                        f"`{mtype}` constructed "
                        f"{'inside a loop' if c_loop else 'in a per-call function'}"
                        f" registers a new metric per execution — the "
                        f"registry leaks an entry per call; construct "
                        f"it once at module scope / __init__, or expose "
                        f"the values via a scrape-time "
                        f"register_callback"))
            visit(child, c_scope, c_class, c_loop, c_exempt)

    visit(ctx.tree, "<module>", None, False, True)
    return findings


# span-closing method names (span-leak)
_SPAN_CLOSERS = {"__exit__", "end", "close", "finish"}


def _is_span_open_call(call: ast.Call) -> bool:
    """True when `call` manually opens a tracing span: a
    ``start_span(...)`` call (any holder), or a span contextmanager
    entered by hand — ``span(...).__enter__()`` /
    ``submit_span(...).__enter__()``."""
    name = dotted(call.func)
    if name and name.split(".")[-1] == "start_span":
        return True
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr == "__enter__" and \
            isinstance(call.func.value, ast.Call):
        inner = dotted(call.func.value.func)
        return bool(inner) and inner.split(".")[-1] in (
            "span", "submit_span", "execute_span")
    return False


def check_span_leak(ctx: ModuleContext) -> List[Finding]:
    """Flag manually-opened spans not guaranteed to close on exception
    paths. A span bound by ``s = start_span(...)`` (or
    ``span(...).__enter__()``) must reach its ``__exit__``/``end``/
    ``close``/``finish`` through a ``finally:`` block — straight-line
    closes run only on the happy path, so any exception in between
    leaks the span (no end timestamp; a contextvar-parented span also
    mis-parents every later span on the thread). ``with span(...)`` is
    the sanctioned form."""
    findings: List[Finding] = []

    def scan_function(func: ast.AST, scope: str) -> None:
        opens: List[Tuple[str, int]] = []
        closes: Dict[str, List[bool]] = {}

        def visit(node: ast.AST, in_finally: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # nested scopes are scanned as their own funcs
            if isinstance(node, ast.Try):
                for n in node.body + node.orelse:
                    visit(n, in_finally)
                for h in node.handlers:
                    for n in h.body:
                        visit(n, in_finally)
                for n in node.finalbody:
                    visit(n, True)
                return
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _is_span_open_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        opens.append((t.id, node.lineno))
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SPAN_CLOSERS and \
                    isinstance(node.func.value, ast.Name):
                closes.setdefault(node.func.value.id,
                                  []).append(in_finally)
            for child in ast.iter_child_nodes(node):
                visit(child, in_finally)

        for stmt in getattr(func, "body", []):
            visit(stmt, False)
        for var, lineno in opens:
            close_sites = closes.get(var, [])
            if any(close_sites):
                continue
            why = ("its close runs only on the happy path — an "
                   "exception in between leaks the span"
                   if close_sites else "it is never closed")
            findings.append(Finding(
                ctx.relpath, "span-leak", scope, f"span:{var}", lineno,
                f"span `{var}` is opened manually and {why}; close it "
                f"in a `finally:` block or use `with span(...)`"))

    def walk_scopes(node: ast.AST, classname: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk_scopes(child, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                scope = (f"{classname}.{child.name}" if classname
                         else child.name)
                scan_function(child, scope)
                walk_scopes(child, None)
            else:
                walk_scopes(child, classname)

    walk_scopes(ctx.tree, None)
    return findings


# ---------------------------------------------------------------------------
# checker 8: snapshot-read
# ---------------------------------------------------------------------------

# reads that bind a generation-validated copy of the shared table
_SNAPSHOT_READS = {"snapshot", "rr_snapshot"}
# receiver mutators that advance the version/generation the copy was
# validated against
_SNAPSHOT_MUTATORS = {"publish", "mark_dead", "done", "release",
                      "rr_publish", "rr_mark_dead", "rr_done"}


def _walk_no_nested(fn: ast.AST):
    """Every node in `fn`'s body except nested function/lambda scopes
    (their bodies run at another time — often another thread)."""
    stack = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def check_snapshot_read(ctx: ModuleContext) -> List[Finding]:
    """Flag snapshot rows reused after the receiver mutated. The
    dispatch plane's ABA guard is a *read-time* fact: ``snapshot()``
    returns rows consistent with the version/generation words at the
    moment of the seqlock read. A later ``publish``/``mark_dead``/
    ``done`` on the same receiver can retire a row and re-issue its
    slot — decisions made from the stale copy then target a replica
    the generation check would reject. Conservative straight-line
    analysis: bind (or derive) → same-receiver mutate → reuse flags;
    uses that land before the mutate, or a fresh snapshot taken after
    it, stay silent."""
    findings: List[Finding] = []
    for classname, fn in _iter_func_nodes(ctx.tree):
        scope = f"{classname}.{fn.name}" if classname else fn.name
        assigns: List[Tuple[Tuple[int, int], ast.Assign]] = []
        muts: List[Tuple[Tuple[int, int], str, str, int]] = []
        uses: List[Tuple[Tuple[int, int], ast.Name]] = []
        mut_inner: Set[int] = set()   # Name nodes inside a mutator call
        has_snap = False
        for node in _walk_no_nested(fn):
            pos = (getattr(node, "lineno", 0),
                   getattr(node, "col_offset", 0))
            if isinstance(node, ast.Assign):
                assigns.append((pos, node))
                v = node.value
                if isinstance(v, ast.Call) and \
                        isinstance(v.func, ast.Attribute) and \
                        v.func.attr in _SNAPSHOT_READS:
                    has_snap = True
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SNAPSHOT_MUTATORS:
                recv = dotted(node.func.value)
                if recv:
                    muts.append((pos, recv, node.func.attr, node.lineno))
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Name):
                            mut_inner.add(id(sub))
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load):
                uses.append((pos, node))
        if not has_snap or not muts:
            continue

        # merge into one source-ordered event stream; at equal position
        # assigns commit before uses are judged
        events: List[Tuple[Tuple[int, int], int, object]] = []
        events += [(pos, 0, node) for pos, node in assigns]
        events += [(pos, 1, (recv, attr, line))
                   for pos, recv, attr, line in muts]
        events += [(pos, 2, node) for pos, node in uses]
        events.sort(key=lambda e: (e[0], e[1]))

        seq = 0
        taint: Dict[str, Tuple[str, int]] = {}   # var -> (receiver, seq)
        released: Dict[str, Tuple[str, int, int]] = {}
        flagged: Set[str] = set()
        for _pos, kind, payload in events:
            seq += 1
            if kind == 0:
                node = payload
                v = node.value
                recv = None
                if isinstance(v, ast.Call) and \
                        isinstance(v.func, ast.Attribute) and \
                        v.func.attr in _SNAPSHOT_READS:
                    recv = dotted(v.func.value)
                src = {taint[n.id][0] for n in ast.walk(v)
                       if isinstance(n, ast.Name) and n.id in taint}
                names: List[str] = []
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.append(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        names.extend(e.id for e in t.elts
                                     if isinstance(e, ast.Name))
                for nm in names:
                    if recv:
                        taint[nm] = (recv, seq)
                    elif src:
                        taint[nm] = (sorted(src)[0], seq)
                    else:
                        taint.pop(nm, None)   # rebound to unrelated data
            elif kind == 1:
                recv, attr, line = payload
                released[recv] = (attr, seq, line)
            else:
                node = payload
                if id(node) in mut_inner or node.id in flagged:
                    continue
                hit = taint.get(node.id)
                if hit is None:
                    continue
                recv, tseq = hit
                rel = released.get(recv)
                if rel is not None and rel[1] > tseq:
                    flagged.add(node.id)
                    findings.append(Finding(
                        ctx.relpath, "snapshot-read", scope,
                        f"snap:{node.id}", node.lineno,
                        f"`{node.id}` was validated by the "
                        f"`{recv}.snapshot()` generation check, but "
                        f"`{recv}.{rel[0]}()` (line {rel[2]}) advanced "
                        f"the table since — the row may describe a "
                        f"retired replica; finish every use before the "
                        f"mutating call or re-snapshot after it"))
    return findings


# ---------------------------------------------------------------------------
# checker 9: watchdog-probe
# ---------------------------------------------------------------------------

def check_watchdog_probe(ctx: ModuleContext) -> List[Finding]:
    """Flag health-probe ``beat()`` calls taken under a tracked lock.

    The deadman watchdog (`ray_tpu/_private/health.py`) decides a loop
    is stalled when its beat counter freezes while work is pending. The
    whole scheme rests on one invariant: the beat is lock-free — a
    beat taken inside ``with self._lock`` freezes together with the
    lock, so the exact wedge the watchdog exists to catch (a thread
    stuck acquiring the loop's lock) also silences its own liveness
    signal. Any attribute call named ``beat`` inside a lexically held
    lock region is flagged; move the beat before the lock."""
    findings: List[Finding] = []
    for classname, fn in _iter_func_nodes(ctx.tree):
        scope = f"{classname}.{fn.name}" if classname else fn.name
        lock_test = ctx.lock_test_for_class(classname)
        for node, held, _nested in _scan_held(fn.body, (), False,
                                              lock_test):
            if not held or not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            if not name.endswith(".beat"):
                continue
            findings.append(Finding(
                ctx.relpath, "watchdog-probe", scope,
                f"beat:{name}", node.lineno,
                f"`{name}()` beats while holding "
                f"{', '.join(held)} — a probe beaten under the "
                f"watched loop's lock freezes with it and can never "
                f"witness the stall; beat outside the lock"))
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

_CHECKERS = {
    "lock-discipline": check_lock_discipline,
    "blocking-under-lock": check_blocking_under_lock,
    "jit-purity": check_jit_purity,
    "seeded-rng": check_seeded_rng,
    "jit-cache-stability": check_jit_cache_stability,
    "metric-in-hot-loop": check_metric_in_hot_loop,
    "span-leak": check_span_leak,
    "snapshot-read": check_snapshot_read,
    "watchdog-probe": check_watchdog_probe,
}


def analyze_source(source: str, relpath: str = "<string>",
                   path: Optional[str] = None,
                   checks: Sequence[str] = CHECKS,
                   suppression_hits: Optional[Set[Tuple[str, int]]] = None,
                   ) -> List[Finding]:
    ctx = ModuleContext(path or relpath, relpath, source)
    findings: List[Finding] = []
    for check in checks:
        for f in _CHECKERS[check](ctx):
            hit = ctx.suppression_line(f.check, f.line)
            if hit is None:
                findings.append(f)
            elif suppression_hits is not None:
                suppression_hits.add((relpath, hit))
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.detail))
    return findings


def analyze_file(path: str, root: str,
                 checks: Sequence[str] = CHECKS,
                 suppression_hits: Optional[Set[Tuple[str, int]]] = None,
                 ) -> List[Finding]:
    relpath = os.path.relpath(path, root).replace(os.sep, "/")
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    try:
        return analyze_source(source, relpath, path, checks,
                              suppression_hits=suppression_hits)
    except SyntaxError as e:
        return [Finding(relpath, "parse-error", "<module>", "syntax",
                        e.lineno or 0, f"syntax error: {e.msg}")]


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "build", ".git")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def analyze_paths(paths: Sequence[str], root: Optional[str] = None,
                  checks: Sequence[str] = CHECKS,
                  suppression_hits: Optional[Set[Tuple[str, int]]] = None,
                  ) -> List[Finding]:
    root = root or os.getcwd()
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(analyze_file(path, root, checks,
                                     suppression_hits=suppression_hits))
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.detail))
    return findings


def collect_suppressions(paths: Sequence[str], root: Optional[str] = None
                         ) -> List[Tuple[str, int, str]]:
    """Every `# raylint: disable=` comment in `paths`:
    [(relpath, line, raw check list)] — input to the unused-suppression
    audit in the CLI."""
    root = root or os.getcwd()
    out: List[Tuple[str, int, str]] = []
    for path in iter_python_files(paths):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as fh:
                for i, line in enumerate(fh, start=1):
                    m = _SUPPRESS_RE.search(line)
                    if m:
                        out.append((relpath, i, m.group(1)))
        except OSError:
            continue
    return out
