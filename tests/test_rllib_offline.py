"""Offline RL tests: episode IO, BC/MARWIL training, OPE estimators.

Models the reference's offline tests (`rllib/offline/tests/`,
`rllib/algorithms/bc/tests/test_bc.py` — BC on recorded CartPole data
to a reward threshold) scaled to CI budgets.
"""

import numpy as np
import pytest

from ray_tpu.rllib import BC, BCConfig, MARWIL, MARWILConfig, RLModuleSpec
from ray_tpu.rllib.env.env_runner import Episode, SingleAgentEnvRunner
from ray_tpu.rllib.offline import (
    ImportanceSampling,
    JsonReader,
    JsonWriter,
    WeightedImportanceSampling,
)
from ray_tpu.rllib.offline.io import episode_from_json, episode_to_json


def _heuristic_cartpole_episodes(n_episodes: int, seed: int = 0):
    """Expert-ish demonstrations from the classic CartPole balancing
    heuristic (push toward the falling direction) — scores ~200+ where
    a random policy scores ~20."""
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    episodes = []
    for i in range(n_episodes):
        obs, _ = env.reset(seed=seed + i)
        ep = Episode()
        for _ in range(300):
            action = 1 if (obs[2] + 0.5 * obs[3]) > 0 else 0
            ep.obs.append(np.asarray(obs, np.float32))
            ep.actions.append(action)
            ep.logps.append(0.0)
            ep.vf_preds.append(0.0)
            obs, reward, term, trunc, _ = env.step(action)
            ep.rewards.append(float(reward))
            if term or trunc:
                ep.terminated = bool(term)
                ep.truncated = bool(trunc)
                break
        ep.last_obs = np.asarray(obs, np.float32)
        episodes.append(ep)
    env.close()
    return episodes


def test_episode_json_roundtrip():
    eps = _heuristic_cartpole_episodes(2)
    ep2 = episode_from_json(episode_to_json(eps[0]))
    assert ep2.length == eps[0].length
    assert ep2.actions == eps[0].actions
    assert ep2.terminated == eps[0].terminated
    np.testing.assert_allclose(np.stack(ep2.obs), np.stack(eps[0].obs))
    np.testing.assert_allclose(ep2.last_obs, eps[0].last_obs)


def test_json_writer_reader_shards(tmp_path):
    eps = _heuristic_cartpole_episodes(6)
    path = str(tmp_path / "data")
    # small shard cap -> multiple files
    w = JsonWriter(path, max_rows_per_shard=150)
    w.write(eps[:3])
    w.write(eps[3:])
    reader = JsonReader(path)
    assert len(reader.files) >= 2
    assert reader.num_episodes == 6
    assert reader.num_steps == sum(ep.length for ep in eps)
    sampled = reader.sample_episodes(100)
    assert sum(ep.length for ep in sampled) >= 100


def test_bc_learns_from_expert_data(tmp_path):
    """BC clones the heuristic from recorded episodes: greedy eval
    return far above random (~20) within bounded iterations."""
    path = str(tmp_path / "expert")
    JsonWriter(path).write(_heuristic_cartpole_episodes(30))

    cfg = (
        BCConfig()
        .environment("CartPole-v1")
        .offline_data(input_=path)
        .training(lr=1e-3, train_batch_size=2000, minibatch_size=256,
                  num_epochs=2)
        .evaluation(evaluation_duration=600)
        .debugging(seed=0)
    )
    algo = BC(config=cfg)
    try:
        best = 0.0
        for _ in range(15):
            result = algo.train()
            assert np.isfinite(result["policy_loss"])
            ev = algo.evaluate()
            if np.isfinite(ev["episode_return_mean"]):
                best = max(best, ev["episode_return_mean"])
            if best >= 100.0:
                break
        assert best >= 100.0, f"BC failed to clone expert: best={best}"
    finally:
        algo.stop()


def test_marwil_advantage_weighting_runs(tmp_path):
    """MARWIL (beta>0) trains on mixed-quality data with finite losses
    and a live value head, and evaluation_interval wires eval into
    step()."""
    path = str(tmp_path / "mixed")
    # mixed quality: expert + short random episodes
    eps = _heuristic_cartpole_episodes(10)
    rng = np.random.default_rng(0)
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    for i in range(10):
        obs, _ = env.reset(seed=100 + i)
        ep = Episode()
        for _ in range(50):
            action = int(rng.integers(2))
            ep.obs.append(np.asarray(obs, np.float32))
            ep.actions.append(action)
            ep.logps.append(float(np.log(0.5)))
            ep.vf_preds.append(0.0)
            obs, reward, term, trunc, _ = env.step(action)
            ep.rewards.append(float(reward))
            if term or trunc:
                ep.terminated = bool(term)
                break
        ep.last_obs = np.asarray(obs, np.float32)
        eps.append(ep)
    env.close()
    JsonWriter(path).write(eps)

    cfg = (
        MARWILConfig()
        .environment("CartPole-v1")
        .offline_data(input_=path)
        .training(lr=1e-3, beta=1.0, train_batch_size=1000,
                  minibatch_size=256)
        .evaluation(evaluation_interval=2, evaluation_duration=200)
        .debugging(seed=0)
    )
    algo = MARWIL(config=cfg)
    try:
        r1 = algo.train()
        assert np.isfinite(r1["policy_loss"])
        assert r1["vf_loss"] > 0.0  # value head actually trained
        assert "evaluation" not in r1  # interval=2
        r2 = algo.train()
        assert "evaluation" in r2
        assert "episode_return_mean" in r2["evaluation"]
    finally:
        algo.stop()


def test_estimators_identity_policy():
    """Target policy == behavior policy -> all importance ratios are 1,
    so IS and WIS both reproduce the behavior value exactly."""
    spec = RLModuleSpec(observation_dim=4, action_dim=2, hidden=(16,))
    import gymnasium as gym

    import jax

    runner = SingleAgentEnvRunner(
        lambda: gym.make("CartPole-v1"), spec, num_envs=2, seed=0)
    module = runner.module
    params = module.init_params(jax.random.PRNGKey(0))
    runner.set_weights(params)
    episodes = [ep for ep in runner.sample(400)
                if ep.terminated or ep.truncated]
    assert episodes, "need completed episodes"

    gamma = 0.99
    is_est = ImportanceSampling(module, params, gamma)
    wis_est = WeightedImportanceSampling(module, params, gamma)
    r_is = is_est.estimate(episodes)
    r_wis = wis_est.estimate(episodes)
    np.testing.assert_allclose(r_is["v_target"], r_is["v_behavior"],
                               rtol=1e-4)
    np.testing.assert_allclose(r_wis["v_target"], r_wis["v_behavior"],
                               rtol=1e-4)
    assert r_is["v_behavior"] > 0


def test_estimators_prefer_better_target():
    """A target policy matching the (good) heuristic on data from a
    uniform-random behavior policy should get v_gain > 1 under WIS —
    the estimator detects the better policy from off-policy data."""
    import gymnasium as gym

    # behavior: uniform random, logged logp = log(0.5)
    env = gym.make("CartPole-v1")
    rng = np.random.default_rng(1)
    episodes = []
    for i in range(40):
        obs, _ = env.reset(seed=200 + i)
        ep = Episode()
        for _ in range(200):
            action = int(rng.integers(2))
            ep.obs.append(np.asarray(obs, np.float32))
            ep.actions.append(action)
            ep.logps.append(float(np.log(0.5)))
            ep.vf_preds.append(0.0)
            obs, reward, term, trunc, _ = env.step(action)
            ep.rewards.append(float(reward))
            if term or trunc:
                ep.terminated = bool(term)
                break
        ep.last_obs = np.asarray(obs, np.float32)
        episodes.append(ep)
    env.close()

    class HeuristicModule:
        """Deterministic-ish target: big logit margin toward the
        heuristic action."""

        def forward_train(self, params, batch):
            obs = np.asarray(batch["obs"])
            pref = (obs[:, 2] + 0.5 * obs[:, 3]) > 0
            logits = np.zeros((obs.shape[0], 2), np.float32)
            logits[np.arange(len(pref)), pref.astype(int)] = 3.0
            return {"action_dist_inputs": logits}

    est = WeightedImportanceSampling(HeuristicModule(), {}, gamma=1.0)
    r = est.estimate(episodes)
    assert r["v_target"] > r["v_behavior"], r
