import numpy as np

from ray_tpu._private import serialization


def test_roundtrip_basic():
    for value in [1, "x", [1, 2, {"a": (3, 4)}], None, b"bytes", {1: 2}]:
        assert serialization.loads(serialization.dumps(value)) == value


def test_numpy_out_of_band():
    arr = np.random.rand(1000, 10)
    pickled, buffers = serialization.serialize(arr)
    assert len(buffers) == 1  # array payload captured out-of-band
    out = serialization.loads(serialization.pack(pickled, buffers))
    np.testing.assert_array_equal(out, arr)


def test_nested_arrays():
    value = {"a": np.arange(10), "b": [np.ones(5), "text"]}
    out = serialization.loads(serialization.dumps(value))
    np.testing.assert_array_equal(out["a"], value["a"])
    np.testing.assert_array_equal(out["b"][0], value["b"][0])
    assert out["b"][1] == "text"


def test_custom_serializer():
    class Opaque:
        def __init__(self, v):
            self.v = v

    serialization.register_serializer(
        Opaque,
        serializer=lambda o: o.v * 2,
        deserializer=lambda payload: Opaque(payload),
    )
    try:
        out = serialization.loads(serialization.dumps(Opaque(21)))
        assert out.v == 42
    finally:
        serialization.deregister_serializer(Opaque)


def test_closures_cloudpickled():
    x = 10
    fn = lambda y: x + y  # noqa: E731
    out = serialization.loads(serialization.dumps(fn))
    assert out(5) == 15
