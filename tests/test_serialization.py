import numpy as np
import pytest

from ray_tpu._private import serialization


def test_roundtrip_basic():
    for value in [1, "x", [1, 2, {"a": (3, 4)}], None, b"bytes", {1: 2}]:
        assert serialization.loads(serialization.dumps(value)) == value


def test_numpy_out_of_band():
    arr = np.random.rand(1000, 10)
    pickled, buffers = serialization.serialize(arr)
    assert len(buffers) == 1  # array payload captured out-of-band
    out = serialization.loads(serialization.pack(pickled, buffers))
    np.testing.assert_array_equal(out, arr)


def test_nested_arrays():
    value = {"a": np.arange(10), "b": [np.ones(5), "text"]}
    out = serialization.loads(serialization.dumps(value))
    np.testing.assert_array_equal(out["a"], value["a"])
    np.testing.assert_array_equal(out["b"][0], value["b"][0])
    assert out["b"][1] == "text"


def test_custom_serializer():
    class Opaque:
        def __init__(self, v):
            self.v = v

    serialization.register_serializer(
        Opaque,
        serializer=lambda o: o.v * 2,
        deserializer=lambda payload: Opaque(payload),
    )
    try:
        out = serialization.loads(serialization.dumps(Opaque(21)))
        assert out.v == 42
    finally:
        serialization.deregister_serializer(Opaque)


def test_serialize_value_one_copy_roundtrip():
    value = {"a": np.arange(10000, dtype=np.float64), "b": "text", "c": 7}
    sv = serialization.serialize_value(value)
    # nothing large copied yet: the pickle stream is a view over the
    # pickler's buffer, oob buffers are views over the original arrays
    assert isinstance(sv.pickled, memoryview)
    assert len(sv.buffers) == 1
    dst = bytearray(sv.size)
    written = sv.write_into(memoryview(dst))
    assert written == sv.size
    out = serialization.loads(dst)
    np.testing.assert_array_equal(out["a"], value["a"])
    assert out["b"] == "text" and out["c"] == 7


def test_serialize_value_frame_matches_pack():
    value = [np.ones(777, np.uint8), {"k": b"v" * 1000}]
    sv = serialization.serialize_value(value)
    pickled, buffers = serialization.serialize(value)
    assert sv.size == serialization.serialized_size(pickled, buffers)
    assert sv.to_bytes() == serialization.pack(pickled, buffers)


def test_serialize_into():
    arr = np.arange(4096, dtype=np.int32)
    sv = serialization.serialize_value(arr)
    dst = bytearray(sv.size)
    n = serialization.serialize_into(memoryview(dst), arr)
    assert n == sv.size
    np.testing.assert_array_equal(serialization.loads(dst), arr)
    with pytest.raises(ValueError):
        serialization.serialize_into(memoryview(bytearray(8)), arr)


def test_serialize_value_noncontiguous_buffer():
    # a transposed (non-C-contiguous parent) array still frames and
    # round-trips — write_into must normalize oob views to flat bytes
    arr = np.arange(64, dtype=np.float32).reshape(8, 8)[:, :4].copy()
    sv = serialization.serialize_value(arr)
    dst = bytearray(sv.size)
    sv.write_into(memoryview(dst))
    np.testing.assert_array_equal(serialization.loads(dst), arr)


def test_closures_cloudpickled():
    x = 10
    fn = lambda y: x + y  # noqa: E731
    out = serialization.loads(serialization.dumps(fn))
    assert out(5) == 15
