"""Job submission + workflow + DAG tests.

Reference ground: `python/ray/dashboard/modules/job/tests/test_sdk.py`,
`python/ray/workflow/tests/`, `python/ray/dag/tests/` — compressed.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu import dag as dag_api
from ray_tpu import workflow


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


# -- job submission ---------------------------------------------------------

def test_job_submission_lifecycle(tmp_path):
    from ray_tpu.job_submission import (
        SUCCEEDED,
        JobSubmissionClient,
    )

    script = tmp_path / "driver.py"
    script.write_text(
        "import ray_tpu\n"
        "ray_tpu.init()\n"
        "@ray_tpu.remote\n"
        "def f(x):\n"
        "    return x + 1\n"
        "print('RESULT', ray_tpu.get(f.remote(41)))\n"
        "ray_tpu.shutdown()\n")

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"python {script}",
        env_vars={"JAX_PLATFORMS": "cpu"})
    status = client.wait_until_finished(job_id, timeout=180)
    assert status == SUCCEEDED
    logs = client.get_job_logs(job_id)
    assert "RESULT 42" in logs
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id and j["status"] == SUCCEEDED
               for j in jobs)


def test_job_stop(tmp_path):
    from ray_tpu.job_submission import STOPPED, JobSubmissionClient

    script = tmp_path / "sleeper.py"
    script.write_text("import time\ntime.sleep(300)\n")
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=f"python {script}")
    time.sleep(1.0)
    assert client.stop_job(job_id)
    assert client.wait_until_finished(job_id, timeout=60) == STOPPED


# -- DAG --------------------------------------------------------------------

def test_dag_bind_execute():
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    x = dag_api.InputNode(0)
    y = dag_api.InputNode(1)
    graph = dag_api.bind(add, dag_api.bind(mul, x, y), 10)
    ref = graph.execute(3, 4)
    assert ray_tpu.get(ref) == 22  # 3*4 + 10


def test_dag_diamond_executes_shared_node_once():
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1

        def get(self):
            return self.n

    c = Counter.options(name="dag_counter").remote()
    ray_tpu.get(c.get.remote())

    @ray_tpu.remote
    def source(x):
        h = ray_tpu.get_actor("dag_counter")
        ray_tpu.get(h.bump.remote())
        return x

    @ray_tpu.remote
    def combine(a, b):
        return a + b

    shared = dag_api.bind(source, dag_api.InputNode())
    graph = dag_api.bind(combine, shared, shared)
    assert ray_tpu.get(graph.execute(5)) == 10
    assert ray_tpu.get(c.get.remote()) == 1  # shared node ran ONCE
    ray_tpu.kill(c)


def test_compiled_jax_chain_fuses():
    import jax.numpy as jnp
    import numpy as np

    def scale(x):
        return x * 2.0

    def shift(x):
        return x + 1.0

    s1 = dag_api.jax_stage(scale)
    s2 = dag_api.jax_stage(shift)
    graph = dag_api.bind(s2, dag_api.bind(s1, dag_api.InputNode()))
    compiled = graph.experimental_compile()
    assert compiled._jitted is not None  # fused into one jit
    out = compiled.execute(jnp.ones(8))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))
    # the uncompiled path still runs through the cluster
    assert float(ray_tpu.get(graph.execute(1.0))) == 3.0


# -- workflow ---------------------------------------------------------------

def test_workflow_checkpointed_resume(tmp_path):
    workflow.init(storage=str(tmp_path / "wf"))

    marker = tmp_path / "mode"
    marker.write_text("fail")

    @ray_tpu.remote
    def step_a(x):
        return x + 1

    @ray_tpu.remote
    def flaky(x):
        with open(marker) as f:
            if f.read() == "fail":
                raise RuntimeError("injected failure")
        return x * 10

    graph = dag_api.bind(flaky, dag_api.bind(step_a, dag_api.InputNode()))

    with pytest.raises(ray_tpu.RayTaskError):
        workflow.run(graph, 4, workflow_id="wf1")
    assert workflow.status("wf1") == "FAILED"

    # fix the environment, resume: step_a's checkpoint is reused and
    # only the failed step re-executes
    marker.write_text("ok")
    out = workflow.resume("wf1")
    assert out == 50
    assert workflow.status("wf1") == "SUCCEEDED"
    from ray_tpu.workflow.execution import get_output

    assert get_output("wf1") == 50
    assert {"workflow_id": "wf1", "status": "SUCCEEDED"} in \
        workflow.list_all()


def test_serve_multiplex_lru():
    from ray_tpu.serve import multiplex as mp

    loads = []

    class Host:
        @mp.multiplexed(max_num_models_per_replica=2)
        async def load(self, model_id):
            loads.append(model_id)
            return f"model-{model_id}"

    import asyncio

    host = Host()

    async def drive():
        assert await host.load("a") == "model-a"
        assert await host.load("b") == "model-b"
        assert await host.load("a") == "model-a"  # cache hit
        assert await host.load("c") == "model-c"  # evicts b
        assert await host.load("b") == "model-b"  # reloads
        assert mp.get_multiplexed_model_id() == "b"

    asyncio.run(drive())
    assert loads == ["a", "b", "c", "b"]
