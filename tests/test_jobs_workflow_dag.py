"""Job submission + workflow + DAG tests.

Reference ground: `python/ray/dashboard/modules/job/tests/test_sdk.py`,
`python/ray/workflow/tests/`, `python/ray/dag/tests/` — compressed.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu import dag as dag_api
from ray_tpu import workflow


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


# -- job submission ---------------------------------------------------------

def test_job_submission_lifecycle(tmp_path):
    from ray_tpu.job_submission import (
        SUCCEEDED,
        JobSubmissionClient,
    )

    script = tmp_path / "driver.py"
    script.write_text(
        "import ray_tpu\n"
        "ray_tpu.init()\n"
        "@ray_tpu.remote\n"
        "def f(x):\n"
        "    return x + 1\n"
        "print('RESULT', ray_tpu.get(f.remote(41)))\n"
        "ray_tpu.shutdown()\n")

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"python {script}",
        env_vars={"JAX_PLATFORMS": "cpu"})
    status = client.wait_until_finished(job_id, timeout=180)
    assert status == SUCCEEDED
    logs = client.get_job_logs(job_id)
    assert "RESULT 42" in logs
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id and j["status"] == SUCCEEDED
               for j in jobs)


def test_job_stop(tmp_path):
    from ray_tpu.job_submission import STOPPED, JobSubmissionClient

    script = tmp_path / "sleeper.py"
    script.write_text("import time\ntime.sleep(300)\n")
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=f"python {script}")
    time.sleep(1.0)
    assert client.stop_job(job_id)
    assert client.wait_until_finished(job_id, timeout=60) == STOPPED


# -- DAG --------------------------------------------------------------------

def test_dag_bind_execute():
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    x = dag_api.InputNode(0)
    y = dag_api.InputNode(1)
    graph = dag_api.bind(add, dag_api.bind(mul, x, y), 10)
    ref = graph.execute(3, 4)
    assert ray_tpu.get(ref) == 22  # 3*4 + 10


def test_dag_diamond_executes_shared_node_once():
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1

        def get(self):
            return self.n

    c = Counter.options(name="dag_counter").remote()
    ray_tpu.get(c.get.remote())

    @ray_tpu.remote
    def source(x):
        h = ray_tpu.get_actor("dag_counter")
        ray_tpu.get(h.bump.remote())
        return x

    @ray_tpu.remote
    def combine(a, b):
        return a + b

    shared = dag_api.bind(source, dag_api.InputNode())
    graph = dag_api.bind(combine, shared, shared)
    assert ray_tpu.get(graph.execute(5)) == 10
    assert ray_tpu.get(c.get.remote()) == 1  # shared node ran ONCE
    ray_tpu.kill(c)


def test_compiled_jax_chain_fuses():
    import jax.numpy as jnp
    import numpy as np

    def scale(x):
        return x * 2.0

    def shift(x):
        return x + 1.0

    s1 = dag_api.jax_stage(scale)
    s2 = dag_api.jax_stage(shift)
    graph = dag_api.bind(s2, dag_api.bind(s1, dag_api.InputNode()))
    compiled = graph.experimental_compile()
    assert compiled._jitted is not None  # fused into one jit
    out = compiled.execute(jnp.ones(8))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))
    # the uncompiled path still runs through the cluster
    assert float(ray_tpu.get(graph.execute(1.0))) == 3.0


# -- workflow ---------------------------------------------------------------

def test_workflow_checkpointed_resume(tmp_path):
    workflow.init(storage=str(tmp_path / "wf"))

    marker = tmp_path / "mode"
    marker.write_text("fail")

    @ray_tpu.remote
    def step_a(x):
        return x + 1

    @ray_tpu.remote
    def flaky(x):
        with open(marker) as f:
            if f.read() == "fail":
                raise RuntimeError("injected failure")
        return x * 10

    graph = dag_api.bind(flaky, dag_api.bind(step_a, dag_api.InputNode()))

    with pytest.raises(ray_tpu.RayTaskError):
        workflow.run(graph, 4, workflow_id="wf1")
    assert workflow.status("wf1") == "FAILED"

    # fix the environment, resume: step_a's checkpoint is reused and
    # only the failed step re-executes
    marker.write_text("ok")
    out = workflow.resume("wf1")
    assert out == 50
    assert workflow.status("wf1") == "SUCCEEDED"
    from ray_tpu.workflow.execution import get_output

    assert get_output("wf1") == 50
    assert {"workflow_id": "wf1", "status": "SUCCEEDED"} in \
        workflow.list_all()


def test_workflow_continuation_dynamic(tmp_path):
    """VERDICT r4 item 10: a step returning workflow.continuation grows
    the DAG at runtime — recursive factorial through continuations."""
    workflow.init(storage=str(tmp_path / "wfc"))

    @ray_tpu.remote
    def fact(n, acc):
        if n <= 1:
            return acc
        return workflow.continuation(dag_api.bind(fact, n - 1, acc * n))

    out = workflow.run(dag_api.bind(fact, 5, 1), workflow_id="wfc1")
    assert out == 120
    assert workflow.status("wfc1") == "SUCCEEDED"
    # chained continuations each checkpointed their hop
    meta = workflow.get_metadata("wfc1")
    assert meta["steps_checkpointed"] >= 5
    assert meta["status"] == "SUCCEEDED"


def test_workflow_recovery_across_continuation(tmp_path):
    """A crash INSIDE a continuation resumes into the continuation: the
    parent step must not re-execute (its side-effect counter stays at
    1), completed continuation hops skip, and only the failed hop
    re-runs."""
    workflow.init(storage=str(tmp_path / "wfr"))
    parent_runs = tmp_path / "parent_runs"
    parent_runs.write_text("0")
    marker = tmp_path / "mode"
    marker.write_text("fail")

    @ray_tpu.remote
    def parent(x):
        with open(parent_runs) as f:
            n = int(f.read())
        with open(parent_runs, "w") as f:
            f.write(str(n + 1))
        return workflow.continuation(
            dag_api.bind(child, x + 100))

    @ray_tpu.remote
    def child(x):
        with open(marker) as f:
            if f.read() == "fail":
                raise RuntimeError("child crashed")
        return x * 2

    with pytest.raises(ray_tpu.RayTaskError):
        workflow.run(dag_api.bind(parent, 1), workflow_id="wfr1")
    assert workflow.status("wfr1") == "FAILED"

    marker.write_text("ok")
    assert workflow.resume("wfr1") == 202
    # the parent ran exactly once across run + resume
    assert parent_runs.read_text() == "1"


def test_workflow_events_and_metadata(tmp_path):
    """wait_for_event blocks the workflow until send_event; payload is
    durable; user metadata round-trips."""
    import time as time_mod

    workflow.init(storage=str(tmp_path / "wfe"))

    @ray_tpu.remote
    def combine(payload, x):
        return f"{payload}:{x}"

    graph = dag_api.bind(
        combine, workflow.wait_for_event("go"), dag_api.InputNode())
    wid = workflow.run_async(graph, 7, workflow_id="wfe1",
                             metadata={"owner": "tests"})
    time_mod.sleep(0.5)
    assert workflow.status("wfe1") == "RUNNING"  # blocked on the event
    workflow.send_event("wfe1", "go", "launch")
    deadline = time_mod.monotonic() + 60
    while workflow.status("wfe1") == "RUNNING" \
            and time_mod.monotonic() < deadline:
        time_mod.sleep(0.1)
    assert workflow.status("wfe1") == "SUCCEEDED"
    assert workflow.get_output("wfe1") == "launch:7"
    meta = workflow.get_metadata("wfe1")
    assert meta["user_metadata"] == {"owner": "tests"}
    assert meta["end_time"] >= meta["start_time"]


def test_serve_multiplex_lru():
    from ray_tpu.serve import multiplex as mp

    loads = []

    class Host:
        @mp.multiplexed(max_num_models_per_replica=2)
        async def load(self, model_id):
            loads.append(model_id)
            return f"model-{model_id}"

    import asyncio

    host = Host()

    async def drive():
        assert await host.load("a") == "model-a"
        assert await host.load("b") == "model-b"
        assert await host.load("a") == "model-a"  # cache hit
        assert await host.load("c") == "model-c"  # evicts b
        assert await host.load("b") == "model-b"  # reloads
        assert mp.get_multiplexed_model_id() == "b"

    asyncio.run(drive())
    assert loads == ["a", "b", "c", "b"]


def test_compiled_actor_chain_channels():
    """VERDICT r4 item 7: a compiled linear actor chain executes over
    pre-allocated shm channels — no per-call task submission — and must
    beat the .remote() loop by a wide margin. Errors propagate; teardown
    unlinks the channels and the actors stay usable."""
    import time as time_mod

    from ray_tpu import dag as dag_mod

    @ray_tpu.remote
    class Stage:
        def __init__(self, add):
            self.add = add

        def f(self, x):
            if x == "boom":
                raise ValueError("stage exploded")
            return x + self.add

    a, b, c = Stage.remote(1), Stage.remote(10), Stage.remote(100)
    ray_tpu.get([a.f.remote(0), b.f.remote(0), c.f.remote(0)],
                timeout=60)

    node = dag_mod.bind(
        c.f, dag_mod.bind(b.f, dag_mod.bind(a.f, dag_mod.InputNode())))
    compiled = node.experimental_compile()
    assert compiled._channels is not None, "actor chain not lowered"
    assert compiled.execute(5) == 116
    assert compiled.execute(0) == 111

    # latency: compiled channel path >> submit-per-call loop
    n, start = 0, time_mod.perf_counter()
    while time_mod.perf_counter() - start < 2.0:
        compiled.execute(n)
        n += 1
    compiled_rate = n / (time_mod.perf_counter() - start)
    n, start = 0, time_mod.perf_counter()
    while time_mod.perf_counter() - start < 2.0:
        ray_tpu.get(c.f.remote(ray_tpu.get(
            b.f.remote(ray_tpu.get(a.f.remote(n))))), timeout=60)
        n += 1
    remote_rate = n / (time_mod.perf_counter() - start)
    assert compiled_rate > 3 * remote_rate, (compiled_rate, remote_rate)

    # stage errors surface at execute() with the original cause
    with pytest.raises(ray_tpu.RayTaskError, match="stage exploded"):
        compiled.execute("boom")
    # the pipeline survives an error
    assert compiled.execute(7) == 118

    compiled.teardown()
    # actors remain plain callable actors after teardown
    assert ray_tpu.get(a.f.remote(1), timeout=60) == 2


def test_workflow_deep_continuation_chain(tmp_path):
    """Continuation unwinding is iterative: a chain far deeper than any
    comfortable recursion budget completes (one checkpoint per hop, no
    stack growth per hop)."""
    import sys

    workflow.init(storage=str(tmp_path / "wfd"))

    @ray_tpu.remote
    def countdown(n):
        if n == 0:
            return "done"
        return workflow.continuation(dag_api.bind(countdown, n - 1))

    depth = 300
    limit = sys.getrecursionlimit()
    try:
        sys.setrecursionlimit(150)  # make frame-per-hop designs fail
        out = workflow.run(dag_api.bind(countdown, depth),
                           workflow_id="wfd1")
    finally:
        sys.setrecursionlimit(limit)
    assert out == "done"


def test_compiled_diamond_graph():
    """VERDICT r5 item 3: a diamond A->(B,C)->D actor graph compiles
    onto channels — fan-out writes a channel per consumer, the fan-in
    combine reads one channel per argument — and beats the .remote()
    equivalent. Constants pass through descriptors, and the shared
    source executes once per call."""
    import time as time_mod

    from ray_tpu import dag as dag_mod

    @ray_tpu.remote
    class Node:
        def __init__(self):
            self.calls = 0

        def double(self, x):
            self.calls += 1
            return x * 2

        def inc(self, x):
            return x + 1

        def combine(self, a, b, c):
            return (a, b, c)

        def n_calls(self):
            return self.calls

    a, b, c, d = [Node.remote() for _ in range(4)]
    ray_tpu.get([w.inc.remote(0) for w in (a, b, c, d)], timeout=60)

    src = dag_mod.bind(a.double, dag_mod.InputNode())
    left = dag_mod.bind(b.inc, src)
    right = dag_mod.bind(c.double, src)
    out = dag_mod.bind(d.combine, left, right, 99)
    compiled = out.experimental_compile()
    assert compiled._channels is not None, "diamond not lowered"
    assert compiled.execute(3) == (7, 12, 99)
    assert compiled.execute(0) == (1, 0, 99)
    # the shared source ran once per execute, not once per consumer
    assert ray_tpu.get(a.n_calls.remote(), timeout=60) == 2

    n, start = 0, time_mod.perf_counter()
    while time_mod.perf_counter() - start < 2.0:
        compiled.execute(n)
        n += 1
    compiled_rate = n / (time_mod.perf_counter() - start)
    n, start = 0, time_mod.perf_counter()
    while time_mod.perf_counter() - start < 2.0:
        s = a.double.remote(n)
        ray_tpu.get(d.combine.remote(
            b.inc.remote(s), c.double.remote(s), 99), timeout=60)
        n += 1
    remote_rate = n / (time_mod.perf_counter() - start)
    assert compiled_rate > 3 * remote_rate, (compiled_rate, remote_rate)
    compiled.teardown()


def test_compiled_multi_output_and_multi_input():
    """MultiOutputNode returns every leaf; InputNode(i) binds distinct
    execute() arguments to different stages (fan-in from the driver)."""
    from ray_tpu import dag as dag_mod

    @ray_tpu.remote
    class Calc:
        def add(self, a, b):
            return a + b

        def mul(self, a, b):
            return a * b

    x, y = Calc.remote(), Calc.remote()
    ray_tpu.get([x.add.remote(0, 0), y.add.remote(0, 0)], timeout=60)

    added = dag_mod.bind(x.add, dag_mod.InputNode(0), dag_mod.InputNode(1))
    scaled = dag_mod.bind(y.mul, added, 10)
    both = dag_mod.MultiOutputNode([added, scaled])
    compiled = both.experimental_compile()
    assert compiled._channels is not None
    assert compiled.execute(3, 4) == [7, 70]
    assert compiled.execute(1, 1) == [2, 20]
    compiled.teardown()


def test_compiled_pipeline_parallel_actors():
    """A 2-stage pipeline-parallel actor graph on channels (the aDAG
    flagship use): each stage actor owns a layer's weights; the chain
    computes tanh(tanh(x @ W1) @ W2) and matches the local reference."""
    import numpy as np

    from ray_tpu import dag as dag_mod

    @ray_tpu.remote
    class Layer:
        def __init__(self, seed):
            rng = np.random.RandomState(seed)
            self.w = rng.randn(8, 8).astype(np.float32) * 0.3

        def forward(self, x):
            return np.tanh(x @ self.w)

        def weights(self):
            return self.w

    s1, s2 = Layer.remote(0), Layer.remote(1)
    w1, w2 = ray_tpu.get([s1.weights.remote(), s2.weights.remote()],
                         timeout=60)

    graph = dag_mod.bind(
        s2.forward, dag_mod.bind(s1.forward, dag_mod.InputNode()))
    compiled = graph.experimental_compile()
    assert compiled._channels is not None
    rng = np.random.RandomState(2)
    for _ in range(3):
        x = rng.randn(4, 8).astype(np.float32)
        np.testing.assert_allclose(
            compiled.execute(x), np.tanh(np.tanh(x @ w1) @ w2),
            rtol=1e-6)
    compiled.teardown()


def test_compiled_timeout_does_not_desync():
    """ADVICE r4: a timed-out execute() must not leave the ring
    desynchronized — the seq tag makes the next call discard the stale
    frame instead of returning the previous result."""
    import time as time_mod

    from ray_tpu import dag as dag_mod

    @ray_tpu.remote
    class Slow:
        def f(self, x):
            delay, v = x
            if delay:
                time_mod.sleep(delay)
            return ("out", v)

    s = Slow.remote()
    ray_tpu.get(s.f.remote((0, 0)), timeout=60)
    compiled = dag_mod.bind(
        s.f, dag_mod.InputNode()).experimental_compile()
    assert compiled.execute((0, "A")) == ("out", "A")
    with pytest.raises(TimeoutError):
        compiled.execute((2.0, "SLOW"), timeout=0.3)
    # the stale ("out", "SLOW") frame must be discarded, not returned
    assert compiled.execute((0, "B"), timeout=30) == ("out", "B")
    compiled.teardown()
