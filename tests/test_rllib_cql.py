"""CQL: conservative offline Q-learning from logged episodes.

Covers: dataset loading through JsonReader into the transition buffer,
the CQL(H) regularizer inside the jitted SAC update (finite, positive on
random data — Q must be pushed below the logsumexp of sampled actions),
and that the conservative penalty actually suppresses Q on
out-of-distribution actions relative to plain SAC updates.
"""

import numpy as np

from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.env.env_runner import Episode
from ray_tpu.rllib.offline.io import JsonWriter


def _write_pendulum_dataset(path, n_episodes=30, ep_len=50, seed=0):
    """Mediocre behavior policy on Pendulum: random torques."""
    import gymnasium as gym

    env = gym.make("Pendulum-v1")
    writer = JsonWriter(str(path))
    rng = np.random.default_rng(seed)
    episodes = []
    for i in range(n_episodes):
        obs, _ = env.reset(seed=seed + i)
        ep = Episode()
        for _ in range(ep_len):
            a = rng.uniform(-2.0, 2.0, size=(1,)).astype(np.float32)
            nxt, r, term, trunc, _ = env.step(a)
            ep.obs.append(np.asarray(obs, np.float32))
            ep.actions.append(a)
            ep.rewards.append(float(r))
            ep.logps.append(0.0)
            ep.vf_preds.append(0.0)
            obs = nxt
            if term or trunc:
                break
        ep.truncated = True
        ep.last_obs = np.asarray(obs, np.float32)
        episodes.append(ep)
    writer.write(episodes)
    env.close()


def test_cql_trains_offline(tmp_path):
    data = tmp_path / "pendulum"
    _write_pendulum_dataset(data)
    cfg = (
        CQLConfig()
        .environment("Pendulum-v1")
        .offline_data(input_=str(data))
        .training(lr=3e-4, train_batch_size=64,
                  num_updates_per_iteration=6, cql_alpha=5.0,
                  num_sampled_actions=4)
        .debugging(seed=0)
    )
    algo = CQL(config=cfg)
    try:
        assert len(algo.replay) > 1000  # dataset loaded as transitions
        stats = algo.train()
        for k in ("q_loss", "policy_loss", "cql_loss", "alpha"):
            assert np.isfinite(stats[k]), (k, stats)
        # on a random-behavior dataset the logsumexp over sampled actions
        # exceeds the dataset-action Q -> positive conservative gap
        assert stats["cql_loss"] > 0.0
        assert stats["num_offline_steps_trained"] == 6 * 64
        # a second iteration keeps training from the same buffer
        stats2 = algo.train()
        assert np.isfinite(stats2["q_loss"])
    finally:
        algo.stop()


def test_cql_suppresses_q_vs_sac(tmp_path):
    """Same data, same seeds: the conservative penalty must leave the
    critic ranking the policy's own (out-of-distribution) actions BELOW
    dataset actions, where plain SAC ranks them above (its policy climbs
    Q). Absolute dataset-action Q is NOT the right probe: the CQL term
    pushes q_data *up* relative to OOD, and the policy is detached from
    the penalty — reference CQL applies the regularizer to critic
    optimizers only."""
    data = tmp_path / "pendulum"
    _write_pendulum_dataset(data)

    def train(alpha):
        cfg = (
            CQLConfig()
            .environment("Pendulum-v1")
            .offline_data(input_=str(data))
            .training(lr=1e-3, train_batch_size=64,
                      num_updates_per_iteration=50, cql_alpha=alpha,
                      num_sampled_actions=4)
            .debugging(seed=0)
        )
        algo = CQL(config=cfg)
        try:
            for _ in range(3):
                stats = algo.train()
            return (stats["q_ood_mean"] - stats["q_mean"],
                    stats["cql_loss"])
        finally:
            algo.stop()

    rank_conservative, gap_conservative = train(alpha=10.0)
    rank_plain, gap_plain = train(alpha=0.0)
    assert rank_conservative < rank_plain
    # the penalty also narrows the OOD-vs-data Q gap it optimizes
    assert gap_conservative < gap_plain
