"""Multi-tenant isolation plane: weighted-fair dispatch, per-job store
quotas, admission control, and job-identity plumbing.

Covers the PR-11 tentpole invariants: grant shares track quota weights,
the no_feasible/no_capacity autoscaler signal split, over-quota leases
deferring (not failing), init(job_quotas=...) propagating GCS → pubsub →
raylet → shared arena, two drivers' tasks carrying distinct job ids end
to end, and the lockdep-gated two-job quota race at the byte-quota
boundary (no torn counters, no cross-job eviction, referenced==0 at
quiesce — same shape as the PR-3 object-store gate).
"""

import multiprocessing
import os
import subprocess
import sys
import textwrap
import time

import pytest

from ray_tpu._private import scheduling as sched
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectStore, QuotaExceededError
from ray_tpu._private.scheduling import (
    ClusterView,
    FairDispatchQueue,
    JobQuota,
    SCHED_STATS,
)


@pytest.fixture(autouse=True)
def _clean_quota_registry():
    saved = dict(sched.JOB_QUOTAS)
    sched.JOB_QUOTAS.clear()
    yield
    sched.JOB_QUOTAS.clear()
    sched.JOB_QUOTAS.update(saved)


def _job(n: int) -> bytes:
    return bytes([n]) + b"\0" * 15


# -- weighted-fair dispatch queue -----------------------------------------


def test_fair_queue_shares_track_weights():
    """Backlogged jobs with weights 1/2/4 must receive grant shares
    within 10% of the weight ratio (the bench_multitenant acceptance
    bound, checked here at the queue level with zero noise)."""
    weights = {_job(1): 1.0, _job(2): 2.0, _job(3): 4.0}
    for job, w in weights.items():
        sched.set_job_quota(job, JobQuota(weight=w))
    q = FairDispatchQueue()
    seq = 0
    for job in weights:
        for _ in range(5):
            q.push(job, ("item", job, seq))
            seq += 1
    grants = {job: 0 for job in weights}
    rounds = 700
    for _ in range(rounds):
        item = q.fair_scan()[0]
        job = item[1]
        q.charge(job, item)
        q.remove(item)
        grants[job] += 1
        # keep every lane backlogged: shares are only defined while all
        # jobs have queued work
        q.push(job, ("item", job, seq))
        seq += 1
    total_w = sum(weights.values())
    for job, w in weights.items():
        expected = rounds * w / total_w
        assert abs(grants[job] - expected) <= 0.10 * rounds, (
            f"job {job[0]}: {grants[job]} grants, expected ~{expected}")


def test_fair_queue_fifo_within_lane():
    q = FairDispatchQueue()
    job = _job(1)
    items = [("i", n) for n in range(10)]
    for it in items:
        q.push(job, it)
    assert q.fair_scan() == items
    assert q.head(3) == items[:3]


def test_fair_queue_identity_remove_and_contains():
    q = FairDispatchQueue()
    a, b = ["lease"], ["lease"]  # equal but distinct objects
    q.push(_job(1), a)
    q.push(_job(1), b)
    assert a in q and b in q
    assert q.remove(a) is True
    assert a not in q and b in q
    assert len(q) == 1


def test_fair_queue_no_idle_credit_either_direction():
    """After one job drains 20 items alone, a newly arriving equal-weight
    job must NOT get a catch-up monopoly for the time before it existed,
    and the incumbent must not burst either: from the shared frontier
    the next grants alternate."""
    q = FairDispatchQueue()
    for n in range(20):
        q.push(_job(1), ("a", n))
    for _ in range(20):
        item = q.fair_scan()[0]
        q.charge(_job(1), item)
        q.remove(item)
    # job 2 arrives fresh against the (now idle) incumbent, then job 1
    # re-enters: both lanes backlogged from a common frontier
    for n in range(10):
        q.push(_job(2), ("b", n))
    for n in range(10):
        q.push(_job(1), ("a2", n))
    grants = {1: 0, 2: 0}
    for _ in range(10):
        item = q.fair_scan()[0]
        job = _job(1) if item[0].startswith("a") else _job(2)
        q.charge(job, item)
        q.remove(item)
        grants[job[0]] += 1
    assert grants[1] == 5 and grants[2] == 5, grants


def test_fair_scan_is_pure_and_charge_advances_clock():
    """fair_scan() is simulation only — peeking must never advance a
    job's clock; only charge() (an actual grant) does."""
    sched.set_job_quota(_job(1), JobQuota(weight=1.0))
    sched.set_job_quota(_job(2), JobQuota(weight=1.0))
    q = FairDispatchQueue()
    q.push(_job(1), "x1")
    q.push(_job(2), "y1")
    first = q.fair_scan()[0]
    for _ in range(5):
        assert q.fair_scan()[0] is first  # repeated peeks: same order
    job = _job(1) if first == "x1" else _job(2)
    q.charge(job, first)
    q.remove(first)
    q.push(job, "again")
    assert q.fair_scan()[0] is not first  # the other lane's turn now


def test_queue_depths_and_grant_metrics():
    sched.set_job_quota(_job(7), JobQuota(weight=2.0))
    q = FairDispatchQueue()
    q.push(_job(7), "x")
    q.push(_job(7), "y")
    q.push(_job(9), "z")
    depths = q.depths()
    assert depths[sched.job_label(_job(7))] == 2
    assert depths[sched.job_label(_job(9))] == 1
    before = SCHED_STATS.job_granted.get(sched.job_label(_job(7)), 0)
    q.charge(_job(7), "x")
    assert SCHED_STATS.job_granted[sched.job_label(_job(7))] == before + 1
    assert sched.job_label(_job(7)) in sched.metrics_text()


# -- no_feasible vs no_capacity (autoscaler demand signal) ----------------


def _view(total, available):
    view = ClusterView()
    view.update_node(b"n1", "addr:1", total, available)
    return view


def test_pick_node_counts_no_capacity_when_transiently_full():
    """Demand fits the node's TOTAL but not its current availability:
    that is lack of capacity (more of the same nodes, or wait), not
    infeasibility."""
    view = _view({"CPU": 2.0}, {"CPU": 0.0})
    before_cap = SCHED_STATS.no_capacity
    before_feas = SCHED_STATS.no_feasible
    assert sched.pick_node(view, {"CPU": 1.0}) is None
    assert SCHED_STATS.no_capacity == before_cap + 1
    assert SCHED_STATS.no_feasible == before_feas


def test_pick_node_counts_no_feasible_when_demand_never_fits():
    """Demand no alive node's total can ever hold (and the empty
    cluster) must count as no_feasible — the autoscaler needs BIGGER
    nodes, not more of these."""
    view = _view({"CPU": 2.0}, {"CPU": 2.0})
    before_cap = SCHED_STATS.no_capacity
    before_feas = SCHED_STATS.no_feasible
    assert sched.pick_node(view, {"CPU": 8.0}) is None
    assert SCHED_STATS.no_feasible == before_feas + 1
    assert SCHED_STATS.no_capacity == before_cap
    # empty cluster: nothing could ever fit
    assert sched.pick_node(ClusterView(), {"CPU": 1.0}) is None
    assert SCHED_STATS.no_feasible == before_feas + 2


# -- raylet admission control (over-quota defers, never fails) ------------


class _FakeRaylet:
    """Just enough state for Raylet._job_usage/_over_quota."""

    def __init__(self, leases):
        self._leases = leases


class _FakeLease:
    def __init__(self, job, resources, acquired):
        from types import SimpleNamespace

        self.spec = SimpleNamespace(job_id=job)
        self.resources = resources
        self.acquired = acquired


def test_over_quota_checks_cpu_and_memory_against_held():
    from ray_tpu._private.raylet import Raylet

    job = _job(3)
    sched.set_job_quota(job, JobQuota(cpu=2.0, memory=1000.0))
    fake = _FakeRaylet({
        1: _FakeLease(job, {"CPU": 1.0}, acquired=True),
        2: _FakeLease(job, {"CPU": 0.5}, acquired=False),  # not held
    })
    usage = Raylet._job_usage(fake)
    assert usage[job]["CPU"] == 1.0
    # 1.0 held + 1.0 demand == quota: admitted
    assert not Raylet._over_quota(fake, job, {"CPU": 1.0}, usage)
    # 1.0 held + 1.5 demand > quota: deferred
    assert Raylet._over_quota(fake, job, {"CPU": 1.5}, usage)
    # memory dimension enforced independently
    assert Raylet._over_quota(fake, job, {"memory": 1001.0}, usage)
    # an unlimited job never defers
    free = _job(4)
    assert not Raylet._over_quota(fake, free, {"CPU": 99.0}, usage)


# -- chaos grammar: quota_flood (containment fault class) -----------------


def test_quota_flood_parses_and_fires_against_registered_target():
    from ray_tpu._private import fault_injection as _fi

    plan = _fi.FaultPlan("at=0:quota_flood:0.4@worker")
    tf = plan.timed[0]
    assert (tf.fault, tf.arg, tf.role) == ("quota_flood", 0.4, "worker")
    # default window when no arg given
    assert _fi._parse_timed("1:quota_flood")[0].arg == 5.0
    calls = {"n": 0}

    def target():
        calls["n"] += 1
        if calls["n"] % 2 == 0:
            raise QuotaExceededError("at quota")

    _fi.install(plan)
    try:
        _fi.set_quota_flood_target(target)
        _fi.set_role("worker")  # arms the @worker entry; fires at t+0
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not any(
                s[0] == "timed.quota_flood.done" for s in plan.schedule):
            time.sleep(0.02)
        done = [s for s in plan.schedule
                if s[0] == "timed.quota_flood.done"]
        assert done, "flood window never completed"
        assert calls["n"] > 0
        assert "rejects=" in done[0][2]
        assert not plan.flooding()
    finally:
        _fi.set_quota_flood_target(None)
        _fi.uninstall()
        _fi.set_role("driver")


def test_serve_timeout_metric_carries_deployment_and_job_labels():
    from ray_tpu.serve.handle import REQUEST_TIMEOUTS

    assert REQUEST_TIMEOUTS.tag_keys == ("deployment", "job")


# -- two-job quota race at the byte-quota boundary (satellite 3) ----------
# 4 threads + 2 processes split across two jobs hammer creates/frees,
# job A pinned past its quota, while job B's parked objects stay
# referenced. Runs under the lockdep gate (module is listed in
# conftest._LOCKDEP_SUITES).

_QUOTA = 4 * 1024 * 1024


def _flood_job(store_name, job, seed, iters, obj_size, keep, q=None):
    """Create/seal objects pinned by their creator reference, releasing
    + deleting FIFO beyond `keep` live ones. With keep*obj_size above
    the job's quota this drives SS_QUOTA rejects (nothing of the job's
    is evictable); below it the job must never see a reject."""
    from ray_tpu._private.object_store import (
        ObjectStore as _OS,
        ObjectStoreError,
        QuotaExceededError as _QE,
    )

    store = _OS.attach(store_name)
    store.set_current_job(job)
    rejects = 0
    pinned = []
    try:
        for i in range(iters):
            oid = ObjectID(bytes([seed]) + i.to_bytes(4, "little")
                           + b"\0" * 11)
            try:
                buf = store.create_buffer(oid, obj_size)
                buf[:4] = b"ok!!"
                del buf
                store.seal(oid)
                pinned.append(oid)
            except _QE:
                rejects += 1
            except ObjectStoreError:
                pass  # arena-level pressure: legal under the race
            while len(pinned) > keep:
                old = pinned.pop(0)
                store.release(old)
                store.delete(old)
        # quiesce: this worker's objects all released and deleted
        while pinned:
            old = pinned.pop()
            store.release(old)
            store.delete(old)
    finally:
        store.close()
    if q is not None:
        q.put((seed, rejects))
    return rejects


def test_two_job_quota_race_no_torn_counters_no_cross_eviction():
    import threading

    name = f"/ray_tpu_test_mt_{os.getpid()}"
    store = ObjectStore.create(name, capacity=32 * 1024 * 1024,
                               table_size=4096, shards=8)
    job_a, job_b = _job(21), _job(22)
    big, small = 128 * 1024, 32 * 1024
    try:
        store.set_job_quota(job_a, _QUOTA, label="jobA")
        store.set_job_quota(job_b, _QUOTA, label="jobB")

        # job B parks referenced objects well under its quota — the race
        # must never evict them or account them to job A
        b_handle = ObjectStore.attach(name)
        b_handle.set_current_job(job_b)
        b_oids = []
        for i in range(8):
            oid = ObjectID(b"B" + i.to_bytes(4, "little") + b"\0" * 11)
            buf = b_handle.create_buffer(oid, big)
            buf[:4] = b"keep"
            del buf
            b_handle.seal(oid)  # creator reference kept: pinned
            b_oids.append(oid)
        b_used_before = store.job_stats(job_b)["used"]
        assert b_used_before >= len(b_oids) * big

        # job A's workers pin past A's quota (40*128K > 4M): guaranteed
        # rejects. Job B's workers churn far below B's remaining quota:
        # any B reject or eviction would mean A's overload leaked across.
        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        procs = [
            ctx.Process(target=_flood_job,
                        args=(name, job_a, 101, 150, big, 40, q)),
            ctx.Process(target=_flood_job,
                        args=(name, job_b, 102, 150, small, 2, q)),
        ]
        for p in procs:
            p.start()
        results = {}
        lock = threading.Lock()

        def run(seed, jb, n_keep, size):
            r = _flood_job(name, jb, seed, 200, size, n_keep)
            with lock:
                results[seed] = r

        threads = [
            threading.Thread(target=run, args=args)
            for args in ((1, job_a, 40, big), (2, job_a, 40, big),
                         (3, job_b, 2, small), (4, job_b, 2, small))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for p in procs:
            seed, r = q.get(timeout=120)
            results[seed] = r
        for p in procs:
            p.join(timeout=30)
        assert len(results) == 6

        sa = store.job_stats(job_a)
        sb = store.job_stats(job_b)
        # the offender was capped: its quota held throughout the race
        assert sa["used"] <= _QUOTA, sa
        assert sa["quota_rejects"] >= 1, sa
        assert results[1] + results[2] + results[101] >= 1
        # containment: job B never felt job A's flood
        assert sb["used"] <= _QUOTA, sb
        assert sb["quota_rejects"] == 0, sb
        assert sb["evicted_bytes"] == 0, sb
        assert results[3] == results[4] == results[102] == 0
        # B's parked objects survived, bytes intact
        for oid in b_oids:
            assert store.contains(oid)
            view = b_handle.get_buffer(oid)
            assert view is not None and bytes(view[:4]) == b"keep"
            view = None
        assert store.job_stats(job_b)["used"] >= b_used_before

        # quiesce: drop the parked pins, then both jobs' counters must
        # drain to exactly zero — a torn fetch_add/sub anywhere in the
        # race leaves a residue here
        for oid in b_oids:
            b_handle.release(oid)
            b_handle.delete(oid)
        b_handle.close()
        st = store.stats()
        assert st["referenced"] == 0, st
        store.evict(2 ** 62)
        for jb in (job_a, job_b):
            row = store.job_stats(jb)
            assert row["used"] == 0, (jb, row)
            assert row["num_objects"] == 0, (jb, row)
        assert store.stats()["num_objects"] == 0
    finally:
        store.destroy()


# -- end-to-end: quota propagation + distinct job ids on one cluster ------


@pytest.fixture(scope="module")
def mt_cluster():
    import ray_tpu

    quota = 2 * 1024 * 1024
    ray_tpu.init(num_cpus=4, num_tpus=0,
                 object_store_memory=64 * 1024 * 1024,
                 job_quotas={"weight": 2.0, "object_store_bytes": quota})
    yield ray_tpu, quota
    ray_tpu.shutdown()


def test_job_quota_registered_at_init_reaches_the_store(mt_cluster):
    """init(job_quotas=...) → GCS register_job → jobs-channel pubsub →
    raylet stamps the byte quota into the shared arena — after which
    this driver's own creates hit QuotaExceededError at the boundary."""
    ray_tpu, quota = mt_cluster
    from ray_tpu._private.worker_api import _require_state

    cw = _require_state().core_worker
    store = cw.store
    job = cw.job_id.binary()
    # quota application is async (pubsub through the raylet): poll
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        st = store.job_stats(job)
        if st is not None and st["quota"] == quota:
            break
        time.sleep(0.05)
    st = store.job_stats(job)
    assert st is not None and st["quota"] == quota, st

    chunk = 256 * 1024
    pinned = []
    rejected = False
    try:
        for _ in range(quota // chunk + 8):
            oid = ObjectID.from_random()
            try:
                buf = store.create_buffer(oid, chunk)
                del buf
                store.seal(oid)  # creator ref kept: nothing evictable
                pinned.append(oid)
            except QuotaExceededError:
                rejected = True
                break
        assert rejected, "creates never hit the registered byte quota"
        st = store.job_stats(job)
        assert st["quota_rejects"] >= 1
        assert st["used"] <= quota
    finally:
        for oid in pinned:
            store.release(oid)
            store.delete(oid)


def test_two_drivers_tasks_carry_distinct_job_ids(mt_cluster):
    """Two drivers against one cluster: each driver's tasks must run in
    workers stamped with THAT driver's job id (the raylet pools workers
    per job) — never a shared job-0 bucket."""
    ray_tpu, _ = mt_cluster
    from ray_tpu._private import worker_api
    from ray_tpu.util import state as state_api

    gcs_addr = worker_api._global_state.cluster.gcs_addr

    @ray_tpu.remote
    def whoami():
        return ray_tpu.get_runtime_context().get_job_id()

    my_job = ray_tpu.get_runtime_context().get_job_id()
    assert my_job != "00" * 16  # the old JobID.from_int(0) default
    assert ray_tpu.get(whoami.remote(), timeout=120) == my_job

    script = textwrap.dedent(f"""
        import ray_tpu
        ray_tpu.init(address={gcs_addr!r})
        @ray_tpu.remote
        def whoami():
            return ray_tpu.get_runtime_context().get_job_id()
        me = ray_tpu.get_runtime_context().get_job_id()
        worker = ray_tpu.get(whoami.remote(), timeout=120)
        assert worker == me, (worker, me)
        print("JOB=" + me)
        ray_tpu.shutdown()
    """)
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True,
        text=True, timeout=240, cwd="/root/repo",
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    other_job = [ln for ln in out.stdout.splitlines()
                 if ln.startswith("JOB=")][0].split("=", 1)[1]
    assert other_job != my_job
    # both jobs registered as distinct accounting buckets at the GCS
    jobs = {j["job_id"] for j in state_api.list_jobs()}
    assert my_job in jobs and other_job in jobs
