"""Host-level (CPU control-plane) collectives: barrier / broadcast /
allreduce / allgather / reducescatter / send-recv over the rendezvous
actor.

Reference: `python/ray/util/collective/collective.py:258-594` — the GLOO
host path (allreduce/allgather/reducescatter/broadcast/send/recv over
named-actor rendezvous). The rebuilt HostGroup covers the same operation
vocabulary; device collectives are XLA ops tested in test_parallel.py.
"""

import numpy as np
import pytest


def test_host_group_collectives(ray_start):
    import ray_tpu

    @ray_tpu.remote
    class Member:
        def __init__(self, rank: int, world: int):
            from ray_tpu.parallel.collectives import HostGroup

            self.rank = rank
            self.world = world
            self.group = HostGroup("test-hg", world, rank)

        def run(self):
            g = self.group
            out = {}
            g.barrier()
            # broadcast: everyone sees root 0's value
            out["bcast"] = g.broadcast(
                value=("payload", self.rank) if self.rank == 0 else None,
                root=0)
            # allreduce: sum of ranks
            out["sum"] = g.allreduce_sum(np.full(4, float(self.rank)))
            # allgather: rank-ordered values
            out["gather"] = g.allgather(self.rank * 10)
            # reducescatter: each rank keeps its shard of the sum
            out["rs"] = g.reducescatter_sum(
                np.arange(6, dtype=np.float64) + self.rank)
            # ring send/recv: pass rank to the right neighbor
            g.send(self.rank, dst=(self.rank + 1) % self.world)
            out["recv"] = g.recv(src=(self.rank - 1) % self.world)
            # tag reuse across rounds must not collide
            g.barrier()
            out["sum2"] = g.allreduce_sum(1)
            return out

    world = 3
    members = [Member.remote(r, world) for r in range(world)]
    results = ray_tpu.get([m.run.remote() for m in members], timeout=120)

    for r, res in enumerate(results):
        assert res["bcast"] == ("payload", 0)
        np.testing.assert_allclose(res["sum"], np.full(4, 3.0))  # 0+1+2
        assert res["gather"] == [0, 10, 20]
        # reduce-scatter of sum_r (arange(6)+r): total = 3*arange(6)+3
        total = 3 * np.arange(6, dtype=np.float64) + 3
        np.testing.assert_allclose(
            res["rs"], np.array_split(total, world)[r])
        assert res["recv"] == (r - 1) % world
        assert res["sum2"] == world
    # the detached rendezvous actor must be cleaned up
    rdv = ray_tpu.get_actor("collective:test-hg")
    ray_tpu.kill(rdv)


def test_host_groups_concurrent_no_crosstalk(ray_start):
    """Two groups with different names run interleaved collectives in
    parallel; tags/rounds never leak across groups."""
    import ray_tpu

    @ray_tpu.remote
    class Member:
        def __init__(self, group, rank, world, base):
            from ray_tpu.parallel.collectives import HostGroup

            self.g = HostGroup(group, world, rank)
            self.base = base
            self.rank = rank

        def run(self):
            out = []
            for i in range(3):
                out.append(self.g.allreduce_sum(self.base + i))
                self.g.barrier()
            return out

    world = 2
    a = [Member.remote("grp-a", r, world, 100) for r in range(world)]
    b = [Member.remote("grp-b", r, world, 1000) for r in range(world)]
    results = ray_tpu.get([m.run.remote() for m in a + b], timeout=120)
    for res in results[:world]:
        assert res == [200 + 2 * i for i in range(3)]
    for res in results[world:]:
        assert res == [2000 + 2 * i for i in range(3)]
    for name in ("grp-a", "grp-b"):
        ray_tpu.kill(ray_tpu.get_actor(f"collective:{name}"))


def test_host_group_rank_failure_times_out(ray_start):
    """A collective with a dead/absent rank fails with a timeout after
    the group's timeout_s instead of hanging forever (reference: GLOO
    group timeouts)."""
    import time as time_mod

    import ray_tpu

    @ray_tpu.remote
    class Flaky:
        def __init__(self, rank, world):
            from ray_tpu.parallel.collectives import HostGroup

            self.g = HostGroup("grp-fail", world, rank, timeout_s=3.0)
            self.rank = rank

        def run(self):
            if self.rank == 1:
                import os
                os._exit(1)  # dies before joining the barrier
            t0 = time_mod.monotonic()
            try:
                self.g.barrier()
                return ("ok", time_mod.monotonic() - t0)
            except Exception as e:
                return (type(e).__name__, time_mod.monotonic() - t0)

    world = 2
    members = [Flaky.remote(r, world) for r in range(world)]
    ref0 = members[0].run.remote()
    members[1].run.remote()  # rank 1 kills itself
    kind, elapsed = ray_tpu.get(ref0, timeout=60)
    assert kind == "GetTimeoutError"
    assert 2.0 < elapsed < 30.0  # bounded by timeout_s, not 300s
    ray_tpu.kill(ray_tpu.get_actor("collective:grp-fail"))
