"""Push-based resource gossip staleness test (VERDICT r3 weak #9).

Own module: it manages its own cluster + heartbeat-period env and must
not share the multi-node module's session-scoped init.
"""


def test_resource_gossip_push_beats_heartbeat():
    """VERDICT r3 weak #9: spillback decisions must not ride views up to
    a heartbeat period stale. With the heartbeat timer cranked to 120s,
    the ONLY way freed remote capacity can reach a peer raylet quickly
    is the push path (freed -> nudged heartbeat -> GCS delta publish ->
    peer view update -> respill). A queued task must land on the freed
    node within the 75s bound, not at the next timer tick."""
    import os
    import time

    import ray_tpu
    from ray_tpu._private.node import Cluster

    env_key = "RAY_TPU_RAYLET_HEARTBEAT_PERIOD_S"
    old = os.environ.get(env_key)
    # the margin between the assert bound below and this period is what
    # discriminates push from timer — wide enough to stay meaningful
    # under heavy CPU contention on a 1-core CI box
    os.environ[env_key] = "120"
    try:
        cluster = Cluster(head_resources={"CPU": 1.0})
        cluster.add_node({"CPU": 1.0})
        ray_tpu.init(address=cluster.gcs_addr)
        try:
            @ray_tpu.remote
            def busy(seconds):
                d = time.monotonic() + seconds
                while time.monotonic() < d:
                    time.sleep(0.02)
                return "done"

            # occupy BOTH nodes: one long task locally, one spilled to
            # the second node (its registration delta seeded the view)
            long_ref = busy.remote(45)
            short_ref = busy.remote(4)
            time.sleep(1.0)
            # third task: no capacity anywhere -> queues
            start = time.monotonic()
            queued_ref = busy.remote(0.1)
            assert ray_tpu.get(queued_ref, timeout=60) == "done"
            elapsed = time.monotonic() - start
            # short task frees its node at ~4s; the queued task must
            # follow the push path there LONG before the 120s heartbeat
            assert elapsed < 75.0, f"gossip too stale: {elapsed:.1f}s"
            assert ray_tpu.get(short_ref, timeout=60) == "done"
            ray_tpu.cancel(long_ref, force=True)
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()
    finally:
        if old is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = old
