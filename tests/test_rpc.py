import asyncio

import pytest

from ray_tpu._private.rpc import ClientPool, RpcClient, RpcError, RpcServer


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def test_request_reply(loop):
    async def main():
        server = RpcServer()

        async def echo(payload):
            return {"echoed": payload["msg"]}

        server.register("echo", echo)
        await server.start()
        client = await RpcClient(server.address).connect()
        out = await client.call("echo", {"msg": "hi"})
        assert out == {"echoed": "hi"}
        await client.close()
        await server.stop()

    loop.run_until_complete(main())


def test_remote_error_propagates(loop):
    async def main():
        server = RpcServer()

        async def boom(payload):
            raise ValueError("kaboom")

        server.register("boom", boom)
        await server.start()
        client = await RpcClient(server.address).connect()
        with pytest.raises(RpcError, match="kaboom"):
            await client.call("boom", {})
        await client.close()
        await server.stop()

    loop.run_until_complete(main())


def test_concurrent_requests_interleave(loop):
    async def main():
        server = RpcServer()

        async def slow(payload):
            await asyncio.sleep(payload["t"])
            return payload["t"]

        server.register("slow", slow)
        await server.start()
        client = await RpcClient(server.address).connect()
        # Issue slow-then-fast; fast must not be blocked behind slow.
        results = await asyncio.gather(
            client.call("slow", {"t": 0.3}), client.call("slow", {"t": 0.01})
        )
        assert results == [0.3, 0.01]
        await client.close()
        await server.stop()

    loop.run_until_complete(main())


def test_binary_payload(loop):
    async def main():
        server = RpcServer()

        async def double(payload):
            return payload + payload

        server.register("double", double)
        await server.start()
        client = await RpcClient(server.address).connect()
        blob = bytes(range(256)) * 100
        assert await client.call("double", blob) == blob + blob
        await client.close()
        await server.stop()

    loop.run_until_complete(main())


def test_client_pool_reuses_connections(loop):
    async def main():
        server = RpcServer()

        async def ping(payload):
            return "pong"

        server.register("ping", ping)
        await server.start()
        pool = ClientPool()
        c1 = await pool.get(server.address)
        c2 = await pool.get(server.address)
        assert c1 is c2
        assert await c1.call("ping", {}) == "pong"
        await pool.close_all()
        await server.stop()

    loop.run_until_complete(main())


def test_ids():
    from ray_tpu._private.ids import JobID, ObjectID, TaskID

    job = JobID.from_int(1)
    t = TaskID.for_driver(job)
    o1 = ObjectID.for_task_return(t, 0)
    o2 = ObjectID.for_task_return(t, 1)
    assert o1 != o2
    assert ObjectID.for_task_return(t, 0) == o1  # deterministic
    assert len(o1.binary()) == 16
    assert ObjectID.from_hex(o1.hex()) == o1
