"""AOT executable cache + steps_per_call folding (ROADMAP r5 #3).

Covers the dispatch plane behind sub-2 ms driver overhead: hit/miss
counters, donation actually taking effect (the donated carry's buffer is
consumed), the retrace guard firing on an abstract-signature change, and
loss-trajectory equivalence of one folded K-step dispatch vs K single
steps.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel.compile_cache import (
    ExecutableCache,
    RetraceError,
    cache_stats,
    compiled_step,
    fold_steps,
    global_cache,
    stack_batches,
)


def _sgd_step(w, batch):
    x, y = batch
    def loss_fn(w):
        return jnp.mean((x @ w - y) ** 2)
    loss, g = jax.value_and_grad(loss_fn)(w)
    return w - 0.1 * g, loss


def _make_data(seed, n=32, d=4):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, d), jnp.float32)
    true_w = jnp.asarray(rng.randn(d), jnp.float32)
    return x, x @ true_w


def test_hit_miss_counters_and_entries():
    cache = ExecutableCache()
    step = compiled_step(_sgd_step, donate_argnums=(0,), cache=cache)
    w = jnp.zeros(4)
    batch = _make_data(0)
    w, _ = step(w, batch)
    assert cache.stats.as_dict() == {"hits": 0, "misses": 1,
                                     "retraces": 0}
    assert cache.size() == 1
    for _ in range(3):
        w, _ = step(w, batch)
    assert cache.stats.hits == 3
    assert cache.stats.misses == 1
    assert cache.size() == 1  # one executable serves every step


def test_donation_buffer_consumed():
    """donate_argnums must reach the AOT executable: the donated carry
    is consumed by the call (its buffer was reused for the output)."""
    cache = ExecutableCache()
    step = compiled_step(_sgd_step, donate_argnums=(0,), cache=cache)
    batch = _make_data(1)
    w0 = jnp.zeros(4)
    w1, _ = step(w0, batch)  # compile + run
    assert w0.is_deleted(), "donated carry should be consumed"
    w2, _ = step(w1, batch)  # cached-executable path donates too
    assert w1.is_deleted()
    assert not w2.is_deleted()
    # and without donation the input survives
    cache2 = ExecutableCache()
    step_nd = compiled_step(_sgd_step, cache=cache2)
    w3 = jnp.zeros(4)
    step_nd(w3, batch)
    assert not w3.is_deleted()


def test_retrace_guard_fires_on_shape_change():
    cache = ExecutableCache()
    step = compiled_step(_sgd_step, donate_argnums=(0,), cache=cache)
    step(jnp.zeros(4), _make_data(0, d=4))
    assert cache.stats.retraces == 0
    # same function, new aval signature: miss + retrace recorded
    step(jnp.zeros(8), _make_data(0, d=8))
    assert cache.stats.retraces == 1
    assert cache.stats.misses == 2
    # strict mode raises instead of silently compiling a third variant
    strict = compiled_step(_sgd_step, donate_argnums=(0,), cache=cache,
                           on_retrace="error")
    with pytest.raises(RetraceError, match="new abstract signature"):
        strict(jnp.zeros(16), _make_data(0, d=16))


def test_dtype_change_is_a_retrace():
    cache = ExecutableCache()
    f = compiled_step(lambda x: x * 2, cache=cache)
    f(jnp.zeros(4, jnp.float32))
    f(jnp.zeros(4, jnp.int32))
    assert cache.stats.retraces == 1


def test_fold_steps_matches_k_single_steps():
    """One steps_per_call=K dispatch must walk the same loss trajectory
    as K single-step dispatches."""
    k = 4
    x, y = _make_data(2)
    batches = [( x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8])
               for i in range(k)]

    w_ref = jnp.zeros(4)
    ref_losses = []
    for b in batches:
        w_ref, loss = _sgd_step(w_ref, b)
        ref_losses.append(float(loss))

    cache = ExecutableCache()
    multi = fold_steps(_sgd_step, k, cache=cache)
    assert multi.steps_per_call == k
    w_fold, losses = multi(jnp.zeros(4), stack_batches(batches))
    assert losses.shape == (k,)
    np.testing.assert_allclose(np.asarray(losses), ref_losses,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(w_fold), np.asarray(w_ref),
                               rtol=1e-5)
    # the folded program is ONE cached executable: driver cost for the
    # next K steps is a single hit
    w2, _ = multi(w_fold, stack_batches(batches))
    assert cache.stats.as_dict() == {"hits": 1, "misses": 1,
                                     "retraces": 0}


def test_fold_steps_donates_carry():
    k = 2
    x, y = _make_data(3)
    batches = stack_batches([(x, y)] * k)
    cache = ExecutableCache()
    multi = fold_steps(_sgd_step, k, cache=cache)
    w0 = jnp.zeros(4)
    multi(w0, batches)
    assert w0.is_deleted(), "folded carry should be donated"


def test_train_step_runner_equivalence_and_stats():
    from ray_tpu.train import TrainStepRunner

    k = 3
    x, y = _make_data(4)
    batches = [(x, y)] * (2 * k)

    w_ref = jnp.zeros(4)
    ref_losses = []
    for b in batches:
        w_ref, loss = _sgd_step(w_ref, b)
        ref_losses.append(float(loss))

    runner = TrainStepRunner(_sgd_step, steps_per_call=k)
    w = jnp.zeros(4)
    it = iter(batches)
    got = []
    for _ in range(2):
        w, losses = runner.run(w, it)
        got.extend(float(v) for v in losses)
    np.testing.assert_allclose(got, ref_losses, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(w), np.asarray(w_ref),
                               rtol=1e-5)
    stats = runner.cache_stats()
    assert stats["misses"] == 1 and stats["hits"] == 1

    # steps_per_call=1 path: plain per-batch stepping, same trajectory
    runner1 = TrainStepRunner(_sgd_step)
    w1 = jnp.zeros(4)
    for b in batches:
        w1, _ = runner1.run(w1, b)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w_ref),
                               rtol=1e-5)


def test_global_cache_stats_shape():
    before = cache_stats()
    assert set(before) == {"hits", "misses", "retraces", "entries",
                           "lowering_ms"}

    @compiled_step
    def bump(x):
        return x + 1

    bump(jnp.zeros(2))
    bump(jnp.zeros(2))
    after = cache_stats()
    assert after["misses"] >= before["misses"] + 1
    assert after["hits"] >= before["hits"] + 1
    global_cache().clear()
    cleared = cache_stats()
    assert cleared["entries"] == 0


def test_python_scalar_is_part_of_the_key():
    """Non-array leaves are baked into the trace; a changed scalar must
    be a different executable, not a stale cache hit."""
    cache = ExecutableCache()
    f = compiled_step(lambda x, s: x * s, cache=cache)
    a = f(jnp.ones(2), 2.0)
    b = f(jnp.ones(2), 3.0)
    np.testing.assert_allclose(np.asarray(a), [2.0, 2.0])
    np.testing.assert_allclose(np.asarray(b), [3.0, 3.0])
    assert cache.size() == 2
