"""Train harness tests: worker group, report/checkpoint flow, JaxTrainer.

Reference ground: `python/ray/train/tests/test_data_parallel_trainer.py`,
`test_backend.py` — adapted to the jax backend.
"""

import os

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.air import Checkpoint, CheckpointConfig, RunConfig, ScalingConfig


@pytest.fixture(scope="module", autouse=True)
def cluster(tmp_path_factory):
    ray_tpu.init(num_cpus=8, num_tpus=0,
                 object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@pytest.fixture()
def storage(tmp_path):
    return str(tmp_path / "results")


def test_two_worker_report_lockstep(storage):
    def loop(config):
        ctx = train.get_context()
        assert ctx.get_world_size() == 2
        for step in range(3):
            train.report({"step": step, "rank": ctx.get_world_rank(),
                          "val": config["base"] + step})

    trainer = train.DataParallelTrainer(
        loop,
        train_loop_config={"base": 10},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=storage, name="lockstep"),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics is not None
    assert result.metrics["step"] == 2
    assert result.metrics["val"] == 12


def test_checkpoint_roundtrip_and_topk(storage):
    def loop(config):
        ctx = train.get_context()
        for step in range(4):
            ckpt = Checkpoint.from_dict({"step": step,
                                         "rank": ctx.get_world_rank()})
            train.report({"score": float(step)}, checkpoint=ckpt)

    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path=storage, name="ckpt",
            checkpoint_config=CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score"),
        ),
    )
    result = trainer.fit()
    assert result.checkpoint is not None
    state = result.checkpoint.to_dict()
    assert state["step"] == 3
    # top-K eviction happened on disk
    trial_root = os.path.dirname(result.checkpoint.path)
    kept = [d for d in os.listdir(trial_root) if d.startswith("checkpoint_")]
    assert len(kept) == 2


def test_restore_from_checkpoint(storage):
    def loop(config):
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["step"] + 1
        train.report({"resumed_from": start})

    ckpt = Checkpoint.from_dict({"step": 41})
    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=storage, name="restore"),
        resume_from_checkpoint=ckpt,
    )
    result = trainer.fit()
    assert result.metrics["resumed_from"] == 42


def test_worker_failure_propagates(storage):
    def loop(config):
        ctx = train.get_context()
        if ctx.get_world_rank() == 1:
            raise ValueError("boom from rank 1")
        train.report({"ok": True})

    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=storage, name="fail"),
    )
    from ray_tpu.train._internal.backend_executor import TrainingFailedError
    with pytest.raises(TrainingFailedError, match="boom from rank 1"):
        trainer.fit()


def test_jax_trainer_trains_on_device(storage):
    """End-to-end: JaxTrainer runs a real jitted SGD loop in the worker."""

    def loop(config):
        import jax
        import jax.numpy as jnp
        import optax

        key = jax.random.PRNGKey(0)
        w = jnp.zeros((4,), jnp.float32)
        x = jax.random.normal(key, (64, 4))
        true_w = jnp.array([1.0, -2.0, 3.0, 0.5])
        y = x @ true_w
        opt = optax.sgd(0.1)
        opt_state = opt.init(w)

        @jax.jit
        def step(w, opt_state):
            def loss_fn(w):
                return jnp.mean((x @ w - y) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(w)
            updates, opt_state = opt.update(g, opt_state)
            return optax.apply_updates(w, updates), opt_state, loss

        for i in range(50):
            w, opt_state, loss = step(w, opt_state)
        train.report({"loss": float(loss)})

    trainer = train.JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=storage, name="jax"),
    )
    result = trainer.fit()
    assert result.metrics["loss"] < 1e-2
