"""Data tests: transforms, fusion, exchanges, IO, iteration, train ingest.

Reference ground: `python/ray/data/tests/test_map.py`,
`test_sort.py`, `test_consumption.py`, `test_splitblocks.py` — compressed.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=8, num_tpus=0,
                 object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_range_count_schema():
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.num_blocks() == 4
    assert ds.schema() == {"id": "int64"}


def test_map_chain_fuses_and_computes():
    ds = (rd.range(32, parallelism=4)
          .map(lambda r: {"x": r["id"] * 2})
          .filter(lambda r: r["x"] % 4 == 0)
          .map_batches(lambda b: {"x": b["x"], "y": b["x"] + 1}))
    from ray_tpu.data import logical as L
    optimized = L.optimize(ds._op)
    # the map chain fuses, then fuses INTO the read: one task wave
    assert isinstance(optimized, L.FusedRead)
    assert len(optimized.transforms) == 3
    rows = ds.take_all()
    xs = sorted(r["x"] for r in rows)
    assert xs == [i * 2 for i in range(32) if (i * 2) % 4 == 0]
    assert all(r["y"] == r["x"] + 1 for r in rows)


def test_flat_map_and_columns():
    ds = (rd.from_items([{"a": 1}, {"a": 2}])
          .flat_map(lambda r: [{"a": r["a"]}, {"a": r["a"] * 10}])
          .add_column("b", lambda acc: acc.block["a"] + 1)
          .select_columns(["b"]))
    assert sorted(r["b"] for r in ds.take_all()) == [2, 3, 11, 21]


def test_limit_streams():
    ds = rd.range(1000, parallelism=8).limit(25)
    assert ds.count() == 25


def test_repartition():
    ds = rd.range(100, parallelism=2).repartition(5)
    assert ds.num_blocks() == 5
    assert ds.count() == 100


def test_random_shuffle_permutes():
    ds = rd.range(200, parallelism=4).random_shuffle(seed=7)
    vals = [r["id"] for r in ds.take_all()]
    assert sorted(vals) == list(range(200))
    assert vals != list(range(200))


def test_sort_descending_and_ascending():
    rng = np.random.default_rng(0)
    vals = rng.permutation(500)
    ds = rd.from_numpy({"v": vals}, parallelism=5).sort("v")
    out = [r["v"] for r in ds.take_all()]
    assert out == sorted(out)
    out_d = [r["v"] for r in
             rd.from_numpy({"v": vals}, parallelism=5)
             .sort("v", descending=True).take_all()]
    assert out_d == sorted(out_d, reverse=True)


def test_groupby_aggregations():
    items = [{"k": i % 3, "v": float(i)} for i in range(30)]
    ds = rd.from_items(items, parallelism=4)
    out = {r["k"]: r for r in ds.groupby("k").sum("v").take_all()}
    for k in (0, 1, 2):
        expected = sum(i for i in range(30) if i % 3 == k)
        assert out[k]["sum(v)"] == expected
    counts = {r["k"]: r["count()"] for r in
              ds.groupby("k").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10}
    # global aggregate (no key)
    total = ds.groupby(None).sum("v").take_all()
    assert total[0]["sum(v)"] == sum(builtins_range_f(30))


def builtins_range_f(n):
    return [float(i) for i in range(n)]


def test_iter_batches_exact_sizes():
    ds = rd.range(100, parallelism=3)
    batches = list(ds.iter_batches(batch_size=32))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [32, 32, 32, 4]
    all_ids = np.concatenate([b["id"] for b in batches])
    assert sorted(all_ids.tolist()) == list(range(100))
    # drop_last
    sizes2 = [len(b["id"]) for b in
              ds.iter_batches(batch_size=32, drop_last=True)]
    assert sizes2 == [32, 32, 32]


def test_union_and_zip():
    a = rd.range(10, parallelism=2)
    b = rd.range(10, parallelism=2).map(lambda r: {"id2": r["id"] + 100})
    assert a.union(rd.range(5, parallelism=1)).count() == 15
    z = a.zip(b)
    rows = z.take_all()
    assert all(r["id2"] == r["id"] + 100 for r in rows)


def test_csv_json_parquet_roundtrip(tmp_path):
    ds = rd.range(50, parallelism=2).map(
        lambda r: {"id": r["id"], "sq": r["id"] ** 2})
    for fmt, writer, reader in [
        ("csv", ds.write_csv, rd.read_csv),
        ("json", ds.write_json, rd.read_json),
        ("parquet", ds.write_parquet, rd.read_parquet),
    ]:
        out_dir = str(tmp_path / fmt)
        files = writer(out_dir)
        assert len(files) == 2
        back = reader(out_dir)
        rows = sorted(back.take_all(), key=lambda r: r["id"])
        assert len(rows) == 50
        assert rows[7]["sq"] == 49


def test_split_for_train_ingest():
    ds = rd.range(64, parallelism=4)
    shards = ds.streaming_split(2)
    assert len(shards) == 2
    seen = []
    for sh in shards:
        for b in sh.iter_batches(batch_size=8):
            seen.extend(b["id"].tolist())
    assert sorted(seen) == list(range(64))


def test_train_integration_dataset_shard(tmp_path):
    """get_dataset_shard inside a train worker (reference
    `python/ray/train/tests/test_data_parallel_trainer.py` datasets)."""
    from ray_tpu import train
    from ray_tpu.air import RunConfig, ScalingConfig

    def loop(config):
        it = train.get_dataset_shard("train")
        total = 0
        count = 0
        for batch in it.iter_batches(batch_size=16):
            total += int(batch["id"].sum())
            count += len(batch["id"])
        train.report({"total": total, "count": count})

    ds = rd.range(128, parallelism=4)
    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path), name="ingest"),
        datasets={"train": ds},
    )
    result = trainer.fit()
    # rank0's shard is half the data; totals across workers sum to full
    assert result.metrics["count"] == 64


def test_tfrecords_roundtrip(tmp_path):
    """write_tfrecords -> read_tfrecords round-trips int/float/str
    columns through the dependency-free Example codec."""
    from ray_tpu import data

    ds = data.from_items([
        {"id": i, "score": float(i) / 2, "name": f"row{i}"}
        for i in range(20)
    ])
    out = str(tmp_path / "tfr")
    import os

    os.makedirs(out, exist_ok=True)
    files = ds.write_tfrecords(out)
    assert files

    back = data.read_tfrecords(out).to_pandas().sort_values(
        "id").reset_index(drop=True)
    assert list(back["id"]) == list(range(20))
    assert back["name"][3] == b"row3"  # BytesList stays bytes
    import numpy as np

    np.testing.assert_allclose(back["score"],
                               [i / 2 for i in range(20)], rtol=1e-6)


def test_tfrecord_codec_vectors_and_negatives(tmp_path):
    """Multi-element lists and negative ints survive the proto wire."""
    from ray_tpu.data import _tfrecord as tfr

    row = {"vec": np.asarray([1.5, -2.5, 3.0], np.float32),
           "ints": np.asarray([-7, 8], np.int64),
           "blob": b"\x00\x01\xff"}
    data_bytes = tfr.build_example(row)
    parsed = tfr.parse_example(data_bytes)
    np.testing.assert_allclose(parsed["vec"], row["vec"])
    np.testing.assert_array_equal(parsed["ints"], row["ints"])
    assert parsed["blob"] == [b"\x00\x01\xff"]
    # framing round-trip
    path = str(tmp_path / "one.tfrecords")
    tfr.write_records(path, [data_bytes, data_bytes])
    assert len(list(tfr.read_records(path))) == 2


def test_read_sql():
    import sqlite3

    conn = sqlite3.connect("/tmp/ray_tpu_test_sql.db")
    conn.execute("DROP TABLE IF EXISTS t")
    conn.execute("CREATE TABLE t (a INTEGER, b TEXT)")
    conn.executemany("INSERT INTO t VALUES (?, ?)",
                     [(i, f"v{i}") for i in range(10)])
    conn.commit()
    conn.close()

    from ray_tpu import data

    def sqlite_factory():  # nested -> cloudpickled by value
        import sqlite3 as sq

        return sq.connect("/tmp/ray_tpu_test_sql.db")

    df = data.read_sql("SELECT * FROM t WHERE a >= 5",
                       sqlite_factory).to_pandas()
    assert sorted(df["a"]) == [5, 6, 7, 8, 9]
    assert set(df["b"]) == {f"v{i}" for i in range(5, 10)}


def test_from_arrow_to_arrow():
    import pyarrow as pa

    from ray_tpu import data

    table = pa.table({"x": list(range(12)), "y": [i * 2 for i in range(12)]})
    ds = data.from_arrow(table, parallelism=3)
    back = ds.to_arrow()
    assert back.num_rows == 12
    assert sorted(back.column("x").to_pylist()) == list(range(12))
    # transforms apply on arrow-sourced data
    total = data.from_arrow(table).map_batches(
        lambda b: {"z": b["x"] + b["y"]}).to_pandas()["z"].sum()
    assert total == sum(i + 2 * i for i in range(12))


def test_push_based_shuffle_paths():
    """With many input blocks and a small merge factor, repartition/
    shuffle/sort/groupby route through the push-based (pipelined-merge)
    exchange and must produce identical results to the pull-based path."""
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    old_factor, old_flag = ctx.shuffle_merge_factor, \
        ctx.use_push_based_shuffle
    try:
        ctx.shuffle_merge_factor = 3
        ctx.use_push_based_shuffle = True
        # 12 blocks > merge factor 3 -> push path engages
        ds = rd.range(240, parallelism=12)
        assert ds.repartition(4).count() == 240
        vals = [r["id"] for r in
                rd.range(240, parallelism=12)
                .random_shuffle(seed=3).take_all()]
        assert sorted(vals) == list(range(240))
        assert vals != list(range(240))

        rng = np.random.default_rng(1)
        raw = rng.permutation(300)
        out = [r["v"] for r in
               rd.from_numpy({"v": raw}, parallelism=12)
               .sort("v").take_all()]
        assert out == sorted(out)

        items = [{"k": i % 4, "v": float(i)} for i in range(120)]
        sums = {r["k"]: r["sum(v)"] for r in
                rd.from_items(items, parallelism=12)
                .groupby("k").sum("v").take_all()}
        assert sums == {k: float(sum(i for i in range(120) if i % 4 == k))
                        for k in range(4)}

        # pull path (flag off) agrees exactly on the same seed
        ctx.use_push_based_shuffle = False
        vals_pull = [r["id"] for r in
                     rd.range(240, parallelism=12)
                     .random_shuffle(seed=3).take_all()]
        assert vals_pull == vals
    finally:
        ctx.shuffle_merge_factor = old_factor
        ctx.use_push_based_shuffle = old_flag


def test_scalar_aggregates_and_unique():
    ds = rd.from_items([{"k": i % 3, "v": float(i)} for i in range(30)],
                       parallelism=4)
    assert ds.sum("v") == sum(range(30))
    assert ds.min("v") == 0.0
    assert ds.max("v") == 29.0
    assert abs(ds.mean("v") - 14.5) < 1e-9
    assert ds.unique("k") == [0, 1, 2]
    # mixed/None columns fall back to first-seen order instead of raising
    mixed = rd.from_items([{"k": 1}, {"k": None}, {"k": 1}],
                          parallelism=1)
    vals = mixed.unique("k")
    assert len(vals) == 2 and 1 in vals


def test_random_sample():
    ds = rd.range(2000, parallelism=4)
    frac = ds.random_sample(0.3, seed=5)
    n = frac.count()
    assert 400 < n < 800  # ~600 expected
    # deterministic per (seed, partitioning)
    assert rd.range(2000, parallelism=4).random_sample(
        0.3, seed=5).count() == n
    # duplicate rows draw independently (not all-or-nothing)
    dup = rd.from_items([{"x": 1}] * 1000, parallelism=2)
    m = dup.random_sample(0.5, seed=1).count()
    assert 300 < m < 700, m


def test_train_test_split():
    ds = rd.range(100, parallelism=4)
    train, test = ds.train_test_split(0.25)
    train_ids = [r["id"] for r in train.take_all()]
    test_ids = [r["id"] for r in test.take_all()]
    assert len(train_ids) == 75 and len(test_ids) == 25
    # unshuffled contract: test is the LAST fraction, order preserved
    assert sorted(train_ids + test_ids) == list(range(100))
    assert test_ids == list(range(75, 100))

    train, test = ds.train_test_split(0.25, shuffle=True, seed=0)
    ids = sorted([r["id"] for r in train.take_all()]
                 + [r["id"] for r in test.take_all()])
    assert ids == list(range(100))
    assert test.count() == 25


# -- actor-compute map stages (reference actor_pool_map_operator.py) --------


def test_map_batches_actor_pool_class_udf():
    class AddTag:
        def __init__(self, tag):
            # expensive state: built once per pool actor
            import os
            self.tag = tag
            self.instance = f"{os.getpid()}-{id(self)}"

        def __call__(self, batch):
            n = len(batch["id"])
            return {"id": batch["id"],
                    "tag": np.asarray([self.tag] * n),
                    "who": np.asarray([self.instance] * n)}

    ds = rd.range(64, parallelism=8).map_batches(
        AddTag, compute=rd.ActorPoolStrategy(min_size=2, max_size=2),
        fn_constructor_args=("t",))
    rows = ds.take_all()
    assert len(rows) == 64
    assert all(r["tag"] == "t" for r in rows)
    # 8 blocks ran on at most 2 warm instances (one per pool actor) —
    # the class was NOT instantiated per block
    assert 1 <= len({r["who"] for r in rows}) <= 2


def test_map_batches_actor_pool_autoscales():
    import time as _t

    class Slow:
        def __init__(self):
            self.instance = id(self)

        def __call__(self, batch):
            _t.sleep(0.2)
            return {"id": batch["id"],
                    "who": np.asarray([self.instance] * len(batch["id"]))}

    ds = rd.range(64, parallelism=8).map_batches(
        Slow, compute=rd.ActorPoolStrategy(
            min_size=1, max_size=3, max_tasks_in_flight_per_actor=1))
    rows = ds.take_all()
    # a saturated 1-actor pool with backlog must have grown
    assert len({r["who"] for r in rows}) > 1


def test_map_batches_class_udf_requires_actor_compute():
    class F:
        def __call__(self, b):
            return b

    with pytest.raises(ValueError, match="ActorPoolStrategy"):
        rd.range(8).map_batches(F)


def test_actor_map_does_not_fuse_with_task_maps():
    from ray_tpu.data import logical as L

    class Id:
        def __call__(self, b):
            return b

    ds = (rd.range(32, parallelism=4)
          .map(lambda r: {"x": r["id"]})
          .map_batches(Id, compute=rd.ActorPoolStrategy(min_size=1))
          .map(lambda r: {"x": r["x"] + 1}))
    optimized = L.optimize(ds._op)
    # the actor stage stays a lone MapBatches between two task stages
    assert isinstance(optimized, L.MapRows)
    assert isinstance(optimized.input_op, L.MapBatches)
    assert optimized.input_op.compute is not None
    assert [r["x"] for r in sorted(ds.take_all(),
                                   key=lambda r: r["x"])] == \
        list(range(1, 33))


def test_streaming_ingest_actor_pool_to_train_worker():
    """VERDICT round-2 item 3 'done' criterion: a stateful actor pool
    tokenizes and feeds iter_batches into a train worker without
    materializing the dataset on the driver."""

    class Tokenizer:
        def __init__(self, vocab_base):
            self.vocab_base = vocab_base  # stands in for a real vocab load

        def __call__(self, batch):
            return {"tokens": batch["id"] + self.vocab_base}

    ds = rd.range(256, parallelism=8).map_batches(
        Tokenizer, compute=rd.ActorPoolStrategy(min_size=2, max_size=2),
        fn_constructor_args=(1000,))

    @ray_tpu.remote
    def train_worker(it):
        total, nbatches = 0, 0
        for b in it.iter_batches(batch_size=32):
            total += int(b["tokens"].sum())
            nbatches += 1
        return total, nbatches

    [shard] = ds.streaming_split(1)
    total, nbatches = ray_tpu.get(train_worker.remote(shard), timeout=180)
    assert nbatches == 8
    assert total == sum(i + 1000 for i in range(256))


def test_resource_budget_backpressure():
    from ray_tpu.data.context import DataContext
    from ray_tpu.data.executor import _ResourceBudget

    # default: window derives from cluster CPUs, not a constant
    ctx = DataContext(max_concurrent_tasks=None)
    b = _ResourceBudget(ctx)
    assert b.task_cap() == max(2, int(8 * 1.5))  # fixture cluster: 8 CPUs
    ctx2 = DataContext(max_concurrent_tasks=3)
    assert _ResourceBudget(ctx2).task_cap() == 3

    # with the high-water mark forced to 0 every allocated byte counts as
    # pressure; submission serializes but the stage still completes
    ctx3 = DataContext(store_backpressure_fraction=0.0)
    from ray_tpu.data import executor as ex
    old = rd.DataContext.get_current().store_backpressure_fraction
    rd.DataContext.get_current().store_backpressure_fraction = 0.0
    try:
        big = rd.range_tensor(64, shape=(1024,), parallelism=8) \
            .map_batches(lambda b: {"data": b["data"] * 2})
        assert big.count() == 64
    finally:
        rd.DataContext.get_current().store_backpressure_fraction = old


# ---------------------------------------------------------------------------
# limit pushdown + streaming ingest (VERDICT r4 items 4 and 6)
# ---------------------------------------------------------------------------

def test_limit_pushdown_plan():
    """Limit commutes below cardinality-preserving maps and stamps
    limit_rows on the Read; Limit(Limit) collapses."""
    from ray_tpu.data import logical as L

    ds = (rd.range(1000, parallelism=8)
          .map(lambda r: {"id": r["id"] * 2})
          .limit(100)
          .limit(40))
    op = L.optimize(ds._op)
    # map stays on top (runs only on the surviving rows)
    assert isinstance(op, L.MapRows) or isinstance(op, L.FusedMap)
    inner = op.input_op
    assert isinstance(inner, L.Limit) and inner.n == 40
    assert isinstance(inner.input_op, L.Read)
    assert inner.input_op.limit_rows == 40

    # filter blocks pushdown (changes cardinality); the filter itself
    # fuses into the read, with the limit staying on top
    ds2 = rd.range(100, parallelism=4).filter(
        lambda r: r["id"] % 2 == 0).limit(10)
    op2 = L.optimize(ds2._op)
    assert isinstance(op2, L.Limit)
    assert isinstance(op2.input_op, L.FusedRead)
    assert "Filter" in op2.input_op.name


def test_limit_pushdown_reads_fewer_tasks(tmp_path):
    """With limit pushed into the read, only enough read tasks run to
    satisfy it — the datasource records which partitions were read."""
    import json

    marker_dir = tmp_path / "reads"
    marker_dir.mkdir()

    def make_read(i):
        def read():
            with open(marker_dir / f"{i}", "w") as f:
                f.write("1")
            return {"id": np.arange(i * 10, (i + 1) * 10)}
        return read

    from ray_tpu.data.datasource import SimpleDatasource

    ds = rd.read_datasource(
        SimpleDatasource([make_read(i) for i in range(16)]))
    got = ds.limit(10).map(lambda r: {"id": r["id"]}).take_all()
    assert len(got) == 10
    # far fewer than 16 partitions were touched (the launch window is 4)
    assert len(list(marker_dir.iterdir())) <= 8


def test_streaming_split_dynamic_balance():
    """A deliberately slow consumer receives FEWER blocks than a fast
    one — the coordinator hands blocks to whoever asks (VERDICT r3 weak
    #6: static round-robin gave no rebalancing)."""
    import threading
    import time as time_mod

    ds = rd.range(320, parallelism=16)
    fast_it, slow_it = ds.streaming_split(2)
    counts = {"fast": 0, "slow": 0}
    rows = {"fast": 0, "slow": 0}

    errors = []

    def consume(name, it, delay):
        try:
            for block in it._iter_blocks():
                counts[name] += 1
                rows[name] += len(block["id"])
                time_mod.sleep(delay)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append((name, repr(e)))

    t1 = threading.Thread(target=consume, args=("fast", fast_it, 0.0))
    t2 = threading.Thread(target=consume, args=("slow", slow_it, 0.25))
    t1.start(); t2.start()
    t1.join(timeout=180); t2.join(timeout=180)
    assert not t1.is_alive() and not t2.is_alive(), "consumers hung"
    assert not errors, errors
    assert rows["fast"] + rows["slow"] == 320
    assert counts["fast"] + counts["slow"] == 16
    # every block still arrives exactly once, and the fast consumer
    # carried the bulk of the stream
    assert counts["fast"] > counts["slow"]


def test_streaming_split_first_block_before_pipeline_done():
    """First block is consumable while upstream still produces: the
    time-to-first-block must be far below total pipeline time."""
    import time as time_mod

    def slow_identity(b):
        time_mod.sleep(0.5)
        return {"id": b["id"]}

    ds = rd.range(160, parallelism=8).map_batches(slow_identity)
    (it,) = ds.streaming_split(1)
    start = time_mod.monotonic()
    gen = it._iter_blocks()
    first = next(gen)
    first_latency = time_mod.monotonic() - start
    rest = list(gen)
    total = time_mod.monotonic() - start
    assert len(first["id"]) + sum(len(b["id"]) for b in rest) == 160
    # 8 blocks x 0.5s of map work: with streaming the first block lands
    # after ~1 task, not after the whole wave
    assert first_latency < total * 0.75, (first_latency, total)


def test_iter_batches_prefetch_overlaps():
    """prefetch_batches resolves blocks ahead of the consumer; values
    are unchanged and consumption overlaps production."""
    ds = rd.range(128, parallelism=8)
    it = ds.streaming_split(1)[0]
    seen = []
    for batch in it.iter_batches(batch_size=16, prefetch_batches=2):
        seen.extend(batch["id"].tolist())
    assert sorted(seen) == list(range(128))

    # plain materialized iterator path too
    got = []
    from ray_tpu.data.iterator import DataIterator
    refs = rd.range(64, parallelism=4)._execute()
    for batch in DataIterator(refs).iter_batches(batch_size=8,
                                                 prefetch_batches=3):
        got.extend(batch["id"].tolist())
    assert sorted(got) == list(range(64))


def test_optimize_does_not_mutate_shared_plan():
    """Datasets share plan nodes; executing a derived .limit() dataset
    must not truncate the parent's later executions."""
    ds = rd.range(500, parallelism=8).map(lambda r: {"id": r["id"]})
    assert ds.limit(10).count() == 10
    assert ds.count() == 500  # parent plan untouched
    assert ds.limit(25).count() == 25
    assert ds.count() == 500


def test_read_map_fusion_single_task_wave():
    """VERDICT r5 item 8: a read->map->map pipeline executes as ONE task
    wave — intermediate blocks never round-trip through the store
    (reference `rules/zero_copy_map_fusion.py` + read fusion)."""
    import time as time_mod

    from ray_tpu.util.state import summarize_tasks

    def quiesced_summary():
        # task events flush to the GCS asynchronously; wait until the
        # stream settles so earlier tests' in-flight events don't
        # pollute the before/after diff
        prev = summarize_tasks()
        deadline = time_mod.monotonic() + 30
        while time_mod.monotonic() < deadline:
            time_mod.sleep(1.0)
            cur = summarize_tasks()
            if cur == prev:
                return cur
            prev = cur
        return prev

    before = quiesced_summary()

    ds = (rd.range(64, parallelism=4)
          .map_batches(lambda b: {"x": b["id"] * 2})
          .map_batches(lambda b: {"x": b["x"] + 1}))
    rows = sorted(r["x"] for r in ds.take_all())
    assert rows == [i * 2 + 1 for i in range(64)]

    def delta(after, name):
        b = sum(before.get(name, {}).values())
        a = sum(after.get(name, {}).values())
        return a - b

    after = quiesced_summary()

    # one wave: one task per block, nothing per stage
    assert delta(after, "_run_read_fused") == 4, after.get("_run_read_fused")
    assert delta(after, "_run_read") == 0
    assert delta(after, "_run_transform") == 0


def test_actor_pool_grows_and_shrinks():
    """VERDICT r5 item 8: the actor-compute pool scales with queue depth
    both ways — grows while every actor is saturated with backlog,
    releases idle actors once the tail no longer needs them (reference
    `execution/autoscaler/default_autoscaler.py`). Asserted on the
    executor's own autoscaling trace: the GCS ALIVE view lags worker
    spawn by seconds on slow hosts, which is scheduler latency, not
    pool policy."""
    import time as time_mod

    from ray_tpu.data.context import DataContext

    class Slow:
        def __call__(self, batch):
            # the last block is much slower: during its tail the idle
            # surplus actors must be released while the stage still runs
            time_mod.sleep(1.5 if int(batch["id"][0]) >= 150 else 0.2)
            return batch

    ds = rd.range(160, parallelism=16).map_batches(
        Slow, compute=rd.ActorPoolStrategy(
            min_size=1, max_size=4, max_tasks_in_flight_per_actor=2),
        batch_size=10)
    assert ds.count() == 160

    stats = DataContext.get_current().last_actor_pool_stats
    assert stats is not None
    assert stats["peak"] == 4, stats       # grew to max under backlog
    assert stats["grows"] == 3, stats
    assert stats["shrinks"] >= 1, stats    # released idle tail capacity


def test_webdataset_roundtrip(tmp_path):
    """write_webdataset -> read_webdataset round-trips tar shards of
    keyed samples (reference `datasource/webdataset_datasource.py`,
    here dependency-free via stdlib tarfile)."""
    ds = rd.from_items([
        {"__key__": f"s{i:03d}", "txt": f"caption {i}", "cls": i % 3,
         "bin": bytes([i, i + 1])}
        for i in range(12)
    ], parallelism=2)
    out = str(tmp_path / "wds")
    os.makedirs(out, exist_ok=True)
    files = ds.write_webdataset(out)
    assert len(files) == 2 and all(f.endswith(".tar") for f in files)

    back = sorted(rd.read_webdataset(out).take_all(),
                  key=lambda r: r["__key__"])
    assert len(back) == 12
    assert back[4]["txt"] == "caption 4"
    assert back[4]["cls"] == 1
    assert back[4]["bin"] == bytes([4, 5])


def test_webdataset_binary_and_heterogeneous(tmp_path):
    """Binary payloads with trailing NULs survive (bytes stay
    object-dtype, never fixed-width 'S'), and samples with differing
    member sets keep the union of columns."""
    import tarfile
    import io

    out = tmp_path / "shard.tar"
    with tarfile.open(out, "w") as tar:
        def add(name, payload):
            info = tarfile.TarInfo(name=name)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))

        add("a.txt", b"first")          # no .cls member
        add("b.txt", b"second")
        add("b.cls", b"7")
        add("a.bin", b"\x04\x00")       # trailing NUL
        add("b.bin", b"\x05\x06")

    rows = sorted(rd.read_webdataset(str(out)).take_all(),
                  key=lambda r: r["__key__"])
    assert rows[0]["bin"] == b"\x04\x00"
    assert rows[1]["bin"] == b"\x05\x06"
    assert rows[0]["cls"] is None       # union schema, missing -> None
    assert rows[1]["cls"] == 7


# -- start_batch_index: elastic resume-from-offset --------------------------


def test_iter_batches_start_batch_index_exact_resume():
    """Resuming at batch k replays the deterministic stream's suffix
    exactly — no batch duplicated, none skipped (the soak driver's
    watermark audit relies on this)."""
    ds = rd.range(100, parallelism=3)
    full = [b["id"].tolist() for b in ds.iter_batches(batch_size=32)]
    for k in range(len(full) + 1):
        resumed = [b["id"].tolist() for b in
                   ds.iter_batches(batch_size=32, start_batch_index=k)]
        assert resumed == full[k:], f"resume at batch {k} diverged"


def test_iter_batches_start_batch_index_crosses_blocks():
    # 4 blocks of 25 rows; skipping 3 batches of 10 lands 5 rows INTO
    # block 1 — the first emitted batch stitches a mid-block slice
    ds = rd.range(100, parallelism=4)
    batches = list(ds.iter_batches(batch_size=10, start_batch_index=3))
    assert batches[0]["id"].tolist() == list(range(30, 40))
    assert [len(b["id"]) for b in batches] == [10] * 7
    assert batches[-1]["id"].tolist() == list(range(90, 100))


def test_iter_batches_start_batch_index_past_end():
    ds = rd.range(20, parallelism=2)
    assert list(ds.iter_batches(batch_size=8, start_batch_index=3)) == []
    # partial last batch is itself resumable
    last = list(ds.iter_batches(batch_size=8, start_batch_index=2))
    assert len(last) == 1 and last[0]["id"].tolist() == [16, 17, 18, 19]


def test_iter_batches_start_batch_index_validation():
    ds = rd.range(10, parallelism=1)
    with pytest.raises(ValueError, match=">= 0"):
        list(ds.iter_batches(batch_size=4, start_batch_index=-1))
    with pytest.raises(ValueError, match="deterministic"):
        list(ds.iter_batches(batch_size=4, start_batch_index=1,
                             local_shuffle_buffer_size=8))
