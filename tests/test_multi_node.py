"""Multi-node tests: spillback scheduling, cross-node objects, placement
groups, node failure (reference: `ray_start_cluster`-based tests).

Marked `slow`: spawns a 3-node cluster (3 raylets + GCS + workers) on one
machine. Run with `-m slow` or as part of the full suite.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
from ray_tpu._private.node import Cluster

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def three_nodes():
    cluster = Cluster(head_resources={"CPU": 2},
                      object_store_memory=64 * 1024 * 1024)
    cluster.add_node({"CPU": 2})
    cluster.add_node({"CPU": 2})
    ray_tpu.init(address=cluster.gcs_addr)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


@ray_tpu.remote
def where_am_i():
    return os.environ.get("RAY_TPU_NODE_ID")


def test_spread_uses_multiple_nodes(three_nodes):
    @ray_tpu.remote
    def where_am_i_slow():
        # hold the worker briefly so one fast node cannot serially
        # absorb every task before the others finish spawning workers
        # (the assertion is about PLACEMENT, not about timing luck)
        time.sleep(0.3)
        return os.environ.get("RAY_TPU_NODE_ID")

    locs = set(ray_tpu.get(
        [where_am_i_slow.options(scheduling_strategy="SPREAD").remote()
         for _ in range(12)],
        timeout=240,
    ))
    assert len(locs) >= 2


def test_node_affinity(three_nodes):
    node_id = ray_tpu.nodes()[1]["NodeID"]
    loc = ray_tpu.get(
        where_am_i.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_id)
        ).remote(),
        timeout=240,
    )
    assert loc == node_id


def test_cross_node_object_transfer(three_nodes):
    node_ids = [n["NodeID"] for n in ray_tpu.nodes()]

    @ray_tpu.remote
    def produce():
        return np.arange(2_000_000, dtype=np.float64)  # 16MB

    @ray_tpu.remote
    def consume(arr):
        return float(arr.sum())

    r = produce.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_ids[1])
    ).remote()
    out = consume.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_ids[2])
    ).remote(r)
    assert ray_tpu.get(out, timeout=240) == 1999999 * 2000000 / 2


def test_strict_spread_placement_group(three_nodes):
    pg = ray_tpu.placement_group(
        [{"CPU": 1}] * 3, strategy="STRICT_SPREAD"
    )
    assert pg.ready(timeout=60)
    refs = [
        where_am_i.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(pg, i)
        ).remote()
        for i in range(3)
    ]
    locs = ray_tpu.get(refs, timeout=240)
    assert len(set(locs)) == 3
    ray_tpu.remove_placement_group(pg)


def test_infeasible_strict_spread_stays_pending(three_nodes):
    # 4 bundles on 3 nodes cannot STRICT_SPREAD.
    pg = ray_tpu.placement_group([{"CPU": 1}] * 4, strategy="STRICT_SPREAD")
    assert not pg.ready(timeout=3)
    ray_tpu.remove_placement_group(pg)


def test_actor_restart(three_nodes):
    @ray_tpu.remote
    class Flaky:
        def pid(self):
            return os.getpid()

        def die(self):
            os._exit(1)

    f = Flaky.options(max_restarts=1).remote()
    pid1 = ray_tpu.get(f.pid.remote(), timeout=240)
    try:
        ray_tpu.get(f.die.remote(), timeout=60)
    except Exception:
        pass
    deadline = time.time() + 60
    pid2 = None
    while time.time() < deadline:
        try:
            pid2 = ray_tpu.get(f.pid.remote(), timeout=30)
            break
        except Exception:
            time.sleep(1)
    assert pid2 is not None and pid2 != pid1
