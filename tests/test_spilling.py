"""Chunked object transfer + disk spilling tests.

Reference surface: `src/ray/object_manager/object_manager.h:117` +
`object_buffer_pool.h` (chunked push/pull) and
`src/ray/raylet/local_object_manager.h:41` (spill/restore).
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.node import Cluster


def test_chunked_cross_node_transfer():
    """A multi-chunk object (size >> chunk size) transfers node-to-node
    intact. Chunk size shrunk via env so a ~10MB object needs many
    chunks — the scaled-down version of the >2GiB path, which the chunk
    protocol handles identically (no whole-object frame ever built)."""
    os.environ["RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES"] = str(1 << 20)
    cluster = Cluster()
    try:
        cluster.add_node({"CPU": 2.0})
        worker = cluster.add_node({"CPU": 2.0})
        ray_tpu.init(address=cluster.gcs_addr)

        aff = ray_tpu.NodeAffinitySchedulingStrategy(
            worker.node_id_hex, soft=False)

        @ray_tpu.remote(scheduling_strategy=aff)
        def produce():
            rng = np.random.default_rng(0)
            return rng.integers(0, 255, 10_000_000, np.uint8)

        ref = produce.remote()
        out = ray_tpu.get(ref, timeout=120)
        expect = np.random.default_rng(0).integers(0, 255, 10_000_000,
                                                   np.uint8)
        np.testing.assert_array_equal(out, expect)
    finally:
        os.environ.pop("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", None)
        ray_tpu.shutdown()
        cluster.shutdown()


def test_put_beyond_capacity_spills_and_restores():
    """Puts totalling ~2x the store capacity all succeed (pinned copies
    spill to disk) and every value reads back correctly (restore)."""
    ray_tpu.init(num_cpus=2, object_store_memory=32 * 1024 * 1024)
    try:
        refs = []
        for i in range(8):  # 8 x 8MB = 64MB = 2x capacity
            refs.append(ray_tpu.put(np.full(8_000_000, i, np.uint8)))
            time.sleep(0.1)  # let pins land before the next put
        for i, ref in enumerate(refs):
            out = ray_tpu.get(ref, timeout=30)
            assert out[0] == i and out.shape == (8_000_000,)
    finally:
        ray_tpu.shutdown()


def test_task_returns_beyond_capacity_spill():
    """Worker-produced plasma returns also ride the spill path."""
    ray_tpu.init(num_cpus=2, object_store_memory=32 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def produce(i):
            return np.full(8_000_000, i, np.uint8)

        refs = [produce.remote(i) for i in range(8)]
        for i, ref in enumerate(refs):
            out = ray_tpu.get(ref, timeout=60)
            assert out[0] == i
    finally:
        ray_tpu.shutdown()


def test_unpin_removes_spill_files():
    """Dropping the last ref to a spilled object deletes its disk file."""
    cluster = Cluster(head_resources={"CPU": 2.0},
                      object_store_memory=32 * 1024 * 1024)
    try:
        ray_tpu.init(address=cluster.gcs_addr)
        refs = [ray_tpu.put(np.full(8_000_000, i, np.uint8))
                for i in range(8)]
        time.sleep(0.5)
        spill_dirs = [
            os.path.join(cluster.session_dir, d)
            for d in os.listdir(cluster.session_dir) if d.startswith("spill-")
        ]
        spilled = sum(len(os.listdir(d)) for d in spill_dirs)
        assert spilled > 0, "expected some objects to be spilled"
        del refs
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            left = sum(len(os.listdir(d)) for d in spill_dirs
                       if os.path.isdir(d))
            if left == 0:
                break
            time.sleep(0.5)
        assert left == 0, f"{left} spill files not reclaimed after unpin"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
