"""Runtime environment tests: env vars, working_dir, py_modules.

Reference ground: `python/ray/tests/test_runtime_env.py` /
`test_runtime_env_working_dir.py` — compressed to the supported surface.
"""

import os
import time

import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_env_vars_per_task():
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_FLAG": "hello"}})
    def read_env():
        return os.environ.get("MY_FLAG")

    @ray_tpu.remote
    def read_env_plain():
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(read_env.remote(), timeout=60) == "hello"
    # a different env means a different worker pool: no leakage
    assert ray_tpu.get(read_env_plain.remote(), timeout=60) is None


def test_same_function_different_envs_do_not_share_workers():
    """One function, two envs: each call must see ITS env — distinct
    scheduling keys keep distinct env workers (a shared lease queue
    would silently run the second env's task in the first's worker)."""
    @ray_tpu.remote
    def read_flag():
        return os.environ.get("SHARED_FLAG")

    a = read_flag.options(
        runtime_env={"env_vars": {"SHARED_FLAG": "one"}})
    b = read_flag.options(
        runtime_env={"env_vars": {"SHARED_FLAG": "two"}})
    # interleave submissions so a shared queue WOULD mix them
    refs = [a.remote(), b.remote(), a.remote(), b.remote()]
    assert ray_tpu.get(refs, timeout=120) == ["one", "two", "one", "two"]


def test_env_vars_for_actor():
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_MODE": "42"}})
    class EnvActor:
        def read(self):
            return os.environ.get("ACTOR_MODE")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote(), timeout=60) == "42"
    ray_tpu.kill(a)


def test_working_dir_ships_code(tmp_path):
    wd = tmp_path / "proj"
    wd.mkdir()
    (wd / "data.txt").write_text("shipped-payload")
    (wd / "helper.py").write_text("VALUE = 'from-helper'\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
    def use_working_dir():
        import helper  # importable: working_dir is on sys.path

        with open("data.txt") as f:  # cwd is the working_dir
            return f.read(), helper.VALUE

    data, helper_value = ray_tpu.get(use_working_dir.remote(), timeout=60)
    assert data == "shipped-payload"
    assert helper_value == "from-helper"


def test_py_modules(tmp_path):
    mod = tmp_path / "shiplib"
    mod.mkdir()
    (mod / "__init__.py").write_text("def shipped():\n    return 'ok'\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod)]})
    def use_module():
        import shiplib

        return shiplib.shipped()

    assert ray_tpu.get(use_module.remote(), timeout=60) == "ok"


def test_unsupported_field_rejected():
    @ray_tpu.remote(runtime_env={"no_such_backend": "x"})
    def nope():
        return 1

    with pytest.raises(ValueError):
        nope.remote()


# -- pip isolation (reference python/ray/_private/runtime_env/pip.py) -------


def _build_wheel(tmpdir: str, name: str, version: str = "0.1") -> str:
    """Hand-roll a minimal wheel (a zip with dist-info metadata) so the
    pip-env test needs no network: pip installs it with --no-index."""
    import zipfile
    whl = os.path.join(tmpdir, f"{name}-{version}-py3-none-any.whl")
    di = f"{name}-{version}.dist-info"
    with zipfile.ZipFile(whl, "w") as zf:
        zf.writestr(f"{name}/__init__.py",
                    f"MAGIC = 'wheel-{name}-{version}'\n")
        zf.writestr(f"{di}/METADATA",
                    f"Metadata-Version: 2.1\nName: {name}\n"
                    f"Version: {version}\n")
        zf.writestr(f"{di}/WHEEL",
                    "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib:"
                    " true\nTag: py3-none-any\n")
        zf.writestr(f"{di}/RECORD", "")
    return whl


def test_pip_env_isolates_package(tmp_path):
    whl = _build_wheel(str(tmp_path), "rt_pip_probe")
    env = {"pip": {"packages": [whl],
                   "install_options": ["--no-index", "--no-deps"]}}

    @ray_tpu.remote(runtime_env=env)
    def use_pkg():
        import rt_pip_probe
        return rt_pip_probe.MAGIC

    @ray_tpu.remote
    def base_env_has_it():
        import importlib.util
        return importlib.util.find_spec("rt_pip_probe") is not None

    assert ray_tpu.get(use_pkg.remote(), timeout=180) == \
        "wheel-rt_pip_probe-0.1"
    # the package exists ONLY inside the env's venv
    assert ray_tpu.get(base_env_has_it.remote(), timeout=60) is False


def test_pip_env_venv_is_cached(tmp_path):
    from ray_tpu._private.runtime_env import ensure_pip_env, normalize_pip
    whl = _build_wheel(str(tmp_path), "rt_pip_cache")
    wire = normalize_pip({"packages": [whl],
                          "install_options": ["--no-index", "--no-deps"]})
    t0 = time.monotonic()
    py1 = ensure_pip_env(wire)
    first = time.monotonic() - t0
    t1 = time.monotonic()
    py2 = ensure_pip_env(wire)
    second = time.monotonic() - t1
    assert py1 == py2 and os.path.exists(py1)
    assert second < first / 5  # cache hit skips venv+install entirely


def test_pip_env_install_failure_fails_task(tmp_path):
    env = {"pip": {"packages": ["definitely-not-a-real-pkg-xyz"],
                   "install_options": ["--no-index", "--no-deps"]}}

    @ray_tpu.remote(runtime_env=env)
    def f():
        return 1

    with pytest.raises(Exception, match="runtime_env setup failed"):
        ray_tpu.get(f.remote(), timeout=180)


def test_runtime_env_plugin_seam(tmp_path):
    """Custom runtime_env fields route through registered plugins
    (reference: `python/ray/_private/runtime_env/plugin.py` +
    RAY_RUNTIME_ENV_PLUGINS): driver-side prepare produces the wire
    form, worker-side materialize applies it before the task runs. The
    plugin module ships to workers via py_modules and loads there via
    the RAY_TPU_RUNTIME_ENV_PLUGINS env var."""
    import sys
    import textwrap

    from ray_tpu._private import runtime_env as renv

    mod_dir = tmp_path / "touchplugin"
    mod_dir.mkdir()
    (mod_dir / "__init__.py").write_text(textwrap.dedent("""
        import os
        from ray_tpu._private.runtime_env import RuntimeEnvPlugin

        class TouchPlugin(RuntimeEnvPlugin):
            name = "touch_file"

            def prepare(self, value, upload):
                return {"path": str(value), "token": "prepared"}

            def materialize(self, value, fetch, target_root):
                with open(value["path"], "w") as f:
                    f.write(value["token"])
    """))
    sys.path.insert(0, str(tmp_path))
    try:
        import touchplugin

        renv.register_plugin(touchplugin.TouchPlugin())
        marker = tmp_path / "touched.txt"

        @ray_tpu.remote(runtime_env={
            "touch_file": str(marker),
            "py_modules": [str(mod_dir)],
            "env_vars": {
                "RAY_TPU_RUNTIME_ENV_PLUGINS": "touchplugin:TouchPlugin",
            },
        })
        def probe():
            with open(str(marker)) as f:
                return f.read()

        assert ray_tpu.get(probe.remote(), timeout=120) == "prepared"
    finally:
        sys.path.remove(str(tmp_path))
        renv._plugins.pop("touch_file", None)


def test_container_image_overlay(tmp_path):
    """`container` runtime env (reference `runtime_env/container.py`,
    podman): the zero-egress stand-in applies a LOCAL overlay image dir
    — site-packages onto sys.path, bin onto PATH — via the shipped
    LocalImagePlugin."""
    image = tmp_path / "image"
    (image / "site-packages").mkdir(parents=True)
    (image / "bin").mkdir()
    (image / "site-packages" / "img_probe_mod.py").write_text(
        "LAYER = 'overlay-42'\n")
    (image / "bin" / "imgtool").write_text("#!/bin/sh\necho tool\n")
    os.chmod(image / "bin" / "imgtool", 0o755)

    @ray_tpu.remote(runtime_env={"container": {"image": str(image)}})
    def probe():
        import shutil

        import img_probe_mod

        return img_probe_mod.LAYER, shutil.which("imgtool") is not None

    layer, has_tool = ray_tpu.get(probe.remote(), timeout=120)
    assert layer == "overlay-42"
    assert has_tool

    @ray_tpu.remote
    def base():
        import importlib.util
        return importlib.util.find_spec("img_probe_mod") is not None

    assert ray_tpu.get(base.remote(), timeout=60) is False


def test_container_image_rejects_bad_value():
    with pytest.raises(ValueError, match="container"):
        @ray_tpu.remote(runtime_env={"container": "not-a-dict"})
        def f():
            pass

        f.remote()
