"""Sharded-array checkpointing + elastic restore.

Reference ground: `python/ray/train/tests/test_new_persistence.py` (the
checkpoint persistence seam) and SURVEY §7.3's hard-part deliverable —
"checkpoint-restore of sharded arrays under elastic recovery". The save
format is native per-host shard files + index
(`ray_tpu/train/array_checkpoint.py`); the integration test runs a REAL
multi-process jax.distributed gang (2 train-worker processes x 2 virtual
CPU devices = one global 4-device mesh), kills a worker mid-run, and
resumes from the sharded checkpoint bit-identically.

Own file: the trainer workers need their own spawn-time env
(XLA device count), and the module-scoped cluster keeps init exclusive.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train import array_checkpoint as ac
from ray_tpu.train.backend import JaxConfig


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=8, num_tpus=0,
                 object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@pytest.fixture()
def storage(tmp_path):
    return str(tmp_path / "results")


# ---------------------------------------------------------------------------
# unit: save/restore across topologies (single process, 8-device CPU mesh)
# ---------------------------------------------------------------------------


def test_cross_topology_restore(tmp_path):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    assert len(devs) == 8
    mesh = Mesh(np.array(devs).reshape(4, 2), ("dp", "tp"))
    state = {
        "w": jax.device_put(
            jnp.arange(32, dtype=jnp.bfloat16).reshape(8, 4),
            NamedSharding(mesh, P("dp", "tp"))),
        "b": jax.device_put(jnp.full((4,), 2.5, jnp.float32),
                            NamedSharding(mesh, P(None))),
        "step": 7,
        "rng": np.arange(3),
    }
    d = str(tmp_path / "ck")
    ac.save_sharded(d, state)
    assert ac.is_sharded_checkpoint(d)
    assert ac.is_usable(d)

    # restore onto a transposed 2x4 mesh with different partition specs
    mesh2 = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
    like = {
        "w": jax.ShapeDtypeStruct(
            (8, 4), jnp.bfloat16,
            sharding=NamedSharding(mesh2, P("tp", "dp"))),
        "b": jax.ShapeDtypeStruct(
            (4,), jnp.float32, sharding=NamedSharding(mesh2, P("dp"))),
        "step": 0,
        "rng": np.zeros(3, dtype=np.int64),
    }
    out = ac.restore_sharded(d, like)
    assert out["step"] == 7
    np.testing.assert_array_equal(np.asarray(out["rng"]), np.arange(3))
    np.testing.assert_array_equal(
        np.asarray(out["w"]).astype(np.float32),
        np.arange(32, dtype=np.float32).reshape(8, 4))
    assert out["w"].dtype == jnp.bfloat16
    assert out["w"].sharding.spec == P("tp", "dp")
    np.testing.assert_array_equal(np.asarray(out["b"]),
                                  np.full((4,), 2.5, np.float32))


def test_structure_mismatch_rejected(tmp_path):
    import jax
    import jax.numpy as jnp

    d = str(tmp_path / "ck")
    ac.save_sharded(d, {"a": jnp.ones((4,)), "b": 1})
    with pytest.raises(ValueError, match="structure mismatch"):
        ac.restore_sharded(d, {"a": jnp.ones((4,))})
    with pytest.raises(ValueError, match="shape mismatch"):
        ac.restore_sharded(d, {"a": jnp.ones((5,)), "b": 0})


def test_incomplete_checkpoint_detected(tmp_path):
    import json

    import jax.numpy as jnp

    d = str(tmp_path / "ck")
    ac.save_sharded(d, {"a": jnp.ones((4,))})
    ipath = os.path.join(
        d, [f for f in os.listdir(d) if f.startswith("asv_index")][0])
    with open(ipath) as f:
        rec = json.load(f)
    rec["num_processes"] = 2  # pretend a second writer never finished
    with open(ipath, "w") as f:
        json.dump(rec, f)
    assert not ac.is_usable(d)


# ---------------------------------------------------------------------------
# integration: multi-process gang, worker kill, elastic resume
# ---------------------------------------------------------------------------


def _make_elastic_loop():
    # defined inside a factory so cloudpickle serializes it by value —
    # train workers cannot import the test module
    import os as os_mod

    def _elastic_loop(config):
        import jax
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ray_tpu import train as train_mod
        from ray_tpu.train import array_checkpoint as ac_mod

        devs = jax.devices()  # global: 2 procs x 2 devices
        mesh = Mesh(np.array(devs).reshape(len(devs)), ("dp",))
        # make_array_from_callback, not device_put: each process can only
        # materialize its addressable shards of a global sharding
        w0 = np.arange(32, dtype=np.float32).reshape(8, 4)
        state = {
            "w": jax.make_array_from_callback(
                (8, 4), NamedSharding(mesh, P("dp")), lambda idx: w0[idx]),
            "step": jax.make_array_from_callback(
                (), NamedSharding(mesh, P()),
                lambda idx: np.zeros((), np.int32)),
        }

        start = 0
        ckpt = train_mod.get_checkpoint()
        if ckpt is not None and ac_mod.is_sharded_checkpoint(ckpt):
            state = ac_mod.restore_sharded(ckpt, state)
            start = int(np.asarray(state["step"].addressable_shards[0].data))

        @jax.jit
        def update(s):
            return {"w": s["w"] * 2.0 + 1.0, "step": s["step"] + 1}

        rank = train_mod.get_context().get_world_rank()
        for i in range(start, 4):
            if i == 2 and rank == 1 and start == 0:
                # Simulated hardware loss, first attempt only. The kill is
                # deterministic because checkpoint reports are a gang
                # barrier: the step-2 report (i == 1) did not return on
                # THIS rank until every rank's shard was durable and the
                # controller registered the checkpoint
                # (session.report gang_commit + ack_commit), so reaching
                # this line proves step 2 is gang-committed and the
                # walk-back can only land there.
                train_mod.report({"step": i, "pre_crash": True})
                os_mod._exit(1)
            state = update(state)
            # local fingerprint: addressable shards only (no collective,
            # so a dead gang-mate cannot wedge the survivor in a psum)
            fp = float(sum(np.asarray(s.data).sum()
                           for s in state["w"].addressable_shards
                           if s.replica_id == 0))
            train_mod.report(
                {"step": i + 1, "fp": fp, "resumed_from": start,
                 "rank": rank},
                checkpoint=ac_mod.save_to_checkpoint(state))

    return _elastic_loop


def test_elastic_restore_bit_identical(storage):
    trainer = train.JaxTrainer(
        _make_elastic_loop(),
        backend_config=JaxConfig(
            distributed="on", platform="cpu",
            xla_flags="--xla_force_host_platform_device_count=2"),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path=storage, name="elastic",
            failure_config=FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 4
    # the retried run actually restored from the step-2 sharded
    # checkpoint rather than restarting from scratch
    assert result.metrics["resumed_from"] == 2
    # bit-identical resume: w_i = w_{i-1} * 2 + 1 from arange(32) — any
    # drift in the restored shards changes the fingerprint. The lead
    # (rank-0) fingerprint covers its addressable half of the dp-sharded
    # array: rows 0:4 (devices 0,1 of the 4-device mesh).
    w = np.arange(32, dtype=np.float32).reshape(8, 4)
    for _ in range(4):
        w = w * 2.0 + 1.0
    assert result.metrics["fp"] == pytest.approx(float(w[:4].sum()), abs=0.0)
