"""SLO & health plane: burn-rate alerting, deadman watchdogs, hang
diagnosis.

Three layers under test, bottom-up:

- the tsdb's windowed measurements (`increase`/`avg_over_time`/
  `max_over_time`/`histogram_quantile_over_time`) with monotonic-reset
  clamping, plus `# scrape_error` degradation tracking;
- the alert state machine (`util/slo.py`): multi-window entry, `for_s`
  pending hold, flap suppression while firing, resolution only when
  both windows clear — driven over synthetic series whose breach
  timestamps are known exactly, so assertions are arithmetic;
- the deadman watchdog (`_private/health.py`): a REAL blocked thread is
  detected, its stack captured into a `health.stalled` event, and the
  `health_loop_stalled` gauge feeds the SLO plane's deadman rule. The
  chaos row composes all of it end to end against a RecoveryLedger
  outage window and is gated N-of-N by tools/flake_gate.py.

Events-rotation tests pin the `RAY_TPU_EVENTS_MAX_BYTES` keep-last-K
contract: no JSON line is ever torn across generations and
`list_events()` merges rotated shards transparently.
"""

import glob
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from ray_tpu.util import slo as slo_mod
from ray_tpu.util import tsdb as tsdb_mod
from ray_tpu.util.events import list_events


def _db():
    # no prefix filter: synthetic series keep whatever name reads best
    return tsdb_mod.TSDB(prefixes=())


def _feed(db, rows, ts, source="test"):
    """Ingest exposition rows (a str or list of str) at an exact ts."""
    if isinstance(rows, str):
        rows = [rows]
    db.ingest("\n".join(rows) + "\n", source=source, ts=ts)


# ---------------------------------------------------------------------------
# tsdb windowed measurements
# ---------------------------------------------------------------------------


def test_increase_sums_deltas_and_clamps_resets():
    db = _db()
    # counter: 0 → 40 → 5 (daemon restart) → 25: growth is 40 + 20
    for i, v in enumerate((0, 40, 5, 25)):
        _feed(db, f"requests_total {v}", ts=100.0 + 10 * i)
    assert db.increase("requests_total", window_s=60.0) == \
        pytest.approx(60.0)
    # rate over the same window clamps at 0 across the reset pair
    assert db.rate("requests_total", window_s=12.0) == \
        pytest.approx(20 / 10)
    # single point: no delta to measure
    db2 = _db()
    _feed(db2, "requests_total 7", ts=100.0)
    assert db2.increase("requests_total") is None


def test_increase_window_cutoff():
    db = _db()
    for i, v in enumerate((0, 100, 110, 120)):
        _feed(db, f"c_total {v}", ts=100.0 + 30 * i)
    # window spans only the last two intervals (cutoff at last-60)
    assert db.increase("c_total", window_s=60.0) == pytest.approx(20.0)


def test_avg_and_max_over_time():
    db = _db()
    for i, v in enumerate((1.0, 3.0, 5.0, 11.0)):
        _feed(db, f"queue_depth {v}", ts=100.0 + 10 * i)
    # trailing 20 s window holds the last three points
    assert db.avg_over_time("queue_depth", window_s=20.0) == \
        pytest.approx((3 + 5 + 11) / 3)
    assert db.max_over_time("queue_depth", window_s=20.0) == \
        pytest.approx(11.0)
    # the whole history
    assert db.avg_over_time("queue_depth", window_s=1000.0) == \
        pytest.approx(5.0)
    assert db.avg_over_time("missing_series") is None
    assert db.max_over_time("missing_series") is None


def test_histogram_quantile_over_time_is_windowed():
    """Cumulative buckets remember every bad observation forever; the
    windowed quantile sees only what landed inside the window. An early
    burst of slow requests must stop dominating once the window has
    rolled past it."""
    db = _db()

    def rows(le_counts):
        return [f'lat_ms_bucket{{le="{le}"}} {c}'
                for le, c in le_counts]

    # scrape 1: 100 observations, all slow (≤ +Inf only)
    _feed(db, rows([("10", 0), ("100", 0), ("+Inf", 100)]), ts=100.0)
    # scrapes 2..3: 100 more observations, all fast (≤ 10)
    _feed(db, rows([("10", 50), ("100", 50), ("+Inf", 150)]), ts=160.0)
    _feed(db, rows([("10", 100), ("100", 100), ("+Inf", 200)]), ts=220.0)

    # cumulative p90 (rank 180 of 200) sits in the slow +Inf bucket
    cumulative = tsdb_mod.histogram_quantile(db, "lat_ms", 0.9)
    assert cumulative == pytest.approx(100.0)
    # windowed over the last 70 s: only fast observations landed there
    windowed = tsdb_mod.histogram_quantile_over_time(
        db, "lat_ms", 0.9, window_s=70.0)
    assert windowed is not None and windowed <= 10.0


def test_histogram_quantile_over_time_falls_back_cumulative():
    db = _db()
    _feed(db, ['lat_ms_bucket{le="10"} 3', 'lat_ms_bucket{le="+Inf"} 4'],
          ts=100.0)
    # one scrape: no window increase yet — cumulative estimate instead
    got = tsdb_mod.histogram_quantile_over_time(db, "lat_ms", 0.5)
    assert got == tsdb_mod.histogram_quantile(db, "lat_ms", 0.5)
    assert tsdb_mod.histogram_quantile_over_time(db, "nope", 0.9) is None


def test_scrape_error_tracked_and_cleared():
    db = _db()
    db.ingest('ok_metric 1\n# scrape_error source="engine" '
              'error="TypeError"\n', source="local")
    assert "local" in db.scrape_errors
    assert "engine" in db.scrape_errors["local"]
    assert db.snapshot()["scrape_errors"]["local"]
    # a clean scrape from the same source clears the degradation
    db.ingest("ok_metric 2\n", source="local")
    assert db.scrape_errors == {}


def test_registry_callback_failure_renders_scrape_error():
    """A throwing metrics callback degrades to a `# scrape_error`
    comment (the DEGRADED banner's trigger) instead of poisoning the
    whole exposition body."""
    from ray_tpu.util.metrics import _Registry

    reg = _Registry()
    reg.register_callback("boom", lambda: 1 / 0)
    text = reg.prometheus_text()
    assert '# scrape_error source="boom"' in text
    db = tsdb_mod.TSDB()
    db.ingest(text, source="local")
    assert "boom" in db.scrape_errors["local"]


# ---------------------------------------------------------------------------
# alert state machine
# ---------------------------------------------------------------------------


def _gauge_rule(**kw):
    kw.setdefault("fast_window_s", 10.0)
    kw.setdefault("slow_window_s", 40.0)
    return slo_mod.Rule(kw.pop("name", "test-queue"),
                        kw.pop("metric", "queue_depth"),
                        kw.pop("threshold", 5.0), **kw)


def _evaluator(db, rules, tmp_path, monkeypatch, source="SLO_TEST"):
    monkeypatch.setenv("RAY_TPU_EVENT_DIR", str(tmp_path / "events"))
    return slo_mod.AlertEvaluator(db, rules=rules,
                                  register_metrics=False,
                                  event_source=source)


def test_alert_pending_hold_then_firing_then_resolved(tmp_path,
                                                      monkeypatch):
    db = _db()
    ev = _evaluator(db, [_gauge_rule(for_s=10.0)], tmp_path, monkeypatch)

    # clean series: stays ok
    for i in range(9):
        _feed(db, "queue_depth 1", ts=100.0 + 5 * i)
        ev.evaluate(now=100.0 + 5 * i)
    [a] = ev.snapshot()["alerts"]
    assert a["state"] == "ok" and ev.snapshot()["transitions"] == {}

    # breach both windows → pending (for_s not yet served)
    for i in range(9, 18):
        _feed(db, "queue_depth 50", ts=100.0 + 5 * i)
    ev.evaluate(now=145.0)
    [a] = ev.snapshot()["alerts"]
    assert a["state"] == "pending" and a["firing_since"] is None

    # hold served → firing, with a structured ALERT_FIRING event
    ev.evaluate(now=156.0)
    [a] = ev.snapshot()["alerts"]
    assert a["state"] == "firing" and a["firing_since"] == 156.0
    fired = list_events(source="SLO_TEST", label="ALERT_FIRING")
    assert len(fired) == 1 and fired[0]["rule"] == "test-queue"

    # both windows clear → resolved (back to ok), ALERT_RESOLVED event
    for i in range(18, 30):
        _feed(db, "queue_depth 0", ts=100.0 + 5 * i)
    ev.evaluate(now=250.0)
    [a] = ev.snapshot()["alerts"]
    assert a["state"] == "ok" and a["resolved_ts"] == 250.0
    assert list_events(source="SLO_TEST", label="ALERT_RESOLVED")
    assert ev.snapshot()["transitions"] == {
        "test-queue:pending": 1, "test-queue:firing": 1,
        "test-queue:resolved": 1}


def test_alert_pending_retracts_without_firing(tmp_path, monkeypatch):
    """A blip shorter than for_s never fires — pending walks back to ok
    and no event is emitted."""
    db = _db()
    ev = _evaluator(db, [_gauge_rule(for_s=30.0)], tmp_path, monkeypatch,
                    source="SLO_BLIP")
    for i in range(12):
        _feed(db, "queue_depth 50", ts=100.0 + 5 * i)
    ev.evaluate(now=155.0)
    assert ev.snapshot()["alerts"][0]["state"] == "pending"
    for i in range(12, 24):
        _feed(db, "queue_depth 0", ts=100.0 + 5 * i)
    ev.evaluate(now=215.0)
    assert ev.snapshot()["alerts"][0]["state"] == "ok"
    assert list_events(source="SLO_BLIP", label="ALERT_FIRING") == []


def test_flap_suppression_fast_dip_keeps_firing(tmp_path, monkeypatch):
    """Multi-window resolution: once firing, a clear FAST window with a
    still-breaching slow window keeps the alert up (SRE Workbook ch.5 —
    the slow window is the flap suppressor)."""
    db = _db()
    rule = _gauge_rule(for_s=0.0)
    ev = _evaluator(db, [rule], tmp_path, monkeypatch, source="SLO_FLAP")
    for i in range(10):
        _feed(db, "queue_depth 50", ts=100.0 + 5 * i)
    ev.evaluate(now=145.0)
    assert ev.firing() == ["test-queue"]

    # a dip long enough to clear the fast(10s) window while the slow
    # (40s) window is still dominated by the breach plateau
    for ts in (150.0, 155.0, 160.0):
        _feed(db, "queue_depth 0", ts=ts)
    [a] = ev.evaluate(now=160.0)
    assert a["state"] == "firing"
    assert a["fast_value"] < rule.threshold < a["slow_value"]

    # plateau rolls out of the slow window too → resolved
    for i in range(13, 22):
        _feed(db, "queue_depth 0", ts=100.0 + 5 * i)
    [a] = ev.evaluate(now=205.0)
    assert a["state"] == "ok" and a["resolved_ts"] == 205.0
    # exactly one firing/resolved pair despite the dip
    t = ev.snapshot()["transitions"]
    assert t["test-queue:firing"] == 1 and t["test-queue:resolved"] == 1


def test_no_false_positives_on_clean_series(tmp_path, monkeypatch):
    """The default serve rule pack over realistic healthy series: many
    evaluations, zero transitions, zero events. Absent series never
    breach either."""
    db = tsdb_mod.TSDB()
    ev = _evaluator(db, None, tmp_path, monkeypatch, source="SLO_CLEAN")
    for i in range(40):
        ts = 100.0 + 2 * i
        _feed(db, [
            f'serve_ttft_ms_bucket{{le="50"}} {10 * i}',
            f'serve_ttft_ms_bucket{{le="+Inf"}} {10 * i}',
            f'serve_tpot_ms_bucket{{le="10"}} {40 * i}',
            f'serve_tpot_ms_bucket{{le="+Inf"}} {40 * i}',
            "serve_llm_waiting_seqs 2",
            "serve_llm_kv_page_utilization 0.41",
            f'object_store_job_quota_rejects{{job="j"}} 0',
            "ray_tpu_reconstruction_failures_total 0",
            'health_loop_stalled{loop="pump"} 0',
        ], ts=ts, source="local")
        ev.evaluate(now=ts)
    snap = ev.snapshot()
    assert snap["evaluations"] == 40
    assert snap["firing"] == []
    assert all(a["state"] == "ok" for a in snap["alerts"])
    assert snap["transitions"] == {}
    assert list_events(source="SLO_CLEAN") == []


def test_burn_rate_rule(tmp_path, monkeypatch):
    """burn_rate = (err_increase/total_increase)/budget: burning 14×
    the 1% budget breaches a 10× threshold; burning 0.5× doesn't."""
    db = _db()
    rule = slo_mod.Rule(
        "err-budget", "errors_total", 10.0, kind="burn_rate",
        total_metric="requests_total", budget=0.01,
        fast_window_s=30.0, slow_window_s=30.0)
    ev = _evaluator(db, [rule], tmp_path, monkeypatch, source="SLO_BURN")
    # 14 errors / 100 requests in-window → ratio 0.14 → burn 14 > 10
    _feed(db, ["errors_total 0", "requests_total 0"], ts=100.0)
    _feed(db, ["errors_total 14", "requests_total 100"], ts=110.0)
    [a] = ev.evaluate(now=110.0)
    assert a["fast_value"] == pytest.approx(14.0)
    assert a["state"] == "firing"

    db2 = _db()
    ev2 = _evaluator(db2, [rule], tmp_path, monkeypatch,
                     source="SLO_BURN2")
    _feed(db2, ["errors_total 0", "requests_total 0"], ts=100.0)
    _feed(db2, ["errors_total 1", "requests_total 200"], ts=110.0)
    [a] = ev2.evaluate(now=110.0)
    assert a["fast_value"] == pytest.approx(0.5)
    assert a["state"] == "ok"


def test_alert_metrics_text_rows(tmp_path, monkeypatch):
    db = _db()
    ev = _evaluator(db, [_gauge_rule(for_s=0.0)], tmp_path, monkeypatch,
                    source="SLO_ROWS")
    for i in range(10):
        _feed(db, "queue_depth 50", ts=100.0 + 5 * i)
    ev.evaluate(now=145.0)
    text = ev.metrics_text()
    assert 'alerts_firing{rule="test-queue"} 1' in text
    assert 'alert_transitions_total{rule="test-queue",to="firing"} 1' \
        in text
    # the rows round-trip through the tsdb's default prefix filter
    db2 = tsdb_mod.TSDB()
    db2.ingest(text, source="local")
    assert db2.latest("alerts_firing", {"rule": "test-queue"}) == 1.0


def test_rule_validation():
    with pytest.raises(ValueError, match="unknown rule kind"):
        slo_mod.Rule("r", "m", 1.0, kind="percentile")
    with pytest.raises(ValueError, match="unknown rule op"):
        slo_mod.Rule("r", "m", 1.0, op=">=")
    with pytest.raises(ValueError, match="total_metric"):
        slo_mod.Rule("r", "m", 1.0, kind="burn_rate")


# ---------------------------------------------------------------------------
# events rotation
# ---------------------------------------------------------------------------


def test_events_rotation_keeps_k_whole_generations(tmp_path,
                                                   monkeypatch):
    from ray_tpu.util import events

    monkeypatch.setenv("RAY_TPU_EVENT_DIR", str(tmp_path))
    monkeypatch.setenv("RAY_TPU_EVENTS_MAX_BYTES", "2048")
    monkeypatch.setenv("RAY_TPU_EVENTS_KEEP", "3")
    n = 200
    for i in range(n):
        events.report("ROT", "INFO", "TICK", f"event {i:04d}", seq=i,
                      pad="x" * 64)
    shards = sorted(glob.glob(str(tmp_path / "event_ROT_*.jsonl")))
    # the cap forced rotation; at most keep(3) rotated + 1 active file
    assert 2 <= len(shards) <= 4
    for fn in shards:
        assert os.path.getsize(fn) <= 2048 + 512  # cap + one line slack
        with open(fn) as f:
            for line in f:
                ev = json.loads(line)  # every line is whole JSON
                assert ev["label"] == "TICK"
    # list_events merges the generations, oldest first; the newest
    # keep-K generations survive in order with no torn/duplicated seq
    merged = list_events(source="ROT")
    seqs = [e["seq"] for e in merged]
    assert seqs == list(range(n - len(seqs), n))
    assert len(merged) >= 20  # at least ~2 generations survived


def test_events_rotation_concurrent_writers_never_tear(tmp_path,
                                                       monkeypatch):
    """8 threads × 100 events through a 1 KiB cap: rotation happens
    constantly, yet every surviving line parses — the write+rotate
    critical section admits no torn JSON."""
    from ray_tpu.util import events

    monkeypatch.setenv("RAY_TPU_EVENT_DIR", str(tmp_path))
    monkeypatch.setenv("RAY_TPU_EVENTS_MAX_BYTES", "1024")

    def spam(k):
        for i in range(100):
            events.report("TORN", "INFO", "SPAM", f"w{k} e{i}",
                          w=k, i=i, pad="y" * 32)

    threads = [threading.Thread(target=spam, args=(k,))
               for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for fn in glob.glob(str(tmp_path / "event_TORN_*.jsonl")):
        with open(fn) as f:
            for line in f:
                assert json.loads(line)["label"] == "SPAM"


def test_events_unbounded_without_cap(tmp_path, monkeypatch):
    from ray_tpu.util import events

    monkeypatch.setenv("RAY_TPU_EVENT_DIR", str(tmp_path))
    monkeypatch.delenv("RAY_TPU_EVENTS_MAX_BYTES", raising=False)
    for i in range(50):
        events.report("NOCAP", "INFO", "TICK", "m", seq=i)
    shards = glob.glob(str(tmp_path / "event_NOCAP_*.jsonl"))
    assert len(shards) == 1  # no rotation without the cap
    assert [e["seq"] for e in list_events(source="NOCAP")] == \
        list(range(50))


# ---------------------------------------------------------------------------
# deadman watchdog
# ---------------------------------------------------------------------------


def _quiesce_singleton_watchdog():
    """Earlier tests (or an engine) may have started the process-wide
    watchdog; park it so synchronous check_once() assertions can't race
    its sweep, and drop any stalled flags stray probes may still carry
    (they would feed the deadman gauge this suite asserts on)."""
    from ray_tpu._private import health

    with health._lock:
        wd, health._watchdog_singleton = health._watchdog_singleton, None
    if wd is not None:
        wd.stop()
    for p in health.probes():
        p.stalled = False


def test_watchdog_detects_stall_and_recovery(tmp_path, monkeypatch):
    """A REAL thread blocks with work pending: the deadman flags it,
    captures the culprit stack (naming the blocking call), emits
    `health.stalled`, and emits `health.recovered` at the next beat."""
    from ray_tpu._private import health

    monkeypatch.setenv("RAY_TPU_EVENT_DIR", str(tmp_path))
    _quiesce_singleton_watchdog()
    gate = threading.Event()
    gate.set()
    stop = threading.Event()
    probe = health.watch_loop("wd_test_loop", backlog_fn=lambda: 3)

    def loop():
        while not stop.is_set():
            probe.beat()
            gate.wait()          # the injected wedge parks here
            time.sleep(0.005)

    t = threading.Thread(target=loop, name="wd-test-loop", daemon=True)
    t.start()
    wd = health.Watchdog(source="WD_TEST", stall_s=0.3, interval_s=0.05)
    try:
        wd.check_once()                       # baseline sighting
        time.sleep(0.1)
        assert "wd_test_loop" not in wd.check_once()  # beating: fine
        gate.clear()                          # wedge the loop
        deadline = time.time() + 10.0
        while not probe.stalled and time.time() < deadline:
            time.sleep(0.05)
            wd.check_once()
        assert probe.stalled
        assert probe.stalled and probe.stalls_total == 1
        [ev] = list_events(source="WD_TEST", label="health.stalled")
        assert ev["loop"] == "wd_test_loop" and ev["backlog"] == 3.0
        assert ev["frozen_s"] >= 0.3
        assert "gate.wait()" in ev["stack"]   # the culprit line itself
        # the gauge the deadman alert rule watches
        assert 'health_loop_stalled{loop="wd_test_loop"} 1' \
            in health.metrics_text()

        gate.set()                            # un-wedge
        deadline = time.time() + 10.0
        while probe.stalled and time.time() < deadline:
            time.sleep(0.05)
            wd.check_once()
        assert not probe.stalled
        [rec] = list_events(source="WD_TEST", label="health.recovered")
        assert rec["loop"] == "wd_test_loop" and rec["stalled_s"] > 0
    finally:
        stop.set()
        gate.set()
        t.join(timeout=5)
        health.unwatch_loop("wd_test_loop")


def test_watchdog_idle_loop_is_not_stalled():
    """Frozen counter + EMPTY backlog = a legitimately quiet loop."""
    from ray_tpu._private import health

    _quiesce_singleton_watchdog()
    probe = health.watch_loop("idle_loop", backlog_fn=lambda: 0)
    probe.beat()
    wd = health.Watchdog(source="WD_IDLE", stall_s=0.1)
    try:
        wd.check_once(now=1000.0)
        wd.check_once(now=2000.0)   # frozen forever, but idle
        assert not probe.stalled
        # no backlog_fn at all behaves the same
        probe2 = health.watch_loop("idle_loop2")
        probe2.beat()
        wd.check_once(now=2000.0)
        wd.check_once(now=3000.0)
        assert not probe2.stalled
    finally:
        health.unwatch_loop("idle_loop")
        health.unwatch_loop("idle_loop2")


def test_watchdog_synthetic_clock():
    """check_once(now=) drives the deadman rule without real waiting:
    the stall threshold is a pure monotonic-time comparison."""
    from ray_tpu._private import health

    _quiesce_singleton_watchdog()
    probe = health.watch_loop("clock_loop", backlog_fn=lambda: 1)
    probe.beat()
    wd = health.Watchdog(source="WD_CLOCK", stall_s=5.0)
    try:
        wd.check_once(now=100.0)
        wd.check_once(now=104.9)                 # under stall_s
        assert not probe.stalled
        assert "clock_loop" in wd.check_once(now=105.1)
        assert "clock_loop" not in wd.check_once(now=200.0)  # once only
        assert probe.stalls_total == 1
        probe.beat()                             # progress resumes
        wd.check_once(now=201.0)
        assert not probe.stalled
    finally:
        health.unwatch_loop("clock_loop")


def test_dump_stacks_annotates_probes_and_locks():
    """dump_stacks() reports every thread with a formatted stack; the
    thread driving a probe is annotated with its loop name, and — with
    lockdep armed (this suite runs under the conftest gate) — a thread
    parked holding a tracked lock shows it in held_locks."""
    from ray_tpu._private import health, lockdep

    _quiesce_singleton_watchdog()
    probe = health.watch_loop("dump_loop")
    probe.beat()   # binds this thread's ident
    lk = threading.Lock()
    holding = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            holding.set()
            release.wait()

    t = threading.Thread(target=holder, name="lock-holder", daemon=True)
    t.start()
    assert holding.wait(timeout=10)
    try:
        threads = health.dump_stacks()
        by_ident = {e["ident"]: e for e in threads}
        me = by_ident[threading.get_ident()]
        assert me["loop"] == "dump_loop"
        assert "dump_stacks" in me["stack"] or "test_dump" in me["stack"]
        holder_entry = by_ident[t.ident]
        assert holder_entry["name"] == "lock-holder"
        assert "release.wait()" in holder_entry["stack"]
        if lockdep.enabled():   # conftest arms it for this suite
            assert any("Lock@" in n for n in
                       holder_entry.get("held_locks", [])), holder_entry
    finally:
        release.set()
        t.join(timeout=5)
        health.unwatch_loop("dump_loop")


# ---------------------------------------------------------------------------
# the chaos row: data stall → stalled event + alert bracketing the
# RecoveryLedger outage window
# ---------------------------------------------------------------------------


def test_chaos_data_stall_alert_brackets_outage(tmp_path, monkeypatch):
    """End-to-end chaos proof, compressed: a driver-shaped loop steps at
    ~50 Hz recording StepStats-shaped completions; an injected data
    stall blocks its feed. The deadman watchdog flags the frozen loop
    (capturing the wedged stack), the `health_loop_stalled` gauge rides
    a scrape into the tsdb, and the SLO deadman rule fires — then
    resolves once stepping resumes. The firing timestamp must land
    inside the RecoveryLedger's computed outage window for the same
    fault, and resolution must follow recovery:
    fault_ts <= firing_ts <= recovered_ts <= resolved_ts.

    Determinism-gated 5-of-5 by:
    python tools/flake_gate.py -n 5 \
        tests/test_slo.py::test_chaos_data_stall_alert_brackets_outage
    """
    from ray_tpu._private import health
    from ray_tpu.soak.ledger import RecoveryLedger

    monkeypatch.setenv("RAY_TPU_EVENT_DIR", str(tmp_path / "events"))
    _quiesce_singleton_watchdog()

    records = []
    stall_gate = threading.Event()
    stall_gate.set()
    stop = threading.Event()
    probe = health.watch_loop("soak_driver_chaos", backlog_fn=lambda: 1)
    step = [0]

    def drive():
        while not stop.is_set():
            probe.beat()
            stall_gate.wait()        # the data plane: stall parks here
            time.sleep(0.02)
            records.append({"step": step[0], "ts": time.time(),
                            "total_ms": 20.0})
            step[0] += 1

    db = tsdb_mod.TSDB()
    evaluator = slo_mod.AlertEvaluator(
        db, rules=[slo_mod.deadman_rule(fast_window_s=0.5,
                                        slow_window_s=0.5)],
        register_metrics=False, event_source="SLO_CHAOS")
    wd = health.Watchdog(source="HEALTH_CHAOS", stall_s=0.4,
                         interval_s=0.05)

    def tick():
        # one observability beat: watchdog sweep → scrape → evaluate
        wd.check_once()
        db.ingest(health.metrics_text(), source="local")
        evaluator.evaluate()

    t = threading.Thread(target=drive, name="soak-drive-chaos",
                         daemon=True)
    t.start()
    try:
        # healthy warmup: the pre-fault rate window the ledger needs,
        # and the zero-false-positive bar for a clean run
        end = time.time() + 1.2
        while time.time() < end:
            tick()
            time.sleep(0.04)
        assert evaluator.firing() == []
        assert not probe.stalled

        fault_ts = time.time()
        stall_gate.clear()                       # ← data_stall fires
        firing_ts = None
        deadline = time.time() + 15.0
        while firing_ts is None and time.time() < deadline:
            tick()
            if evaluator.firing():
                firing_ts = time.time()
            time.sleep(0.04)
        assert firing_ts is not None, "deadman alert never fired"
        [sev] = list_events(source="HEALTH_CHAOS",
                            label="health.stalled")
        assert sev["loop"] == "soak_driver_chaos"
        assert "stall_gate.wait()" in sev["stack"]   # captured culprit

        time.sleep(0.2)                          # hold the outage open
        recovered_ts = time.time()
        stall_gate.set()                         # ← stall ends
        resolved_ts = None
        deadline = time.time() + 15.0
        while resolved_ts is None and time.time() < deadline:
            tick()
            snap = evaluator.snapshot()["alerts"][0]
            if snap["state"] == "ok" and snap["resolved_ts"]:
                resolved_ts = snap["resolved_ts"]
            time.sleep(0.04)
        assert resolved_ts is not None, "alert never resolved"
        assert list_events(source="HEALTH_CHAOS",
                           label="health.recovered")
    finally:
        stop.set()
        stall_gate.set()
        t.join(timeout=10)
        health.unwatch_loop("soak_driver_chaos")

    # the ledger's view of the same outage, from the step record ring
    led = RecoveryLedger(rate_threshold=0.9, rate_window=4)
    led.add_fault("data_stall@train", fault_ts)
    [m] = led.compute_mttr(records)
    assert m["degraded"] and m["recovered"]
    outage_end = fault_ts + m["mttr_s"]
    # the alert bracketed the ledger's outage window
    assert fault_ts <= firing_ts <= recovered_ts
    assert firing_ts <= outage_end
    assert resolved_ts >= recovered_ts
    # exactly one firing/resolved pair — no flapping across recovery
    trans = evaluator.snapshot()["transitions"]
    assert trans["loop-stalled:firing"] == 1
    assert trans["loop-stalled:resolved"] == 1


# ---------------------------------------------------------------------------
# clean closed-loop serve run: zero alerts, pump probe registered
# ---------------------------------------------------------------------------


def test_clean_serve_run_fires_zero_alerts(tmp_path, monkeypatch):
    """A healthy closed-loop LLM engine driven under the full alert
    plane (default serve rules + deadman, scraping the live registry):
    zero transitions, zero events — the acceptance bar that the rule
    pack is quiet on a clean system. Also pins that the engine pump
    registers its loop probe on start() and retires it on stop()."""
    from ray_tpu._private import health
    from ray_tpu.serve.llm import EngineConfig, LLMEngine
    from ray_tpu.util import request_recorder as rr

    monkeypatch.setenv("RAY_TPU_EVENT_DIR", str(tmp_path / "events"))
    rr.clear()
    db = tsdb_mod.TSDB()
    evaluator = slo_mod.AlertEvaluator(db, register_metrics=False,
                                       event_source="SLO_SERVE")
    eng = LLMEngine(model="llama",
                    engine_config=EngineConfig(batch_buckets=(1, 2),
                                               prefill_buckets=(8,)),
                    seed=0)
    eng.warmup()
    eng.start()
    try:
        assert any(p.name.startswith("llm_engine_pump_")
                   for p in health.probes())
        end = time.time() + 1.5
        while time.time() < end:
            req = eng.submit([3, 4, 5], 4)
            req.result(timeout=60)
            tsdb_mod.scrape_local(db)
            evaluator.evaluate()
        eng.quiesce(timeout=60)
    finally:
        assert eng.shutdown() == 0
    snap = evaluator.snapshot()
    assert snap["firing"] == []
    assert snap["transitions"] == {}
    assert all(a["state"] == "ok" for a in snap["alerts"])
    assert list_events(source="SLO_SERVE") == []
    # stop() retired the pump probe
    assert not any(p.name.startswith("llm_engine_pump_")
                   for p in health.probes())


# ---------------------------------------------------------------------------
# operator CLI against a live cluster
# ---------------------------------------------------------------------------


def test_cli_stack_and_alerts_against_live_cluster(tmp_path):
    """`ray_tpu stack` aggregates the dump_stacks RPC across a live
    cluster — even a one-node cluster yields ≥3 distinct processes
    (gcs, raylet, cli) — and `ray_tpu alerts` evaluates the default
    rule pack over live scrapes: a healthy idle cluster reports
    0 firing. Isolated CLI state file, same idiom as
    test_observability."""
    env = dict(os.environ)
    env.pop("RAY_TPU_ADDRESS", None)
    env["RAY_TPU_CLI_STATE_FILE"] = str(tmp_path / "cli_node.json")

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "start", "--head",
         "--port", "0", "--resources", '{"CPU": 2.0}'],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stderr
    with open(env["RAY_TPU_CLI_STATE_FILE"]) as f:
        gcs_addr = json.load(f)["gcs_addr"]
    try:
        stack = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "stack", "--json",
             "--address", gcs_addr],
            capture_output=True, text=True, env=env, timeout=300)
        assert stack.returncode == 0, stack.stderr
        reports = [r for r in json.loads(stack.stdout)
                   if "error" not in r]
        assert len({r["pid"] for r in reports}) >= 3
        assert {"gcs", "raylet", "cli"} <= {r["role"] for r in reports}
        # every process report carries real formatted thread stacks
        for r in reports:
            assert r["threads"] and all(t["stack"] for t in r["threads"])

        text = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "stack",
             "--address", gcs_addr],
            capture_output=True, text=True, env=env, timeout=300)
        assert text.returncode == 0, text.stderr
        assert "==== gcs" in text.stdout
        assert "==== raylet" in text.stdout
        assert "processes," in text.stdout  # summary line

        alerts = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "alerts",
             "--scrapes", "2", "--interval", "0.2",
             "--address", gcs_addr],
            capture_output=True, text=True, env=env, timeout=300)
        assert alerts.returncode == 0, alerts.stderr
        assert "0 firing" in alerts.stdout
        assert "DEGRADED" not in alerts.stdout
    finally:
        subprocess.run([sys.executable, "-m", "ray_tpu", "stop"],
                       capture_output=True, text=True, env=env,
                       timeout=60)
