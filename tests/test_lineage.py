"""Lineage reconstruction + distributed primary-copy pinning tests.

Reference surface: `src/ray/core_worker/task_manager.h:208,269` (lineage
+ resubmit), `object_recovery_manager.h:41`, `reference_count.h:61`, and
the raylet's primary-copy pinning (`local_object_manager.h:41`).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.node import Cluster


def test_pin_prevents_eviction_of_referenced_objects():
    """An owned, referenced plasma object survives store pressure that
    evicts unreferenced ones."""
    ray_tpu.init(num_cpus=2, object_store_memory=32 * 1024 * 1024)
    try:
        keep = ray_tpu.put(np.arange(2_000_000, dtype=np.uint8))  # ~2MB
        time.sleep(0.3)  # let the pin RPC land
        # pressure: 30MB of filler whose refs die immediately
        for i in range(15):
            ray_tpu.put(np.full(2_000_000, i, np.uint8))
        # the pinned object must still be readable
        out = ray_tpu.get(keep, timeout=10)
        assert out[12345] == np.arange(2_000_000, dtype=np.uint8)[12345]
    finally:
        ray_tpu.shutdown()


def test_unpin_after_ref_drop_allows_eviction():
    """Dropping the last ref unpins: the store can then reclaim the
    space under pressure instead of erroring."""
    ray_tpu.init(num_cpus=2, object_store_memory=32 * 1024 * 1024)
    try:
        refs = [ray_tpu.put(np.full(6_000_000, i, np.uint8))
                for i in range(4)]  # ~24MB pinned
        time.sleep(0.3)
        del refs  # unpin all
        time.sleep(0.5)
        # must fit: requires eviction of the unpinned objects
        big = ray_tpu.put(np.full(20_000_000, 7, np.uint8))
        assert ray_tpu.get(big, timeout=10)[0] == 7
    finally:
        ray_tpu.shutdown()


@pytest.fixture
def two_node_cluster():
    cluster = Cluster()
    cluster.add_node({"CPU": 2.0})  # head / driver side
    worker_node = cluster.add_node({"CPU": 2.0, "scratch": 1.0})
    ray_tpu.init(address=cluster.gcs_addr)
    yield cluster, worker_node
    ray_tpu.shutdown()
    cluster.shutdown()


def test_lineage_reconstruction_after_node_death(two_node_cluster):
    """Kill the node holding a task's plasma return: get() on the SAME
    ref re-executes the task on a surviving node and returns the value
    (soft node affinity lets the re-execution relocate)."""
    cluster, worker_node = two_node_cluster

    affinity = ray_tpu.NodeAffinitySchedulingStrategy(
        worker_node.node_id_hex, soft=True)

    @ray_tpu.remote(scheduling_strategy=affinity)
    def produce():
        return np.full(500_000, 42, np.uint8)  # plasma-sized

    ref = produce.remote()
    # wait, don't get — a get would localize a driver-side copy and the
    # kill below wouldn't actually lose the object
    ready, _ = ray_tpu.wait([ref], timeout=60)
    assert ready

    # kill the node that holds the only copy
    cluster.remove_node(worker_node)
    time.sleep(1.0)

    out = ray_tpu.get(ref, timeout=120)
    assert out[0] == 42 and out.shape == (500_000,)


def test_lineage_reconstruction_recovers_value():
    """Same-node recovery: object evicted/destroyed behind the owner's
    back is re-created by re-executing its task, exactly once per loss."""
    cluster = Cluster()
    cluster.add_node({"CPU": 4.0})
    ray_tpu.init(address=cluster.gcs_addr)
    try:
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

            def get(self):
                return self.n

        # The counter must SURVIVE the victim-node kill below, so it is
        # created before the victim exists (the scheduler legitimately
        # tiebreaks equal nodes at random — r5 — and must not be
        # assumed to avoid the victim).
        counter = Counter.options(name="exec_counter").remote()
        ray_tpu.get(counter.bump.remote())  # ensure alive
        ray_tpu.get(counter.bump.remote())
        victim = cluster.add_node({"CPU": 2.0, "scratch": 1.0})

        @ray_tpu.remote(resources={"scratch": 1.0}, num_cpus=0,
                        scheduling_strategy="SPREAD")
        def produce():
            c = ray_tpu.get_actor("exec_counter")
            ray_tpu.get(c.bump.remote())
            return np.full(400_000, 9, np.uint8)

        ref = produce.remote()
        # wait (not get!) — get would localize a second copy onto the
        # driver's node and defeat the loss scenario
        ready, _ = ray_tpu.wait([ref], timeout=60)
        assert ready
        before = ray_tpu.get(counter.get.remote())

        cluster.remove_node(victim)  # destroy the only copy
        time.sleep(1.0)

        # ...but produce's spec requires "scratch", which died with the
        # node — bring a fresh scratch-capable node so re-execution can
        # schedule (elastic recovery: replacement capacity arrives)
        cluster.add_node({"CPU": 2.0, "scratch": 1.0})
        time.sleep(1.0)

        out = ray_tpu.get(ref, timeout=120)
        assert out[0] == 9 and out.shape == (400_000,)
        after = ray_tpu.get(counter.get.remote())
        assert after == before + 1, \
            f"expected exactly one re-execution, got {after - before}"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
