"""serve.llm tests: paged KV-cache accounting, decode-path math,
continuous batching on the compile cache, streaming, deadlines, and the
full Serve integration.

The load-bearing properties:
  * page accounting is exact — leaks fail loudly at quiesce;
  * continuous batching (join/leave) produces the SAME tokens as
    one-at-a-time greedy decoding (iteration-level scheduling must not
    change the math);
  * steady-state serving never retraces (`parallel.cache_stats()`).
"""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu


# ---------------------------------------------------------------------------
# paged KV-cache: allocation accounting (no jax, no cluster)
# ---------------------------------------------------------------------------


def _cache(**kw):
    from ray_tpu.serve.llm import PagedKVCache
    base = dict(num_pages=8, n_layer=2, block_size=4, n_kv_head=2,
                head_dim=4)
    base.update(kw)
    return PagedKVCache(**base)


def test_page_alloc_free_roundtrip():
    kv = _cache()
    owner = object()
    assert kv.free_pages == 8 and kv.live_pages == 0
    pages = kv.alloc(3, owner)
    assert len(pages) == 3 and len(set(pages)) == 3
    assert kv.free_pages == 5 and kv.live_pages == 3
    assert abs(kv.utilization() - 3 / 8) < 1e-9
    kv.free(pages, owner)
    assert kv.free_pages == 8 and kv.live_pages == 0
    kv.assert_quiesced()
    assert kv.close() == 0


def test_page_double_free_and_foreign_free_raise():
    from ray_tpu.serve.llm import KVCacheError
    kv = _cache()
    a, b = object(), object()
    pa = kv.alloc(2, a)
    kv.alloc(2, b)
    with pytest.raises(KVCacheError):
        kv.free(pa, b)  # foreign owner
    kv.free(pa, a)
    with pytest.raises(KVCacheError):
        kv.free(pa, a)  # double free
    # nothing was partially freed by the failing calls
    assert kv.live_pages == 2


def test_page_exhaustion_is_atomic():
    from ray_tpu.serve.llm import OutOfPagesError
    kv = _cache(num_pages=4)
    kv.alloc(3, "x")
    with pytest.raises(OutOfPagesError):
        kv.alloc(2, "y")
    # the failed alloc took nothing
    assert kv.free_pages == 1
    assert kv.pages_for_tokens(1) == 1
    assert kv.pages_for_tokens(4) == 1
    assert kv.pages_for_tokens(5) == 2


def test_leak_detected_at_quiesce():
    from ray_tpu.serve.llm import KVCacheError
    kv = _cache()
    kv.alloc(1, "leaker")
    with pytest.raises(KVCacheError, match="leak"):
        kv.assert_quiesced()
    assert kv.close() == 1  # close reports the leak


def test_append_and_prefill_layout():
    kv = _cache(num_pages=4, n_layer=2, block_size=4, n_kv_head=2,
                head_dim=3)
    pages = kv.alloc(2, "s")
    rng = np.random.default_rng(0)
    k_seq = rng.normal(size=(6, 2, 2, 3)).astype(np.float32)
    v_seq = rng.normal(size=(6, 2, 2, 3)).astype(np.float32)
    kv.write_prefill(pages, k_seq, v_seq, 6)
    # token t lives at page[t // block], offset t % block
    for t in range(6):
        page, off = pages[t // 4], t % 4
        np.testing.assert_array_equal(kv.k_pages[page, :, off], k_seq[t])
        np.testing.assert_array_equal(kv.v_pages[page, :, off], v_seq[t])
    # append one more token at position 6
    k7 = rng.normal(size=(2, 2, 3)).astype(np.float32)
    v7 = rng.normal(size=(2, 2, 3)).astype(np.float32)
    kv.append(pages, 6, k7, v7)
    np.testing.assert_array_equal(kv.k_pages[pages[1], :, 2], k7)
    np.testing.assert_array_equal(kv.v_pages[pages[1], :, 2], v7)


def test_shm_arena_create_and_reclaim():
    """The arena is one sealed shm object; `reclaim_arena` force-deletes
    it by id from any process attached to the store (dead-replica
    path)."""
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import ObjectStore
    from ray_tpu.serve.llm import reclaim_arena

    name = f"/ray_tpu_test_llmkv_{os.getpid()}"
    store = ObjectStore.create(name, capacity=16 * 1024 * 1024,
                               table_size=256)
    try:
        kv = _cache(store=store)
        hex_id = kv.arena_id_hex
        assert hex_id is not None
        assert store.contains(ObjectID.from_hex(hex_id))
        # the arena view really is shm-backed
        kv.k_pages[0, 0, 0, 0, 0] = 7.0
        assert kv.arena_nbytes > 0
        # reclaim-by-id despite the creator's live reference
        assert reclaim_arena(hex_id, store=store)
        assert not store.contains(ObjectID.from_hex(hex_id))
        assert not reclaim_arena(hex_id, store=store)  # already gone
        kv.close()
    finally:
        store.destroy()


# ---------------------------------------------------------------------------
# engine: decode math + continuous batching (jax cpu, no cluster)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def llama_engine():
    from ray_tpu.serve.llm import EngineConfig, LLMEngine
    eng = LLMEngine(model="llama",
                    engine_config=EngineConfig(
                        batch_buckets=(1, 2, 4), prefill_buckets=(8, 16)),
                    seed=0)
    eng.warmup()
    yield eng
    assert eng.shutdown() == 0  # zero leaked pages at teardown


def _reference_greedy(engine, prompt, max_new):
    """One-at-a-time greedy over the model's FULL forward pass — the
    ground truth continuous batching must reproduce."""
    import jax.numpy as jnp
    mod = engine._mod
    cfg = engine.model_cfg
    net = (mod.Llama if engine.model_name == "llama" else mod.GPT)(cfg)
    toks = list(prompt)
    out = []
    for _ in range(max_new):
        logits = net.apply(engine.params,
                           jnp.asarray([toks], jnp.int32))
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
    return out


def test_continuous_batching_matches_one_at_a_time(llama_engine):
    """Requests of different lengths joining and leaving the decode
    batch mid-flight generate exactly the same tokens as sequential
    full-forward greedy decoding."""
    eng = llama_engine
    prompts = [[5, 9, 3], [7], [1, 2, 3, 4, 5, 6, 7, 8], [11, 13]]
    new = [6, 9, 3, 7]  # different lengths -> staggered leave/join
    reqs = [eng.submit(p, n) for p, n in zip(prompts, new)]
    eng.run_until_idle()
    for p, n, r in zip(prompts, new, reqs):
        got = r.result(timeout=30)
        assert got == _reference_greedy(eng, p, n), (p, n)
        assert r.finish_reason == "length"
    eng.quiesce()


def test_no_retrace_in_steady_state(llama_engine):
    """After warmup every bucketed shape is an executable-cache hit:
    zero retraces AND zero new misses across a steady-state burst."""
    from ray_tpu import parallel
    eng = llama_engine
    # populate every bucket once (shapes seen -> compiled)
    reqs = [eng.submit([3 + i], 4) for i in range(4)]
    eng.run_until_idle()
    [r.result(timeout=30) for r in reqs]
    before = parallel.cache_stats()
    reqs = [eng.submit([i + 1, i + 2], 5) for i in range(4)]
    eng.run_until_idle()
    [r.result(timeout=30) for r in reqs]
    after = parallel.cache_stats()
    assert after["retraces"] == before["retraces"]
    assert after["misses"] == before["misses"]
    assert after["hits"] > before["hits"]
    eng.quiesce()


def test_streaming_order_and_indices(llama_engine):
    eng = llama_engine
    req = eng.submit([5, 9, 3], 6)
    eng.run_until_idle()
    streamed = list(req.stream(timeout=30))
    assert streamed == req.result(timeout=5)
    assert len(streamed) == 6


def test_pump_thread_and_queueing_past_capacity(llama_engine):
    """More concurrent requests than max_running: the overflow waits on
    the queue and completes as pages free up; zero pages live after."""
    eng = llama_engine
    eng.start()
    try:
        reqs = [eng.submit([2 + (i % 5)], 5) for i in range(10)]
        outs = [r.result(timeout=60) for r in reqs]
        assert all(len(o) == 5 for o in outs)
        # same prompt -> same tokens, regardless of batch placement
        assert outs[0] == outs[5]
        eng.quiesce()
        assert eng.metrics()["kv_pages_live"] == 0
    finally:
        eng.stop()


def test_engine_deadline_shed(llama_engine):
    """A queued request whose deadline passed before admission is failed
    with a timeout and counted — never prefilled."""
    eng = llama_engine
    req = eng.submit([4, 4], 4, timeout_s=0.001)
    time.sleep(0.05)
    before = eng.metrics()["requests_timed_out"]
    eng.run_until_idle()
    from ray_tpu.serve.llm import RequestRejected
    with pytest.raises(RequestRejected, match="deadline"):
        req.result(timeout=10)
    assert eng.metrics()["requests_timed_out"] == before + 1
    assert req.tokens == []


def test_submit_validation(llama_engine):
    from ray_tpu.serve.llm import RequestRejected
    eng = llama_engine
    with pytest.raises(RequestRejected, match="empty"):
        eng.submit([], 4)
    with pytest.raises(RequestRejected, match="prefill bucket"):
        eng.submit(list(range(17)), 4)  # largest bucket is 16
    with pytest.raises(RequestRejected, match="max_seq_len"):
        eng.submit([1, 2], 1000)


def test_engine_metrics_text(llama_engine):
    text = llama_engine._metrics_text()
    for name in ("serve_llm_running_seqs", "serve_llm_kv_pages_live",
                 "serve_llm_tokens_generated_total",
                 "serve_llm_requests_timed_out_total"):
        assert name in text


def test_gpt_decode_matches_full_forward():
    """The GPT decode path (LayerNorm + learned positions + biases) is
    bit-compatible with the full forward too."""
    from ray_tpu.serve.llm import EngineConfig, LLMEngine
    eng = LLMEngine(model="gpt",
                    engine_config=EngineConfig(
                        batch_buckets=(1, 2), prefill_buckets=(8,)),
                    seed=1)
    eng.warmup()
    try:
        cases = [([5, 9, 3], 5), ([2, 4], 6)]
        reqs = [eng.submit(p, n) for p, n in cases]
        eng.run_until_idle()
        for (p, n), r in zip(cases, reqs):
            assert r.result(timeout=30) == _reference_greedy(eng, p, n)
        eng.quiesce()
    finally:
        assert eng.shutdown() == 0


# ---------------------------------------------------------------------------
# @serve.batch satellite: per-item errors + flush-flag reset
# ---------------------------------------------------------------------------


def test_batch_per_item_exception():
    """A batched fn returning an Exception INSTANCE in an item's slot
    fails that caller alone; batch-mates get their results."""
    from ray_tpu.serve.batching import batch

    @batch(max_batch_size=3, batch_wait_timeout_s=5.0)
    def work(items):
        return [ValueError(f"bad {x}") if x < 0 else x * 2
                for x in items]

    results, errors = {}, {}

    def call(x):
        try:
            results[x] = work(x)
        except Exception as e:  # noqa: BLE001
            errors[x] = e

    threads = [threading.Thread(target=call, args=(x,))
               for x in (1, -5, 3)]
    [t.start() for t in threads]
    [t.join(timeout=30) for t in threads]
    assert results == {1: 2, 3: 6}
    assert isinstance(errors[-5], ValueError)


def test_batch_flush_flag_resets_when_timer_fails():
    """If the flush timer can't start, the scheduled flag must reset —
    otherwise no later submit ever schedules a flush and every queued
    caller hangs."""
    from ray_tpu.serve.batching import _Batcher

    calls = []

    def fn(items):
        calls.append(list(items))
        return [x + 1 for x in items]

    b = _Batcher(fn, max_batch_size=4, batch_wait_timeout_s=0.05)

    class _BoomTimer:
        def __init__(self, *a, **k):
            self.daemon = True

        def start(self):
            raise RuntimeError("no threads left")

    import ray_tpu.serve.batching as batching_mod
    real_timer = batching_mod.threading.Timer
    batching_mod.threading.Timer = _BoomTimer
    try:
        with pytest.raises(RuntimeError, match="no threads left"):
            b.submit(None, 1)
        assert b._flush_scheduled is False  # un-wedged
    finally:
        batching_mod.threading.Timer = real_timer
    # the batcher still works: next submit schedules a real flush that
    # drains the stranded first item too
    out = b.submit(None, 2)
    assert out == 3
    assert sorted(sum(calls, [])) == [1, 2]


# ---------------------------------------------------------------------------
# Serve integration (cluster)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    from ray_tpu import serve
    ray_tpu.init(num_cpus=8, num_tpus=0,
                 object_store_memory=256 * 1024 * 1024)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture()
def clean_deployments(cluster):
    from ray_tpu import serve
    yield
    for name in list(serve.status()):
        serve.delete(name)


def test_handle_timeout_s_sheds_expired(clean_deployments):
    """handle.options(timeout_s=...) sheds a request whose deadline
    passed before dispatch, raises RequestTimeoutError, and counts it in
    serve_request_timeouts."""
    from ray_tpu import serve
    from ray_tpu.serve.handle import REQUEST_TIMEOUTS

    @serve.deployment
    def echo(x):
        return x

    handle = serve.run(echo.bind())
    assert handle.remote(1).result(timeout=30) == 1  # warm route
    def shed_count():
        return sum(REQUEST_TIMEOUTS._values.values())

    before = shed_count()
    with pytest.raises(serve.RequestTimeoutError):
        handle.options(timeout_s=-0.001).remote(2)
    assert shed_count() == before + 1
    # a sane deadline still dispatches
    assert handle.options(timeout_s=30.0).remote(3).result(timeout=30) == 3


def test_serve_llm_end_to_end(clean_deployments):
    """build_app -> serve.run -> stream tokens over the handle; replica
    reports queue depth + KV occupancy + arena id through the controller
    poll."""
    from ray_tpu import serve

    handle = serve.run(serve.llm.build_app(name="llm", num_replicas=1))
    streamed = [c["token"] for c in
                handle.generate.options(stream=True).remote([5, 9, 3], 8)]
    assert len(streamed) == 8
    unary = handle.generate_once.remote([5, 9, 3], 8).result(timeout=60)
    assert unary == streamed  # greedy determinism across paths

    m = handle.engine_metrics.remote().result(timeout=60)
    assert m["requests_completed"] >= 2
    assert m["kv_pages_live"] == 0  # all pages returned

    # the controller's poll sees the merged autoscaling metrics
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    info = ray_tpu.get(ctrl.get_replicas.remote("llm"), timeout=30)
    rm = ray_tpu.get(info["replicas"][0].get_metrics.remote(), timeout=30)
    for key in ("ongoing", "queue_depth", "kv_pages_live",
                "kv_pages_total", "kv_arena_id"):
        assert key in rm
    assert rm["kv_arena_id"]  # shm arena (replica runs inside a cluster)
