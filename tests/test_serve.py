"""Serve tests: deployments, handles, routing, composition, autoscaling,
batching, HTTP proxy, redeploy, replica recovery.

Reference ground: `python/ray/serve/tests/test_standalone.py`,
`test_autoscaling_policy.py`, `test_batching.py` — compressed.
"""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=8, num_tpus=0,
                 object_store_memory=256 * 1024 * 1024)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def clean_deployments():
    yield
    for name in list(serve.status()):
        serve.delete(name)


def test_function_deployment():
    @serve.deployment
    def doubler(x):
        return x * 2

    handle = serve.run(doubler.bind())
    assert handle.remote(21).result() == 42


def test_class_deployment_and_methods():
    @serve.deployment(num_replicas=2)
    class Counter:
        def __init__(self, start):
            self.base = start

        def __call__(self, x):
            return self.base + x

        def describe(self):
            return "counter"

    handle = serve.run(Counter.bind(100))
    assert handle.remote(5).result() == 105
    assert handle.describe.remote().result() == "counter"
    st = serve.status()
    assert st["Counter"]["num_replicas"] == 2


def test_composition():
    @serve.deployment
    class Preprocessor:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Model:
        def __init__(self, pre):
            self.pre = pre

        def __call__(self, x):
            y = self.pre.remote(x).result(timeout=30)
            return y * 10

    handle = serve.run(Model.bind(Preprocessor.bind()))
    assert handle.remote(4).result() == 50


def test_batching():
    @serve.deployment(max_ongoing_requests=16)
    class BatchAdder:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        def __call__(self, xs):
            # returns list; batch size recorded in each result
            return [(x, len(xs)) for x in xs]

    handle = serve.run(BatchAdder.bind())
    responses = [handle.remote(i) for i in range(8)]
    results = [r.result(timeout=30) for r in responses]
    assert sorted(x for x, _ in results) == list(range(8))
    # at least one real batch formed (size > 1)
    assert max(bs for _, bs in results) > 1


def test_autoscaling_scales_up():
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0, "upscale_delay_s": 0.0,
        "downscale_delay_s": 60.0})
    class Slow:
        def __call__(self, x):
            time.sleep(2.0)
            return x

    handle = serve.run(Slow.bind())
    # flood with concurrent requests to build up ongoing count
    responses = [handle.remote(i) for i in range(6)]
    deadline = time.monotonic() + 30
    scaled = False
    while time.monotonic() < deadline:
        if serve.status()["Slow"]["num_replicas"] > 1:
            scaled = True
            break
        time.sleep(0.5)
    for r in responses:
        r.result(timeout=60)
    assert scaled, f"autoscaler never scaled up: {serve.status()}"


def test_redeploy_updates_version():
    @serve.deployment
    def v(x):
        return "v1"

    handle = serve.run(v.bind())
    assert handle.remote(0).result() == "v1"

    @serve.deployment(name="v")
    def v2(x):
        return "v2"

    handle = serve.run(v2.bind())
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if handle.remote(0).result(timeout=30) == "v2":
            return
        time.sleep(0.2)
    raise AssertionError("redeploy never took effect")


def test_replica_death_recovery():
    @serve.deployment(num_replicas=1)
    class Sturdy:
        def __call__(self, x):
            return x + 1

    handle = serve.run(Sturdy.bind())
    assert handle.remote(1).result() == 2
    # murder the replica behind the controller's back
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    info = ray_tpu.get(ctrl.get_replicas.remote("Sturdy"), timeout=30)
    ray_tpu.kill(info["replicas"][0])
    # reconcile loop must replace it
    deadline = time.monotonic() + 40
    while time.monotonic() < deadline:
        try:
            if handle.remote(5).result(timeout=10) == 6:
                return
        except Exception:
            time.sleep(0.5)
    raise AssertionError("replica never recovered")


def test_http_proxy():
    import urllib.request
    import json as json_mod

    @serve.deployment
    def echo(body):
        return {"got": body}

    serve.run(echo.bind(), route_prefix="/echo", http_port=8123)
    req = urllib.request.Request(
        "http://127.0.0.1:8123/echo",
        data=json_mod.dumps({"k": 1}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        out = json_mod.loads(resp.read())
    assert out == {"got": {"k": 1}}
    # 404 for unknown route
    try:
        urllib.request.urlopen("http://127.0.0.1:8123/nope", timeout=30)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_streaming_handle_response():
    """stream=True handles yield chunks as the replica produces them
    (reference: DeploymentResponseGenerator)."""
    @serve.deployment
    class Tokens:
        def generate(self, n):
            for i in range(n):
                yield {"token": i}

    handle = serve.run(Tokens.bind())
    chunks = list(handle.generate.options(stream=True).remote(4))
    assert chunks == [{"token": i} for i in range(4)]


def test_streaming_handle_early_close():
    @serve.deployment
    class Endless:
        def stream(self):
            i = 0
            while True:
                yield i
                i += 1

    handle = serve.run(Endless.bind())
    gen = handle.stream.options(stream=True).remote()
    got = [next(gen) for _ in range(3)]
    gen.close()
    assert got == [0, 1, 2]
    # replica metrics drain back to zero ongoing once cancelled
    time.sleep(1.0)
    st = serve.status()
    assert st["Endless"]["num_replicas"] == 1


def test_streaming_http_jsonl():
    """Generator deployments stream JSON-lines over the HTTP proxy."""
    import urllib.request

    @serve.deployment
    def streamer(body):
        for i in range(3):
            yield {"chunk": i, "echo": body}

    serve.run(streamer.bind(), route_prefix="/stream", http_port=8123)
    req = urllib.request.Request(
        "http://127.0.0.1:8123/stream", data=b'"hi"',
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        lines = [ln for ln in r.read().decode().splitlines() if ln]
    import json as json_mod

    parsed = [json_mod.loads(ln) for ln in lines]
    assert parsed == [{"chunk": i, "echo": "hi"} for i in range(3)]


def test_grpc_proxy_unary_and_streaming():
    """gRPC ingress (reference `_private/proxy.py:534` gRPCProxy):
    unary Call routes to a deployment, CallStreaming streams generator
    chunks, Healthz answers, unknown deployment -> INTERNAL."""
    import grpc
    import json as json_mod

    @serve.deployment
    def square(x):
        return {"sq": x * x}

    @serve.deployment
    def counter(n):
        for i in range(n):
            yield {"i": i}

    serve.run(square.bind(), route_prefix="/square")
    serve.run(counter.bind(), route_prefix="/counter")
    from ray_tpu.serve import _start_grpc_proxy

    info = _start_grpc_proxy(0)  # ephemeral port
    addr = f"127.0.0.1:{info['port']}"
    with grpc.insecure_channel(addr) as channel:
        call = channel.unary_unary("/ray_tpu.serve.ServeAPI/Call")
        out = json_mod.loads(call(
            json_mod.dumps({"deployment": "square", "data": 7}).encode(),
            timeout=60))
        assert out == {"result": {"sq": 49}}

        healthz = channel.unary_unary("/ray_tpu.serve.ServeAPI/Healthz")
        assert healthz(b"", timeout=30) == b"ok"

        stream = channel.unary_stream(
            "/ray_tpu.serve.ServeAPI/CallStreaming")
        chunks = [json_mod.loads(c) for c in stream(
            json_mod.dumps({"deployment": "counter", "data": 3}).encode(),
            timeout=60)]
        assert chunks == [{"result": {"i": 0}}, {"result": {"i": 1}},
                          {"result": {"i": 2}}]

        with pytest.raises(grpc.RpcError):
            call(json_mod.dumps({"deployment": "missing",
                                 "data": 1}).encode(), timeout=60)


# -- ASGI ingress (reference serve/api.py:248 @serve.ingress) ---------------


def _tiny_asgi_router():
    """A framework-free ASGI app with path params, query handling, a
    middleware layer, and a streaming endpoint — the protocol surface a
    FastAPI/Starlette app exercises."""

    async def app(scope, receive, send):
        assert scope["type"] == "http"
        path = scope["path"]
        root = scope.get("root_path", "")
        rel = path[len(root):] if root and path.startswith(root) else path
        await receive()  # consume the request body event
        if rel.startswith("/items/"):
            item_id = rel.split("/items/", 1)[1]
            qs = scope["query_string"].decode()
            body = ('{"item": "%s", "qs": "%s", "method": "%s"}'
                    % (item_id, qs, scope["method"])).encode()
            await send({"type": "http.response.start", "status": 200,
                        "headers": [(b"content-type",
                                     b"application/json")]})
            await send({"type": "http.response.body", "body": body})
        elif rel == "/stream":
            await send({"type": "http.response.start", "status": 200,
                        "headers": [(b"content-type", b"text/plain")]})
            for i in range(4):
                await send({"type": "http.response.body",
                            "body": f"chunk{i};".encode(),
                            "more_body": True})
            await send({"type": "http.response.body", "body": b"",
                        "more_body": False})
        else:
            await send({"type": "http.response.start", "status": 404,
                        "headers": []})
            await send({"type": "http.response.body", "body": b"nope"})

    async def middleware(scope, receive, send):
        # header-injecting middleware wrapping the router
        async def wrapped_send(ev):
            if ev["type"] == "http.response.start":
                ev = dict(ev)
                ev["headers"] = list(ev.get("headers", [])) + [
                    (b"x-middleware", b"on")]
            await send(ev)
        await app(scope, receive, wrapped_send)

    return middleware


def test_asgi_ingress_path_params_and_middleware():
    import urllib.request
    import json as json_mod

    @serve.deployment
    @serve.ingress(_tiny_asgi_router())
    class Api:
        pass

    serve.run(Api.bind(), route_prefix="/api", http_port=8123)
    with urllib.request.urlopen(
            "http://127.0.0.1:8123/api/items/42?a=1", timeout=60) as r:
        assert r.headers["x-middleware"] == "on"
        out = json_mod.loads(r.read())
    assert out == {"item": "42", "qs": "a=1", "method": "GET"}

    # 404 generated BY the app (not the proxy) passes through
    try:
        urllib.request.urlopen("http://127.0.0.1:8123/api/missing",
                               timeout=30)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert e.read() == b"nope"


def test_asgi_ingress_streaming_response():
    import urllib.request

    @serve.deployment
    @serve.ingress(_tiny_asgi_router())
    class StreamApi:
        pass

    serve.run(StreamApi.bind(), route_prefix="/s", http_port=8123)
    with urllib.request.urlopen("http://127.0.0.1:8123/s/stream",
                                timeout=60) as r:
        body = r.read()
    assert body == b"chunk0;chunk1;chunk2;chunk3;"


def test_asgi_ingress_instance_factory_and_body():
    """One-arg factory: routes close over the deployment instance, and
    the request body reaches the app through the forwarded scope."""
    import urllib.request
    import json as json_mod

    def make_app(instance):
        async def app(scope, receive, send):
            ev = await receive()
            n = json_mod.loads(ev["body"] or b"0")
            out = json_mod.dumps(
                {"scaled": n * instance.factor}).encode()
            await send({"type": "http.response.start", "status": 200,
                        "headers": [(b"content-type",
                                     b"application/json")]})
            await send({"type": "http.response.body", "body": out})
        return app

    @serve.deployment
    @serve.ingress(make_app)
    class Scaler:
        def __init__(self, factor):
            self.factor = factor

    serve.run(Scaler.bind(3), route_prefix="/scale", http_port=8123)
    req = urllib.request.Request("http://127.0.0.1:8123/scale",
                                 data=b"7")
    with urllib.request.urlopen(req, timeout=60) as r:
        assert json_mod.loads(r.read()) == {"scaled": 21}


def test_declarative_config_build_and_deploy(tmp_path):
    """serve.build -> YAML -> serve.deploy_config round trip (reference
    `serve build` / `serve deploy` + schema.py), with a num_replicas
    override applied from config."""
    import sys
    import yaml

    # the config deploy imports the app by path: write a real module
    mod = tmp_path / "cfg_app_mod.py"
    mod.write_text(
        "from ray_tpu import serve\n"
        "@serve.deployment\n"
        "def pinger(body):\n"
        "    return {'pong': body}\n"
        "app = pinger.bind()\n")
    sys.path.insert(0, str(tmp_path))
    try:
        import cfg_app_mod

        cfg = serve.build(cfg_app_mod.app, name="cfgapp",
                          import_path="cfg_app_mod:app",
                          route_prefix="/cfg")
        assert cfg["applications"][0]["deployments"][0]["name"] == "pinger"
        # operator edit: bump replicas in the YAML
        cfg["applications"][0]["deployments"][0]["num_replicas"] = 2
        # the module proxy (other tests) owns 8123; a mismatched port
        # must be rejected loudly, so point the config at the same one
        cfg["http_options"] = {"port": 8123}
        yml = yaml.safe_dump(cfg)
        path = tmp_path / "serve.yaml"
        path.write_text(yml)

        handles = serve.deploy_config(str(path))
        assert handles["cfgapp"].remote("x").result() == {"pong": "x"}
        st = serve.status()
        assert st["pinger"]["target_replicas"] == 2, st

        # overrides land on a CLONE of the module-cached app: a second
        # deploy without the override reverts to the code default
        cfg2 = serve.build(cfg_app_mod.app, name="cfgapp",
                           import_path="cfg_app_mod:app",
                           route_prefix="/cfg")
        cfg2["http_options"] = {"port": 8123}
        serve.deploy_config(cfg2)
        assert serve.status()["pinger"]["target_replicas"] == 1

        # unknown override fields fail loudly
        bad = {"http_options": {"port": 8123},
               "applications": [{"name": "b", "import_path":
                                 "cfg_app_mod:app",
                                 "deployments": [{"name": "pinger",
                                                  "nope": 1}]}]}
        with pytest.raises(ValueError, match="unknown deployment"):
            serve.deploy_config(bad)
    finally:
        sys.path.remove(str(tmp_path))


def test_asgi_lifespan_and_blocking_receive():
    """Framework-compat contract points: (a) the lifespan protocol runs
    once per replica (startup state visible to requests); (b) after the
    body, receive() BLOCKS instead of returning http.disconnect — a
    concurrent disconnect-listener (Starlette's listen_for_disconnect
    pattern) must not cancel a live streaming response."""
    import urllib.request

    def make_app():
        state = {}

        async def app(scope, receive, send):
            import asyncio
            if scope["type"] == "lifespan":
                while True:
                    ev = await receive()
                    if ev["type"] == "lifespan.startup":
                        state["ready"] = "yes"
                        await send({"type":
                                    "lifespan.startup.complete"})
                    elif ev["type"] == "lifespan.shutdown":
                        await send({"type":
                                    "lifespan.shutdown.complete"})
                        return
                return
            await receive()  # body

            async def listen_for_disconnect():
                # Starlette-style: second receive must BLOCK while the
                # response streams; an eager http.disconnect here would
                # cancel the stream below
                ev = await receive()
                return ev

            listener = asyncio.ensure_future(listen_for_disconnect())
            try:
                await send({"type": "http.response.start", "status": 200,
                            "headers": [(b"x-ready",
                                         state.get("ready",
                                                   "no").encode())]})
                for i in range(3):
                    await asyncio.sleep(0.05)
                    if listener.done():
                        return  # disconnected mid-stream: abort
                    await send({"type": "http.response.body",
                                "body": f"s{i};".encode(),
                                "more_body": True})
                await send({"type": "http.response.body", "body": b"",
                            "more_body": False})
            finally:
                listener.cancel()

        return app

    @serve.deployment
    @serve.ingress(make_app)
    class LifespanApp:
        pass

    serve.run(LifespanApp.bind(), route_prefix="/ls", http_port=8123)
    with urllib.request.urlopen("http://127.0.0.1:8123/ls", timeout=60) \
            as r:
        assert r.headers["x-ready"] == "yes"  # lifespan startup ran
        assert r.read() == b"s0;s1;s2;"  # stream survived the listener
