"""Chaos plane: deterministic fault injection + gang-durable commit.

The seeded `FaultPlan` (`ray_tpu/_private/fault_injection.py`) replaces
ad-hoc SIGKILLs with named, replayable injection points. This matrix
drives the plan through RPC loss/duplication/delay, delayed heartbeat
handling, worker-spawn failure (including the crash-loop breaker), node
kill during a live Tune run, and a kill landed *between* one train rank's
shard persist and the gang checkpoint commit — proving walk-back to the
last gang-durable checkpoint.

Activation is per-process via the RAY_TPU_CHAOS env var: daemons spawned
while the var is set parse their own plan, so a fault can be scoped to one
node by setting the var only around that node's spawn (the driver process
keeps no plan — it was imported before the var existed).

Reference ground: `python/ray/tests/test_chaos.py` and
`python/ray/_private/test_utils.py` (WorkerKillerActor / NodeKillerActor),
made seeded and deterministic.
"""

import asyncio
import os
import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import fault_injection as fi
from ray_tpu._private.node import Cluster

pytestmark = pytest.mark.chaos


@contextmanager
def chaos_env(spec: str):
    """Export RAY_TPU_CHAOS so daemons spawned inside the block parse the
    plan; the test process itself stays plan-free."""
    os.environ[fi.ENV_VAR] = spec
    try:
        yield
    finally:
        os.environ.pop(fi.ENV_VAR, None)


# ---------------------------------------------------------------------------
# plan: parsing + determinism (no cluster)
# ---------------------------------------------------------------------------


def test_fault_plan_parsing():
    p = fi.FaultPlan(
        "seed=3;rpc_drop=0.1;rpc_delay=0.5:0.02;rpc_dup=0.05;"
        "rpc_recv_drop=0.2;rpc_recv_delay=0.004;"
        "rpc_match=heartbeat|pull;heartbeat_delay=0.25;heartbeat_drop=0.1;"
        "health_delay=0.05;spawn_fail=3;lease_delay=0.01;"
        "pull_delay=1.0:0.002;kill_node=heartbeats:4;commit_kill=1:2")
    assert p.seed == 3
    assert p.rpc_drop == 0.1 and p.rpc_dup == 0.05
    assert p.rpc_delay == (0.5, 0.02)
    assert p.rpc_recv_drop == 0.2
    assert p.rpc_recv_delay == (1.0, 0.004)  # bare seconds -> p=1
    assert p.rpc_match == ("heartbeat", "pull")
    assert p.heartbeat_delay == 0.25 and p.heartbeat_drop == 0.1
    assert p.health_delay == 0.05
    assert p.spawn_fail == 3
    assert p.lease_delay == (1.0, 0.01)
    assert p.pull_delay == (1.0, 0.002)
    assert p.kill_node == ("heartbeats", 4)
    assert p.commit_kill == (1, 2)

    # method scoping
    assert p.rpc_send("other_method") is None
    # an empty plan injects nothing
    empty = fi.FaultPlan("")
    assert empty.rpc_send("heartbeat") is None
    assert empty.rpc_recv("heartbeat") is None

    with pytest.raises(ValueError, match="probability"):
        fi.FaultPlan("rpc_drop=1.5")
    with pytest.raises(ValueError, match="unknown chaos key"):
        fi.FaultPlan("frobnicate=1")
    with pytest.raises(ValueError, match="kill_node"):
        fi.FaultPlan("kill_node=tasks:3")
    with pytest.raises(ValueError, match="key=value"):
        fi.FaultPlan("rpc_drop")


def test_fault_plan_env_activation():
    # no env var -> no plan, and the injection-point guard is a single
    # module-global None check
    assert fi._PLAN is None
    assert fi.init_from_env() is None
    try:
        os.environ[fi.ENV_VAR] = "seed=2;rpc_drop=0.5"
        p = fi.init_from_env()
        assert p is not None and fi._PLAN is p and p.seed == 2
    finally:
        os.environ.pop(fi.ENV_VAR, None)
        fi.init_from_env()
    assert fi._PLAN is None


def test_fault_plan_determinism():
    """The same seed replays the identical fault schedule: decisions are
    per-site RNG streams, a pure function of (seed, site, draw index)."""
    spec = ("seed=41;rpc_drop=0.3;rpc_dup=0.2;rpc_delay=0.4:0.01;"
            "rpc_recv_drop=0.25;heartbeat_drop=0.5;spawn_fail=2;"
            "pull_delay=0.5:0.003;lease_delay=0.5:0.001")

    def drive(plan: fi.FaultPlan):
        decisions = []
        for i in range(300):
            decisions.append(plan.rpc_send(f"method_{i % 7}"))
            decisions.append(plan.rpc_recv(f"method_{i % 5}"))

        async def drive_async():
            # zero-delay async sites still draw from their streams
            for _ in range(50):
                decisions.append(await plan.gcs_heartbeat())
                await plan.object_pull()
                await plan.lease_request()

        asyncio.run(drive_async())
        for _ in range(4):
            try:
                plan.spawn_attempt()
                decisions.append("spawn_ok")
            except fi.ChaosError:
                decisions.append("spawn_fail")
        return decisions

    a, b = fi.FaultPlan(spec), fi.FaultPlan(spec)
    da, db = drive(a), drive(b)
    assert da == db
    assert a.schedule == b.schedule and len(a.schedule) > 0
    # draws landed on both faulting and non-faulting outcomes
    assert any(d is not None for d in da if not isinstance(d, (str, bool)))
    # a different seed produces a different schedule
    c = fi.FaultPlan(spec.replace("seed=41", "seed=42"))
    assert drive(c) != da


# ---------------------------------------------------------------------------
# gang-durable commit barrier (unit, no cluster)
# ---------------------------------------------------------------------------


def test_gang_commit_barrier_unit(tmp_path):
    """report(checkpoint=) must not return until the controller acks; an
    abort releases the reporter with an error instead of wedging it."""
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.train._internal.session import SessionConfig, _TrainSession

    sess = _TrainSession(SessionConfig(
        experiment_name="t", storage_path=str(tmp_path), world_rank=0,
        world_size=2, local_rank=0, local_world_size=2, node_rank=0,
        trial_dir=str(tmp_path / "trial"), gang_commit=True))
    state = {"returned": False, "error": None}

    def reporter():
        try:
            sess.report({"step": 1},
                        checkpoint=Checkpoint.from_dict({"x": 1}))
            state["returned"] = True
        except BaseException as e:  # noqa: BLE001
            state["error"] = e

    t = threading.Thread(target=reporter, daemon=True)
    t.start()
    item = sess.result_queue.get(timeout=10)
    assert item["gang_commit"] is True and item["report_index"] == 0
    # the shard is durable and the report drained — but with no ack the
    # barrier must hold
    time.sleep(0.3)
    assert not state["returned"] and state["error"] is None
    sess.ack_commit(0)
    t.join(timeout=10)
    assert state["returned"] and state["error"] is None

    # metrics-only reports never arm the barrier
    t2 = threading.Thread(
        target=lambda: sess.report({"step": 2}), daemon=True)
    t2.start()
    assert sess.result_queue.get(timeout=10).get("gang_commit") is None
    t2.join(timeout=10)
    assert not t2.is_alive()

    # abort releases a blocked reporter with an error
    state2 = {"error": None}

    def reporter2():
        try:
            sess.report({"step": 3},
                        checkpoint=Checkpoint.from_dict({"x": 3}))
        except BaseException as e:  # noqa: BLE001
            state2["error"] = e

    t3 = threading.Thread(target=reporter2, daemon=True)
    t3.start()
    sess.result_queue.get(timeout=10)
    sess.abort_commit("gang teardown")
    t3.join(timeout=10)
    assert isinstance(state2["error"], RuntimeError)
    assert "gang teardown" in str(state2["error"])


def test_incomplete_checkpoint_rejected(tmp_path):
    """The controller's commit gate refuses to register a sharded
    checkpoint that is missing shard contributions."""
    import json

    import jax.numpy as jnp

    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.train import array_checkpoint as ac
    from ray_tpu.train._internal.checkpoint_manager import (
        CheckpointManager,
        IncompleteCheckpointError,
    )

    d = str(tmp_path / "ck")
    ac.save_sharded(d, {"a": jnp.ones((4,))})
    ipath = os.path.join(
        d, [f for f in os.listdir(d) if f.startswith("asv_index")][0])
    with open(ipath) as f:
        rec = json.load(f)
    rec["num_processes"] = 2  # a second writer that never finished
    with open(ipath, "w") as f:
        json.dump(rec, f)

    mgr = CheckpointManager()
    with pytest.raises(IncompleteCheckpointError):
        mgr.register_checkpoint(Checkpoint(d), {"step": 1},
                                require_usable=True)
    assert mgr.latest_checkpoint is None
    # without the gate (non-gang callers) registration still works
    mgr.register_checkpoint(Checkpoint(d), {"step": 1})
    assert mgr.latest_checkpoint is not None


# ---------------------------------------------------------------------------
# satellite hardening (unit, no cluster)
# ---------------------------------------------------------------------------


def test_merge_wire_rejects_pip_plus_conda():
    """ADVICE #1: a job-level conda merged with a per-actor pip (or vice
    versa) must raise, not silently prefer pip at spawn time."""
    from ray_tpu._private import runtime_env as re_mod

    base = {"conda": {"name": "base-env"}, "_hash": "a"}
    override = {"pip": {"packages": ["x"]}, "_hash": "b"}
    with pytest.raises(ValueError, match="pip and conda"):
        re_mod.merge_wire(base, override)
    with pytest.raises(ValueError, match="pip and conda"):
        re_mod.merge_wire(override, base)
    # either alone merges fine
    merged = re_mod.merge_wire({"env_vars": {"A": "1"}, "_hash": "c"},
                               override)
    assert merged["pip"] == {"packages": ["x"]} and "_hash" in merged


def test_conda_empty_stdout_is_setup_error(monkeypatch):
    """ADVICE #2: `conda run` exiting 0 with empty stdout must be a
    deterministic RuntimeEnvSetupError (IndexError would read as
    transient and respawn forever while leases hang)."""
    import subprocess

    from ray_tpu._private import runtime_env as re_mod

    monkeypatch.setattr(re_mod, "_conda_exe", lambda: "/bin/conda-stub")
    monkeypatch.setattr(
        re_mod.subprocess, "run",
        lambda *a, **k: subprocess.CompletedProcess(a, 0, stdout="",
                                                    stderr="boom"))
    re_mod._conda_named_cache.pop("ghost-env", None)
    with pytest.raises(re_mod.RuntimeEnvSetupError,
                       match="no interpreter path"):
        re_mod.ensure_conda_env({"name": "ghost-env"})


def test_store_client_merges_legacy_table_dir(tmp_path):
    """ADVICE #4: when both the legacy and canonical table dirs exist,
    legacy key files merge into the canonical dir (existing keys win)
    instead of being orphaned on restore."""
    import pickle

    from ray_tpu._private.store_client import FileStoreClient
    from urllib.parse import quote

    root = tmp_path / "store"
    legacy = root / "kv:default"          # pre-quote encoding
    canon = root / quote("kv:default", safe="")
    legacy.mkdir(parents=True)
    canon.mkdir(parents=True)
    k_old, k_both, k_new = b"\x01".hex(), b"\x02".hex(), b"\x03".hex()
    (legacy / k_old).write_bytes(pickle.dumps("legacy-only"))
    (legacy / k_both).write_bytes(pickle.dumps("legacy-version"))
    (canon / k_both).write_bytes(pickle.dumps("canonical-version"))
    (canon / k_new).write_bytes(pickle.dumps("canonical-only"))

    store = FileStoreClient(str(root))
    table = store.get_all("kv:default")
    assert table[b"\x01"] == "legacy-only"          # recovered
    assert table[b"\x02"] == "canonical-version"    # newer write kept
    assert table[b"\x03"] == "canonical-only"
    assert not legacy.exists()                       # merged away


# ---------------------------------------------------------------------------
# chaos matrix: live cluster runs under an active plan
# ---------------------------------------------------------------------------


def _simple_task_workload(n: int = 60) -> None:
    @ray_tpu.remote
    def double(x):
        return 2 * x

    got = ray_tpu.get([double.remote(i) for i in range(n)], timeout=120)
    assert got == [2 * i for i in range(n)]


def _session_logs_contain(pattern: str) -> bool:
    """Grep the live init() cluster's daemon/worker logs for evidence the
    chaos plan actually fired in the target process."""
    import glob

    from ray_tpu._private import worker_api

    state = worker_api._global_state
    if state is None or state.cluster is None:
        return False
    for path in glob.glob(
            os.path.join(state.cluster.session_dir, "logs", "*")):
        try:
            with open(path, errors="replace") as f:
                if pattern in f.read():
                    return True
        except OSError:
            continue
    return False


def test_chaos_rpc_faults_during_train(tmp_path):
    """RPC loss/duplication/delay scoped to the heartbeat plane while a
    2-worker Train run reports checkpoints: the run must complete and
    the node must stay alive (drops are i.i.d. at p=0.3 — nowhere near
    the 10-consecutive-miss death threshold)."""
    from ray_tpu import train
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train.backend import JaxConfig

    with chaos_env("seed=5;rpc_drop=0.3;rpc_dup=0.2;rpc_delay=0.3:0.01;"
                   "rpc_match=heartbeat"):
        ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    try:
        def loop(config):
            from ray_tpu import train as train_mod
            from ray_tpu.air.checkpoint import Checkpoint

            for i in range(3):
                train_mod.report(
                    {"step": i + 1},
                    checkpoint=Checkpoint.from_dict({"step": i + 1}))

        trainer = train.JaxTrainer(
            loop,
            backend_config=JaxConfig(distributed="off", platform="cpu"),
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(storage_path=str(tmp_path / "results"),
                                 name="rpc_chaos"),
        )
        result = trainer.fit()
        assert result.metrics["step"] == 3
        assert all(n["Alive"] for n in ray_tpu.nodes())
    finally:
        ray_tpu.shutdown()


def test_chaos_heartbeat_delay(tmp_path):
    """Delayed heartbeat HANDLING at the GCS (0.6s per beat, under the
    5s death threshold): liveness bookkeeping lags but nothing dies and
    the task plane stays correct."""
    with chaos_env("seed=6;heartbeat_delay=0.6"):
        ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        _simple_task_workload()
        assert all(n["Alive"] for n in ray_tpu.nodes())
    finally:
        ray_tpu.shutdown()


def test_chaos_spawn_fail_recovers():
    """First two worker spawns fail (non-RuntimeEnvSetupError): the
    raylet must count them in the crash-loop breaker AND immediately
    re-drive dispatch, so the third spawn serves the lease — without the
    re-dispatch (ADVICE #5) this hangs until an unrelated event."""
    with chaos_env("seed=8;spawn_fail=2"):
        ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        start = time.monotonic()
        _simple_task_workload(n=8)
        assert time.monotonic() - start < 60
        # the plan really fired in the raylet (not a silently inactive env)
        assert _session_logs_contain("injected worker spawn failure")
    finally:
        ray_tpu.shutdown()


def test_chaos_spawn_fail_breaker_trips():
    """Persistent spawn failure must trip the crash-loop breaker and
    fail the waiting leases with a diagnosable error instead of hanging
    them forever (ADVICE #5's second half)."""
    with chaos_env("seed=9;spawn_fail=1000"):
        ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def probe():
            return 1

        with pytest.raises(Exception, match="crash-loop|spawn"):
            ray_tpu.get(probe.remote(), timeout=90)
    finally:
        ray_tpu.shutdown()


def test_chaos_node_kill_during_tune():
    """Abrupt node death (plan-driven os._exit after 6 heartbeats on the
    victim raylet only) during a live Tune run: FailureConfig retries
    must carry every trial to completion on the surviving node, and the
    GCS must have marked the victim dead."""
    from ray_tpu import tune
    from ray_tpu.air.config import FailureConfig, RunConfig

    cluster = Cluster(head_resources={"CPU": 2.0})
    with chaos_env("seed=12;kill_node=heartbeats:6"):
        victim = cluster.add_node({"CPU": 4.0})
    ray_tpu.init(address=cluster.gcs_addr)
    try:
        def trainable(config):
            for i in range(8):
                time.sleep(0.25)
                tune.report({"step": i, "value": config["x"] * i})

        tuner = tune.Tuner(
            trainable,
            param_space={"x": tune.grid_search([1, 2, 3, 4])},
            tune_config=tune.TuneConfig(metric="value", mode="max"),
            run_config=RunConfig(
                storage_path="/tmp/ray_tpu_chaos_nodekill",
                name=f"nodekill_{int(time.time())}",
                failure_config=FailureConfig(max_failures=8),
            ),
        )
        grid = tuner.fit()
        assert len(grid) == 4
        for res in grid:
            assert res.error is None, f"trial failed: {res.error}"
            assert res.metrics["step"] == 7
        # the plan actually fired: the victim raylet process is gone and
        # the GCS noticed
        assert victim.process.proc.poll() is not None
        dead = [n for n in ray_tpu.nodes() if not n["Alive"]]
        assert dead, "GCS never marked the chaos-killed node dead"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


# ---------------------------------------------------------------------------
# the gang-commit kill window (integration)
# ---------------------------------------------------------------------------


def _make_commit_kill_loop():
    # factory so cloudpickle serializes by value (workers can't import
    # this test module)
    def _loop(config):
        import jax
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ray_tpu import train as train_mod
        from ray_tpu.train import array_checkpoint as ac_mod

        devs = jax.devices()
        mesh = Mesh(np.array(devs).reshape(len(devs)), ("dp",))
        w0 = np.arange(32, dtype=np.float32).reshape(8, 4)
        state = {
            "w": jax.make_array_from_callback(
                (8, 4), NamedSharding(mesh, P("dp")), lambda idx: w0[idx]),
            "step": jax.make_array_from_callback(
                (), NamedSharding(mesh, P()),
                lambda idx: np.zeros((), np.int32)),
        }
        start = 0
        ckpt = train_mod.get_checkpoint()
        if ckpt is not None and ac_mod.is_sharded_checkpoint(ckpt):
            state = ac_mod.restore_sharded(ckpt, state)
            start = int(np.asarray(state["step"].addressable_shards[0].data))

        @jax.jit
        def update(s):
            return {"w": s["w"] * 2.0 + 1.0, "step": s["step"] + 1}

        for i in range(start, 3):
            state = update(state)
            fp = float(sum(np.asarray(s.data).sum()
                           for s in state["w"].addressable_shards
                           if s.replica_id == 0))
            # On the fresh attempt the chaos plan kills rank 1 inside
            # report(): after its step-2 shard persist, before the gang
            # commit (commit_kill=1:1 -> report_index 1).
            train_mod.report(
                {"step": i + 1, "fp": fp, "resumed_from": start},
                checkpoint=ac_mod.save_to_checkpoint(state))

    return _loop


def test_commit_kill_walks_back_to_gang_durable(tmp_path):
    """THE gang-durability proof: a rank killed between its own shard
    persist and the gang commit leaves a checkpoint that is durable on
    disk but never registered — walk-back must land on the previous
    (gang-committed) checkpoint, never on the half-committed one, and
    never below the last commit."""
    from ray_tpu import train
    from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train.backend import JaxConfig

    with chaos_env("seed=11;commit_kill=1:1"):
        ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)
    try:
        trainer = train.JaxTrainer(
            _make_commit_kill_loop(),
            backend_config=JaxConfig(
                distributed="on", platform="cpu",
                xla_flags="--xla_force_host_platform_device_count=2"),
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(
                storage_path=str(tmp_path / "results"), name="commitkill",
                failure_config=FailureConfig(max_failures=1)),
        )
        result = trainer.fit()
        assert result.metrics["step"] == 3
        # Walk-back landed exactly on the last gang-COMMITTED checkpoint
        # (step 1). The step-2 checkpoint was fully durable (both ranks
        # persisted before the kill) but the controller never registered
        # it — resuming from it would have made report()'s return a lie.
        assert result.metrics["resumed_from"] == 1
        # bit-identical math across the restore
        w = np.arange(32, dtype=np.float32).reshape(8, 4)
        for _ in range(3):
            w = w * 2.0 + 1.0
        assert result.metrics["fp"] == pytest.approx(float(w[:4].sum()),
                                                     abs=0.0)
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# serve.llm: replica kill mid-stream (seeded, deterministic)
# ---------------------------------------------------------------------------


def test_chaos_llm_replica_kill_midstream():
    """Kill the replica serving a token stream mid-generation. The
    handle must fail over to the surviving replica and replay-skip the
    already-delivered chunks (greedy decode is deterministic and both
    replicas share a seed, so the resumed stream is the SAME stream) —
    no accepted request is lost. Afterwards the controller reconciles
    the death and force-reclaims the dead replica's KV arena from the
    shm store: a killed replica leaks zero KV pages."""
    from ray_tpu import serve
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_ref import get_core_worker
    from ray_tpu.serve.llm import LLMDeployment

    ray_tpu.init(num_cpus=8, num_tpus=0,
                 object_store_memory=256 * 1024 * 1024)
    try:
        class SlowLLM(LLMDeployment):
            """Per-chunk delay so the kill reliably lands mid-stream."""

            def generate(self, prompt, max_new_tokens=16,
                         timeout_s=None):
                for chunk in LLMDeployment.generate(
                        self, prompt, max_new_tokens, timeout_s):
                    time.sleep(0.05)
                    yield chunk

        app = serve.deployment(name="llm", num_replicas=2)(
            SlowLLM).bind(seed=0)
        handle = serve.run(app)
        ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
        # prime the controller's metrics cache (arena ids) pre-kill
        ray_tpu.get(ctrl.reconcile_now.remote(), timeout=60)

        n_tokens = 24
        gen = handle.generate.options(stream=True).remote(
            [5, 9, 3], n_tokens)
        tokens = [next(gen)["token"] for _ in range(4)]

        # find the replica carrying the stream (ongoing >= 1) and
        # remember its arena id, then murder it
        info = ray_tpu.get(ctrl.get_replicas.remote("llm"), timeout=30)
        serving = dead_arena = None
        for r in info["replicas"]:
            m = ray_tpu.get(r.get_metrics.remote(), timeout=30)
            if m["ongoing"] >= 1 and serving is None:
                serving, dead_arena = r, m["kv_arena_id"]
        assert serving is not None and dead_arena
        ray_tpu.kill(serving)

        # the stream completes on the survivor via replay
        for chunk in gen:
            tokens.append(chunk["token"])
        assert len(tokens) == n_tokens

        # ground truth: a fresh request (now served by the survivor)
        rerun = handle.generate_once.remote([5, 9, 3], n_tokens).result(
            timeout=120)
        assert tokens == rerun  # the failed-over stream lost nothing

        # flight-recorder regression (ISSUE 12): the failed-over stream
        # produced exactly ONE client record (the resubmit's temporary
        # response is neutered), the survivor-replayed chunks are
        # counted but never timed, and TPOT is averaged over delivered-
        # token gaps only — the recovery gap is excluded, so every
        # timed gap carries the 50 ms per-chunk delay.
        from ray_tpu.util import request_recorder as rr

        fo = [r for r in rr.ring().recent()
              if r.role == "client" and r.outcome == "failed_over"]
        assert len(fo) == 1
        crec = fo[0]
        assert crec.tokens_out == n_tokens
        assert crec.replayed_tokens >= 4  # >= chunks delivered pre-kill
        # one untimed first chunk per stream half: pre-kill k chunks
        # give k-1 gaps, post-failover (n-k) chunks give n-k-1 gaps
        assert crec.attrs["timed_gaps"] == n_tokens - 2
        assert crec.tpot_ms is not None and crec.tpot_ms >= 40.0

        # reconcile notices the death and reclaims the dead arena
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            ray_tpu.get(ctrl.reconcile_now.remote(), timeout=60)
            reclaimed = ray_tpu.get(
                ctrl.get_reclaimed_arenas.remote(), timeout=30)
            if dead_arena in reclaimed:
                break
            time.sleep(0.5)
        else:
            raise AssertionError("dead replica's KV arena never "
                                 "reclaimed")
        store = get_core_worker().store
        assert not store.contains(ObjectID.from_hex(dead_arena))
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_chaos_llm_replica_kill_midstream_spec_prefix():
    """Mid-stream replica kill with SPECULATIVE DECODING and the
    shared-prefix cache both on, drafting with an independent (smaller)
    model. The failover replay contract must survive the fast path:
    greedy speculative decode is bit-identical to plain greedy and the
    draft inits from the shared seed, so the survivor's resumed stream
    is the SAME stream even though its prefill rides aliased
    prefix-cache pages and its decode rides the verify window."""
    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMDeployment

    ray_tpu.init(num_cpus=8, num_tpus=0,
                 object_store_memory=256 * 1024 * 1024)
    try:
        class SlowLLM(LLMDeployment):
            def generate(self, prompt, max_new_tokens=16,
                         timeout_s=None):
                for chunk in LLMDeployment.generate(
                        self, prompt, max_new_tokens, timeout_s):
                    time.sleep(0.05)
                    yield chunk

        # small buckets keep warmup (target + draft + verify fns) well
        # under the controller's 10 s liveness-poll timeout; the
        # chunked-prefill window lets the 36-token prompt through the
        # 16-token top bucket
        app = serve.deployment(name="llm", num_replicas=2)(
            SlowLLM).bind(
                seed=0,
                engine_config={"spec_k": 2, "prefix_cache": 1,
                               "prefill_chunk": 8, "block_size": 4,
                               "batch_buckets": (1, 2),
                               "prefill_buckets": (8, 16)},
                draft_config={"vocab_size": 512, "max_seq_len": 128,
                              "n_layer": 1, "n_head": 4,
                              "n_kv_head": 2, "d_model": 64})
        handle = serve.run(app)
        ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
        ray_tpu.get(ctrl.reconcile_now.remote(), timeout=60)

        # a 36-token prompt spans 8 full KV pages (block 4): prime
        # BOTH replicas' prefix caches so wherever the failed-over
        # stream replays, its prefill aliases cached pages
        rng = np.random.RandomState(18)
        prompt = [int(t) for t in rng.randint(1, 500, size=36)]
        n_tokens = 24
        for _ in range(4):
            handle.generate_once.remote(prompt, 4).result(timeout=120)

        gen = handle.generate.options(stream=True).remote(
            prompt, n_tokens)
        tokens = [next(gen)["token"] for _ in range(4)]

        info = ray_tpu.get(ctrl.get_replicas.remote("llm"), timeout=30)
        serving = None
        for r in info["replicas"]:
            m = ray_tpu.get(r.get_metrics.remote(), timeout=30)
            assert m.get("spec_k") == 2.0  # spec plane live on both
            if m["ongoing"] >= 1 and serving is None:
                serving = r
        assert serving is not None
        ray_tpu.kill(serving)

        for chunk in gen:                  # survivor replays + resumes
            tokens.append(chunk["token"])
        assert len(tokens) == n_tokens

        rerun = handle.generate_once.remote(prompt, n_tokens).result(
            timeout=120)
        assert tokens == rerun             # failed-over stream lost nothing

        # the survivor really took the fast path: speculative rounds
        # ran and its prefill aliased the primed prefix pages (the
        # controller may not have reconciled the death yet, so polls
        # can still hit the corpse — skip it)
        info = ray_tpu.get(ctrl.get_replicas.remote("llm"), timeout=30)
        live = []
        for r in info["replicas"]:
            try:
                live.append(ray_tpu.get(r.get_metrics.remote(),
                                        timeout=30))
            except Exception:
                pass
        live = [m for m in live if m.get("spec_k")]
        assert any(m.get("spec_mean_accept", 0) > 0 for m in live)
        assert any(m.get("prefix_cache_hit_rate", 0) > 0 for m in live)
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# timed wall-clock fault schedules (`at=` grammar) + post-mortem replay
# ---------------------------------------------------------------------------


def test_timed_schedule_parsing():
    p = fi.FaultPlan(
        "seed=4;at=5:kill@train|3.5:data_stall:2.5@worker|7:ckpt_fail:2"
        "|9:hb_brownout:1.5@gcs|11:crash_loop:3@raylet")
    assert p.timed == [
        fi.TimedFault(5.0, "kill", 0.0, "train"),
        fi.TimedFault(3.5, "data_stall", 2.5, "worker"),
        fi.TimedFault(7.0, "ckpt_fail", 2.0, None),
        fi.TimedFault(9.0, "hb_brownout", 1.5, "gcs"),
        fi.TimedFault(11.0, "crash_loop", 3.0, "raylet"),
    ]
    # bare ckpt_fail defaults to one persist; repeated at= keys accumulate
    q = fi.FaultPlan("at=1:ckpt_fail;at=2:kill@train")
    assert q.timed == [fi.TimedFault(1.0, "ckpt_fail", 1.0, None),
                       fi.TimedFault(2.0, "kill", 0.0, "train")]

    # drop_objects: bare form sweeps half the sealed set; the fraction
    # must stay inside (0, 1]
    r = fi.FaultPlan("at=4:drop_objects@raylet|6:drop_objects:0.25")
    assert r.timed == [fi.TimedFault(4.0, "drop_objects", 0.5, "raylet"),
                       fi.TimedFault(6.0, "drop_objects", 0.25, None)]
    with pytest.raises(ValueError, match="outside"):
        fi.FaultPlan("at=1:drop_objects:1.5")
    with pytest.raises(ValueError, match="outside"):
        fi.FaultPlan("at=1:drop_objects:0")

    with pytest.raises(ValueError, match="unknown role"):
        fi.FaultPlan("at=1:kill@mainframe")
    with pytest.raises(ValueError, match="unknown fault"):
        fi.FaultPlan("at=1:meteor")
    with pytest.raises(ValueError, match="kill takes no argument"):
        fi.FaultPlan("at=1:kill:2")
    with pytest.raises(ValueError, match="requires an argument"):
        fi.FaultPlan("at=1:data_stall")
    with pytest.raises(ValueError, match="not <offset>"):
        fi.FaultPlan("at=5")


def test_timed_fire_once_and_replay(tmp_path, monkeypatch):
    """Timed entries fire at their offsets, flip the injection state the
    fault sites consume, are gated to ONE fire per soak run by the
    once-sentinels, and the post-mortem artifact rebuilds the identical
    plan via `from_artifact`."""
    monkeypatch.setenv(fi.LOG_ENV, str(tmp_path))
    spec = "seed=2;at=0.05:ckpt_fail:2|0.1:data_stall:0.2|0.1:hb_brownout:30"
    p = fi.FaultPlan(spec)
    p.arm_timed("worker")   # unroled entries arm in any process
    deadline = time.monotonic() + 5
    while len(p.timed_fired) < 3 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert sorted(f["fault"] for f in p.timed_fired) == \
        ["ckpt_fail", "data_stall", "hb_brownout"]

    # state the fault sites consume: two persist failures, then clean
    with pytest.raises(fi.ChaosError, match="chaos"):
        p.checkpoint_persist()
    with pytest.raises(fi.ChaosError, match="chaos"):
        p.checkpoint_persist()
    p.checkpoint_persist()   # pending exhausted
    # brownout window active: the GCS handler drops the heartbeat
    assert asyncio.run(p.gcs_heartbeat()) is True

    # once-sentinels: a second plan (a restarted attempt re-reading the
    # same env spec) re-arms but never re-fires
    q = fi.FaultPlan(spec)
    q.arm_timed("worker")
    time.sleep(0.4)
    assert q.timed_fired == []
    q._timed_stop.set()

    # post-mortem artifact -> exact replay
    path = p.export_artifact(str(tmp_path / "chaos-test.json"))
    r = fi.FaultPlan.from_artifact(path)
    assert r.spec == spec and r.seed == p.seed and r.timed == p.timed
    p._timed_stop.set()


def test_timed_epoch_anchor_expiry(tmp_path, monkeypatch):
    """With RAY_TPU_CHAOS_EPOCH set, offsets are wall-clock soak time:
    a process arming AFTER an entry's fire time (a restarted attempt)
    records it as expired instead of firing it into the fresh attempt;
    a still-future entry fires at its original wall-clock slot."""
    monkeypatch.setenv(fi.LOG_ENV, str(tmp_path))
    monkeypatch.setenv(fi.EPOCH_ENV, repr(time.time() - 10.0))
    p = fi.FaultPlan("seed=3;at=5:data_stall:1|10.3:ckpt_fail")
    p.arm_timed("train")
    time.sleep(0.7)
    # offset 5 was 5 s in the past at arm -> expired, never fired
    assert [f["fault"] for f in p.timed_fired] == ["ckpt_fail"]
    assert any(site == "timed.data_stall" and "expired" in decision
               for site, _, decision in p.schedule)
    # and the anchored entry fired ~0.3 s after arm, not 10.3 s after
    p._timed_stop.set()


def test_timed_stop_event_cancels():
    p = fi.FaultPlan("seed=1;at=0.3:ckpt_fail")
    p.arm_timed("worker")
    p._timed_stop.set()      # uninstall()/install() path
    time.sleep(0.5)
    assert p.timed_fired == []


def test_timed_two_fault_smoke(tmp_path):
    """Seeded two-fault timed schedule against a live 2-worker train
    run: the stall fires first (harmless), the persist failure fails the
    attempt and FailureConfig walks training back to the last
    gang-committed checkpoint. Both firings are exported as replayable
    post-mortem artifacts. Gated N-of-N by tools/flake_gate.py."""
    from ray_tpu import train
    from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train.backend import JaxConfig

    log_dir = tmp_path / "chaos"
    spec = "seed=12;at=1.0:data_stall:0.5@train|2.5:ckpt_fail@train"
    os.environ[fi.LOG_ENV] = str(log_dir)
    with chaos_env(spec):
        ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)
    try:
        def loop(config):
            from ray_tpu import train as train_mod
            from ray_tpu.air.checkpoint import Checkpoint

            start, resumed = 0, None
            ckpt = train_mod.get_checkpoint()
            if ckpt is not None:
                start = resumed = ckpt.to_dict()["step"]
            for i in range(start, 25):
                time.sleep(0.2)
                train_mod.report(
                    {"step": i + 1, "resumed_from": resumed},
                    checkpoint=Checkpoint.from_dict({"step": i + 1}))

        trainer = train.JaxTrainer(
            loop,
            backend_config=JaxConfig(distributed="off", platform="cpu"),
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(
                storage_path=str(tmp_path / "results"), name="timed",
                failure_config=FailureConfig(max_failures=2)),
        )
        result = trainer.fit()
        # the run completed across the injected walk-back
        assert result.metrics["step"] == 25
        assert result.metrics["resumed_from"] is not None
        assert result.metrics["resumed_from"] >= 1

        # both entries fired exactly once (once-sentinels), and every
        # faulted process exported an artifact that replays the plan
        import glob as glob_mod
        fired = []
        for path in glob_mod.glob(str(log_dir / "chaos-*.json")):
            import json
            art = json.loads(open(path).read())
            fired += [f["fault"] for f in art["timed_fired"]]
            replay = fi.FaultPlan.from_artifact(path)
            assert replay.spec == spec
            assert replay.timed == fi.FaultPlan(spec).timed
        assert sorted(fired) == ["ckpt_fail", "data_stall"]
        assert (log_dir / "once-ckpt_fail-2.5-train").exists()
        assert (log_dir / "once-data_stall-1-train").exists()
    finally:
        os.environ.pop(fi.LOG_ENV, None)
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# object-loss matrix rows: lineage recovery under timed faults
# ---------------------------------------------------------------------------


def _cluster_logs_contain(cluster, pattern: str) -> bool:
    import glob as glob_mod

    for path in glob_mod.glob(
            os.path.join(cluster.session_dir, "logs", "*")):
        try:
            with open(path, errors="replace") as f:
                if pattern in f.read():
                    return True
        except OSError:
            continue
    return False


def test_timed_kill_raylet_mid_pipeline_reconstructs(tmp_path):
    """Matrix row: `kill@raylet` lands mid-pipeline on the node holding
    stage-1's plasma outputs. Downstream consumers submitted AFTER the
    node death must still complete — the owner re-executes the lost
    producers from lineage on the surviving node — and the recovered
    arrays are bit-identical to a local recompute. Gated 5/5 by
    tools/flake_gate.py."""
    log_dir = tmp_path / "chaos"
    os.environ[fi.LOG_ENV] = str(log_dir)
    cluster = Cluster(head_resources={"CPU": 2.0},
                      object_store_memory=64 * 1024 * 1024)
    # arm the plan only around the victim's spawn: the kill is scoped to
    # that one raylet process
    with chaos_env("seed=7;at=3:kill@raylet"):
        victim = cluster.add_node({"CPU": 2.0, "scratch": 1.0})
    ray_tpu.init(address=cluster.gcs_addr)
    try:
        affinity = ray_tpu.NodeAffinitySchedulingStrategy(
            victim.node_id_hex, soft=True)

        @ray_tpu.remote(scheduling_strategy=affinity)
        def stage1(i):
            return (np.arange(250_000, dtype=np.uint32) * (i + 1)) \
                .astype(np.uint8)

        @ray_tpu.remote
        def stage2(x):
            return int(x.astype(np.uint64).sum())

        refs = [stage1.remote(i) for i in range(4)]
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=60)
        assert len(ready) == len(refs)

        # the plan fires ~3s after the victim raylet armed; wait for the
        # process to actually die so the consumers race nothing
        deadline = time.monotonic() + 60
        while victim.process.proc.poll() is None \
                and time.monotonic() < deadline:
            time.sleep(0.2)
        assert victim.process.proc.poll() is not None, \
            "chaos kill@raylet never fired"
        time.sleep(1.0)

        expect = [
            int((np.arange(250_000, dtype=np.uint32) * (i + 1))
                .astype(np.uint8).astype(np.uint64).sum())
            for i in range(4)
        ]
        outs = ray_tpu.get([stage2.remote(r) for r in refs],
                           timeout=240)
        assert outs == expect, "re-executed stage-1 outputs differ"
        # and the raw arrays really are bit-identical post-recovery
        arr0 = ray_tpu.get(refs[0], timeout=240)
        assert np.array_equal(
            arr0, (np.arange(250_000, dtype=np.uint32) * 1)
            .astype(np.uint8))
    finally:
        os.environ.pop(fi.LOG_ENV, None)
        ray_tpu.shutdown()
        cluster.shutdown()


def test_timed_drop_objects_sweep_recovers(tmp_path):
    """Matrix row: `drop_objects@raylet` force-deletes every sealed
    object on one node WITHOUT killing the process (silent-loss fault —
    the raylet keeps heartbeating, so only the pull path notices).
    Owned task returns must recover via lineage re-execution."""
    log_dir = tmp_path / "chaos"
    os.environ[fi.LOG_ENV] = str(log_dir)
    cluster = Cluster(object_store_memory=64 * 1024 * 1024)
    with chaos_env("seed=5;at=2:drop_objects:1.0@raylet"):
        cluster.add_node({"CPU": 2.0})
    ray_tpu.init(address=cluster.gcs_addr)
    try:
        @ray_tpu.remote
        def produce(i):
            return np.full(300_000, i + 1, np.uint8)

        refs = [produce.remote(i) for i in range(3)]
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=60)
        assert len(ready) == len(refs)

        # the sweep fires ~2s after the raylet armed and logs its kill
        # count — wait for the evidence before poking the store
        deadline = time.monotonic() + 60
        while not _cluster_logs_contain(
                cluster, "drop_objects force-deleted") \
                and time.monotonic() < deadline:
            time.sleep(0.3)
        assert _cluster_logs_contain(
            cluster, "drop_objects force-deleted"), \
            "drop_objects sweep never fired"

        outs = ray_tpu.get(refs, timeout=240)
        for i, out in enumerate(outs):
            assert out[0] == i + 1 and out.shape == (300_000,), \
                "post-sweep get returned wrong bytes"
    finally:
        os.environ.pop(fi.LOG_ENV, None)
        ray_tpu.shutdown()
        cluster.shutdown()
