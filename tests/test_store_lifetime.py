"""Object-store reference lifetime and allocator accounting regressions."""

import gc

import numpy as np

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID


def _put(store, oid, nbytes):
    buf = store.create_buffer(oid, nbytes)
    buf[:4] = b"xxxx"
    store.seal(oid)
    store.release(oid)  # creator drops its ref


def test_reader_ref_released_on_gc(shm_store):
    """A get() pins the object only while views of it are alive."""
    oid = ObjectID.from_random()
    arr = np.zeros(4 * 1024 * 1024, dtype=np.uint8)
    pickled, bufs = serialization.serialize(arr)
    shm_store.put_serialized(oid, pickled, bufs)

    out = shm_store.get(oid)
    assert out is not None
    del out
    gc.collect()
    # With the reader's ref dropped, the object must be evictable.
    assert shm_store.evict(1) >= 4 * 1024 * 1024
    assert shm_store.get_buffer(oid) is None


def test_live_view_blocks_eviction(shm_store):
    oid = ObjectID.from_random()
    arr = np.zeros(4 * 1024 * 1024, dtype=np.uint8)
    pickled, bufs = serialization.serialize(arr)
    shm_store.put_serialized(oid, pickled, bufs)
    out = shm_store.get(oid)  # live numpy view holds a store ref
    assert shm_store.evict(1) == 0
    assert out.sum() == 0  # memory still intact


def test_allocator_accounting_balances(shm_store):
    """create/delete churn with odd sizes must return allocated to baseline
    (regression: whole-block grants used to leak the unsplit remainder)."""
    baseline = shm_store.stats()["allocated"]
    for round_ in range(5):
        oids = [ObjectID.from_random() for _ in range(50)]
        for i, oid in enumerate(oids):
            shm_store.create_buffer(oid, 1000 + 37 * i + round_)
        for oid in oids:
            shm_store.delete(oid)
    assert shm_store.stats()["allocated"] == baseline


def test_churn_keeps_lookups_fast(shm_store):
    """Heavy create/delete churn must not degrade absent-id lookups
    (regression: tombstone accumulation)."""
    import time

    for _ in range(20):
        oids = [ObjectID.from_random() for _ in range(100)]
        for oid in oids:
            shm_store.create_buffer(oid, 256)
        for oid in oids:
            shm_store.delete(oid)
    start = time.perf_counter()
    for _ in range(1000):
        shm_store.contains(ObjectID.from_random())
    elapsed = time.perf_counter() - start
    assert elapsed < 0.5, f"absent-id lookups too slow: {elapsed:.3f}s"


def test_evict_until_fit(shm_store):
    # Fill with small objects; a large create must evict as many as needed.
    oids = [ObjectID.from_random() for _ in range(14)]
    for oid in oids:
        _put(shm_store, oid, 4 * 1024 * 1024)
    big = ObjectID.from_random()
    buf = shm_store.create_buffer(big, 40 * 1024 * 1024)
    assert buf.nbytes == 40 * 1024 * 1024
