"""Tune tests: search spaces, controller loop, schedulers, stoppers,
failure retry, and Train-on-Tune layering.

Reference ground: `python/ray/tune/tests/test_tune_*.py`,
`test_trial_scheduler.py` — compressed to the essential behaviors.
"""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import RunConfig, FailureConfig


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=8, num_tpus=0,
                 object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@pytest.fixture()
def storage(tmp_path):
    return str(tmp_path / "tune_results")


def test_grid_and_random_resolution():
    gen = tune.BasicVariantGenerator(
        {"lr": tune.grid_search([0.1, 0.01]),
         "wd": tune.grid_search([1, 2]),
         "mom": tune.uniform(0.0, 1.0),
         "nested": {"units": tune.choice([8, 16])}},
        num_samples=2, seed=0)
    cfgs = []
    while True:
        c = gen.suggest(f"t{len(cfgs)}")
        if c is None:
            break
        cfgs.append(c)
    assert len(cfgs) == 8  # 2x2 grid x 2 samples
    assert {(c["lr"], c["wd"]) for c in cfgs} == \
        {(0.1, 1), (0.1, 2), (0.01, 1), (0.01, 2)}
    assert all(0.0 <= c["mom"] <= 1.0 for c in cfgs)
    assert all(c["nested"]["units"] in (8, 16) for c in cfgs)


def test_tuner_function_api(storage):
    def objective(config):
        score = -((config["x"] - 3.0) ** 2)
        for i in range(2):
            tune.report({"score": score + i * 0.01})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([1.0, 3.0, 5.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=storage, name="fn_api"),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result(metric="score", mode="max")
    assert best.metrics["score"] == pytest.approx(0.01)
    # loggers wrote per-trial files
    trial_dirs = [r.path for r in grid]
    assert all(os.path.exists(os.path.join(d, "result.json"))
               for d in trial_dirs)
    assert all(os.path.exists(os.path.join(d, "progress.csv"))
               for d in trial_dirs)


def test_tuner_class_api(storage):
    class Quad(tune.Trainable):
        def setup(self, config):
            self.x = config["x"]
            self.i = 0

        def step(self):
            self.i += 1
            return {"val": self.x * self.i}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "state"), "w") as f:
                f.write(str(self.i))

        def load_checkpoint(self, d):
            with open(os.path.join(d, "state")) as f:
                self.i = int(f.read())

    tuner = tune.Tuner(
        Quad,
        param_space={"x": tune.grid_search([2, 4])},
        tune_config=tune.TuneConfig(metric="val", mode="max"),
        run_config=RunConfig(storage_path=storage, name="cls_api",
                             stop={"training_iteration": 3}),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    vals = sorted(r.metrics["val"] for r in grid)
    assert vals == [6, 12]  # x * 3 iterations


def test_asha_stops_bad_trials(storage):
    def objective(config):
        for i in range(20):
            tune.report({"acc": config["q"] * (i + 1),
                         "training_iteration": i + 1})

    sched = tune.AsyncHyperBandScheduler(
        max_t=20, grace_period=2, reduction_factor=2)
    # good trials first: ASHA is asynchronous, a later-arriving weak trial
    # is culled against the bar set by earlier strong ones
    tuner = tune.Tuner(
        objective,
        param_space={"q": tune.grid_search([1.0, 0.5, 0.2, 0.1])},
        tune_config=tune.TuneConfig(metric="acc", mode="max",
                                    scheduler=sched,
                                    max_concurrent_trials=2),
        run_config=RunConfig(storage_path=storage, name="asha"),
    )
    grid = tuner.fit()
    iters = [len(r.metrics_history) for r in grid]
    # at least one trial must have been early-stopped
    assert min(iters) < 20
    # the best trial survived to max_t (ASHA stops at >= max_t)
    assert max(iters) >= 19


def test_straggler_preempted_by_cancel(storage):
    """An out-of-band stop (time budget) lands while a straggler is
    mid-step: the controller cancels the in-flight step
    (ray_tpu.cancel in _stop_actor) and tears the trial down instead of
    waiting out the step (VERDICT r3 item 5 — Tune preempting
    stragglers)."""
    import time as time_mod

    def objective(config):
        for i in range(5):
            if config["q"] < 0.5:
                # straggler: one cooperative-but-long step per report
                deadline = time_mod.monotonic() + 300
                while time_mod.monotonic() < deadline:
                    time_mod.sleep(0.02)
            tune.report({"acc": config["q"] * (i + 1),
                         "training_iteration": i + 1})

    start = time_mod.monotonic()
    tuner = tune.Tuner(
        objective,
        param_space={"q": tune.grid_search([1.0, 0.1])},
        tune_config=tune.TuneConfig(metric="acc", mode="max",
                                    max_concurrent_trials=2,
                                    time_budget_s=20),
        run_config=RunConfig(storage_path=storage, name="straggler"),
    )
    grid = tuner.fit()
    elapsed = time_mod.monotonic() - start
    # the good trial finished all 5 iters before the budget expired
    iters = [len(r.metrics_history) for r in grid]
    assert max(iters) == 5
    # without preemption the fit would ride out the straggler's 300s
    # step; with the cancel + teardown it must return near the budget
    assert elapsed < 120, f"straggler not preempted ({elapsed:.0f}s)"


def test_failure_retry_restores(storage):
    marker = os.path.join(storage, "crash_marker")

    def flaky(config):
        ckpt = tune.get_checkpoint()
        start = ckpt.to_dict()["i"] + 1 if ckpt else 0
        for i in range(start, 4):
            from ray_tpu.air import Checkpoint
            if i == 2 and not os.path.exists(marker):
                os.makedirs(storage, exist_ok=True)
                open(marker, "w").close()
                raise RuntimeError("synthetic crash")
            tune.report({"i": i}, checkpoint=Checkpoint.from_dict({"i": i}))

    tuner = tune.Tuner(
        flaky,
        param_space={},
        run_config=RunConfig(storage_path=storage, name="flaky",
                             failure_config=FailureConfig(max_failures=2)),
    )
    grid = tuner.fit()
    assert len(grid.errors) == 0
    assert grid[0].metrics["i"] == 3


def test_pbt_exploits(storage):
    def objective(config):
        ckpt = tune.get_checkpoint()
        base = ckpt.to_dict()["score"] if ckpt else 0.0
        for i in range(12):
            from ray_tpu.air import Checkpoint
            base += config["rate"]
            tune.report({"score": base, "rate": config["rate"],
                         "training_iteration": i + 1},
                        checkpoint=Checkpoint.from_dict({"score": base}))

    sched = tune.PopulationBasedTraining(
        time_attr="training_iteration",
        perturbation_interval=3,
        hyperparam_mutations={"rate": [0.5, 1.0, 2.0]},
        quantile_fraction=0.5, seed=0)
    tuner = tune.Tuner(
        objective,
        param_space={"rate": tune.grid_search([0.5, 2.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=sched),
        run_config=RunConfig(storage_path=storage, name="pbt"),
    )
    grid = tuner.fit()
    assert len(grid.errors) == 0
    best = grid.get_best_result(metric="score", mode="max")
    # the slow trial should have been pulled up by exploiting the fast one
    scores = sorted(r.metrics["score"] for r in grid if r.metrics)
    assert scores[-1] > 12 * 0.5  # better than pure-slow trajectory


def test_pb2_gp_explore(storage):
    """PB2: exploit uses GP-UCB selection within hyperparam_bounds —
    configs stay inside the bounds, the GP path actually engages (enough
    observations accumulate), and the population improves on the slow
    trajectory exactly like PBT."""
    def objective(config):
        ckpt = tune.get_checkpoint()
        base = ckpt.to_dict()["score"] if ckpt else 0.0
        for i in range(12):
            from ray_tpu.air import Checkpoint
            base += config["rate"]
            tune.report({"score": base, "rate": config["rate"],
                         "training_iteration": i + 1},
                        checkpoint=Checkpoint.from_dict({"score": base}))

    sched = tune.PB2(
        time_attr="training_iteration",
        perturbation_interval=3,
        hyperparam_bounds={"rate": [0.1, 3.0]},
        quantile_fraction=0.5, seed=0)
    tuner = tune.Tuner(
        objective,
        param_space={"rate": tune.grid_search([0.2, 2.5])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=sched),
        run_config=RunConfig(storage_path=storage, name="pb2"),
    )
    grid = tuner.fit()
    assert len(grid.errors) == 0
    assert len(sched._obs) >= 4  # the GP had data to fit
    # every explored rate stayed within bounds
    for r in grid:
        if r.metrics and "rate" in r.metrics:
            assert 0.1 <= r.metrics["rate"] <= 3.0
    scores = sorted(r.metrics["score"] for r in grid if r.metrics)
    assert scores[-1] > 12 * 0.2  # beat the pure-slow trajectory


def test_pb2_selection_is_gp_driven():
    """With seeded observations favoring high rate, the GP-UCB argmax
    should land in the high-reward region, not uniformly."""
    sched = tune.PB2(metric="score", mode="max",
                     hyperparam_bounds={"rate": [0.0, 1.0]}, seed=1)
    # synthetic: reward-improvement grows with rate
    for i in range(30):
        rate = i / 29.0
        sched._obs.append([float(i), rate, rate * 2.0])
    picks = [sched._mutate({"rate": 0.5})["rate"] for _ in range(8)]
    assert all(0.0 <= p <= 1.0 for p in picks)
    assert sum(p > 0.6 for p in picks) >= 6, picks


def test_train_runs_on_tune(storage):
    """Reference layering: BaseTrainer.fit wraps itself as a Trainable
    (`python/ray/train/base_trainer.py:567`)."""
    from ray_tpu import train
    from ray_tpu.air import ScalingConfig

    def loop(config):
        for step in range(2):
            train.report({"step": step})

    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=storage, name="train_on_tune"),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 1
    assert result.error is None


def test_min_mode_propagates_to_scheduler(storage):
    """TuneConfig(mode='min') must reach the scheduler (ASHA keeps the
    LOWEST-loss trials)."""
    def objective(config):
        for i in range(10):
            tune.report({"loss": config["l"] * (i + 1),
                         "training_iteration": i + 1})

    sched = tune.AsyncHyperBandScheduler(max_t=10, grace_period=2,
                                         reduction_factor=2)
    tuner = tune.Tuner(
        objective,
        # low-loss (good) trials first so ASHA culls the later bad ones
        param_space={"l": tune.grid_search([0.1, 0.2, 5.0, 10.0])},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    scheduler=sched,
                                    max_concurrent_trials=2),
        run_config=RunConfig(storage_path=storage, name="min_mode"),
    )
    grid = tuner.fit()
    by_l = {r.metrics_history[0]["loss"]: len(r.metrics_history)
            for r in grid}
    # the high-loss trials must have been stopped early
    assert min(len(r.metrics_history) for r in grid) < 10
    # and a low-loss trial survived to the end
    assert by_l[0.1] >= 9


def test_adaptive_searcher_sees_results(storage):
    """Custom searcher contract: suggests are lazy, so results from early
    trials can shape later suggestions."""
    class Adaptive(tune.Searcher):
        def __init__(self):
            super().__init__(metric="score", mode="max")
            self.observed = []

        def suggest(self, trial_id):
            if not self.observed:
                return {"x": 1.0}
            return {"x": max(self.observed) + 1.0}

        def on_trial_complete(self, trial_id, result=None, error=False):
            if result:
                self.observed.append(result["score"])

    def objective(config):
        tune.report({"score": config["x"]})

    tuner = tune.Tuner(
        objective,
        tune_config=tune.TuneConfig(num_samples=3, max_concurrent_trials=1,
                                    search_alg=Adaptive(),
                                    metric="score", mode="max"),
        run_config=RunConfig(storage_path=storage, name="adaptive"),
    )
    grid = tuner.fit()
    scores = sorted(r.metrics["score"] for r in grid)
    assert scores == [1.0, 2.0, 3.0]  # each suggest built on the last


def test_tpe_search_converges_better_than_uniform():
    """Native TPE (the reference's OptunaSearch default algorithm)
    concentrates samples near the optimum of a smooth objective."""
    from ray_tpu.tune.search import TPESearch

    def objective(x, y):
        return -((x - 0.7) ** 2) - ((y - 0.2) ** 2)

    searcher = TPESearch(metric="score", mode="max",
                         n_initial_points=8, seed=7)
    searcher.set_search_properties("score", "max", {
        "x": tune.uniform(0.0, 1.0),
        "y": tune.uniform(0.0, 1.0),
    })
    best = -1e9
    last10 = []
    for i in range(60):
        cfg = searcher.suggest(f"t{i}")
        score = objective(cfg["x"], cfg["y"])
        searcher.on_trial_complete(f"t{i}", {"score": score})
        best = max(best, score)
        if i >= 50:
            last10.append(cfg)
    assert best > -0.02, f"TPE never got close: best={best}"
    # exploitation: late samples cluster near the optimum
    mean_x = sum(c["x"] for c in last10) / len(last10)
    mean_y = sum(c["y"] for c in last10) / len(last10)
    assert abs(mean_x - 0.7) < 0.25 and abs(mean_y - 0.2) < 0.25


def test_tpe_with_tuner(tmp_path):
    from ray_tpu.air.config import RunConfig
    from ray_tpu.tune.search import TPESearch

    def trainable(config):
        tune.report({"loss": (config["lr"] - 0.01) ** 2,
                     "choice_used": config["opt"]})

    tuner = tune.Tuner(
        trainable,
        param_space={
            "lr": tune.loguniform(1e-4, 1.0),
            "opt": tune.choice(["adam", "sgd"]),
        },
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=12,
            search_alg=TPESearch(seed=3)),
        run_config=RunConfig(storage_path=str(tmp_path), name="tpe"),
    )
    grid = tuner.fit()
    assert len(grid) == 12
    best = grid.get_best_result("loss", mode="min")
    assert best.metrics["loss"] < 0.05


def test_bohb_budget_model_selection():
    """BOHB builds its TPE model from the largest budget with enough
    observations: misleading low-budget scores are overridden once
    high-budget evidence accumulates."""
    from ray_tpu.tune.search import BOHBSearch

    searcher = BOHBSearch(metric="score", mode="max",
                          n_initial_points=4, seed=11)
    searcher.set_search_properties("score", "max",
                                   {"x": tune.uniform(0.0, 1.0)})
    # low budget (rung 1) lies: rewards x near 0. high budget (rung 9)
    # tells the truth: rewards x near 0.8
    for i in range(30):
        cfg = searcher.suggest(f"t{i}")
        x = cfg["x"]
        searcher.on_trial_result(f"t{i}",
                                 {"score": -abs(x - 0.0),
                                  "training_iteration": 1})
        searcher.on_trial_complete(f"t{i}",
                                   {"score": -abs(x - 0.8),
                                    "training_iteration": 9})
    late = []
    for i in range(30, 42):
        cfg = searcher.suggest(f"t{i}")
        late.append(cfg["x"])
        searcher.on_trial_complete(f"t{i}",
                                   {"score": -abs(cfg["x"] - 0.8),
                                    "training_iteration": 9})
    mean_x = sum(late) / len(late)
    assert abs(mean_x - 0.8) < 0.3, f"BOHB ignored the big budget: {mean_x}"


def test_bohb_with_hyperband_tuner(tmp_path):
    """BOHB + HyperBand end-to-end through the Tuner (the reference's
    TuneBOHB + HyperBandForBOHB pairing)."""
    from ray_tpu.air.config import RunConfig
    from ray_tpu.tune.schedulers import HyperBandScheduler
    from ray_tpu.tune.search import BOHBSearch

    def trainable(config):
        for i in range(8):
            tune.report({"loss": (config["lr"] - 0.1) ** 2 / (i + 1)})

    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.loguniform(1e-3, 1.0)},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=10,
            search_alg=BOHBSearch(seed=5),
            scheduler=HyperBandScheduler(max_t=8)),
        run_config=RunConfig(storage_path=str(tmp_path), name="bohb"),
    )
    grid = tuner.fit()
    assert len(grid) == 10
    best = min(r.metrics["loss"] for r in grid if r.error is None)
    assert best < 0.5


def test_pb2_exploit_resets_segment_baseline():
    """After an exploit, the next report must not contribute a GP row
    (the donor-checkpoint score jump is not the new config's doing)."""
    sched = tune.PB2(metric="score", mode="max",
                     hyperparam_bounds={"rate": [0.0, 1.0]}, seed=0)

    class T:
        trial_id = "t1"
        config = {"rate": 0.5}

    class C:  # controller stub: only what on_trial_result touches
        def checkpoint_trial(self, trial):
            return "ckpt"

    sched.set_metric("score", "max")
    sched.on_trial_result(C(), T(), {"score": 1.0,
                                     "training_iteration": 1})
    sched.on_trial_result(C(), T(), {"score": 2.0,
                                     "training_iteration": 2})
    assert len(sched._obs) == 1
    sched._on_exploit("t1")  # what PBT fires after exploit_trial
    # first post-exploit report: baseline gone -> no spurious row
    sched.on_trial_result(C(), T(), {"score": 9.0,
                                     "training_iteration": 3})
    assert len(sched._obs) == 1
    # subsequent segments resume normally
    sched.on_trial_result(C(), T(), {"score": 9.5,
                                     "training_iteration": 4})
    assert len(sched._obs) == 2


def test_pb2_rejects_missing_bounds_key():
    sched = tune.PB2(metric="score", mode="max",
                     hyperparam_bounds={"lr": [0.0, 1.0]})

    class T:
        trial_id = "t1"
        config = {"learning_rate": 0.1}  # typo'd key

    import pytest as _pytest
    with _pytest.raises(ValueError, match="hyperparam_bounds"):
        sched.on_trial_add(None, T())
