"""Streaming generator tests (num_returns="streaming").

Reference surface: `python/ray/_raylet.pyx:273` ObjectRefGenerator,
`ReportGeneratorItemReturns` (core_worker.proto:462), generator_waiter
backpressure, and `python/ray/tests/test_streaming_generator.py`.
"""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_task_stream_basic():
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    out = [ray_tpu.get(ref) for ref in gen.remote(5)]
    assert out == [0, 10, 20, 30, 40]


def test_stream_consume_while_producing():
    """Items are visible to the consumer before the producer finishes."""
    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(4):
            yield (i, time.time())
            time.sleep(0.3)

    g = slow_gen.remote()
    first_ref = next(g)
    i, produced_at = ray_tpu.get(first_ref)
    consumed_at = time.time()
    assert i == 0
    # consumed well before the ~0.9s the remaining items take to produce
    assert consumed_at - produced_at < 0.9
    rest = [ray_tpu.get(r)[0] for r in g]
    assert rest == [1, 2, 3]


def test_stream_early_close_cancels_producer():
    @ray_tpu.remote
    class Recorder:
        def __init__(self):
            self.count = 0

        def bump(self):
            self.count += 1

        def get(self):
            return self.count

    rec = Recorder.remote()

    @ray_tpu.remote(num_returns="streaming")
    def gen(rec):
        i = 0
        while True:
            ray_tpu.get(rec.bump.remote())
            yield i
            i += 1

    g = gen.remote(rec)
    next(g)
    next(g)
    g.close()
    time.sleep(1.0)
    produced = ray_tpu.get(rec.get.remote())
    # backpressure caps the run-ahead; cancellation stops it entirely
    cap = 16 + 4
    assert produced <= cap, f"producer kept running: {produced} items"
    snapshot = produced
    time.sleep(1.0)
    assert ray_tpu.get(rec.get.remote()) == snapshot  # fully stopped


def test_stream_backpressure_limits_runahead():
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1

        def get(self):
            return self.n

    c = Counter.remote()

    @ray_tpu.remote(num_returns="streaming")
    def gen(c):
        for i in range(100):
            ray_tpu.get(c.bump.remote())
            yield i

    g = gen.remote(c)
    next(g)  # consume one, then stall
    time.sleep(1.5)
    produced = ray_tpu.get(c.get.remote())
    assert produced <= 16 + 2, \
        f"producer ran {produced} items ahead of a stalled consumer"
    # drain; everything arrives in order
    rest = [ray_tpu.get(r) for r in g]
    assert rest == list(range(1, 100))


def test_stream_midway_error_surfaces_on_get():
    @ray_tpu.remote(num_returns="streaming")
    def gen():
        yield 1
        yield 2
        raise ValueError("boom at 2")

    g = gen.remote()
    assert ray_tpu.get(next(g)) == 1
    assert ray_tpu.get(next(g)) == 2
    err_ref = next(g)
    with pytest.raises(ray_tpu.RayTaskError):
        ray_tpu.get(err_ref)
    with pytest.raises(StopIteration):
        next(g)


def test_stream_non_generator_errors():
    @ray_tpu.remote(num_returns="streaming")
    def not_gen():
        return 42

    g = not_gen.remote()
    with pytest.raises(ray_tpu.RayTaskError):
        next(g)


def test_actor_sync_generator_method():
    @ray_tpu.remote
    class Producer:
        def stream(self, n):
            for i in range(n):
                yield f"item-{i}"

    p = Producer.remote()
    g = p.stream.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r) for r in g] == ["item-0", "item-1", "item-2"]


def test_async_actor_generator_method():
    @ray_tpu.remote
    class AsyncProducer:
        async def stream(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.01)
                yield i * i

    p = AsyncProducer.remote()
    g = p.stream.options(num_returns="streaming").remote(4)
    assert [ray_tpu.get(r) for r in g] == [0, 1, 4, 9]


def test_stream_large_items_via_plasma():
    import numpy as np

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        for i in range(3):
            yield np.full(300_000, i, np.uint8)  # > inline threshold

    for i, ref in enumerate(gen.remote()):
        arr = ray_tpu.get(ref)
        assert arr.shape == (300_000,) and arr[0] == i
