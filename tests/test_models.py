"""Model zoo: GPT + ResNet forward/backward, sharded end-to-end on the
8-device mesh with DP/FSDP/TP rules applied from logical annotations."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import GPT, GPTConfig, ResNet, ResNetConfig
from ray_tpu.models.gpt import count_params, cross_entropy_loss
from ray_tpu.parallel import ShardingStrategy, logical_axis_rules


def test_gpt_forward_loss():
    cfg = GPTConfig.tiny()
    model = GPT(cfg)
    tokens = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    loss = cross_entropy_loss(logits, tokens)
    # Roughly -log(1/vocab) at init.
    assert 4.0 < float(loss) < 8.0


def test_gpt_param_count_125m():
    cfg = GPTConfig.gpt2_125m()
    model = GPT(cfg)
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.ones((1, 8), jnp.int32))
    )
    n = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    assert 120e6 < n < 170e6  # 124M + padded vocab


def _run_sharded_step(strategy):
    """One pjit train step under DP / DP+FSDP / DP+FSDP+TP; loss must agree
    across strategies (same math, different shardings)."""
    cfg = GPTConfig.tiny(dtype=jnp.float32, remat=False)
    model = GPT(cfg)
    mesh = strategy.build_mesh()
    rules = logical_axis_rules(strategy)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                cfg.vocab_size)

    with mesh, nn.logical_axis_rules(rules):
        params = model.init(jax.random.PRNGKey(0), tokens)
        tx = optax.adamw(1e-3)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, tokens):
            def loss_fn(p):
                logits = model.apply(p, tokens[:, :-1])
                return cross_entropy_loss(logits, tokens[:, 1:])

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        params, opt_state, loss1 = step(params, opt_state, tokens)
        _, _, loss2 = step(params, opt_state, tokens)
    assert float(loss2) < float(loss1)  # it learns
    return float(loss1)


@pytest.mark.parametrize("strategy", [
    ShardingStrategy(dp=8),
    ShardingStrategy(dp=2, fsdp=4),
    ShardingStrategy(dp=2, fsdp=2, tp=2),
])
def test_gpt_sharded_train_step(strategy):
    _run_sharded_step(strategy)


def test_strategies_agree_on_loss():
    losses = [
        _run_sharded_step(ShardingStrategy(dp=8)),
        _run_sharded_step(ShardingStrategy(dp=2, fsdp=2, tp=2)),
    ]
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-4)


def test_resnet_forward_backward():
    cfg = ResNetConfig.resnet18(num_classes=10, small_images=True,
                                dtype=jnp.float32)
    model = ResNet(cfg)
    imgs = jnp.ones((4, 32, 32, 3))
    labels = jnp.array([0, 1, 2, 3])
    variables = model.init(jax.random.PRNGKey(0), imgs, train=False)

    def loss_fn(params):
        logits, updates = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            imgs, train=True, mutable=["batch_stats"],
        )
        onehot = jax.nn.one_hot(labels, 10)
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))

    loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
    assert float(loss) > 0
    gnorm = sum(float(jnp.abs(g).sum())
                for g in jax.tree_util.tree_leaves(grads))
    assert gnorm > 0
