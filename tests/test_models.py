"""Model zoo: GPT + ResNet forward/backward, sharded end-to-end on the
8-device mesh with DP/FSDP/TP rules applied from logical annotations."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import GPT, GPTConfig, ResNet, ResNetConfig
from ray_tpu.models.gpt import count_params, cross_entropy_loss
from ray_tpu.parallel import ShardingStrategy, logical_axis_rules


def test_gpt_forward_loss():
    cfg = GPTConfig.tiny()
    model = GPT(cfg)
    tokens = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    loss = cross_entropy_loss(logits, tokens)
    # Roughly -log(1/vocab) at init.
    assert 4.0 < float(loss) < 8.0


def test_gpt_param_count_125m():
    cfg = GPTConfig.gpt2_125m()
    model = GPT(cfg)
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.ones((1, 8), jnp.int32))
    )
    n = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    assert 120e6 < n < 170e6  # 124M + padded vocab


def _run_sharded_step(strategy):
    """One pjit train step under DP / DP+FSDP / DP+FSDP+TP; loss must agree
    across strategies (same math, different shardings)."""
    cfg = GPTConfig.tiny(dtype=jnp.float32, remat=False)
    model = GPT(cfg)
    mesh = strategy.build_mesh()
    rules = logical_axis_rules(strategy)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                cfg.vocab_size)

    with mesh, nn.logical_axis_rules(rules):
        params = model.init(jax.random.PRNGKey(0), tokens)
        tx = optax.adamw(1e-3)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, tokens):
            def loss_fn(p):
                logits = model.apply(p, tokens[:, :-1])
                return cross_entropy_loss(logits, tokens[:, 1:])

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        params, opt_state, loss1 = step(params, opt_state, tokens)
        _, _, loss2 = step(params, opt_state, tokens)
    assert float(loss2) < float(loss1)  # it learns
    return float(loss1)


@pytest.mark.parametrize("strategy", [
    ShardingStrategy(dp=8),
    ShardingStrategy(dp=2, fsdp=4),
    ShardingStrategy(dp=2, fsdp=2, tp=2),
])
def test_gpt_sharded_train_step(strategy):
    _run_sharded_step(strategy)


def test_strategies_agree_on_loss():
    losses = [
        _run_sharded_step(ShardingStrategy(dp=8)),
        _run_sharded_step(ShardingStrategy(dp=2, fsdp=2, tp=2)),
    ]
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-4)


def test_resnet_forward_backward():
    cfg = ResNetConfig.resnet18(num_classes=10, small_images=True,
                                dtype=jnp.float32)
    model = ResNet(cfg)
    imgs = jnp.ones((4, 32, 32, 3))
    labels = jnp.array([0, 1, 2, 3])
    variables = model.init(jax.random.PRNGKey(0), imgs, train=False)

    def loss_fn(params):
        logits, updates = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            imgs, train=True, mutable=["batch_stats"],
        )
        onehot = jax.nn.one_hot(labels, 10)
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))

    loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
    assert float(loss) > 0
    gnorm = sum(float(jnp.abs(g).sum())
                for g in jax.tree_util.tree_leaves(grads))
    assert gnorm > 0


def test_llama_forward_loss():
    from ray_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = Llama(cfg)
    tokens = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    loss = cross_entropy_loss(logits, tokens)
    assert 4.0 < float(loss) < 8.0


def test_llama_gqa_kv_heads_shrink_params():
    """GQA: fewer KV heads -> smaller fused QKV kernel than MHA."""
    from ray_tpu.models import Llama, LlamaConfig

    def qkv_features(n_kv):
        cfg = LlamaConfig.tiny(dtype=jnp.float32, n_kv_head=n_kv)
        model = Llama(cfg)
        shapes = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.ones((1, 8), jnp.int32)))
        kernel = shapes["params"]["layer0"]["attn_qkv"]["kernel"]
        return jax.tree_util.tree_leaves(kernel)[0].shape[-1]

    assert qkv_features(2) < qkv_features(4)  # 4 == n_head -> MHA


def test_llama_rope_rotation_properties():
    """RoPE preserves norms and is position-dependent."""
    from ray_tpu.models.llama import apply_rope, rope_tables

    cos, sin = rope_tables(32, 8, 10000.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 2, 8))
    y = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(y), axis=-1), rtol=1e-5)
    # position 0 is the identity rotation
    np.testing.assert_allclose(np.asarray(y[:, 0]), np.asarray(x[:, 0]),
                               rtol=1e-6)
    assert not np.allclose(np.asarray(y[:, 1]), np.asarray(x[:, 1]))


@pytest.mark.parametrize("strategy", [
    ShardingStrategy(dp=2, fsdp=2, tp=2),
])
def test_llama_sharded_train_step(strategy):
    from ray_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(dtype=jnp.float32, remat=False)
    model = Llama(cfg)
    mesh = strategy.build_mesh()
    rules = logical_axis_rules(strategy)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                cfg.vocab_size)
    with mesh, nn.logical_axis_rules(rules):
        params = model.init(jax.random.PRNGKey(0), tokens)
        tx = optax.adamw(1e-3)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, tokens):
            def loss_fn(p):
                logits = model.apply(p, tokens[:, :-1])
                return cross_entropy_loss(logits, tokens[:, 1:])
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        params, opt_state, loss1 = step(params, opt_state, tokens)
        _, _, loss2 = step(params, opt_state, tokens)
    assert float(loss2) < float(loss1)


def test_vit_forward_backward():
    from ray_tpu.models import ViT, ViTConfig

    cfg = ViTConfig.tiny(dtype=jnp.float32)
    model = ViT(cfg)
    imgs = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 32, 3))
    labels = jnp.array([0, 1, 2, 3])
    params = model.init(jax.random.PRNGKey(1), imgs)
    logits = model.apply(params, imgs)
    assert logits.shape == (4, cfg.num_classes)

    def loss_fn(p):
        lg = model.apply(p, imgs)
        onehot = jax.nn.one_hot(labels, cfg.num_classes)
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(lg), -1))

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    gnorm = sum(float(jnp.abs(g).sum())
                for g in jax.tree_util.tree_leaves(grads))
    assert gnorm > 0


def test_moe_gpt_forward_and_aux_loss():
    from ray_tpu.models import MoEGPT, MoEGPTConfig
    from ray_tpu.models.moe_gpt import total_aux_loss

    cfg = MoEGPTConfig.tiny(dtype=jnp.float32, remat=False)
    model = MoEGPT(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0,
                                cfg.vocab_size)
    variables = model.init(jax.random.PRNGKey(1), tokens)
    logits, aux_vars = model.apply(variables, tokens,
                                   mutable=["moe_aux_loss"])
    assert logits.shape == (2, 16, cfg.vocab_size)
    aux = total_aux_loss(aux_vars)
    # Switch aux loss is ~1.0-ish at uniform routing, scaled by coeff
    assert 0 < float(aux) < 1.0
    # expert params exist with a leading num_experts axis
    k = variables["params"]["h0"]["moe"]["experts_up"]
    assert jax.tree_util.tree_leaves(k)[0].shape[0] == cfg.num_experts


def test_moe_gpt_expert_sharded_train_step():
    """MoE decoder trains under dp x ep sharding: expert params placed
    over the ep axis (GSPMD all_to_all dispatch), loss decreases."""
    from ray_tpu.models import MoEGPT, MoEGPTConfig
    from ray_tpu.models.moe_gpt import total_aux_loss

    strategy = ShardingStrategy(dp=2, ep=4)
    cfg = MoEGPTConfig.tiny(dtype=jnp.float32, remat=False)
    model = MoEGPT(cfg)
    mesh = strategy.build_mesh()
    rules = logical_axis_rules(strategy)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                cfg.vocab_size)
    with mesh, nn.logical_axis_rules(rules):
        variables = model.init(jax.random.PRNGKey(0), tokens)
        params = variables["params"]
        tx = optax.adamw(1e-3)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, tokens):
            def loss_fn(p):
                logits, aux_vars = model.apply(
                    {"params": p}, tokens[:, :-1],
                    mutable=["moe_aux_loss"])
                return (cross_entropy_loss(logits, tokens[:, 1:])
                        + total_aux_loss(aux_vars))
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        params, opt_state, loss1 = step(params, opt_state, tokens)
        _, _, loss2 = step(params, opt_state, tokens)
    assert float(loss2) < float(loss1)


def test_chunked_cross_entropy_matches_dense():
    """Blockwise LM-head loss == full-logits loss (incl. a non-divisible
    tail chunk and ignore_index masking)."""
    from ray_tpu.models import GPT, GPTConfig
    from ray_tpu.models.gpt import chunked_cross_entropy

    cfg = GPTConfig.tiny(dtype=jnp.float32)
    model = GPT(cfg)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 34)))
    targets = toks[:, 1:].at[0, 5].set(-1)  # masked position
    params = model.init(jax.random.PRNGKey(0), toks[:, :-1])
    dense = cross_entropy_loss(model.apply(params, toks[:, :-1]), targets)
    hidden, wte = model.apply(params, toks[:, :-1], return_hidden=True)
    chunked = chunked_cross_entropy(hidden, wte, targets, chunk_size=8)
    np.testing.assert_allclose(float(dense), float(chunked), rtol=1e-5)
    # gradients must match too (scan backward correctness)
    g1 = jax.grad(lambda p: cross_entropy_loss(
        model.apply(p, toks[:, :-1]), targets))(params)
    g2 = jax.grad(lambda p: chunked_cross_entropy(
        *model.apply(p, toks[:, :-1], return_hidden=True), targets,
        chunk_size=8))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


# --------------------------------------------------------------------------
# BERT-family bidirectional encoder
# --------------------------------------------------------------------------

def test_bert_encoder_is_bidirectional():
    """Changing a LATER token must change an EARLIER position's hidden
    state (a causal decoder would leave it untouched)."""
    from ray_tpu.models import BertConfig, BertEncoder

    cfg = BertConfig.tiny(remat=False)
    enc = BertEncoder(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 16)))
    params = enc.init(jax.random.PRNGKey(0), tokens)
    h1, _ = enc.apply(params, tokens)
    tokens2 = tokens.at[0, 12].set((int(tokens[0, 12]) + 1)
                                   % cfg.vocab_size)
    h2, _ = enc.apply(params, tokens2)
    # position 3 sees position 12 through bidirectional attention
    assert float(jnp.abs(h1[0, 3] - h2[0, 3]).max()) > 0


def test_bert_mlm_trains():
    """80/10/10 corruption + fused-CE MLM loss decreases, and the loss
    only scores masked positions (ignore_index contract)."""
    import optax

    from ray_tpu.models import (BertConfig, BertEncoder, mask_tokens,
                                mlm_loss)

    cfg = BertConfig.tiny(remat=False)
    enc = BertEncoder(cfg)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size - 1, (4, 32)))
    mask_id = cfg.vocab_size - 1
    corrupted, targets = mask_tokens(
        tokens, jax.random.PRNGKey(0), mask_token_id=mask_id,
        vocab_size=cfg.vocab_size)
    assert int((targets >= 0).sum()) > 0           # some positions masked
    assert int((targets >= 0).sum()) < targets.size  # not all
    params = enc.init(jax.random.PRNGKey(0), corrupted)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: mlm_loss(enc, p, corrupted, targets))(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, first = step(params, opt_state)
    for _ in range(25):
        params, opt_state, loss = step(params, opt_state)
    assert float(loss) < float(first)


def test_bert_shards_like_the_decoders():
    """The encoder carries the same logical axes, so DP/TP sharding
    applies unchanged (outputs equal across strategies)."""
    import flax.linen as nn

    from ray_tpu.models import BertConfig, BertEncoder
    from ray_tpu.parallel import ShardingStrategy, logical_axis_rules

    cfg = BertConfig.tiny(remat=False)
    enc = BertEncoder(cfg)
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)))
    params = enc.init(jax.random.PRNGKey(0), tokens)
    ref, _ = enc.apply(params, tokens)

    strategy = ShardingStrategy(dp=2, tp=2)
    mesh = strategy.build_mesh(jax.devices()[:4])
    with mesh, nn.logical_axis_rules(logical_axis_rules(strategy)):
        out, _ = jax.jit(lambda p, t: enc.apply(p, t))(params, tokens)
    # bf16 activations reassociate differently under tp sharding
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=5e-2, rtol=5e-2)
