"""Flight-recorder tests: StepStats ring, dispatch sampling, metrics
export, cross-process unified timeline, fork-safe shard writers.

Reference ground: the reference exports task state + OpenCensus metrics
+ `ray timeline` as a first-class observability layer; this suite pins
the reproduction's equivalents (ISSUE 5).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import step_profiler as sp


@pytest.fixture(autouse=True)
def _clean_recorder():
    sp.refresh()
    sp.clear()
    yield
    sp.clear()


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

def test_ring_bounds_and_eviction_under_sustained_stepping(monkeypatch):
    """Sustained stepping must hold steady memory: the ring keeps the
    newest `capacity` records and the total counter keeps counting."""
    monkeypatch.setenv("RAY_TPU_STEP_RING", "32")
    sp.refresh()
    try:
        for i in range(3 * 32 + 5):
            sp.record_step(i, 1.0)
        assert len(sp.ring()) == 32
        assert sp.ring().total_recorded == 3 * 32 + 5
        steps = [r["step"] for r in sp.recent()]
        # oldest evicted, newest kept, order preserved
        assert steps == list(range(69, 101))
        assert sp.recent(5)[-1]["step"] == 100
    finally:
        monkeypatch.delenv("RAY_TPU_STEP_RING")
        sp.refresh()


def test_record_step_computes_mfu_from_tokens_flops():
    rec = sp.record_step(1, 100.0, tokens=1000, flops=5e10, peak=1e12)
    # 5e10 flops in 0.1 s against a 1e12 flop/s peak -> 0.5 MFU
    assert rec.mfu == pytest.approx(0.5)
    # no peak (CPU) and none supplied -> no MFU claim
    rec2 = sp.record_step(2, 100.0, tokens=1000, flops=5e10)
    assert rec2.mfu is None


def test_disabled_recorder_is_inert():
    sp.set_enabled(False)
    try:
        assert sp.record_step(1, 1.0) is None
        sp.add_phase_ms("checkpoint_ms", 5.0)
        assert len(sp.ring()) == 0
    finally:
        sp.set_enabled(True)


def test_pending_phase_accumulators_fold_into_next_step():
    sp.add_phase_ms("checkpoint_ms", 7.0)
    sp.add_phase_ms("collective_ms", 3.0)
    sp.add_phase_ms("collective_ms", 2.0)
    rec = sp.record_step(1, 50.0)
    assert rec.checkpoint_ms == pytest.approx(7.0)
    assert rec.collective_ms == pytest.approx(5.0)
    # consumed: the next step starts clean
    rec2 = sp.record_step(2, 50.0)
    assert rec2.checkpoint_ms == 0.0


def test_attribution_sums_to_one():
    sp.record_step(1, 100.0, host_dispatch_ms=10.0,
                   device_execute_ms=60.0, data_wait_ms=20.0)
    attr = sp.attribution()
    assert attr["host_dispatch"] == pytest.approx(0.10)
    assert attr["device_execute"] == pytest.approx(0.60)
    assert attr["other"] == pytest.approx(0.10)
    assert sum(attr.values()) == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# compiled_step dispatch sampling + TrainStepRunner integration
# ---------------------------------------------------------------------------

def test_compiled_step_samples_dispatch(monkeypatch):
    import jax.numpy as jnp

    from ray_tpu.parallel.compile_cache import (ExecutableCache,
                                                compiled_step)

    monkeypatch.setenv("RAY_TPU_DISPATCH_SAMPLE", "4")
    sp.refresh()
    sp.clear()
    try:
        tick = compiled_step(lambda x: x + 1, cache=ExecutableCache())
        x = jnp.zeros(())
        for _ in range(16):
            x = tick(x)
        stats = sp.dispatch_stats()
        assert stats["calls"] == 16
        assert stats["sampled"] == 4  # 1 in 4
        assert stats["p50_ms"] >= 0
    finally:
        monkeypatch.delenv("RAY_TPU_DISPATCH_SAMPLE")
        sp.refresh()


def test_train_step_runner_records_step_stats():
    import jax.numpy as jnp

    from ray_tpu import train

    def step(carry, batch):
        return carry + jnp.sum(batch), carry

    runner = train.TrainStepRunner(step, steps_per_call=2,
                                   donate_carry=False,
                                   tokens_per_step=128,
                                   flops_per_step=1e6, peak_flops=1e12)
    carry = jnp.zeros(())
    batches = iter([jnp.ones(4)] * 8)
    carry, _aux = runner.run(carry, batches)
    carry, _aux = runner.run(carry, batches)
    recs = runner.step_stats()
    assert len(recs) == 2
    assert recs[-1]["step"] == 4                # 2 dispatches x K=2
    assert recs[-1]["steps_per_call"] == 2
    assert recs[-1]["tokens"] == 256
    assert recs[-1]["total_ms"] > 0
    assert recs[-1]["host_dispatch_ms"] > 0
    assert recs[-1]["mfu"] is not None          # peak supplied
    # the lowering/compile time is accounted by the cache, not the step
    assert runner.cache_stats()["misses"] >= 1


def test_compile_cache_tracks_lowering_ms():
    import jax.numpy as jnp

    from ray_tpu.parallel.compile_cache import (ExecutableCache,
                                                compiled_step)

    cache = ExecutableCache()
    tick = compiled_step(lambda x: x * 2, cache=cache)
    tick(jnp.zeros(3))
    assert cache.stats.lowering_ms > 0
    # as_dict stays counter-only (bench/test equality contracts)
    assert set(cache.stats.as_dict()) == {"hits", "misses", "retraces"}


# ---------------------------------------------------------------------------
# metrics export
# ---------------------------------------------------------------------------

def test_registry_callback_exposes_flight_recorder():
    # importing a plane registers its scrape callback — a process that
    # exercises the compile cache / channels exposes them automatically
    import ray_tpu.experimental.channel  # noqa: F401
    import ray_tpu.parallel.compile_cache  # noqa: F401

    sp.record_step(3, 20.0, host_dispatch_ms=2.0, tokens=64,
                   flops=1e9, peak=1e12)
    text = metrics_mod.DEFAULT_REGISTRY.prometheus_text()
    assert "train_steps_recorded_total 1" in text
    assert 'train_step_time_ms{phase="total"} 20.0' in text
    assert "train_step_mfu" in text
    assert "compile_cache_hits_total" in text       # compile cache rides
    assert "channel_frames_total" in text           # channel plane rides


def test_registry_callback_errors_do_not_break_scrape():
    reg = metrics_mod._Registry()
    metrics_mod.Counter("ok_total", "fine", registry=reg).inc()
    reg.register_callback("bad", lambda: 1 / 0)
    reg.register_callback("good", lambda: "extra_metric 1\n")
    text = reg.prometheus_text()
    assert "ok_total 1.0" in text
    assert "extra_metric 1" in text


def test_label_values_escaped_per_text_format():
    reg = metrics_mod._Registry()
    c = metrics_mod.Counter("named_total", "names", ("name",),
                            registry=reg)
    c.inc(tags={"name": 'quo"te'})
    c.inc(tags={"name": "back\\slash"})
    c.inc(tags={"name": "new\nline"})
    text = reg.prometheus_text()
    assert 'named_total{name="quo\\"te"} 1.0' in text
    assert 'named_total{name="back\\\\slash"} 1.0' in text
    assert 'named_total{name="new\\nline"} 1.0' in text


def test_serve_metrics_body_ends_with_eof():
    import asyncio
    import urllib.request

    async def scrape():
        reg = metrics_mod._Registry()
        metrics_mod.Gauge("g", "gauge", registry=reg).set(1)
        server, port = await metrics_mod.serve_metrics(registry=reg)
        try:
            body = await asyncio.get_event_loop().run_in_executor(
                None,
                lambda: urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=10).read().decode())
        finally:
            server.close()
        return body

    body = asyncio.run(scrape())
    assert body.endswith("# EOF\n")
    assert "g 1.0" in body


# ---------------------------------------------------------------------------
# unified timeline: shards + flow arrows across processes
# ---------------------------------------------------------------------------

_CHILD_SPANS = """
import os
import jax
jax.config.update("jax_platforms", "cpu")
from ray_tpu.util import tracing, step_profiler
with tracing.span("channel.read", kind="consumer",
                  attrs={"channel": "ch0", "seq": 7,
                         "flow_id": "ch0:7"}):
    pass
step_profiler.record_step(11, 4.5, host_dispatch_ms=1.0)
"""


def test_flow_arrows_survive_merge_across_processes(tmp_path):
    """Producer span in THIS process, consumer span + step record in a
    CHILD process: collect()+to_chrome() must stitch one s->f arrow
    pair sharing the flow id, and the unified timeline must carry the
    child's step record — all across pid boundaries."""
    trace_dir = str(tmp_path / "traces")
    os.environ["RAY_TPU_TRACE"] = "1"
    os.environ["RAY_TPU_TRACE_DIR"] = trace_dir
    from ray_tpu.util import tracing
    from ray_tpu.util.timeline import unified_timeline

    tracing._reset_writer()
    sp._reset_shard_writer()
    try:
        with tracing.span("channel.write", kind="producer",
                          attrs={"channel": "ch0", "seq": 7,
                                 "flow_id": "ch0:7"}):
            pass
        sp.record_step(10, 2.5, host_dispatch_ms=0.5)
        env = dict(os.environ)
        r = subprocess.run([sys.executable, "-c", _CHILD_SPANS],
                           capture_output=True, text=True, env=env,
                           timeout=120)
        assert r.returncode == 0, r.stderr

        spans = tracing.collect(trace_dir)
        pids = {s["pid"] for s in spans}
        assert len(pids) == 2, spans  # two processes contributed
        events = tracing.to_chrome(spans)
        starts = [e for e in events
                  if e.get("ph") == "s" and e.get("id") == "ch0:7"]
        finishes = [e for e in events
                    if e.get("ph") == "f" and e.get("id") == "ch0:7"]
        assert len(starts) == 1 and len(finishes) == 1
        assert starts[0]["pid"] != finishes[0]["pid"]  # crossed procs

        # the unified merge carries spans AND both processes' steps
        out = str(tmp_path / "unified.json")
        merged = unified_timeline(out, trace_dir=trace_dir,
                                  include_tasks=False)
        assert any(e.get("cat") == "train_step" and
                   e["name"] == "step 10" for e in merged)
        assert any(e.get("cat") == "train_step" and
                   e["name"] == "step 11" for e in merged)
        assert any(e.get("id") == "ch0:7" and e["ph"] == "s"
                   for e in merged)
        assert any(e.get("id") == "ch0:7" and e["ph"] == "f"
                   for e in merged)
        with open(out) as f:
            assert json.load(f) == merged
    finally:
        os.environ.pop("RAY_TPU_TRACE", None)
        os.environ.pop("RAY_TPU_TRACE_DIR", None)
        tracing._reset_writer()
        sp._reset_shard_writer()


def test_fork_resets_shard_writers(tmp_path):
    """After a fork, the child must write to ITS OWN pid-named shards
    (the inherited parent handles are dropped by the at-fork hooks)."""
    trace_dir = str(tmp_path / "traces")
    os.environ["RAY_TPU_TRACE"] = "1"
    os.environ["RAY_TPU_TRACE_DIR"] = trace_dir
    from ray_tpu.util import tracing

    tracing._reset_writer()
    sp._reset_shard_writer()
    try:
        with tracing.span("parent.span"):
            pass
        sp.record_step(1, 1.0)
        pid = os.fork()
        if pid == 0:
            # child: write one span + one step record, then hard-exit
            # (no pytest teardown in the child)
            try:
                with tracing.span("child.span"):
                    pass
                sp.record_step(2, 1.0)
            finally:
                os._exit(0)
        _, status = os.waitpid(pid, 0)
        assert status == 0
        shards = sorted(os.listdir(trace_dir))
        trace_shards = [s for s in shards if s.startswith("trace-")]
        step_shards = [s for s in shards if s.startswith("steps-")]
        assert len(trace_shards) == 2, shards  # parent + child pids
        assert len(step_shards) == 2, shards
        # the parent's shards contain ONLY the parent's records
        with open(os.path.join(trace_dir,
                               f"trace-{os.getpid()}.jsonl")) as f:
            names = [json.loads(ln)["name"] for ln in f if ln.strip()]
        assert names == ["parent.span"]
    finally:
        os.environ.pop("RAY_TPU_TRACE", None)
        os.environ.pop("RAY_TPU_TRACE_DIR", None)
        tracing._reset_writer()
        sp._reset_shard_writer()


def test_fork_resets_event_writers(tmp_path):
    from ray_tpu.util import events as ev

    os.environ["RAY_TPU_EVENT_DIR"] = str(tmp_path / "ev")
    ev._files.clear()
    try:
        ev.report("GCS", "INFO", "PARENT", "parent event")
        pid = os.fork()
        if pid == 0:
            try:
                ev.report("GCS", "INFO", "CHILD", "child event")
            finally:
                os._exit(0)
        _, status = os.waitpid(pid, 0)
        assert status == 0
        shards = os.listdir(str(tmp_path / "ev"))
        assert len(shards) == 2, shards  # one shard per pid
        labels = {e["label"]: e["pid"] for e in ev.list_events()}
        assert labels["PARENT"] == os.getpid()
        assert labels["CHILD"] != os.getpid()
    finally:
        os.environ.pop("RAY_TPU_EVENT_DIR", None)
        ev._files.clear()


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------

def test_cli_profile_prints_step_table(tmp_path, capsys):
    """`ray_tpu profile` renders the last-N table + attribution from
    the step shards, offline (no cluster)."""
    trace_dir = str(tmp_path / "traces")
    os.environ["RAY_TPU_TRACE"] = "1"
    os.environ["RAY_TPU_TRACE_DIR"] = trace_dir
    sp._reset_shard_writer()
    try:
        for i in range(5):
            sp.record_step(i + 1, 10.0 + i, host_dispatch_ms=1.0,
                           device_execute_ms=7.0, tokens=32,
                           flops=1e9, peak=1e12)
    finally:
        os.environ.pop("RAY_TPU_TRACE", None)
        os.environ.pop("RAY_TPU_TRACE_DIR", None)
        sp._reset_shard_writer()

    from ray_tpu.scripts.cli import main

    main(["profile", "--trace-dir", trace_dir, "--last", "3"])
    out = capsys.readouterr().out
    assert "MFU" in out and "time attribution" in out
    assert f"{'5':>8}" in out  # newest step present
    # --json emits raw records
    main(["profile", "--trace-dir", trace_dir, "--json", "--last", "2"])
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    assert len(lines) == 2
    assert json.loads(lines[-1])["step"] == 5


def test_cli_timeline_unified_offline(tmp_path, capsys):
    trace_dir = str(tmp_path / "traces")
    os.environ["RAY_TPU_TRACE"] = "1"
    os.environ["RAY_TPU_TRACE_DIR"] = trace_dir
    os.environ.pop("RAY_TPU_ADDRESS", None)
    # point the CLI at an empty state file: a stale machine-global
    # /tmp/ray_tpu/cli_node.json must not make --unified try a dead GCS
    os.environ["RAY_TPU_CLI_STATE_FILE"] = str(tmp_path / "none.json")
    from ray_tpu.util import tracing

    tracing._reset_writer()
    sp._reset_shard_writer()
    try:
        with tracing.span("work"):
            pass
        sp.record_step(1, 3.0)
        os.environ.pop("RAY_TPU_TRACE", None)
        os.environ.pop("RAY_TPU_TRACE_DIR", None)

        from ray_tpu.scripts.cli import main

        out_file = str(tmp_path / "unified.json")
        main(["timeline", "--unified", "--trace-dir", trace_dir,
              "--output", out_file])
        assert "step records" in capsys.readouterr().out
        events = json.load(open(out_file))
        assert any(e.get("cat") == "train_step" for e in events)
        assert any(e["name"] == "work" for e in events)
    finally:
        os.environ.pop("RAY_TPU_TRACE", None)
        os.environ.pop("RAY_TPU_TRACE_DIR", None)
        os.environ.pop("RAY_TPU_CLI_STATE_FILE", None)
        tracing._reset_writer()
        sp._reset_shard_writer()
