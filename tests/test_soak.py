"""Elastic pretraining soak: recovery ledger + budgeted soak driver.

The ledger unit tests drive `RecoveryLedger` with synthetic StepStats
rings whose fault/outage/recovery timestamps are known exactly, so MTTR
assertions are arithmetic, not tolerance games. The smoke runs a real
`SoakDriver` campaign (local mode, two fault classes, ~half-minute
budget) and asserts the whole chain end to end: timed faults fire and
export artifacts, the controller walks training back to the last
gang-committed checkpoint, ingest resumes with no duplicated or skipped
batch (watermark audit), and the ledger attributes every failure to an
injected fault.
"""

import json
import os

import numpy as np
import pytest

from ray_tpu.soak import RecoveryLedger, SoakConfig, SoakDriver

pytestmark = pytest.mark.soak


# ---------------------------------------------------------------------------
# synthetic StepStats rings
# ---------------------------------------------------------------------------


def _ring(times, start_step=0, total_ms=0.0):
    """One record per gang step, completing exactly at each timestamp."""
    return [{"step": start_step + i, "ts": t - total_ms / 1e3,
             "total_ms": total_ms}
            for i, t in enumerate(times)]


def _steady(t0, t1, dt):
    n = int(round((t1 - t0) / dt))
    return [t0 + i * dt for i in range(n + 1)]


def _ledger(**kw):
    kw.setdefault("rate_threshold", 0.9)
    kw.setdefault("rate_window", 4)
    return RecoveryLedger(**kw)


# ---------------------------------------------------------------------------
# MTTR arithmetic
# ---------------------------------------------------------------------------


def test_mttr_outage_exact():
    """10 Hz stepping, fault at 5.05, dead until 8.0, 10 Hz again:
    recovery is the first 4-step window after the outage — completion
    8.4 — so MTTR is exactly 3.35 s."""
    led = _ledger()
    led.add_fault("kill@train", 5.05)
    records = _ring(_steady(0.0, 5.0, 0.1)) + \
        _ring(_steady(8.0, 10.0, 0.1), start_step=100)
    [m] = led.compute_mttr(records)
    assert m["recovered"] and m["degraded"]
    assert m["pre_rate"] == pytest.approx(10.0)
    assert m["mttr_s"] == pytest.approx(8.4 - 5.05)


def test_mttr_no_outage_recovers_immediately():
    """A fault that never opens a gap (the plane absorbed it) recovers
    at the first measurable window with degraded=False."""
    led = _ledger()
    led.add_fault("hb_brownout@gcs", 5.05)
    [m] = led.compute_mttr(_ring(_steady(0.0, 10.0, 0.1)))
    assert m["recovered"] and not m["degraded"]
    # first post-fault window ends at 5.5 (4 steps past 5.1)
    assert m["mttr_s"] == pytest.approx(5.5 - 5.05)


def test_mttr_lagged_disruption():
    """A ckpt_fail-style fault: stepping continues ~1 s past the fire
    time before the attempt dies. The healthy post-fire steps must NOT
    count as recovery — the outage starts at the gap, and MTTR spans
    fault -> first healthy window after the restart."""
    led = _ledger()
    led.add_fault("ckpt_fail@train", 5.05)
    records = _ring(_steady(0.0, 6.0, 0.1)) + \
        _ring(_steady(12.0, 13.0, 0.1), start_step=200)
    [m] = led.compute_mttr(records)
    assert m["recovered"] and m["degraded"]
    assert m["mttr_s"] == pytest.approx(12.4 - 5.05)


def test_mttr_threshold_edge():
    """Post-outage stepping at 8.33 Hz sits BELOW 0.9 x 10 Hz and must
    not count as recovered; recovery lands on the first window whose
    rate crosses the threshold."""
    led = _ledger()
    led.add_fault("kill@train", 5.05)
    slow = [8.0 + 0.12 * i for i in range(5)]        # 8.33 Hz
    fast = [slow[-1] + 0.1 * i for i in range(1, 6)]  # 10 Hz
    records = _ring(_steady(0.0, 5.0, 0.1)) + \
        _ring(slow + fast, start_step=100)
    [m] = led.compute_mttr(records)
    assert m["recovered"] and m["degraded"]
    # windows: 8.48 (8.33 Hz, below), 8.58 (8.70, below),
    # 8.68 (4/0.44 = 9.09, first over threshold)
    assert m["mttr_s"] == pytest.approx(8.68 - 5.05)
    assert m["post_rate"] == pytest.approx(4 / 0.44)


def test_mttr_never_recovered():
    """Stepping never returns to threshold after the outage."""
    led = _ledger()
    led.add_fault("kill@train", 5.05)
    records = _ring(_steady(0.0, 5.0, 0.1)) + \
        _ring(_steady(8.0, 20.0, 1.0), start_step=100)   # 1 Hz limp
    [m] = led.compute_mttr(records)
    assert m["degraded"] and not m["recovered"]
    assert m["mttr_s"] is None


def test_mttr_insufficient_history():
    """No pre-fault window or no post-fault records -> unmeasurable,
    reported as not recovered rather than a crash."""
    led = _ledger()
    led.add_fault("kill@train", 5.0)
    assert led.compute_mttr([])[0]["recovered"] is False
    only_pre = _ring(_steady(0.0, 4.0, 0.1))
    assert led.compute_mttr(only_pre)[0]["recovered"] is False


def test_gang_event_collapse():
    """Two ranks record every gang step ~simultaneously; the collapse
    must yield ONE event per dispatch (at the slower rank's completion)
    so window rates measure the gang, not the record interleave —
    replayed steps after a walk-back stay separate events."""
    recs = []
    for i, t in enumerate(_steady(0.0, 5.0, 0.1)):
        recs.append({"step": i, "ts": t, "total_ms": 0.0})
        recs.append({"step": i, "ts": t + 0.004, "total_ms": 0.0})
    events = RecoveryLedger._gang_events(recs)
    assert len(events) == 51
    assert events[0] == pytest.approx(0.004)
    # walk-back replay: steps 3,4 again later -> their own events
    replay = [{"step": s, "ts": 9.0 + 0.1 * j, "total_ms": 0.0}
              for j, s in enumerate((3, 4))]
    assert len(RecoveryLedger._gang_events(recs + replay)) == 53


def test_mttr_is_rank_interleave_invariant():
    """Doubling every record (a second lockstep rank) must not change
    the measured MTTR."""
    led = _ledger()
    led.add_fault("kill@train", 5.05)
    one = _ring(_steady(0.0, 5.0, 0.1)) + \
        _ring(_steady(8.0, 10.0, 0.1), start_step=100)
    two = []
    for r in one:
        two.append(dict(r))
        two.append({**r, "ts": r["ts"] + 0.002})
    m1 = led.compute_mttr(one)[0]
    m2 = led.compute_mttr(two)[0]
    assert m2["mttr_s"] == pytest.approx(m1["mttr_s"], abs=0.01)


# ---------------------------------------------------------------------------
# attribution / resume audits
# ---------------------------------------------------------------------------


def test_failure_attribution():
    led = _ledger()
    led.add_fault("kill@train", 100.0)
    led.add_failure(130.0, "worker died")            # within 60 s window
    led.add_failure(300.0, "IndexError: oops")        # a REAL bug
    led.add_failure(400.0, "ChaosError: chaos: injected persist failure")
    injected, non_injected = led.classify_failures()
    assert len(injected) == 2
    assert [f["ts"] for f in non_injected] == [300.0]
    with pytest.raises(AssertionError, match="non-injected"):
        led.assert_clean(records=[])


def test_resume_accounting():
    led = _ledger()
    led.add_commit(step=128, ts=10.0)
    led.add_commit(step=256, ts=20.0)
    led.add_restore(resumed_from=256, ts=25.0)
    assert led.resume_mismatches() == []
    led.add_restore(resumed_from=128, ts=26.0)   # stale checkpoint!
    bad = led.resume_mismatches()
    assert len(bad) == 1 and bad[0]["expected_step"] == 256
    with pytest.raises(AssertionError, match="resume accounting"):
        led.assert_clean(records=[])


def test_report_mttr_by_class():
    led = _ledger()
    for ts in (5.05, 25.05):
        led.add_fault("kill@train", ts)
    led.add_fault("data_stall@train", 45.05)
    records = []
    for seg in ((0.0, 5.0), (8.0, 25.0), (28.0, 45.0), (47.0, 60.0)):
        records += _ring(_steady(*seg, 0.1),
                         start_step=len(records), total_ms=50.0)
    rep = led.report(records)
    assert rep["faults_injected"] == 3
    assert rep["recovered_count"] == 3
    kill = rep["mttr_by_class"]["kill@train"]
    assert kill["count"] == 2 and kill["recovered"] == 2
    # both kill outages are ~3 s dead + window tail
    assert kill["mttr_p50_s"] == pytest.approx(8.4 - 5.05)
    assert kill["mttr_p95_s"] == pytest.approx(28.4 - 25.05)
    down = rep["downtime_breakdown_s"]
    assert down["total_s"] > down["dead_s"] > 0


def test_ledger_validation():
    with pytest.raises(ValueError, match="rate_threshold"):
        RecoveryLedger(rate_threshold=1.5)
    with pytest.raises(ValueError, match="rate_window"):
        RecoveryLedger(rate_window=0)
    with pytest.raises(ValueError, match="min_outage"):
        RecoveryLedger(min_outage_s=0.0)


def test_load_chaos_artifacts(tmp_path):
    art = {"role": "train", "pid": 4242, "spec": "seed=1;at=5:kill@train",
           "timed_fired": [
               {"fault": "kill", "offset": 5.0, "arg": 0.0, "ts": 105.0}]}
    (tmp_path / "chaos-train-4242.json").write_text(json.dumps(art))
    (tmp_path / "chaos-gcs-1.json").write_text("{not json")   # skipped
    led = _ledger()
    assert led.load_chaos_artifacts(str(tmp_path)) == 1
    assert led.faults[0].fault_class == "kill@train"
    assert led.faults[0].ts == 105.0


# ---------------------------------------------------------------------------
# schedule generation
# ---------------------------------------------------------------------------


def test_schedule_spec_deterministic_and_slotted():
    cfg = SoakConfig(budget_s=120.0, seed=3, faults_per_class=2,
                     fault_classes=("ckpt_fail@train", "data_stall@train",
                                    "kill@train", "hb_brownout@gcs"))
    spec1 = SoakDriver(cfg).schedule_spec()
    spec2 = SoakDriver(cfg).schedule_spec()
    assert spec1 == spec2                       # pure function of config
    assert spec1 != SoakDriver(
        SoakConfig(budget_s=120.0, seed=4, faults_per_class=2,
                   fault_classes=cfg.fault_classes)).schedule_spec()
    body = spec1.split("at=", 1)[1]
    offsets = [float(e.split(":", 1)[0]) for e in body.split("|")]
    assert len(offsets) == 8
    # disjoint slots: strictly increasing, inside [warmup, 2/3 budget]
    assert offsets == sorted(offsets)
    assert offsets[0] >= cfg.fault_warmup_s
    assert offsets[-1] <= 120.0 * 2 / 3


def test_schedule_spec_unknown_class():
    with pytest.raises(ValueError, match="unknown fault class"):
        SoakDriver(SoakConfig(
            fault_classes=("meteor_strike@dc",))).schedule_spec()


def test_soak_config_validation():
    with pytest.raises(ValueError, match="unknown soak mode"):
        SoakDriver(SoakConfig(mode="galactic"))


# ---------------------------------------------------------------------------
# the tier-1 smoke: a real (compressed) soak campaign
# ---------------------------------------------------------------------------


def test_soak_smoke_local(tmp_path):
    """~Half-minute local soak with two fault classes. Asserts the full
    chain: both timed faults fire and export artifacts, the injected
    persist failure walks training back to the last gang-committed
    checkpoint, ingest resumes with no duplicated/skipped batch, and the
    ledger reports clean attribution + bit-exact resume accounting."""
    # seed 3 schedules the (harmless) stall first and the walk-back
    #-inducing persist failure second, so neither fault lands inside
    # the other's recovery window on a slow box
    cfg = SoakConfig(
        budget_s=30.0, mode="local", seed=3,
        fault_classes=("ckpt_fail@train", "data_stall@train"),
        workdir=str(tmp_path / "soak"), keep_workdir=True)
    res = SoakDriver(cfg).run()
    led = res["ledger"]

    assert led["faults_injected"] == 2
    assert set(led["mttr_by_class"]) == {"ckpt_fail@train",
                                         "data_stall@train"}
    assert led["recovered_count"] == 2
    for m in led["recoveries"]:
        assert m["mttr_s"] is not None and m["mttr_s"] > 0
    # zero NON-injected failures; the persist failure is attributed
    assert led["non_injected_failures"] == []
    assert led["failures_observed"] == led["injected_failures"] >= 1
    # walk-back happened and resumed bit-exactly from a gang commit
    assert led["commits"] > 0
    assert led["restores"] >= 1
    assert led["resume_mismatches"] == []
    assert res["post_restore_checks"] >= 1
    # ingest offsets: no duplicated or skipped batch across the restart
    assert res["watermark_checks"] > 0
    assert res["watermark_errors"] == []
    # throughput + progress
    assert res["final_step"] > 0 and res["steps_per_s"] > 0
    assert res["ingest_tokens_per_s"] > 0
    # every faulted process exported a replayable post-mortem artifact
    assert res["chaos_artifacts"]
    for name in res["chaos_artifacts"]:
        art = json.loads(
            (tmp_path / "soak" / "chaos" / name).read_text())
        assert art["spec"] == res["spec"]
    # downtime breakdown covers the recovery windows
    down = led["downtime_breakdown_s"]
    assert down["total_s"] >= down["dead_s"] >= 0
    # the run restored the env it scoped
    for var in ("RAY_TPU_CHAOS", "RAY_TPU_CHAOS_LOG",
                "RAY_TPU_CHAOS_EPOCH", "RAY_TPU_TRACE"):
        assert os.environ.get(var) is None
