"""Host memory monitor + OOM worker-killing policy.

Reference: `src/ray/common/memory_monitor.h:52` (host used/total polling)
+ `src/ray/raylet/worker_killing_policy_group_by_owner.h` (victim
selection). The monitor reads a test-override usage file here
(`memory_usage_path` config), so the tests drive "host memory pressure"
deterministically: a hog task flips the file to 99% and the raylet must
kill it — not the raylet itself, and not co-located actors.
"""

import os

import pytest

import ray_tpu


def _cfg(tmp_path, usage="10 100"):
    usage_file = tmp_path / "usage"
    usage_file.write_text(usage)
    return str(usage_file), {
        "memory_usage_threshold": 0.9,
        "memory_usage_path": str(usage_file),
        "memory_monitor_refresh_ms": 50,
    }


def test_oom_hog_killed_and_retried(tmp_path):
    """The memory hog dies with the host over threshold, is retried once
    pressure clears, and a co-located actor survives the whole episode."""
    usage_file, sys_cfg = _cfg(tmp_path)
    marker = str(tmp_path / "attempted")
    ray_tpu.init(num_cpus=2, object_store_memory=64 << 20,
                 _system_config=sys_cfg)
    try:
        @ray_tpu.remote
        class Bystander:
            def ping(self):
                return "alive"

        bystander = Bystander.remote()
        assert ray_tpu.get(bystander.ping.remote()) == "alive"

        @ray_tpu.remote
        def hog(usage_path, marker_path):
            import time
            if not os.path.exists(marker_path):
                # first attempt: "allocate" past the threshold and hang —
                # the monitor must kill this worker
                open(marker_path, "w").close()
                with open(usage_path, "w") as f:
                    f.write("99 100")
                time.sleep(30)
                return "never"
            # retry: pressure is gone, finish normally
            with open(usage_path, "w") as f:
                f.write("10 100")
            return "done"

        # the retry writes 10/100 before running, but the FIRST attempt
        # must reset it too or the monitor would kill the retry's worker
        # before it starts; reset from the driver once the kill landed
        ref = hog.options(max_retries=2).remote(usage_file, marker)
        # wait for attempt 1 to flag itself, then relieve "pressure" so
        # only the hog's worker gets killed
        import time
        deadline = time.monotonic() + 30
        while not os.path.exists(marker) and time.monotonic() < deadline:
            time.sleep(0.05)
        assert os.path.exists(marker), "hog never started"
        # The 50ms monitor observes the 99% spike and kills the hog
        # within a tick or two; reset pressure BEFORE its next strike
        # window (kill + 0.5s backoff) so neither the retry nor the
        # bystander is ever a candidate.
        time.sleep(0.3)
        with open(usage_file, "w") as f:
            f.write("10 100")
        assert ray_tpu.get(ref, timeout=60) == "done"
        # the co-located actor was never a victim
        assert ray_tpu.get(bystander.ping.remote()) == "alive"
    finally:
        ray_tpu.shutdown()


def test_oom_error_when_retries_exhausted(tmp_path):
    """With retries disabled the caller gets OutOfMemoryError naming the
    killing policy's reasoning, not a generic worker-died error."""
    usage_file, sys_cfg = _cfg(tmp_path)
    ray_tpu.init(num_cpus=1, object_store_memory=64 << 20,
                 _system_config=sys_cfg)
    try:
        @ray_tpu.remote
        def hog(usage_path):
            import time
            with open(usage_path, "w") as f:
                f.write("99 100")
            time.sleep(30)
            return "never"

        ref = hog.options(max_retries=0).remote(usage_file)
        with pytest.raises(ray_tpu.OutOfMemoryError) as exc_info:
            ray_tpu.get(ref, timeout=60)
        msg = str(exc_info.value)
        assert "group-by-owner" in msg
        assert "threshold" in msg
    finally:
        ray_tpu.shutdown()


def test_monitor_prefers_idle_workers(tmp_path):
    """Pressure with an idle pooled worker available: the idle worker is
    reclaimed first and the running task is never disturbed."""
    usage_file, sys_cfg = _cfg(tmp_path)
    ray_tpu.init(num_cpus=2, object_store_memory=64 << 20,
                 _system_config=sys_cfg)
    try:
        @ray_tpu.remote
        def warmup():
            import time
            time.sleep(0.7)  # overlap: lease pipelining would otherwise
            return os.getpid()  # run both on ONE worker

        # two concurrent warmups force two pooled workers; both go idle
        pids = ray_tpu.get([warmup.remote() for _ in range(2)])
        assert len(set(pids)) == 2, "expected two pooled workers"

        @ray_tpu.remote
        def worker_task(usage_path):
            import time
            with open(usage_path, "w") as f:
                f.write("99 100")   # spike while this task runs
            # finish inside the monitor's post-kill backoff (0.5s): the
            # first strike takes the idle worker, and pressure is gone
            # before a second strike could pick this running task
            time.sleep(0.3)
            with open(usage_path, "w") as f:
                f.write("10 100")
            return os.getpid()
        pid = ray_tpu.get(worker_task.options(max_retries=0)
                          .remote(usage_file), timeout=60)
        # the task ran on one of the pooled workers and SURVIVED the
        # spike (an idle worker was sacrificed instead)
        assert pid in pids
    finally:
        ray_tpu.shutdown()
