"""Scale-envelope smoke tests: the control plane at many-raylet scale.

Reference: `release/benchmarks/README.md` (2k+ nodes / 40k+ actors /
10k+ tasks / 1k+ PGs with trivial workloads) and its harnesses
(`release/benchmarks/distributed/test_many_actors.py`, `test_many_tasks.py`,
`test_many_pgs.py`). The workload there is trivial by design — the
envelope measures GCS tables, scheduling, gossip and lease throughput,
not executor compute — so the raylets run in RAY_TPU_VIRTUAL_WORKERS
mode: leases are satisfied by in-process stub workers and one box can
host a whole cluster's control plane. bench.py's scale phase runs the
same shapes bigger on the driver box; these are the smoke sizes.

Own file: needs its own cluster with the virtual-workers env set before
any raylet spawns.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu._private.node import Cluster

N_RAYLETS = 8
N_ACTORS = 200
N_TASKS = 2000
N_PGS = 20


@pytest.fixture(scope="module")
def virtual_cluster():
    os.environ["RAY_TPU_VIRTUAL_WORKERS"] = "1"
    try:
        cluster = Cluster(head_resources={"CPU": 4.0},
                          object_store_memory=32 * 1024 * 1024)
        for _ in range(N_RAYLETS - 1):
            cluster.add_node({"CPU": 4.0},
                             object_store_memory=32 * 1024 * 1024)
        ray_tpu.init(address=cluster.gcs_addr)
        yield cluster
        ray_tpu.shutdown()
        cluster.shutdown()
    finally:
        os.environ.pop("RAY_TPU_VIRTUAL_WORKERS", None)


def test_gossip_sees_every_raylet(virtual_cluster):
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        nodes = [n for n in ray_tpu.nodes() if n["Alive"]]
        if len(nodes) == N_RAYLETS:
            break
        time.sleep(0.5)
    assert len([n for n in ray_tpu.nodes() if n["Alive"]]) == N_RAYLETS
    total = ray_tpu.cluster_resources()
    assert total.get("CPU") == pytest.approx(4.0 * N_RAYLETS)


def test_many_actors_launch_and_call(virtual_cluster):
    @ray_tpu.remote(num_cpus=0.1)
    class A:
        def ping(self):
            return None

    actors = [A.remote() for _ in range(N_ACTORS)]
    # every actor landed, was marked ALIVE, and answered one call
    ray_tpu.get([a.ping.remote() for a in actors], timeout=300)
    # scheduling spread the fleet across nodes, not one hot raylet
    from ray_tpu.util.state import list_actors

    infos = [a for a in list_actors(limit=N_ACTORS + 50)
             if a["state"] == "ALIVE"]
    nodes = {i["node_id"] for i in infos if i["node_id"]}
    assert len(nodes) >= N_RAYLETS // 2, nodes
    # kill/create churn must not leak leases: kill half the fleet and
    # the freed capacity must come back (virtual exit path)
    for a in actors[: N_ACTORS // 2]:
        ray_tpu.kill(a)
    deadline = time.monotonic() + 60
    want = 4.0 * N_RAYLETS  # actors hold 0 CPU while alive anyway
    while time.monotonic() < deadline:
        if ray_tpu.available_resources().get("CPU", 0) >= want - 1.0:
            break
        time.sleep(0.5)
    assert ray_tpu.available_resources().get("CPU", 0) >= want - 1.0


def test_many_queued_tasks_drain(virtual_cluster):
    @ray_tpu.remote(num_cpus=0.5)
    def noop():
        return None

    t0 = time.monotonic()
    refs = [noop.remote() for _ in range(N_TASKS)]
    ray_tpu.get(refs, timeout=300)
    dt = time.monotonic() - t0
    assert dt < 300
    # gossip freshness: after the burst, availability converges back
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        avail = ray_tpu.available_resources().get("CPU", 0)
        if avail >= 4.0 * N_RAYLETS - 1.0:
            break
        time.sleep(0.5)
    assert ray_tpu.available_resources().get("CPU", 0) >= \
        4.0 * N_RAYLETS - 1.0


def test_many_placement_groups(virtual_cluster):
    pgs = [ray_tpu.placement_group([{"CPU": 0.5}, {"CPU": 0.5}],
                                   strategy="PACK")
           for _ in range(N_PGS)]
    for pg in pgs:
        assert pg.ready(timeout=120)
    for pg in pgs:
        ray_tpu.remove_placement_group(pg)
    # removal returns the bundles' resources to the pool
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if ray_tpu.available_resources().get("CPU", 0) >= \
                4.0 * N_RAYLETS - 1.0:
            break
        time.sleep(0.5)
    assert ray_tpu.available_resources().get("CPU", 0) >= \
        4.0 * N_RAYLETS - 1.0
