"""TPU pod-slice gang scheduling tests.

Models the reference's TPU pod convention
(`python/ray/_private/accelerators/tpu.py:363-388`: per-slice head
resource + one worker per host) promoted into the scheduler as an atomic
slice placement primitive (SURVEY.md §7.1).
"""

import time

import pytest

import ray_tpu
from ray_tpu._private import accelerators as acc
from ray_tpu._private.node import Cluster
from ray_tpu._private.scheduling import ClusterView, place_slice_bundles
from ray_tpu.air import RunConfig, ScalingConfig


# ---------------------------------------------------------------------------
# unit: place_slice_bundles over a fake view
# ---------------------------------------------------------------------------

def _add_host(view, nid, name, stype, host_id, num_hosts, chips=4.0,
              available=None):
    total = {"CPU": 4.0, "TPU": chips}
    view.update_node(
        nid, f"addr-{nid.hex()}", total, dict(available or total),
        labels={
            acc.LABEL_SLICE_NAME: name,
            acc.LABEL_SLICE_TYPE: stype,
            acc.LABEL_SLICE_HOST_ID: str(host_id),
            acc.LABEL_SLICE_NUM_HOSTS: str(num_hosts),
        })


def test_place_slice_bundles_complete_slice():
    view = ClusterView()
    _add_host(view, b"a0", "sliceA", "v4-16", 0, 2)
    _add_host(view, b"a1", "sliceA", "v4-16", 1, 2)
    bundles = [{"CPU": 1.0, "TPU": 4.0}] * 2
    placed = place_slice_bundles(view, bundles, "v4-16")
    assert placed is not None
    # bundle i -> slice host i, in ICI order
    assert [int(n.labels[acc.LABEL_SLICE_HOST_ID]) for n in placed] == [0, 1]
    assert {n.labels[acc.LABEL_SLICE_NAME] for n in placed} == {"sliceA"}


def test_place_slice_bundles_incomplete_slice_stays_pending():
    view = ClusterView()
    # only host 0 of a declared 2-host slice has registered
    _add_host(view, b"a0", "sliceA", "v4-16", 0, 2)
    assert place_slice_bundles(
        view, [{"TPU": 4.0}] * 2, "v4-16") is None


def test_place_slice_bundles_no_partial_across_slices():
    view = ClusterView()
    # two DIFFERENT 2-host slices each with only one live host: a naive
    # scheduler would place across them; slices must not be mixed
    _add_host(view, b"a0", "sliceA", "v4-16", 0, 2)
    _add_host(view, b"b1", "sliceB", "v4-16", 1, 2)
    assert place_slice_bundles(
        view, [{"TPU": 4.0}] * 2, "v4-16") is None


def test_place_slice_bundles_bundle_count_must_match_hosts():
    view = ClusterView()
    _add_host(view, b"a0", "sliceA", "v4-16", 0, 2)
    _add_host(view, b"a1", "sliceA", "v4-16", 1, 2)
    assert place_slice_bundles(view, [{"TPU": 4.0}], "v4-16") is None
    assert place_slice_bundles(view, [{"TPU": 4.0}] * 3, "v4-16") is None


def test_place_slice_bundles_prefers_idle_slice():
    view = ClusterView()
    _add_host(view, b"a0", "sliceA", "v4-8", 0, 1,
              available={"CPU": 1.0, "TPU": 4.0})  # busy
    _add_host(view, b"b0", "sliceB", "v4-8", 0, 1)  # idle
    placed = place_slice_bundles(view, [{"TPU": 2.0}], "v4-8")
    assert placed[0].labels[acc.LABEL_SLICE_NAME] == "sliceB"


def test_wrong_topology_not_placed():
    view = ClusterView()
    _add_host(view, b"a0", "sliceA", "v4-16", 0, 1)
    assert place_slice_bundles(view, [{"TPU": 4.0}], "v4-32") is None


# ---------------------------------------------------------------------------
# integration: real cluster of raylet processes forming slices
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def slice_cluster():
    cluster = Cluster()
    # one 2-host v2-8 slice + one plain CPU node
    cluster.add_slice("v2-8", num_hosts=2, chips_per_host=4)
    cluster.add_node({"CPU": 2.0})
    ray_tpu.init(address=cluster.gcs_addr)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_slice_head_resource_advertised(slice_cluster):
    total = ray_tpu.cluster_resources()
    # host 0 of the slice carries the one-per-slice head resource
    assert total.get(acc.head_resource_name("v2-8")) == 1.0
    assert total.get("TPU") == 8.0


def test_slice_pg_gang_places_then_second_stays_pending(slice_cluster):
    bundles = [{"CPU": 1.0, "TPU": 4.0}] * 2
    pg1 = ray_tpu.placement_group(bundles, topology="v2-8")
    assert pg1.ready(timeout=30.0)

    # the slice is fully claimed: an identical request must stay PENDING
    # (all-or-nothing — never partially placed)
    pg2 = ray_tpu.placement_group(bundles, topology="v2-8")
    assert not pg2.ready(timeout=3.0)

    # freeing the slice lets the pending PG gang-place
    ray_tpu.remove_placement_group(pg1)
    assert pg2.ready(timeout=30.0)
    ray_tpu.remove_placement_group(pg2)


def test_train_on_slice_topology(slice_cluster, tmp_path):
    """ScalingConfig(topology=...) gang-places one train worker per slice
    host; each worker sees its host's chips via TPU_VISIBLE_CHIPS."""
    import os as _os

    from ray_tpu import train

    def loop(config):
        import os

        ctx = train.get_context()
        train.report({
            "rank": ctx.get_world_rank(),
            "world": ctx.get_world_size(),
            "chips": os.environ.get("TPU_VISIBLE_CHIPS", ""),
        })

    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=2, topology="v2-8",
            resources_per_worker={"CPU": 1.0, "TPU": 4.0}),
        run_config=RunConfig(storage_path=str(tmp_path), name="slice"),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["world"] == 2
    # the worker got dedicated host-local chips
    assert len(result.metrics["chips"].split(",")) == 4


def test_train_slice_unplaceable_fails_cleanly(slice_cluster, tmp_path):
    """With no complete slice of the requested type anywhere in the
    cluster, fit() raises instead of partially placing workers."""
    from ray_tpu import train
    from ray_tpu.train import TrainingFailedError

    trainer = train.DataParallelTrainer(
        lambda config: None,
        scaling_config=ScalingConfig(
            num_workers=2, topology="v4-4096",  # no such slice exists
            resources_per_worker={"CPU": 1.0, "TPU": 4.0},
            pg_timeout_s=5.0),
        run_config=RunConfig(storage_path=str(tmp_path), name="nofit"),
    )
    with pytest.raises(TrainingFailedError):
        trainer.fit()


# ---------------------------------------------------------------------------
# multislice: N atomic slice gangs, DCN data axis across them
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def multislice_cluster(slice_cluster):
    # grow the shared module cluster to TWO v2-8 slices (a second
    # module-scoped cluster can't coexist with slice_cluster's init).
    # Must be the LAST tests in this module: the extra slice changes
    # capacity assumptions of earlier pending-PG tests.
    slice_cluster.add_slice("v2-8", num_hosts=2, chips_per_host=4)
    return slice_cluster


def test_train_multislice_places_gang_per_slice(multislice_cluster,
                                                tmp_path):
    """ScalingConfig(num_slices=2, topology=...) creates one atomic gang
    PER SLICE (VERDICT r4 item 2); workers learn their slice_rank and
    each slice's gang lands on a distinct slice instance."""
    import json as json_mod

    from ray_tpu import train

    info_dir = tmp_path / "worker_info"
    info_dir.mkdir()

    def loop(config):
        import json
        import os

        ctx = train.get_context()
        rank = ctx.get_world_rank()
        # per-worker invariants checked IN the worker (only rank 0's
        # reports surface in metrics_history)
        assert ctx.get_world_size() == 4
        assert ctx.get_num_slices() == 2
        assert ctx.get_slice_rank() == rank // 2
        with open(os.path.join(config["info_dir"], f"{rank}.json"),
                  "w") as f:
            json.dump({
                "rank": rank,
                "slice_rank": ctx.get_slice_rank(),
                "chips": os.environ.get("TPU_VISIBLE_CHIPS", ""),
                "host": os.environ.get("RAY_TPU_NODE_ID", ""),
            }, f)
        train.report({"rank": rank})

    trainer = train.DataParallelTrainer(
        loop,
        train_loop_config={"info_dir": str(info_dir)},
        scaling_config=ScalingConfig(
            num_workers=4, num_slices=2, topology="v2-8",
            resources_per_worker={"CPU": 1.0, "TPU": 4.0}),
        run_config=RunConfig(storage_path=str(tmp_path),
                             name="multislice"),
    )
    result = trainer.fit()
    assert result.error is None
    infos = {}
    for f in info_dir.iterdir():
        rec = json_mod.loads(f.read_text())
        infos[rec["rank"]] = rec
    assert set(infos) == {0, 1, 2, 3}
    # contiguous rank ranges per slice
    assert infos[0]["slice_rank"] == infos[1]["slice_rank"] == 0
    assert infos[2]["slice_rank"] == infos[3]["slice_rank"] == 1
    # each worker holds a full host's chips
    assert all(len(m["chips"].split(",")) == 4 for m in infos.values())
    # the two gangs landed on 4 DISTINCT hosts (2 per slice)
    assert len({m["host"] for m in infos.values()}) == 4
