"""GCS persistence + chaos tests.

Reference ground: `python/ray/tests/test_gcs_fault_tolerance.py`
(GCS restart with Redis-backed tables) and `test_chaos.py`
(WorkerKillerActor cadence kills during workloads,
`python/ray/_private/test_utils.py:1560`).
"""

import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.node import Cluster


def _find_worker_pids(store_name: str):
    """Worker processes of one cluster, identified by its shm store name
    in their cmdline (session-scoped, never another cluster's)."""
    pids = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace")
        except OSError:
            continue
        if "worker_main" in cmd and store_name in cmd:
            pids.append(int(pid))
    return pids


def test_gcs_restart_preserves_state():
    """Kill + respawn the GCS: named actors, placement groups and jobs
    survive via the snapshot; raylets reregister; calls keep working."""
    cluster = Cluster(head_resources={"CPU": 4.0}, gcs_persistence=True)
    ray_tpu.init(address=cluster.gcs_addr)
    try:
        @ray_tpu.remote
        class Keeper:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        keeper = Keeper.options(name="keeper",
                                lifetime="detached").remote()
        assert ray_tpu.get(keeper.bump.remote()) == 1

        pg = ray_tpu.placement_group([{"CPU": 1.0}], strategy="PACK")
        assert pg.ready(timeout=30)

        time.sleep(1.5)  # let a snapshot land
        cluster.restart_gcs()
        time.sleep(2.0)  # raylet reregisters on its next heartbeat

        # actor directory survived: resolve by name and keep state
        again = ray_tpu.get_actor("keeper")
        assert ray_tpu.get(again.bump.remote(), timeout=30) == 2

        # the PG record survived
        assert pg.ready(timeout=10)

        # fresh work schedules normally against the restarted GCS
        @ray_tpu.remote
        def probe():
            return "alive"

        assert ray_tpu.get(probe.remote(), timeout=60) == "alive"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_gcs_sigkill_restart_against_store():
    """VERDICT r4 item 8: pluggable external StoreClient. SIGKILL the
    GCS immediately after mutations (no snapshot interval can have
    landed — the cluster runs with snapshots disabled entirely) and
    restart it against the write-through file store: actors and PGs
    must be intact, proving durability comes from per-mutation writes,
    not snapshot freshness."""
    cluster = Cluster(head_resources={"CPU": 4.0}, gcs_store=True)
    ray_tpu.init(address=cluster.gcs_addr)
    try:
        @ray_tpu.remote
        class Keeper:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        keeper = Keeper.options(name="storekeeper",
                                lifetime="detached").remote()
        assert ray_tpu.get(keeper.bump.remote(), timeout=60) == 1
        pg = ray_tpu.placement_group([{"CPU": 1.0}], strategy="PACK")
        assert pg.ready(timeout=30)

        # no grace: SIGKILL the instant the mutations are in — a
        # snapshot-based GCS would come back empty here
        cluster.gcs.proc.kill()
        cluster.gcs.proc.wait(timeout=10)
        port = int(cluster.gcs_addr.rsplit(":", 1)[1])
        cluster._start_gcs(port=port)
        time.sleep(2.0)  # raylet reregisters on its next heartbeat

        again = ray_tpu.get_actor("storekeeper")
        assert ray_tpu.get(again.bump.remote(), timeout=60) == 2
        assert pg.ready(timeout=10)

        @ray_tpu.remote
        def probe():
            return "alive"

        assert ray_tpu.get(probe.remote(), timeout=60) == "alive"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_chaos_worker_kills_during_tune():
    """SIGKILL worker processes on a cadence during a Tune run;
    FailureConfig retries must carry every trial to completion."""
    from ray_tpu import tune
    from ray_tpu.air.config import FailureConfig, RunConfig

    cluster = Cluster(head_resources={"CPU": 4.0})
    store_name = cluster.head_node.store_name
    ray_tpu.init(address=cluster.gcs_addr)
    stop_killing = threading.Event()
    killed = []

    def killer():
        # let trials start, then murder a worker every 1.5s, thrice
        time.sleep(2.0)
        for _ in range(3):
            if stop_killing.is_set():
                return
            pids = _find_worker_pids(store_name)
            if pids:
                pid = pids[0]
                try:
                    os.kill(pid, signal.SIGKILL)
                    killed.append(pid)
                except ProcessLookupError:
                    pass
            time.sleep(1.5)

    thread = threading.Thread(target=killer, daemon=True)
    try:
        def trainable(config):
            for i in range(6):
                time.sleep(0.3)
                tune.report({"step": i, "value": config["x"] * i})

        thread.start()
        tuner = tune.Tuner(
            trainable,
            param_space={"x": tune.grid_search([1, 2])},
            tune_config=tune.TuneConfig(metric="value", mode="max"),
            run_config=RunConfig(
                storage_path="/tmp/ray_tpu_chaos",
                name=f"chaos_{int(time.time())}",
                failure_config=FailureConfig(max_failures=8),
            ),
        )
        grid = tuner.fit()
        stop_killing.set()
        assert killed, "chaos killer never killed anything"
        assert len(grid) == 2
        for res in grid:
            assert res.error is None, f"trial failed: {res.error}"
            assert res.metrics["step"] == 5
    finally:
        stop_killing.set()
        thread.join(timeout=10)
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.mark.slow
def test_chaos_node_kill_during_tune_with_autoscaler():
    """VERDICT r2 item 10: SIGKILL a whole raylet (its workers die via
    their watchdog) mid-run while three recovery paths race — lineage
    reconstruction of the objects it held, Tune trial restart/
    rescheduling, and autoscaler replacement of the dead node. The run
    must complete correctly and reconstruction must provably fire.

    Reference ground: NodeKillerActor
    (`python/ray/_private/test_utils.py:1497`) +
    `python/ray/tests/test_chaos.py`.
    """
    import numpy as np

    from ray_tpu import tune
    from ray_tpu.air.config import FailureConfig, RunConfig
    from ray_tpu.autoscaler import (
        Autoscaler, FakeMultiNodeProvider, NodeType)

    # 0-CPU head: every task/trial must land on autoscaled nodes
    cluster = Cluster(head_resources={"CPU": 0.0})
    ray_tpu.init(address=cluster.gcs_addr)
    provider = FakeMultiNodeProvider(cluster)
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("cpu4", {"CPU": 4.0})],
        max_workers=3, idle_timeout_s=9999,
        update_interval_s=1.0).start()
    marker = f"/tmp/ray_tpu_nodechaos_{os.getpid()}_{int(time.time())}"
    try:
        # a plasma object whose only copy will live on the doomed node
        @ray_tpu.remote(num_cpus=1)
        def produce(marker_path):
            with open(marker_path, "a") as f:
                f.write("run\n")
            return np.full(500_000, 7, np.uint8)

        ref = produce.remote(marker)  # infeasible on the 0-CPU head:
        ready, _ = ray_tpu.wait([ref], timeout=90)  # forces a scale-up
        assert ready, "autoscaler never provided capacity"
        assert len(open(marker).readlines()) == 1
        doomed = provider.non_terminated_nodes()[0]

        # Train-on-Tune style sweep riding the scaled nodes
        def trainable(config):
            for i in range(12):
                time.sleep(0.4)
                tune.report({"step": i, "value": config["x"] * i})

        results = {}

        exp_name = f"nodechaos_{int(time.time())}"
        exp_dir = f"/tmp/ray_tpu_nodechaos/{exp_name}"

        def run_tune():
            tuner = tune.Tuner(
                trainable,
                param_space={"x": tune.grid_search([1, 2])},
                tune_config=tune.TuneConfig(metric="value", mode="max"),
                run_config=RunConfig(
                    storage_path="/tmp/ray_tpu_nodechaos",
                    name=exp_name,
                    failure_config=FailureConfig(max_failures=16),
                ),
            )
            try:
                results["grid"] = tuner.fit()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                results["error"] = e

        t = threading.Thread(target=run_tune, daemon=True)
        t.start()
        # the kill must land on RUNNING trials (mid-flight evidence):
        # wait until the persisted experiment state shows a reported
        # result, not a fixed sleep
        import pickle
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                with open(f"{exp_dir}/experiment_state.pkl", "rb") as f:
                    st = pickle.load(f)
                if any(tr.last_result for tr in st["trials"]):
                    break
            except Exception:
                pass
            time.sleep(0.25)
        else:
            raise AssertionError("trials never started reporting")

        # SIGKILL the whole node: raylet AND its workers, like the
        # reference NodeKillerActor (killing only the raylet leaves its
        # workers up to a watchdog interval in which short trials could
        # finish on orphaned owner connections).
        handle = provider._handles[doomed.instance_id][0]
        handle.process.proc.send_signal(signal.SIGKILL)
        for pid in _find_worker_pids(handle.store_name):
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass

        t.join(timeout=240)
        assert not t.is_alive(), "tune run wedged after node kill"
        if "error" in results:
            raise results["error"]
        grid = results["grid"]
        assert len(grid) == 2
        for res in grid:
            assert res.error is None, f"trial failed: {res.error}"
            assert res.metrics["step"] == 11

        # the kill provably disrupted the sweep: at least one trial
        # burned a failure/retry
        assert any(tr.num_failures > 0 for tr in grid._trials), \
            "node kill never hit a running trial"

        # lineage reconstruction FIRED: the object's only copy died with
        # the node, so this get re-executes produce (marker line 2)
        out = ray_tpu.get(ref, timeout=120)
        assert out[0] == 7 and out.shape == (500_000,)
        assert len(open(marker).readlines()) == 2, \
            "reconstruction never re-executed the producer"

        # the autoscaler detected the host drop, terminated the broken
        # instance, and the cluster still has live provider capacity
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            live = provider.non_terminated_nodes()
            if all(i.instance_id != doomed.instance_id for i in live):
                break
            time.sleep(1.0)
        live = provider.non_terminated_nodes()
        assert all(i.instance_id != doomed.instance_id for i in live), \
            "dead node's instance never reaped"
    finally:
        autoscaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()
        try:
            os.unlink(marker)
        except OSError:
            pass
