"""GCS persistence + chaos tests.

Reference ground: `python/ray/tests/test_gcs_fault_tolerance.py`
(GCS restart with Redis-backed tables) and `test_chaos.py`
(WorkerKillerActor cadence kills during workloads,
`python/ray/_private/test_utils.py:1560`).
"""

import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.node import Cluster


def _find_worker_pids(store_name: str):
    """Worker processes of one cluster, identified by its shm store name
    in their cmdline (session-scoped, never another cluster's)."""
    pids = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace")
        except OSError:
            continue
        if "worker_main" in cmd and store_name in cmd:
            pids.append(int(pid))
    return pids


def test_gcs_restart_preserves_state():
    """Kill + respawn the GCS: named actors, placement groups and jobs
    survive via the snapshot; raylets reregister; calls keep working."""
    cluster = Cluster(head_resources={"CPU": 4.0}, gcs_persistence=True)
    ray_tpu.init(address=cluster.gcs_addr)
    try:
        @ray_tpu.remote
        class Keeper:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        keeper = Keeper.options(name="keeper",
                                lifetime="detached").remote()
        assert ray_tpu.get(keeper.bump.remote()) == 1

        pg = ray_tpu.placement_group([{"CPU": 1.0}], strategy="PACK")
        assert pg.ready(timeout=30)

        time.sleep(1.5)  # let a snapshot land
        cluster.restart_gcs()
        time.sleep(2.0)  # raylet reregisters on its next heartbeat

        # actor directory survived: resolve by name and keep state
        again = ray_tpu.get_actor("keeper")
        assert ray_tpu.get(again.bump.remote(), timeout=30) == 2

        # the PG record survived
        assert pg.ready(timeout=10)

        # fresh work schedules normally against the restarted GCS
        @ray_tpu.remote
        def probe():
            return "alive"

        assert ray_tpu.get(probe.remote(), timeout=60) == "alive"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_chaos_worker_kills_during_tune():
    """SIGKILL worker processes on a cadence during a Tune run;
    FailureConfig retries must carry every trial to completion."""
    from ray_tpu import tune
    from ray_tpu.air.config import FailureConfig, RunConfig

    cluster = Cluster(head_resources={"CPU": 4.0})
    store_name = cluster.head_node.store_name
    ray_tpu.init(address=cluster.gcs_addr)
    stop_killing = threading.Event()
    killed = []

    def killer():
        # let trials start, then murder a worker every 1.5s, thrice
        time.sleep(2.0)
        for _ in range(3):
            if stop_killing.is_set():
                return
            pids = _find_worker_pids(store_name)
            if pids:
                pid = pids[0]
                try:
                    os.kill(pid, signal.SIGKILL)
                    killed.append(pid)
                except ProcessLookupError:
                    pass
            time.sleep(1.5)

    thread = threading.Thread(target=killer, daemon=True)
    try:
        def trainable(config):
            for i in range(6):
                time.sleep(0.3)
                tune.report({"step": i, "value": config["x"] * i})

        thread.start()
        tuner = tune.Tuner(
            trainable,
            param_space={"x": tune.grid_search([1, 2])},
            tune_config=tune.TuneConfig(metric="value", mode="max"),
            run_config=RunConfig(
                storage_path="/tmp/ray_tpu_chaos",
                name=f"chaos_{int(time.time())}",
                failure_config=FailureConfig(max_failures=8),
            ),
        )
        grid = tuner.fit()
        stop_killing.set()
        assert killed, "chaos killer never killed anything"
        assert len(grid) == 2
        for res in grid:
            assert res.error is None, f"trial failed: {res.error}"
            assert res.metrics["step"] == 5
    finally:
        stop_killing.set()
        thread.join(timeout=10)
        ray_tpu.shutdown()
        cluster.shutdown()
