"""Elastic multislice recovery: slice loss -> replacement -> re-formation.

SURVEY §7.3's hard part, VERDICT r4 item 7: a multislice training run
loses an entire slice (its NODE dies, not just a worker process), a
replacement slice joins, the jax.distributed world re-forms on a fresh
coordinator, and training resumes from the latest complete sharded
checkpoint bit-identically.

Reference analogues: Train FailureConfig restart
(`python/ray/air/config.py:395`) + worker-group teardown/rebuild
(`python/ray/train/_internal/backend_executor.py:124`); slice loss is
the TPU-flavored node failure.

Own file: needs its own cluster (node kill + replacement mid-test).
"""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
from ray_tpu._private.node import Cluster
from ray_tpu.train.backend import JaxConfig

STEPS = 4
CRASH_STEP = 2


@pytest.fixture(scope="module")
def slice_cluster():
    # head holds the trial controller; each "slice" is one 1-CPU node so
    # every slice gang lands on its own node
    cluster = Cluster(head_resources={"CPU": 2.0},
                      object_store_memory=64 * 1024 * 1024)
    cluster.add_node({"CPU": 1.0})
    cluster.add_node({"CPU": 1.0})
    ray_tpu.init(address=cluster.gcs_addr)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def _make_loop(info_dir):
    def loop(config):
        import json
        import os as os_mod

        import jax
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ray_tpu import train as train_mod
        from ray_tpu.train import array_checkpoint as ac

        ctx = train_mod.get_context()
        rank = ctx.get_world_rank()
        devs = jax.devices()  # 2 procs x 2 devices: 2 virtual slices
        mesh = Mesh(np.array(devs).reshape(2, 2), ("dcn", "fsdp"))
        w0 = np.arange(32, dtype=np.float32).reshape(8, 4)
        state = {
            "w": jax.make_array_from_callback(
                (8, 4), NamedSharding(mesh, P(("dcn", "fsdp"))),
                lambda idx: w0[idx]),
            "step": jax.make_array_from_callback(
                (), NamedSharding(mesh, P()),
                lambda idx: np.zeros((), np.int32)),
        }
        start = 0
        ckpt = train_mod.get_checkpoint()
        if ckpt is not None and ac.is_sharded_checkpoint(ckpt):
            state = ac.restore_sharded(ckpt, state)
            start = int(np.asarray(
                state["step"].addressable_shards[0].data))

        @jax.jit
        def update(s):
            return {"w": s["w"] * 2.0 + 1.0, "step": s["step"] + 1}

        with open(os_mod.path.join(
                info_dir, f"attempt_{start}_{rank}.json"), "w") as f:
            json.dump({"rank": rank, "start": start,
                       "slice_rank": ctx.get_slice_rank(),
                       "node": os_mod.environ.get("RAY_TPU_NODE_ID")}, f)

        for i in range(start, STEPS):
            state = update(state)
            fp = float(sum(np.asarray(s.data).sum()
                           for s in state["w"].addressable_shards
                           if s.replica_id == 0))
            train_mod.report(
                {"step": i + 1, "fp": fp, "resumed_from": start,
                 "rank": rank},
                checkpoint=ac.save_to_checkpoint(state))
            if start == 0 and i + 1 >= CRASH_STEP:
                # first attempt: idle after the crash-step checkpoint so
                # the test can kill slice 1's node at a known point
                import time as time_mod

                time_mod.sleep(600)

    return loop


def test_slice_loss_replacement_resume(slice_cluster, tmp_path):
    info_dir = tmp_path / "info"
    info_dir.mkdir()
    trainer = train.JaxTrainer(
        _make_loop(str(info_dir)),
        backend_config=JaxConfig(
            distributed="on", platform="cpu",
            xla_flags="--xla_force_host_platform_device_count=2"),
        scaling_config=ScalingConfig(num_workers=2, num_slices=2),
        run_config=RunConfig(
            storage_path=str(tmp_path), name="elastic_ms",
            failure_config=FailureConfig(max_failures=2)),
    )
    out: dict = {}

    def run():
        try:
            out["result"] = trainer.fit()
        except BaseException as e:  # noqa: BLE001
            out["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()

    # wait until the first attempt has both ranks' step-2 checkpoint
    # persisted (both workers idle afterwards), then kill slice 1's node
    deadline = time.monotonic() + 240
    seen = set()
    while time.monotonic() < deadline:
        seen = {f for f in os.listdir(info_dir)
                if f.startswith("attempt_0_")}
        trial_dirs = []
        for root, dirs, _files in os.walk(tmp_path):
            trial_dirs += [os.path.join(root, d) for d in dirs
                           if d.startswith(f"checkpoint_{CRASH_STEP-1:06d}")]
        from ray_tpu.train import array_checkpoint as ac
        complete = [d for d in trial_dirs if not d.endswith("_shards")
                    and ac.is_usable(d)]
        if len(seen) == 2 and complete:
            break
        time.sleep(1.0)
    assert len(seen) == 2, seen

    # find which node hosts rank 1 (slice 1) and kill that raylet
    import json as json_mod

    recs = {}
    for f in os.listdir(info_dir):
        if f.startswith("attempt_0_"):
            rec = json_mod.loads((info_dir / f).read_text())
            recs[rec["rank"]] = rec
    victim_node = recs[1]["node"]
    assert recs[1]["slice_rank"] == 1
    victim = next(n for n in slice_cluster.nodes
                  if n.node_id_hex == victim_node)
    slice_cluster.remove_node(victim)
    # replacement slice joins (the autoscaler's replace-broken-slice
    # behavior, driven explicitly here; autoscaler-driven replacement is
    # covered by tests/test_autoscaler.py)
    slice_cluster.add_node({"CPU": 1.0})

    t.join(timeout=420)
    assert not t.is_alive(), "trainer did not finish after slice loss"
    assert "error" not in out, out.get("error")
    result = out["result"]
    # the retried run restored from the step-2 sharded checkpoint on a
    # RE-FORMED world and ran to completion
    assert result.metrics["step"] == STEPS
    assert result.metrics["resumed_from"] == CRASH_STEP
    # bit-identical: w_i = 2*w_{i-1} + 1 from arange(32); rank 0 holds
    # the first half of the flattened (dcn, fsdp) sharding
    w = np.arange(32, dtype=np.float32).reshape(8, 4)
    for _ in range(STEPS):
        w = w * 2.0 + 1.0
    assert result.metrics["fp"] == pytest.approx(float(w[:4].sum()),
                                                 abs=0.0)
    # the second attempt actually re-formed: fresh session files exist
    retry = {f for f in os.listdir(info_dir)
             if f.startswith(f"attempt_{CRASH_STEP}_")}
    assert len(retry) == 2, retry
