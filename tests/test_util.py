"""util tests: ActorPool, distributed Queue.

Reference ground: `python/ray/tests/test_actor_pool.py`,
`test_queue.py`.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Queue


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Doubler:
    def double(self, x):
        return 2 * x


def test_actor_pool_map_ordered():
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(6)))
    assert out == [0, 2, 4, 6, 8, 10]


def test_actor_pool_map_unordered():
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map_unordered(
        lambda a, v: a.double.remote(v), range(6)))
    assert sorted(out) == [0, 2, 4, 6, 8, 10]


def test_actor_pool_submit_get_next():
    pool = ActorPool([Doubler.remote()])
    pool.submit(lambda a, v: a.double.remote(v), 10)
    pool.submit(lambda a, v: a.double.remote(v), 20)  # queues
    assert pool.has_next()
    assert pool.get_next(timeout=30) == 20
    assert pool.get_next(timeout=30) == 40
    assert not pool.has_next()


def test_queue_roundtrip():
    q = Queue(maxsize=4)
    q.put("a")
    q.put_many(["b", "c"])
    assert q.qsize() == 3
    assert q.get() == "a"
    assert q.get() == "b"
    assert q.get() == "c"
    assert q.empty()
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()


def test_queue_blocking_get_across_callers():
    q = Queue()
    got = []

    def consumer():
        got.append(q.get(timeout=30))

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.3)
    q.put("handoff")
    t.join(timeout=30)
    assert got == ["handoff"]
    q.shutdown()


def test_multiprocessing_pool():
    """ray_tpu.util.multiprocessing.Pool: the stdlib surface over
    actors (reference `ray.util.multiprocessing.pool`)."""
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        # map preserves order across chunks
        assert pool.map(lambda x: x * x, range(10), chunksize=3) == [
            x * x for x in range(10)]
        # starmap unpacks tuples
        assert pool.starmap(lambda a, b: a + b,
                            [(1, 2), (3, 4)]) == [3, 7]
        # apply/apply_async
        assert pool.apply(lambda a, k=0: a + k, (5,), {"k": 2}) == 7
        ar = pool.apply_async(lambda: "ok")
        assert ar.get(timeout=60) == "ok"
        assert ar.successful()
        # imap yields in order; imap_unordered yields everything
        assert list(pool.imap(lambda x: x + 1, range(6),
                              chunksize=2)) == [1, 2, 3, 4, 5, 6]
        assert sorted(pool.imap_unordered(
            lambda x: x * 2, range(6), chunksize=2)) == [
                0, 2, 4, 6, 8, 10]
        # map_async + wait/ready
        mr = pool.map_async(lambda x: -x, range(4))
        mr.wait(timeout=60)
        assert mr.ready() and mr.get() == [0, -1, -2, -3]
        # close/join drains, then terminate via context exit
        pool.close()
        pool.join()
