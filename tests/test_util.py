"""util tests: ActorPool, distributed Queue.

Reference ground: `python/ray/tests/test_actor_pool.py`,
`test_queue.py`.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Queue


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Doubler:
    def double(self, x):
        return 2 * x


def test_actor_pool_map_ordered():
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(6)))
    assert out == [0, 2, 4, 6, 8, 10]


def test_actor_pool_map_unordered():
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map_unordered(
        lambda a, v: a.double.remote(v), range(6)))
    assert sorted(out) == [0, 2, 4, 6, 8, 10]


def test_actor_pool_submit_get_next():
    pool = ActorPool([Doubler.remote()])
    pool.submit(lambda a, v: a.double.remote(v), 10)
    pool.submit(lambda a, v: a.double.remote(v), 20)  # queues
    assert pool.has_next()
    assert pool.get_next(timeout=30) == 20
    assert pool.get_next(timeout=30) == 40
    assert not pool.has_next()


def test_queue_roundtrip():
    q = Queue(maxsize=4)
    q.put("a")
    q.put_many(["b", "c"])
    assert q.qsize() == 3
    assert q.get() == "a"
    assert q.get() == "b"
    assert q.get() == "c"
    assert q.empty()
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()


def test_queue_blocking_get_across_callers():
    q = Queue()
    got = []

    def consumer():
        got.append(q.get(timeout=30))

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.3)
    q.put("handoff")
    t.join(timeout=30)
    assert got == ["handoff"]
    q.shutdown()
