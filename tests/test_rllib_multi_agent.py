"""Multi-agent RLlib: env API, runner routing, and PPO learning.

Reference behaviors covered: dict-keyed MultiAgentEnv stepping
(`rllib/env/multi_agent_env.py`), per-agent episode collection routed by
policy_mapping_fn (`multi_agent_env_runner.py`), shared-vs-independent
policies, and a MultiAgentPPO run that actually improves reward.
"""

import numpy as np
import pytest

from ray_tpu.rllib import (MultiAgentEnv, MultiAgentEnvRunner,
                           MultiAgentPPO, MultiAgentPPOConfig)
from ray_tpu.rllib.core.rl_module import RLModuleSpec


class MatchingEnv(MultiAgentEnv):
    """Cooperative 2-agent game: each agent sees a 4-state one-hot and
    earns +1 per step for choosing action == state % 2. Episode length 8.
    Optimal per-episode return (summed over both agents): 16.
    """

    possible_agents = ["a0", "a1"]

    def __init__(self):
        import gymnasium as gym

        obs_sp = gym.spaces.Box(0.0, 1.0, (4,), np.float32)
        act_sp = gym.spaces.Discrete(2)
        self.observation_spaces = {a: obs_sp for a in self.possible_agents}
        self.action_spaces = {a: act_sp for a in self.possible_agents}
        self._rng = np.random.default_rng(0)
        self._t = 0
        self._state = {}

    def _obs(self):
        out = {}
        for a in self.possible_agents:
            s = int(self._rng.integers(0, 4))
            self._state[a] = s
            onehot = np.zeros(4, np.float32)
            onehot[s] = 1.0
            out[a] = onehot
        return out

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._obs(), {}

    def step(self, actions):
        rewards = {
            a: float(int(actions[a]) == self._state[a] % 2)
            for a in self.possible_agents
        }
        self._t += 1
        done = self._t >= 8
        obs = self._obs()
        terms = {a: done for a in self.possible_agents}
        terms["__all__"] = done
        truncs = {a: False for a in self.possible_agents}
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, {}


def _specs(module_ids):
    return {m: RLModuleSpec(observation_dim=4, action_dim=2,
                            hidden=(32,), discrete=True)
            for m in module_ids}


def test_runner_routes_episodes_by_module():
    import jax

    specs = _specs(["p0", "p1"])
    runner = MultiAgentEnvRunner(
        MatchingEnv, specs, lambda a: "p0" if a == "a0" else "p1",
        seed=0)
    weights = {
        mid: specs[mid].build().init_params(jax.random.PRNGKey(i))
        for i, mid in enumerate(specs)
    }
    runner.set_weights(weights)
    out = runner.sample(num_steps=64)
    assert set(out) == {"p0", "p1"}
    # both agents act every step, so both modules collected episodes
    for mid, eps in out.items():
        assert eps, mid
        for ep in eps:
            assert ep.length > 0
            assert len(ep.obs) == ep.length == len(ep.rewards)
            assert ep.obs[0].shape == (4,)
    m = runner.get_metrics()
    assert m["num_episodes"] > 0


def test_runner_shared_policy():
    import jax

    specs = _specs(["shared"])
    runner = MultiAgentEnvRunner(
        MatchingEnv, specs, lambda a: "shared", seed=1)
    runner.set_weights({
        "shared": specs["shared"].build().init_params(
            jax.random.PRNGKey(0))})
    out = runner.sample(num_steps=32)
    assert set(out) == {"shared"}
    # two agents per step -> roughly 2x episodes land on the one module
    assert len(out["shared"]) >= 2


@pytest.mark.parametrize("shared", [True, False])
def test_multi_agent_ppo_learns(shared):
    if shared:
        policies = {"shared": None}
        mapping = lambda a: "shared"  # noqa: E731
    else:
        policies = {"p0": None, "p1": None}
        mapping = lambda a: "p0" if a == "a0" else "p1"  # noqa: E731
    config = (
        MultiAgentPPOConfig()
        .environment(env=lambda: MatchingEnv())
        .multi_agent(policies=policies, policy_mapping_fn=mapping)
        .training(train_batch_size=512, minibatch_size=128,
                  num_epochs=4, lr=3e-3, entropy_coeff=0.01)
    )
    algo = MultiAgentPPO(config)
    try:
        best = -np.inf
        for _ in range(12):
            result = algo.train()
            r = result.get("episode_return_mean")
            if r is not None and not np.isnan(r):
                best = max(best, r)
            if best >= 13.0:
                break
        # random play scores ~8/16; learned play should clearly beat it
        assert best >= 13.0, f"best return {best}"
    finally:
        algo.stop()


def test_multi_agent_checkpoint_roundtrip(tmp_path):
    policies = {"p0": None, "p1": None}
    mapping = lambda a: "p0" if a == "a0" else "p1"  # noqa: E731
    config = (
        MultiAgentPPOConfig()
        .environment(env=lambda: MatchingEnv())
        .multi_agent(policies=policies, policy_mapping_fn=mapping)
        .training(train_batch_size=128, minibatch_size=64, num_epochs=1)
    )
    algo = MultiAgentPPO(config)
    try:
        algo.train()
        algo.save_checkpoint(str(tmp_path))
        w_before = {mid: lg.get_weights()
                    for mid, lg in algo.learner_groups.items()}
        algo.train()  # mutate
        algo.load_checkpoint(str(tmp_path))
        import jax
        for mid, w in w_before.items():
            restored = algo.learner_groups[mid].get_weights()
            for a, b in zip(jax.tree_util.tree_leaves(w),
                            jax.tree_util.tree_leaves(restored)):
                np.testing.assert_allclose(a, b)
    finally:
        algo.stop()


class VanishingAgentEnv(MultiAgentEnv):
    """a1 leaves (no obs, no term flag) after step 3; a0 runs 8 steps."""

    possible_agents = ["a0", "a1"]

    def __init__(self):
        import gymnasium as gym

        obs_sp = gym.spaces.Box(0.0, 1.0, (4,), np.float32)
        act_sp = gym.spaces.Discrete(2)
        self.observation_spaces = {a: obs_sp for a in self.possible_agents}
        self.action_spaces = {a: act_sp for a in self.possible_agents}
        self._t = 0

    def _obs_for(self, agents):
        return {a: np.ones(4, np.float32) for a in agents}

    def reset(self, *, seed=None):
        self._t = 0
        return self._obs_for(self.possible_agents), {}

    def step(self, actions):
        self._t += 1
        live = (self.possible_agents if self._t < 3 else ["a0"])
        done = self._t >= 8
        obs = self._obs_for(live if not done else self.possible_agents)
        rewards = {a: 1.0 for a in actions}
        terms = {a: done for a in self.possible_agents}
        terms["__all__"] = done
        truncs = {a: False for a in self.possible_agents}
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, {}


def test_vanishing_agent_fragment_not_lost():
    import jax

    specs = _specs(["p0", "p1"])
    runner = MultiAgentEnvRunner(
        VanishingAgentEnv, specs, lambda a: "p0" if a == "a0" else "p1",
        seed=0)
    runner.set_weights({
        mid: specs[mid].build().init_params(jax.random.PRNGKey(i))
        for i, mid in enumerate(specs)})
    out = runner.sample(num_steps=20)
    # a1's 3-step fragment closed as truncated when it vanished mid-episode
    assert out["p1"], "vanished agent's episode was dropped"
    assert all(ep.length == 3 and ep.truncated for ep in out["p1"][:1])
    # a0 kept playing to the episode end
    assert any(ep.terminated for ep in out["p0"])
