"""Experiment-level Tuner.restore: a dead driver's sweep resumes.

Reference ground: `python/ray/tune/tuner.py` (Tuner.restore),
`python/ray/tune/execution/experiment_state.py`,
`python/ray/tune/tests/test_tuner_restore.py` — the driver process is
SIGKILLed mid-sweep (taking its whole mini-cluster with it), then the
experiment is restored from `experiment_state.pkl` and finished.
"""

import os
import pickle
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import Checkpoint, RunConfig, FailureConfig


def _make_train_fn():
    # defined as a closure so cloudpickle ships it by value (a module-level
    # fn would pickle as a reference to this test module, which workers
    # can't import)
    def _train_fn(config):
        ckpt = tune.get_checkpoint()
        start = ckpt.to_dict()["i"] + 1 if ckpt else 0
        for i in range(start, 6):
            time.sleep(0.25)
            tune.report({"score": config["x"] * (i + 1), "i": i},
                        checkpoint=Checkpoint.from_dict({"i": i}))
    return _train_fn


DRIVER = """
import sys, time
import ray_tpu
from ray_tpu import tune
from ray_tpu.air import RunConfig, Checkpoint

storage = sys.argv[1]

def _train_fn(config):
    ckpt = tune.get_checkpoint()
    start = ckpt.to_dict()["i"] + 1 if ckpt else 0
    for i in range(start, 6):
        time.sleep(0.25)
        tune.report({"score": config["x"] * (i + 1), "i": i},
                    checkpoint=Checkpoint.from_dict({"i": i}))

ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
tune.Tuner(
    _train_fn,
    param_space={"x": tune.grid_search([1.0, 2.0, 3.0, 4.0])},
    tune_config=tune.TuneConfig(metric="score", mode="max",
                                max_concurrent_trials=2),
    run_config=RunConfig(storage_path=storage, name="restore_exp"),
).fit()
print("DRIVER_DONE", flush=True)
"""


def _load_state(exp_dir):
    with open(os.path.join(exp_dir, "experiment_state.pkl"), "rb") as f:
        return pickle.load(f)


def test_restore_after_driver_sigkill(tmp_path):
    storage = str(tmp_path / "tune_out")
    exp_dir = os.path.join(storage, "restore_exp")
    proc = subprocess.Popen(
        [sys.executable, "-c", DRIVER, storage],
        cwd="/root/repo", start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        # wait until the sweep is provably mid-flight: some trial has
        # reported at least twice, and not every trial has finished
        deadline = time.monotonic() + 90
        while True:
            assert time.monotonic() < deadline, "driver never made progress"
            assert proc.poll() is None, \
                f"driver exited early: {proc.stdout.read()!r}"
            try:
                state = _load_state(exp_dir)
            except (FileNotFoundError, pickle.UnpicklingError, EOFError):
                time.sleep(0.1)
                continue
            trials = state["trials"]
            progressed = [t for t in trials
                          if t.last_result and t.last_result.get("i", 0) >= 1]
            done = [t for t in trials if t.status == "TERMINATED"]
            if progressed and len(done) < 4:
                break
            time.sleep(0.1)
    finally:
        # SIGKILL the whole process group: driver + GCS + raylet + workers
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)

    pre = _load_state(exp_dir)
    unfinished_pre = [t for t in pre["trials"] if t.status != "TERMINATED"]
    assert unfinished_pre, "kill landed after the sweep finished"

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        grid = tune.Tuner.restore(exp_dir, _make_train_fn()).fit()
        assert len(grid.errors) == 0
        assert len(grid) == 4  # all grid points present, none re-suggested
        assert sorted(r.metrics["config"]["x"] for r in grid) == \
            [1.0, 2.0, 3.0, 4.0]
        # every trial ran to completion after restore
        assert all(r.metrics["i"] == 5 for r in grid)
        best = grid.get_best_result()
        assert best.metrics["score"] == pytest.approx(4.0 * 6)
        # trials that had checkpoints resumed from them instead of
        # restarting: their post-restore history must not re-report i=0
        resumed = [t for t in unfinished_pre
                   if t.checkpoint_path and t.last_result]
        if resumed:
            post = {t.trial_id: t
                    for t in _load_state(exp_dir)["trials"]}
            for t in resumed:
                pre_i = t.last_result["i"]
                new_is = [r["i"] for r in post[t.trial_id].metrics_history
                          if r["i"] > pre_i]
                # a trial killed after its final report resumes and
                # finishes immediately — no new history is correct then
                assert new_is or pre_i == 5, \
                    f"trial {t.trial_id} made no post-kill progress"
    finally:
        ray_tpu.shutdown()


def test_restore_resume_errored(tmp_path):
    storage = str(tmp_path / "tune_err")
    marker = str(tmp_path / "healed")

    def sometimes(config):
        if config["x"] == 2.0 and not os.path.exists(marker):
            raise RuntimeError("transient env failure")
        tune.report({"score": config["x"]})

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        run_cfg = RunConfig(storage_path=storage, name="err_exp",
                            failure_config=FailureConfig(max_failures=0))
        grid = tune.Tuner(
            sometimes,
            param_space={"x": tune.grid_search([1.0, 2.0])},
            tune_config=tune.TuneConfig(metric="score", mode="max"),
            run_config=run_cfg,
        ).fit()
        assert len(grid.errors) == 1
        exp_dir = os.path.join(storage, "err_exp")

        # without resume_errored, the errored trial stays errored
        grid2 = tune.Tuner.restore(exp_dir, sometimes).fit()
        assert len(grid2.errors) == 1

        open(marker, "w").close()
        grid3 = tune.Tuner.restore(exp_dir, sometimes,
                                   resume_errored=True).fit()
        assert len(grid3.errors) == 0
        assert sorted(r.metrics["score"] for r in grid3 if r.metrics) == \
            [1.0, 2.0]
    finally:
        ray_tpu.shutdown()


def test_restore_missing_state(tmp_path):
    with pytest.raises(FileNotFoundError):
        tune.Tuner.restore(str(tmp_path), _make_train_fn())
