"""RLlib end-to-end tests: Algorithm / LearnerGroup / PPO / DQN.

Models the reference's algorithm learning tests
(`rllib/algorithms/ppo/tests/test_ppo.py`,
`rllib/tuned_examples/ppo/cartpole_ppo.py` — CartPole-v1 to a reward
threshold in bounded iterations) scaled to CI budgets.
"""

import numpy as np
import pytest

from ray_tpu.rllib import (
    DQN,
    DQNConfig,
    LearnerGroup,
    PPO,
    PPOConfig,
    PPOLearner,
    RLModuleSpec,
)


def _cartpole_ppo_config(**overrides):
    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4)
        .training(lr=3e-4, train_batch_size=1024, minibatch_size=128,
                  num_epochs=6, entropy_coeff=0.01)
        .debugging(seed=0)
    )
    cfg.update_from_dict(overrides)
    return cfg


def test_ppo_cartpole_learns():
    """PPO reaches a mean episode return >= 120 on CartPole-v1 within a
    bounded number of iterations (untrained policy scores ~20)."""
    algo = PPO(config=_cartpole_ppo_config())
    try:
        best = 0.0
        for _ in range(40):
            result = algo.train()
            r = result["episode_return_mean"]
            if np.isfinite(r):
                best = max(best, r)
            if best >= 120.0:
                break
        assert best >= 120.0, f"PPO failed to learn: best return {best}"
    finally:
        algo.stop()


def test_ppo_remote_env_runners(ray_start):
    """Distributed sampling: remote env-runner actors feed the same loop."""
    cfg = _cartpole_ppo_config(
        num_env_runners=2, num_envs_per_env_runner=2,
        train_batch_size=512, num_epochs=2)
    algo = PPO(config=cfg)
    try:
        result = algo.train()
        assert result["num_env_steps_sampled"] >= 512
        assert np.isfinite(result["total_loss"])
        assert result["num_episodes"] >= 0
    finally:
        algo.stop()


def test_dqn_smoke():
    """DQN runs updates once the buffer passes learning_starts and the
    loss/TD stats are finite; epsilon decays across iterations."""
    cfg = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=2,
                     rollout_fragment_length=200)
        .training(lr=1e-3, train_batch_size=32,
                  learning_starts=300, num_updates_per_iteration=4,
                  prioritized_replay=True)
        .debugging(seed=0)
    )
    algo = DQN(config=cfg)
    try:
        eps0 = None
        stats = {}
        for _ in range(6):
            stats = algo.train()
            if eps0 is None:
                eps0 = stats["epsilon"]
        assert stats["replay_size"] >= 300
        assert "td_error_mean" in stats and np.isfinite(
            stats["td_error_mean"])
        assert stats["epsilon"] < eps0
    finally:
        algo.stop()


def test_learner_group_multi_learner_sync(ray_start):
    """Remote learner fleet: after an averaged-gradient update every
    learner holds identical weights, and they differ from the start."""
    spec = RLModuleSpec(observation_dim=4, action_dim=2, hidden=(16,))
    group = LearnerGroup(PPOLearner, spec, {"lr": 1e-2},
                         num_learners=2)
    try:
        w0 = group.get_weights()
        rng = np.random.default_rng(0)
        n = 64
        batch = {
            "obs": rng.normal(size=(n, 4)).astype(np.float32),
            "actions": rng.integers(0, 2, size=n),
            "action_logp": np.full(n, -0.69, np.float32),
            "advantages": rng.normal(size=n).astype(np.float32),
            "value_targets": rng.normal(size=n).astype(np.float32),
        }
        stats = group.update_from_batch(batch)
        assert np.isfinite(stats["total_loss"])
        # every learner actor must hold the same post-update weights
        import jax

        all_w = group._manager.foreach(lambda a: a.get_weights.remote())
        assert len(all_w) == 2
        flat_a = jax.tree_util.tree_leaves(all_w[0])
        flat_b = jax.tree_util.tree_leaves(all_w[1])
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_allclose(a, b, rtol=1e-6)
        # and they moved from initialization
        moved = any(
            not np.allclose(a, b)
            for a, b in zip(jax.tree_util.tree_leaves(w0), flat_a))
        assert moved
    finally:
        group.stop()


def test_learner_multi_device_mesh():
    """Single learner sharding its batch over a 4-device dp mesh matches
    the 1-device update (GSPMD allreduce correctness)."""
    import jax

    spec = RLModuleSpec(observation_dim=4, action_dim=2, hidden=(16,))
    l1 = PPOLearner(spec, {"lr": 1e-2}, seed=0)
    l4 = PPOLearner(spec, {"lr": 1e-2}, seed=0, num_devices=4)
    rng = np.random.default_rng(1)
    n = 64
    batch = {
        "obs": rng.normal(size=(n, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, size=n),
        "action_logp": np.full(n, -0.69, np.float32),
        "advantages": rng.normal(size=n).astype(np.float32),
        "value_targets": rng.normal(size=n).astype(np.float32),
    }
    s1 = l1.update_from_batch(batch)
    s4 = l4.update_from_batch(batch)
    assert np.isclose(s1["total_loss"], s4["total_loss"], rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(l1.params),
                    jax.tree_util.tree_leaves(l4.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_algorithm_checkpoint_roundtrip(tmp_path):
    """save_checkpoint/load_checkpoint restore weights + iteration."""
    import jax

    algo = PPO(config=_cartpole_ppo_config(
        train_batch_size=256, num_epochs=1))
    try:
        algo.train()
        ckpt = str(tmp_path / "ckpt")
        import os

        os.makedirs(ckpt, exist_ok=True)
        algo.save_checkpoint(ckpt)
        w = algo.learner_group.get_weights()
        it = algo._iteration

        algo2 = PPO(config=_cartpole_ppo_config(
            train_batch_size=256, num_epochs=1))
        try:
            algo2.load_checkpoint(ckpt)
            assert algo2._iteration == it
            w2 = algo2.learner_group.get_weights()
            for a, b in zip(jax.tree_util.tree_leaves(w),
                            jax.tree_util.tree_leaves(w2)):
                np.testing.assert_allclose(a, b)
            # optimizer moments must survive the roundtrip too — a
            # restore that resets Adam state is a silent training bug
            s1 = algo.learner_group.get_state()["opt_state"]
            s2 = algo2.learner_group.get_state()["opt_state"]
            for a, b in zip(jax.tree_util.tree_leaves(s1),
                            jax.tree_util.tree_leaves(s2)):
                np.testing.assert_allclose(a, b)
        finally:
            algo2.stop()
    finally:
        algo.stop()


def test_algorithm_on_tune(ray_start, tmp_path):
    """Algorithm is a Tune Trainable: Tuner runs a 2-trial grid over lr
    and returns per-trial results with RL metrics."""
    from ray_tpu import tune
    from ray_tpu.air.config import RunConfig

    tuner = tune.Tuner(
        PPO,
        param_space={
            "env": "CartPole-v1",
            "train_batch_size": 256,
            "minibatch_size": 128,
            "num_epochs": 1,
            "num_envs_per_env_runner": 2,
            "lr": tune.grid_search([1e-3, 3e-4]),
        },
        tune_config=tune.TuneConfig(metric="episode_return_mean",
                                    mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path),
                             stop={"training_iteration": 2}),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    for res in grid:
        assert res.error is None
        assert res.metrics["training_iteration"] == 2
        assert "episode_return_mean" in res.metrics


def test_vtrace_matches_onpolicy_gae_like_returns():
    """With rho=c=1 and behavior == target policy, V-trace targets
    reduce to n-step TD(lambda=1)-corrected values — check against a
    direct numpy recursion."""
    import jax.numpy as jnp

    from ray_tpu.rllib.core.learner import vtrace_returns

    rng = np.random.default_rng(0)
    B, T = 3, 6
    logp = rng.normal(size=(B, T)).astype(np.float32)
    rewards = rng.normal(size=(B, T)).astype(np.float32)
    values = rng.normal(size=(B, T)).astype(np.float32)
    boot = rng.normal(size=(B,)).astype(np.float32)
    mask = np.ones((B, T), np.float32)
    gamma = 0.9

    vs, pg = vtrace_returns(jnp.asarray(logp), jnp.asarray(logp),
                            jnp.asarray(rewards), jnp.asarray(values),
                            jnp.asarray(boot), jnp.asarray(mask), gamma)
    # numpy reference recursion (rho = c = 1)
    expect = np.zeros((B, T), np.float32)
    for b in range(B):
        acc = 0.0
        for t in range(T - 1, -1, -1):
            nv = boot[b] if t == T - 1 else values[b, t + 1]
            delta = rewards[b, t] + gamma * nv - values[b, t]
            acc = delta + gamma * acc
            expect[b, t] = values[b, t] + acc
    np.testing.assert_allclose(np.asarray(vs), expect, rtol=1e-4,
                               atol=1e-5)


def test_impala_cartpole_learns():
    """IMPALA (stale-weight sampling + V-trace correction) improves on
    CartPole within a bounded number of iterations."""
    from ray_tpu.rllib import IMPALA, IMPALAConfig

    cfg = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                     rollout_fragment_length=50)
        .training(lr=1e-3, train_batch_size=800, entropy_coeff=0.005)
        .debugging(seed=0)
    )
    algo = IMPALA(config=cfg)
    try:
        best = 0.0
        for _ in range(60):
            result = algo.train()
            r = result["episode_return_mean"]
            if np.isfinite(r):
                best = max(best, r)
            if best >= 80.0:
                break
        # untrained policy scores ~25; 80+ demonstrates off-policy
        # V-trace learning within the CI budget
        assert best >= 80.0, f"IMPALA failed to learn: best={best}"
    finally:
        algo.stop()


def test_vtrace_short_row_bootstraps_correctly():
    """A row shorter than T must bootstrap at its LAST VALID step from
    bootstrap_value — never from padded-zero observations' values."""
    import jax.numpy as jnp

    from ray_tpu.rllib.core.learner import vtrace_returns

    T = 5
    gamma = 0.9
    # one row, 2 valid steps; padding carries a huge value that must
    # not leak into the targets
    values = np.array([[1.0, 2.0, 99.0, 99.0, 99.0]], np.float32)
    rewards = np.array([[1.0, 1.0, 0.0, 0.0, 0.0]], np.float32)
    mask = np.array([[1.0, 1.0, 0.0, 0.0, 0.0]], np.float32)
    logp = np.zeros((1, T), np.float32)
    boot = np.array([5.0], np.float32)

    vs, pg = vtrace_returns(jnp.asarray(logp), jnp.asarray(logp),
                            jnp.asarray(rewards), jnp.asarray(values),
                            jnp.asarray(boot), jnp.asarray(mask), gamma)
    # hand recursion over the 2 valid steps with bootstrap 5.0
    d1 = 1.0 + gamma * 5.0 - 2.0
    d0 = 1.0 + gamma * 2.0 - 1.0
    vs1 = 2.0 + d1
    vs0 = 1.0 + d0 + gamma * d1
    np.testing.assert_allclose(np.asarray(vs)[0, :2], [vs0, vs1],
                               rtol=1e-5)
    # padded region contributes nothing to pg advantages
    np.testing.assert_allclose(np.asarray(pg)[0, 2:], 0.0)


def test_sequence_batch_splits_long_episodes():
    """Episodes longer than the fragment length split into chained rows
    that bootstrap from the next chunk — no silent truncation."""
    from ray_tpu.rllib.connectors import sequence_batch
    from ray_tpu.rllib.env.env_runner import Episode

    ep = Episode()
    for i in range(7):
        ep.obs.append(np.full(3, i, np.float32))
        ep.actions.append(i % 2)
        ep.rewards.append(1.0)
        ep.logps.append(-0.5)
        ep.vf_preds.append(0.0)
    ep.terminated = True
    ep.last_obs = np.full(3, 99, np.float32)

    batch = sequence_batch([ep], max_len=3)
    assert batch["obs"].shape == (3, 3, 3)  # 7 steps -> 3 rows of <=3
    assert batch["mask"].sum() == 7  # every step kept
    # chunk 0 bootstraps from step 3's obs, not terminated
    np.testing.assert_allclose(batch["last_obs"][0], np.full(3, 3.0))
    assert batch["terminateds"][0] == 0.0
    # final chunk carries the episode's own termination + last_obs
    np.testing.assert_allclose(batch["last_obs"][2], np.full(3, 99.0))
    assert batch["terminateds"][2] == 1.0


def test_sac_pendulum_smoke():
    """SAC on Pendulum-v1 (continuous Box actions): replay fills, the
    combined jitted update produces finite losses, alpha auto-tunes
    away from 1.0, targets polyak-track, actions stay in bounds."""
    import jax

    from ray_tpu.rllib import SAC, SACConfig

    cfg = (
        SACConfig()
        .environment("Pendulum-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=2,
                     rollout_fragment_length=250)
        .training(lr=3e-4, train_batch_size=64, learning_starts=400,
                  num_updates_per_iteration=8)
        .debugging(seed=0)
    )
    algo = SAC(config=cfg)
    try:
        assert not algo.spec.discrete
        assert algo.spec.action_dim == 1
        assert algo.spec.action_scale == (2.0,)  # torque range
        assert algo.spec.action_offset == (0.0,)
        stats = {}
        for _ in range(4):
            stats = algo.train()
        assert stats["replay_size"] >= 400
        assert stats["num_updates"] > 0
        for k in ("q_loss", "policy_loss", "alpha_loss", "entropy"):
            assert np.isfinite(stats[k]), (k, stats)
        assert stats["alpha"] != 1.0  # temperature actually adapting
        # target nets track online critics (polyak), not frozen
        learner = algo.learner_group._local
        diff = sum(
            float(np.abs(np.asarray(a) - np.asarray(b)).sum())
            for a, b in zip(
                jax.tree_util.tree_leaves(learner.target_q["q1"]),
                jax.tree_util.tree_leaves(learner.params["q1"])))
        assert diff > 0  # lagging, but...
        # greedy eval actions respect the Box bounds
        ev = algo.evaluate()
        assert "episode_return_mean" in ev
    finally:
        algo.stop()


def test_appo_cartpole_learns():
    """APPO (V-trace + PPO clip on stale-weight samples) improves on
    CartPole within a bounded number of iterations."""
    from ray_tpu.rllib import APPO, APPOConfig

    cfg = (
        APPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                     rollout_fragment_length=50)
        .training(lr=1e-3, train_batch_size=800, entropy_coeff=0.005)
        .debugging(seed=0)
    )
    algo = APPO(config=cfg)
    try:
        best = 0.0
        for _ in range(60):
            result = algo.train()
            r = result["episode_return_mean"]
            if np.isfinite(r):
                best = max(best, r)
            assert np.isfinite(result["mean_ratio"])
            if best >= 80.0:
                break
        assert best >= 80.0, f"APPO failed to learn: best={best}"
    finally:
        algo.stop()
