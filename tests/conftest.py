"""Test configuration.

JAX-facing tests run on a virtual 8-device CPU mesh (the reference's
`ray_start_cluster`-style multi-node-on-one-machine testing mechanism,
adapted to device meshes): set platform/device-count env vars before jax is
imported anywhere.
"""

import os

# Force CPU unconditionally: the environment may point JAX_PLATFORMS at real
# TPU hardware (and a sitecustomize may have imported jax already), so both
# the env var and the live jax config must be overridden.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Suites exercising the lock-heavy planes run under the runtime lock-order
# validator (ray_tpu/_private/lockdep.py): every Lock/RLock created during
# the test joins the order graph, and any A→B / B→A inversion fails the
# test with both witness stacks. Record-only in-process (raise_on_cycle
# off) so the failure is attributed at teardown instead of perturbing
# control flow mid-test; worker daemons self-install via RAY_TPU_LOCKDEP=1
# in their inherited environment and raise in-daemon.
_LOCKDEP_SUITES = ("test_chaos", "test_object_store", "test_rpc_batch",
                   "test_multitenant", "test_ownership",
                   "test_dispatch_ring", "test_slo")


@pytest.fixture(autouse=True)
def _lockdep_gate(request):
    if request.module.__name__ not in _LOCKDEP_SUITES:
        yield
        return
    from ray_tpu._private import lockdep

    already = lockdep.enabled()
    if not already:
        lockdep.install(raise_on_cycle=False)
    os.environ[lockdep.ENV_VAR] = "1"
    try:
        yield
    finally:
        reports = lockdep.cycle_reports()
        os.environ.pop(lockdep.ENV_VAR, None)
        if not already:
            lockdep.uninstall()
        assert not reports, (
            "lockdep: lock-order cycle(s) detected:\n\n"
            + "\n\n".join(reports))


@pytest.fixture
def shm_store():
    """A fresh native shared-memory store, destroyed at teardown."""
    from ray_tpu._private.object_store import ObjectStore

    name = f"/ray_tpu_test_{os.getpid()}_{os.urandom(4).hex()}"
    store = ObjectStore.create(name, capacity=64 * 1024 * 1024, table_size=4096)
    yield store
    store.destroy()


@pytest.fixture
def ray_start():
    """Start a single-node ray_tpu cluster for the duration of a test."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ray_tpu
    ray_tpu.shutdown()
