"""Test configuration.

JAX-facing tests run on a virtual 8-device CPU mesh (the reference's
`ray_start_cluster`-style multi-node-on-one-machine testing mechanism,
adapted to device meshes): set platform/device-count env vars before jax is
imported anywhere.
"""

import os

# Force CPU unconditionally: the environment may point JAX_PLATFORMS at real
# TPU hardware (and a sitecustomize may have imported jax already), so both
# the env var and the live jax config must be overridden.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def shm_store():
    """A fresh native shared-memory store, destroyed at teardown."""
    from ray_tpu._private.object_store import ObjectStore

    name = f"/ray_tpu_test_{os.getpid()}_{os.urandom(4).hex()}"
    store = ObjectStore.create(name, capacity=64 * 1024 * 1024, table_size=4096)
    yield store
    store.destroy()


@pytest.fixture
def ray_start():
    """Start a single-node ray_tpu cluster for the duration of a test."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ray_tpu
    ray_tpu.shutdown()
