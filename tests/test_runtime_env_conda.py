"""Conda runtime-env backend (reference
`python/ray/_private/runtime_env/conda.py`): per-spec envs created by
the node, content-addressed and cached; the worker interpreter comes
from the env. Driven against a stub `conda` executable (the zero-egress
box carries no conda), which builds the env as a venv — the framework
code paths (normalization, cache, raylet spawn hook) are identical.

Own file: the RAYLET must see RAY_TPU_CONDA_EXE at daemon spawn.
"""

import os
import stat
import time

import pytest

import ray_tpu

_STUB = """#!/bin/bash
# test stub for the conda CLI
if [ "$1" = "env" ] && [ "$2" = "create" ]; then
  shift 2
  while [ $# -gt 0 ]; do
    case "$1" in
      -p) path="$2"; shift 2;;
      -f) file="$2"; shift 2;;
      *) shift;;
    esac
  done
  {python} -m venv --system-site-packages "$path" || exit 1
  cp "$file" "$path/spec.yml"
  exit 0
fi
if [ "$1" = "run" ]; then
  shift
  if [ "$1" = "-n" ]; then
    name="$2"; shift 2
    if [ "$name" != "present-env" ]; then exit 1; fi
  fi
  exec "$@"
fi
exit 2
"""


@pytest.fixture(scope="module", autouse=True)
def cluster(tmp_path_factory):
    import sys

    base = tmp_path_factory.mktemp("conda")
    stub = base / "conda"
    stub.write_text(_STUB.replace("{python}", sys.executable))
    os.chmod(stub, os.stat(stub).st_mode | stat.S_IEXEC)
    os.environ["RAY_TPU_CONDA_EXE"] = str(stub)
    os.environ["RAY_TPU_CONDA_ENV_CACHE"] = str(base / "envs")
    try:
        ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
        yield
        ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_CONDA_EXE", None)
        os.environ.pop("RAY_TPU_CONDA_ENV_CACHE", None)


def test_conda_spec_env_runs_worker_from_env():
    spec = {"name": "probe", "dependencies": ["python"]}

    @ray_tpu.remote(runtime_env={"conda": spec})
    def where():
        import sys
        return sys.executable

    exe = ray_tpu.get(where.remote(), timeout=180)
    cache = os.environ["RAY_TPU_CONDA_ENV_CACHE"]
    assert exe.startswith(cache), exe
    # the stub recorded the spec it was given, next to the interpreter
    env_dir = os.path.dirname(os.path.dirname(exe))
    assert os.path.exists(os.path.join(env_dir, "spec.yml"))


def test_conda_env_is_cached():
    from ray_tpu._private.runtime_env import (ensure_conda_env,
                                              normalize_conda)

    wire = normalize_conda({"name": "cached", "dependencies": ["python"]})
    t0 = time.monotonic()
    py1 = ensure_conda_env(wire)
    first = time.monotonic() - t0
    t1 = time.monotonic()
    py2 = ensure_conda_env(wire)
    second = time.monotonic() - t1
    assert py1 == py2 and os.path.exists(py1)
    assert second < first / 5


def test_conda_named_env_resolves():
    from ray_tpu._private.runtime_env import (ensure_conda_env,
                                              normalize_conda)
    import sys

    wire = normalize_conda("present-env")
    assert wire == {"name": "present-env"}
    assert ensure_conda_env(wire) == sys.executable

    with pytest.raises(Exception, match="not usable"):
        ensure_conda_env(normalize_conda("missing-env"))


def test_conda_and_pip_are_exclusive():
    with pytest.raises(ValueError, match="both pip and conda"):
        @ray_tpu.remote(runtime_env={"conda": {"dependencies": []},
                                     "pip": ["x"]})
        def f():
            pass

        f.remote()
