"""Batched RPC frames: coalescing, demux, per-logical-message faults.

The write coalescer (`rpc._WriteCoalescer`) writes the first message on
a cold connection straight through, then folds everything else queued
within the same event-loop tick into a single BATCH wire frame. These
tests
pin the contract the rest of the stack leans on: logical-message
ordering and reply demux survive batching, fault injection keeps acting
per logical message (seeded FaultPlan replays stay valid), the
high-watermark backpressure engages, and `ClientPool.close_all()`
survives an `invalidate()` racing with shutdown.

This module is listed in conftest's `_LOCKDEP_SUITES`, so everything
here also runs under the runtime lock-order validator.
"""

import asyncio

import pytest

from ray_tpu._private import fault_injection as _fi
from ray_tpu._private import rpc
from ray_tpu._private.config import global_config
from ray_tpu._private.rpc import ClientPool, RpcClient, RpcServer


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture
def no_plan():
    """Make sure no fault plan leaks between tests."""
    _fi.uninstall()
    yield
    _fi.uninstall()


def _echo_server():
    server = RpcServer()
    received = []

    async def echo(payload):
        received.append(payload["i"])
        return payload["i"]

    server.register("echo", echo)
    return server, received


# ---------------------------------------------------------------------------
# coalescing + ordering + demux
# ---------------------------------------------------------------------------


def test_batch_roundtrip_ordering_and_demux(loop, no_plan):
    """N concurrent callers in one tick share wire frames; every caller
    gets its own reply back and the server sees submission order."""

    async def main():
        server, received = _echo_server()
        await server.start()
        client = await RpcClient(server.address).connect()
        n = 200
        results = await asyncio.gather(
            *[client.call("echo", {"i": i}) for i in range(n)])
        assert results == list(range(n))        # reply demux
        assert received == list(range(n))       # arrival order = send order
        # the burst actually coalesced (one frame would have sufficed for
        # each tick's worth of messages)
        assert client._coal.batches_sent >= 1
        assert client._coal.frames_sent < n
        await client.close()
        await server.stop()

    loop.run_until_complete(main())


def test_call_nowait_single_tick_two_frames(loop, no_plan):
    """call_nowait bursts issued in one tick: the first message writes
    through (cold connection, no latency), the 63 followers ride one
    BATCH frame."""

    async def main():
        server, _ = _echo_server()
        await server.start()
        client = await RpcClient(server.address).connect()
        futs = [client.call_nowait("echo", {"i": i}) for i in range(64)]
        results = await asyncio.gather(*futs)
        assert results == list(range(64))
        assert client._coal.frames_sent == 2
        assert client._coal.batches_sent == 1
        await client.close()
        await server.stop()

    loop.run_until_complete(main())


def test_single_message_stays_plain_frame(loop, no_plan):
    """A lone message is emitted as a plain frame — byte-identical wire
    format to the pre-BATCH protocol, no batch overhead."""

    async def main():
        server, _ = _echo_server()
        await server.start()
        client = await RpcClient(server.address).connect()
        assert await client.call("echo", {"i": 7}) == 7
        assert client._coal.batches_sent == 0
        assert client._coal.frames_sent == 1
        await client.close()
        await server.stop()

    loop.run_until_complete(main())


def test_reply_rebatching_on_server_tick(loop, no_plan):
    """Replies completing in the same tick re-batch: a call_nowait burst
    handled by a trivial handler produces fewer reply frames than
    replies (visible through the global receive-side counters)."""

    async def main():
        before = rpc.RPC_STATS.batch_frames_recv
        server, _ = _echo_server()
        await server.start()
        client = await RpcClient(server.address).connect()
        futs = [client.call_nowait("echo", {"i": i}) for i in range(32)]
        await asyncio.gather(*futs)
        # the client decoded at least one batched reply frame
        assert rpc.RPC_STATS.batch_frames_recv > before
        await client.close()
        await server.stop()

    loop.run_until_complete(main())


def test_oversize_burst_flushes_on_watermark(loop, no_plan):
    """Crossing the byte watermark flushes immediately instead of
    growing one giant frame."""

    async def main():
        server = RpcServer()

        async def size(payload):
            return len(payload)

        server.register("size", size)
        await server.start()
        client = await RpcClient(server.address).connect()
        blob = b"x" * (global_config().rpc_batch_max_bytes // 2)
        futs = [client.call_nowait("size", blob) for _ in range(8)]
        results = await asyncio.gather(*futs)
        assert results == [len(blob)] * 8
        # watermark split the burst across several frames
        assert client._coal.frames_sent >= 4
        await client.close()
        await server.stop()

    loop.run_until_complete(main())


# ---------------------------------------------------------------------------
# fault injection: per-logical-message semantics + replay determinism
# ---------------------------------------------------------------------------


def _run_send_drop_burst(loop, seed, n=48):
    """Fire a one-tick call_nowait burst under a seeded drop plan; return
    (set of dropped indices, recorded schedule)."""

    async def main():
        plan = _fi.install(_fi.FaultPlan(
            f"seed={seed};rpc_drop=0.4;rpc_match=echo"))
        try:
            server, received = _echo_server()
            await server.start()
            client = await RpcClient(server.address).connect()
            futs = [client.call_nowait("echo", {"i": i}) for i in range(n)]
            done, pending = await asyncio.wait(
                [asyncio.ensure_future(f) for f in futs], timeout=1.0)
            dropped = {i for i, f in enumerate(futs) if not f.done()}
            for f in pending:
                f.cancel()
            # surviving messages all round-tripped, in order
            alive = [i for i in range(n) if i not in dropped]
            assert received == alive
            await client.close()
            await server.stop()
            return dropped, list(plan.schedule)
        finally:
            _fi.uninstall()

    return loop.run_until_complete(main())


def test_send_faults_act_per_logical_message(loop, no_plan):
    """Messages sharing a BATCH frame are dropped individually — a drop
    never takes down its batchmates."""
    dropped, _ = _run_send_drop_burst(loop, seed=7)
    assert dropped, "seeded plan must drop something at p=0.4"
    assert len(dropped) < 48, "a dropped message must not kill the batch"


def test_send_fault_replay_is_deterministic(loop, no_plan):
    """Same seed → identical per-message fault schedule, with batching
    on: the coalescer must not perturb the per-site draw order."""
    d1, s1 = _run_send_drop_burst(loop, seed=1234)
    d2, s2 = _run_send_drop_burst(loop, seed=1234)
    assert d1 == d2
    assert s1 == s2
    d3, _ = _run_send_drop_burst(loop, seed=4321)
    assert d3 != d1, "different seed should produce a different schedule"


def test_dup_duplicates_one_logical_message(loop, no_plan):
    """rpc_dup duplicates the logical message inside the batch: the
    handler runs twice, the caller still resolves exactly once."""

    async def main():
        _fi.install(_fi.FaultPlan("seed=1;rpc_dup=1.0;rpc_match=echo"))
        try:
            server, received = _echo_server()
            await server.start()
            client = await RpcClient(server.address).connect()
            futs = [client.call_nowait("echo", {"i": i}) for i in range(8)]
            results = await asyncio.gather(*futs)
            assert results == list(range(8))
            assert len(received) == 16  # every message executed twice
            await client.close()
            await server.stop()
        finally:
            _fi.uninstall()

    loop.run_until_complete(main())


def test_send_delay_defers_one_logical_message(loop, no_plan):
    """A delayed message leaves its batchmates' tick; everything still
    arrives and resolves."""

    async def main():
        _fi.install(_fi.FaultPlan(
            "seed=1;rpc_delay=0.5:0.05;rpc_match=echo"))
        try:
            server, received = _echo_server()
            await server.start()
            client = await RpcClient(server.address).connect()
            futs = [client.call_nowait("echo", {"i": i}) for i in range(16)]
            results = await asyncio.gather(*futs)
            assert results == list(range(16))
            assert sorted(received) == list(range(16))
            await client.close()
            await server.stop()
        finally:
            _fi.uninstall()

    loop.run_until_complete(main())


def test_recv_faults_act_per_logical_reply(loop, no_plan):
    """Replies riding one BATCH frame are dropped individually, and the
    drop pattern replays under the same seed."""

    def run(seed):
        async def main():
            plan = _fi.install(_fi.FaultPlan(
                f"seed={seed};rpc_recv_drop=0.4;rpc_match=echo"))
            try:
                server, _ = _echo_server()
                await server.start()
                client = await RpcClient(server.address).connect()
                futs = [client.call_nowait("echo", {"i": i})
                        for i in range(48)]
                await asyncio.wait(
                    [asyncio.ensure_future(f) for f in futs], timeout=1.0)
                lost = frozenset(
                    i for i, f in enumerate(futs) if not f.done())
                for f in futs:
                    if not f.done():
                        f.cancel()
                await client.close()
                await server.stop()
                return lost, list(plan.schedule)
            finally:
                _fi.uninstall()

        return loop.run_until_complete(main())

    lost1, sched1 = run(99)
    lost2, sched2 = run(99)
    assert lost1, "seeded recv-drop plan must lose some replies"
    assert len(lost1) < 48, "one lost reply must not kill the batch"
    assert lost1 == lost2
    assert sched1 == sched2


# ---------------------------------------------------------------------------
# backpressure + pool shutdown
# ---------------------------------------------------------------------------


class _FakeTransport:
    """Transport double whose buffer only shrinks on drain() — models a
    peer that stopped reading."""

    def __init__(self):
        self.buffered = 0

    def get_write_buffer_size(self):
        return self.buffered


class _FakeWriter:
    """Writer double whose drain() blocks until the test releases it —
    models a peer that stopped reading."""

    def __init__(self):
        self.transport = _FakeTransport()
        self.frames = []
        self.drains = 0
        self.release = asyncio.Event()

    def write(self, data: bytes):
        self.frames.append(data)
        self.transport.buffered += len(data)

    def is_closing(self):
        return False

    async def drain(self):
        self.drains += 1
        await self.release.wait()
        self.transport.buffered = 0


def test_high_watermark_backpressure(loop, no_plan):
    """Once the transport buffer crosses the high-watermark the
    coalescer stops writing and falls back to one awaited drain();
    awaited senders park until it clears, then everything goes out."""

    async def main():
        cfg = global_config()
        old = cfg.rpc_send_high_watermark
        cfg.rpc_send_high_watermark = 1024
        before = rpc.RPC_STATS.drain_backoffs
        try:
            writer = _FakeWriter()
            coal = rpc._WriteCoalescer(writer)
            blob = b"y" * 2048
            coal.send([1, rpc.REQUEST, "sink", blob])
            # over the watermark: the coalescer is parked behind a drain
            assert rpc.RPC_STATS.drain_backoffs == before + 1
            assert len(writer.frames) == 1
            # senders park behind the drain instead of writing
            sends = [asyncio.ensure_future(
                coal.send_wait([2 + i, rpc.REQUEST, "sink", b"z"]))
                for i in range(4)]
            await asyncio.sleep(0.01)
            assert len(writer.frames) == 1
            assert not any(s.done() for s in sends)
            # peer reads again: drain clears, parked senders release —
            # the first writes through, its same-tick followers batch
            writer.release.set()
            await asyncio.sleep(0.01)
            assert writer.drains == 1
            assert all(s.done() for s in sends)
            assert len(writer.frames) == 3
            assert coal.messages_sent == 5
            assert coal.batches_sent == 1
        finally:
            cfg.rpc_send_high_watermark = old

    loop.run_until_complete(main())


def test_close_all_survives_racing_invalidate(loop, no_plan):
    """An invalidate() landing while close_all() iterates must not blow
    up the iteration, and the per-address lock table is dropped."""

    async def main():
        s1, _ = _echo_server()
        s2, _ = _echo_server()
        await s1.start()
        await s2.start()
        pool = ClientPool()
        c1 = await pool.get(s1.address)
        await pool.get(s2.address)
        orig_close = c1.close

        async def racing_close():
            # simulates a ReconnectingClient invalidating a peer while
            # shutdown iterates the client table
            pool.invalidate(s2.address)
            await orig_close()

        c1.close = racing_close
        await pool.close_all()
        assert pool._clients == {}
        assert pool._locks == {}
        await s1.stop()
        await s2.stop()

    loop.run_until_complete(main())
