"""Autoscaler tests: scale up on unmet demand, down on idleness.

Reference ground: `python/ray/tests/test_autoscaler_fake_multinode.py`
and the v2 reconciler tests — fake "cloud" nodes are local raylets.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.node import Cluster
from ray_tpu.autoscaler import Autoscaler, FakeMultiNodeProvider, NodeType


@pytest.fixture
def scaling_cluster():
    cluster = Cluster(head_resources={"CPU": 1.0})
    ray_tpu.init(address=cluster.gcs_addr)
    provider = FakeMultiNodeProvider(cluster)
    yield cluster, provider
    ray_tpu.shutdown()
    cluster.shutdown()


def _drain_heartbeat(seconds=1.5):
    """Give raylets a couple heartbeats to report demand/idleness."""
    time.sleep(seconds)


def test_scale_up_for_infeasible_pg(scaling_cluster):
    cluster, provider = scaling_cluster
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("cpu4", {"CPU": 4.0})],
        max_workers=4, idle_timeout_s=9999)

    pg = ray_tpu.placement_group([{"CPU": 4.0}], strategy="PACK")
    assert not pg.ready(timeout=2.0)  # infeasible on the 1-CPU head

    _drain_heartbeat()
    result = autoscaler.update()
    assert result["launched"] == 1
    assert pg.ready(timeout=30.0), "PG still pending after scale-up"
    ray_tpu.remove_placement_group(pg)


def test_scale_up_for_pending_tasks(scaling_cluster):
    cluster, provider = scaling_cluster
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("cpu2", {"CPU": 2.0}), NodeType("cpu8", {"CPU": 8.0})],
        max_workers=4, idle_timeout_s=9999)

    @ray_tpu.remote(num_cpus=2)
    def work(i):
        return i * 2

    refs = [work.remote(i) for i in range(3)]
    _drain_heartbeat()
    autoscaler.update()
    # picks the smallest fitting type for {"CPU": 2} demands
    types = {i.node_type for i in provider.non_terminated_nodes()}
    assert types == {"cpu2"}
    assert sorted(ray_tpu.get(refs, timeout=60)) == [0, 2, 4]


def test_scale_up_slice_for_topology_pg(scaling_cluster):
    """A pending slice-topology PG provisions one whole slice instance
    (atomic multi-host scale-up), after which it gang-places."""
    cluster, provider = scaling_cluster
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("v2-8", {"CPU": 2.0, "TPU": 4.0},
                  slice_type="v2-8", num_hosts=2)],
        max_workers=8, idle_timeout_s=9999)

    pg = ray_tpu.placement_group(
        [{"CPU": 1.0, "TPU": 4.0}] * 2, topology="v2-8")
    assert not pg.ready(timeout=2.0)

    _drain_heartbeat()
    result = autoscaler.update()
    assert result["launched"] == 2  # both hosts of one slice
    assert pg.ready(timeout=30.0)
    ray_tpu.remove_placement_group(pg)


def test_scale_down_idle_nodes(scaling_cluster):
    cluster, provider = scaling_cluster
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("cpu4", {"CPU": 4.0})],
        max_workers=4, idle_timeout_s=2.0)

    @ray_tpu.remote(num_cpus=4)
    def burst():
        return "done"

    ref = burst.remote()
    _drain_heartbeat()
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 1
    assert ray_tpu.get(ref, timeout=60) == "done"

    # wait past the idle timeout, then reconcile until retired
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        time.sleep(1.0)
        result = autoscaler.update()
        if result["terminated"] and not provider.non_terminated_nodes():
            break
    assert not provider.non_terminated_nodes(), "idle node never retired"


def test_max_workers_cap(scaling_cluster):
    cluster, provider = scaling_cluster
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("cpu2", {"CPU": 2.0})],
        max_workers=2, idle_timeout_s=9999)

    @ray_tpu.remote(num_cpus=2)
    def work(i):
        time.sleep(0.2)
        return i

    refs = [work.remote(i) for i in range(8)]  # demand for 8 nodes
    _drain_heartbeat()
    autoscaler.update()
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) <= 2
    assert sorted(ray_tpu.get(refs, timeout=120)) == list(range(8))


# -- GCE TPU queued-resources provider (reference gcp/node_provider.py) -----


class FakeQueuedResourceAPI:
    """A recorded queued-resources API surface: create/list/delete with
    realistic async state transitions. `tick()` advances ACCEPTED ->
    ACTIVE and 'boots' the slice's hosts as local raylets carrying the
    bootstrap script's instance label — exactly what the TPU-VM startup
    script does on real hardware."""

    def __init__(self, cluster):
        self._cluster = cluster
        self._qrs = {}      # name -> {"state", "body"}
        self._handles = {}  # name -> raylet handles

    def request(self, method, url, body=None):
        import re
        if method == "POST":
            name = re.search(r"queuedResourceId=([\w-]+)", url).group(1)
            # the startup script must carry the instance label + address
            script = body["tpu"]["nodeSpec"][0]["node"]["metadata"][
                "startup-script"]
            assert "autoscaler_instance" in script
            assert self._cluster.gcs_addr in script
            self._qrs[name] = {"state": "ACCEPTED", "body": body}
            return {"name": name}
        if method == "GET":
            return {"queuedResources": [
                {"name": f"projects/p/locations/z/queuedResources/{n}",
                 "state": {"state": qr["state"]},
                 "tpu": qr["body"]["tpu"]}
                for n, qr in self._qrs.items()
                if qr["state"] != "DELETED"]}
        if method == "DELETE":
            name = url.rsplit("/", 1)[-1].split("?")[0]
            qr = self._qrs.get(name)
            if qr:
                qr["state"] = "DELETED"
                for h in self._handles.pop(name, []):
                    if h in self._cluster.nodes:
                        self._cluster.remove_node(h)
            return {}
        raise AssertionError(f"unexpected {method} {url}")

    def tick(self):
        """Finish provisioning: ACCEPTED slices become ACTIVE and their
        hosts join the cluster labeled with the instance id."""
        for name, qr in self._qrs.items():
            if qr["state"] != "ACCEPTED":
                continue
            node = qr["body"]["tpu"]["nodeSpec"][0]["node"]
            accel = node["acceleratorType"]  # e.g. v5e-16
            chips_total = int(accel.rsplit("-", 1)[1])
            hosts = max(1, chips_total // 4)
            self._handles[name] = self._cluster.add_slice(
                accel, hosts, chips_per_host=4, cpus_per_host=4.0,
                name=name,
                extra_labels={"autoscaler_instance": name})
            qr["state"] = "ACTIVE"


def test_tpu_pod_provider_scales_slice_up_and_down(scaling_cluster):
    """VERDICT r2 item 6 'done' criterion: the reconciler scales a
    simulated v5e-16 slice up and down through the same NodeProvider ABC
    path the fake provider uses — against a fake queued-resources API."""
    from ray_tpu.autoscaler import TPUQueuedResourceProvider

    cluster, _ = scaling_cluster
    api = FakeQueuedResourceAPI(cluster)
    provider = TPUQueuedResourceProvider(
        "proj", "us-central2-b", cluster.gcs_addr, transport=api)
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("v5e16", {"CPU": 4.0, "TPU": 4.0}, slice_type="v5e-16",
                  num_hosts=4)],
        max_workers=8, idle_timeout_s=2.0)

    # a slice-topology gang demand: 4 hosts x 4 chips, atomic
    pg = ray_tpu.placement_group(
        [{"TPU": 4.0}] * 4, strategy="STRICT_SPREAD", topology="v5e-16")
    assert not pg.ready(timeout=2.0)

    _drain_heartbeat()
    result = autoscaler.update()
    assert result["launched"] == 4  # one whole slice (4 hosts)

    # while provisioning (ACCEPTED), re-reconciling must NOT relaunch
    _drain_heartbeat()
    assert autoscaler.update()["launched"] == 0

    api.tick()  # hosts boot and register, labeled with the instance
    assert pg.ready(timeout=30.0), "gang never placed on the new slice"
    ray_tpu.remove_placement_group(pg)

    # idle past the timeout: the whole slice retires atomically through
    # the provider's DELETE
    deadline = time.monotonic() + 40
    terminated = 0
    while time.monotonic() < deadline:
        _drain_heartbeat()
        terminated = autoscaler.update()["terminated"]
        if terminated:
            break
    assert terminated == 4
    assert provider.non_terminated_nodes() == []


def test_tpu_pod_provider_recovers_type_mapping(scaling_cluster):
    """A restarted autoscaler's provider recovers instance->node-type
    from the labels the API echoes back."""
    from ray_tpu.autoscaler import TPUQueuedResourceProvider

    cluster, _ = scaling_cluster
    api = FakeQueuedResourceAPI(cluster)
    p1 = TPUQueuedResourceProvider("proj", "z", cluster.gcs_addr,
                                   transport=api)
    nt = NodeType("v5e16", {"CPU": 4.0, "TPU": 4.0}, slice_type="v5e-16",
                  num_hosts=4)
    inst = p1.create_node(nt)
    # fresh provider (driver restart) sees the same instance and type
    p2 = TPUQueuedResourceProvider("proj", "z", cluster.gcs_addr,
                                   transport=api)
    found = p2.non_terminated_nodes()
    assert [i.instance_id for i in found] == [inst.instance_id]
    assert found[0].node_type == "v5e16"
    p2.terminate_node(found[0])
    assert p2.non_terminated_nodes() == []


def test_tpu_pod_provider_replaces_broken_slice(scaling_cluster):
    """A slice that LOSES a host after booting is broken, not booting:
    the autoscaler terminates it (slices are atomic — a 3/4 slice can
    never place its gang) instead of absorbing the pending demand with
    phantom capacity forever."""
    from ray_tpu.autoscaler import TPUQueuedResourceProvider

    cluster, _ = scaling_cluster
    api = FakeQueuedResourceAPI(cluster)
    provider = TPUQueuedResourceProvider(
        "proj", "z", cluster.gcs_addr, transport=api)
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("v5e16", {"CPU": 4.0, "TPU": 4.0}, slice_type="v5e-16",
                  num_hosts=4)],
        max_workers=16, idle_timeout_s=9999)

    inst = provider.create_node(autoscaler.node_types["v5e16"])
    api.tick()  # boots 4 hosts
    _drain_heartbeat()
    autoscaler.update()  # records seen_up == 4

    # kill one host behind the autoscaler's back
    name = inst.instance_id
    victim = api._handles[name][0]
    cluster.remove_node(victim)
    api._handles[name] = api._handles[name][1:]

    # the GCS reaps the dead raylet on its heartbeat timeout; the next
    # reconcile after that must terminate the broken slice
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        _drain_heartbeat()
        autoscaler.update()
        if not provider.non_terminated_nodes():
            break
    assert provider.non_terminated_nodes() == []
    assert api._qrs[name]["state"] == "DELETED"


def test_boot_timeout_replaces_wedged_slice(scaling_cluster):
    """An instance whose bootstrap never registers any raylet is
    terminated after boot_timeout_s instead of absorbing its pending
    demand as 'booting' credit forever."""
    from ray_tpu.autoscaler import TPUQueuedResourceProvider

    cluster, _ = scaling_cluster
    api = FakeQueuedResourceAPI(cluster)
    provider = TPUQueuedResourceProvider(
        "proj", "z", cluster.gcs_addr, transport=api)
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("v5e16", {"CPU": 4.0, "TPU": 4.0}, slice_type="v5e-16",
                  num_hosts=4)],
        max_workers=16, idle_timeout_s=9999, boot_timeout_s=1.0)

    inst = provider.create_node(autoscaler.node_types["v5e16"])
    # never api.tick(): the startup script "fails" on every host
    _drain_heartbeat()
    autoscaler.update()  # records first_seen
    time.sleep(1.2)
    autoscaler.update()  # past boot_timeout_s: terminated
    assert provider.non_terminated_nodes() == []
    assert api._qrs[inst.instance_id]["state"] == "DELETED"


# -- replacement idempotence (staleness re-check before provisioning) -------


class _StubProvider:
    """In-memory NodeProvider: `_provision`'s staleness re-check is pure
    provider accounting, so no raylets need to spawn to pin it."""

    def __init__(self):
        from ray_tpu.autoscaler.node_provider import Instance

        self._Instance = Instance
        self._instances = {}
        self._n = 0

    def create_node(self, node_type):
        self._n += 1
        inst = self._Instance(f"stub-{self._n}", node_type.name, [])
        self._instances[inst.instance_id] = inst
        return inst

    def terminate_node(self, instance):
        self._instances.pop(instance.instance_id, None)

    def non_terminated_nodes(self):
        return list(self._instances.values())


def test_provision_absorbs_node_launched_after_snapshot():
    """A launch plan computed from a stale provider snapshot must be
    absorbed by a node of the same type that appeared since (a
    concurrent recovery path, an operator's manual launch) — provisioning
    on the stale plan would double-replace the node."""
    provider = _StubProvider()
    nt = NodeType("cpu4", {"CPU": 4.0})
    # the reconciler never contacts the GCS in _provision, so a bogus
    # address keeps this a pure unit test
    autoscaler = Autoscaler("127.0.0.1:1", provider, [nt],
                            max_workers=4, idle_timeout_s=9999)

    # snapshot taken while the provider was empty ...
    stale_snapshot = {i.instance_id for i in provider.non_terminated_nodes()}
    # ... then a node of the planned type appears behind the plan's back
    provider.create_node(nt)
    launched = autoscaler._provision([nt], stale_snapshot)
    assert launched == 0, "fresh node must absorb the planned launch"
    assert len(provider.non_terminated_nodes()) == 1

    # a node already IN the snapshot is old capacity the plan has seen
    # (and found insufficient) — it must NOT absorb a new launch
    current = {i.instance_id for i in provider.non_terminated_nodes()}
    launched = autoscaler._provision([nt], current)
    assert launched == 1
    assert len(provider.non_terminated_nodes()) == 2

    # one fresh node absorbs only ONE planned launch of its type
    snapshot2 = {i.instance_id for i in provider.non_terminated_nodes()}
    provider.create_node(nt)
    launched = autoscaler._provision([nt, nt], snapshot2)
    assert launched == 1
    assert len(provider.non_terminated_nodes()) == 4


def test_concurrent_updates_do_not_double_launch(scaling_cluster):
    """Two reconcile rounds racing on the same unmet demand (the
    background loop + a driver poking update() after a fault) must
    launch ONE replacement, not two: rounds are serialized and the
    later round sees the earlier one's launch as booting capacity."""
    import threading

    cluster, provider = scaling_cluster
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("cpu4", {"CPU": 4.0})],
        max_workers=4, idle_timeout_s=9999)

    pg = ray_tpu.placement_group([{"CPU": 4.0}], strategy="PACK")
    assert not pg.ready(timeout=2.0)  # infeasible on the 1-CPU head
    _drain_heartbeat()

    results = []
    threads = [threading.Thread(
        target=lambda: results.append(autoscaler.update()))
        for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert sum(r["launched"] for r in results) == 1
    assert len(provider.non_terminated_nodes()) == 1
    assert pg.ready(timeout=30.0)
    ray_tpu.remove_placement_group(pg)
