"""Autoscaler tests: scale up on unmet demand, down on idleness.

Reference ground: `python/ray/tests/test_autoscaler_fake_multinode.py`
and the v2 reconciler tests — fake "cloud" nodes are local raylets.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.node import Cluster
from ray_tpu.autoscaler import Autoscaler, FakeMultiNodeProvider, NodeType


@pytest.fixture
def scaling_cluster():
    cluster = Cluster(head_resources={"CPU": 1.0})
    ray_tpu.init(address=cluster.gcs_addr)
    provider = FakeMultiNodeProvider(cluster)
    yield cluster, provider
    ray_tpu.shutdown()
    cluster.shutdown()


def _drain_heartbeat(seconds=1.5):
    """Give raylets a couple heartbeats to report demand/idleness."""
    time.sleep(seconds)


def test_scale_up_for_infeasible_pg(scaling_cluster):
    cluster, provider = scaling_cluster
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("cpu4", {"CPU": 4.0})],
        max_workers=4, idle_timeout_s=9999)

    pg = ray_tpu.placement_group([{"CPU": 4.0}], strategy="PACK")
    assert not pg.ready(timeout=2.0)  # infeasible on the 1-CPU head

    _drain_heartbeat()
    result = autoscaler.update()
    assert result["launched"] == 1
    assert pg.ready(timeout=30.0), "PG still pending after scale-up"
    ray_tpu.remove_placement_group(pg)


def test_scale_up_for_pending_tasks(scaling_cluster):
    cluster, provider = scaling_cluster
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("cpu2", {"CPU": 2.0}), NodeType("cpu8", {"CPU": 8.0})],
        max_workers=4, idle_timeout_s=9999)

    @ray_tpu.remote(num_cpus=2)
    def work(i):
        return i * 2

    refs = [work.remote(i) for i in range(3)]
    _drain_heartbeat()
    autoscaler.update()
    # picks the smallest fitting type for {"CPU": 2} demands
    types = {i.node_type for i in provider.non_terminated_nodes()}
    assert types == {"cpu2"}
    assert sorted(ray_tpu.get(refs, timeout=60)) == [0, 2, 4]


def test_scale_up_slice_for_topology_pg(scaling_cluster):
    """A pending slice-topology PG provisions one whole slice instance
    (atomic multi-host scale-up), after which it gang-places."""
    cluster, provider = scaling_cluster
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("v2-8", {"CPU": 2.0, "TPU": 4.0},
                  slice_type="v2-8", num_hosts=2)],
        max_workers=8, idle_timeout_s=9999)

    pg = ray_tpu.placement_group(
        [{"CPU": 1.0, "TPU": 4.0}] * 2, topology="v2-8")
    assert not pg.ready(timeout=2.0)

    _drain_heartbeat()
    result = autoscaler.update()
    assert result["launched"] == 2  # both hosts of one slice
    assert pg.ready(timeout=30.0)
    ray_tpu.remove_placement_group(pg)


def test_scale_down_idle_nodes(scaling_cluster):
    cluster, provider = scaling_cluster
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("cpu4", {"CPU": 4.0})],
        max_workers=4, idle_timeout_s=2.0)

    @ray_tpu.remote(num_cpus=4)
    def burst():
        return "done"

    ref = burst.remote()
    _drain_heartbeat()
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 1
    assert ray_tpu.get(ref, timeout=60) == "done"

    # wait past the idle timeout, then reconcile until retired
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        time.sleep(1.0)
        result = autoscaler.update()
        if result["terminated"] and not provider.non_terminated_nodes():
            break
    assert not provider.non_terminated_nodes(), "idle node never retired"


def test_max_workers_cap(scaling_cluster):
    cluster, provider = scaling_cluster
    autoscaler = Autoscaler(
        cluster.gcs_addr, provider,
        [NodeType("cpu2", {"CPU": 2.0})],
        max_workers=2, idle_timeout_s=9999)

    @ray_tpu.remote(num_cpus=2)
    def work(i):
        time.sleep(0.2)
        return i

    refs = [work.remote(i) for i in range(8)]  # demand for 8 nodes
    _drain_heartbeat()
    autoscaler.update()
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) <= 2
    assert sorted(ray_tpu.get(refs, timeout=120)) == list(range(8))
