"""Native shm object store: create/seal/get/evict across processes.

Covers the behavior the reference exercises in
`src/ray/object_manager/plasma/test/` (create/seal/get lifecycle, eviction,
aborts) plus zero-copy numpy reads.
"""

import multiprocessing
import os

import numpy as np
import pytest

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import (
    ObjectStore,
    ObjectStoreError,
    ObjectStoreFullError,
)


def test_create_seal_get_roundtrip(shm_store):
    oid = ObjectID.from_random()
    payload = b"hello world" * 100
    buf = shm_store.create_buffer(oid, len(payload))
    buf[:] = payload
    shm_store.seal(oid)
    out = shm_store.get_buffer(oid)
    assert bytes(out) == payload
    assert shm_store.contains(oid)


def test_get_missing_returns_none(shm_store):
    assert shm_store.get_buffer(ObjectID.from_random()) is None


def test_unsealed_invisible(shm_store):
    oid = ObjectID.from_random()
    shm_store.create_buffer(oid, 128)
    assert not shm_store.contains(oid)
    assert shm_store.get_buffer(oid) is None
    shm_store.seal(oid)
    assert shm_store.contains(oid)


def test_duplicate_create_rejected(shm_store):
    oid = ObjectID.from_random()
    shm_store.create_buffer(oid, 64)
    with pytest.raises(ObjectStoreError):
        shm_store.create_buffer(oid, 64)


def test_serialized_numpy_zero_copy(shm_store):
    oid = ObjectID.from_random()
    arr = np.arange(100000, dtype=np.float32)
    pickled, buffers = serialization.serialize(arr)
    shm_store.put_serialized(oid, pickled, buffers)
    out = shm_store.get(oid)
    np.testing.assert_array_equal(out, arr)
    # The deserialized array must be a view over shared memory, not a copy.
    assert not out.flags["OWNDATA"]


def test_delete_frees_space(shm_store):
    oid = ObjectID.from_random()
    shm_store.create_buffer(oid, 1024 * 1024)
    shm_store.seal(oid)
    before = shm_store.stats()["allocated"]
    shm_store.delete(oid)
    after = shm_store.stats()["allocated"]
    assert after < before
    assert shm_store.get_buffer(oid) is None


def test_lru_eviction_on_full(shm_store):
    # Fill the 64MB store with 8MB objects, then create one more: the least
    # recently used unreferenced object must be evicted to make room.
    oids = []
    for _ in range(7):
        oid = ObjectID.from_random()
        buf = shm_store.create_buffer(oid, 8 * 1024 * 1024)
        buf[:4] = b"abcd"
        shm_store.seal(oid)
        shm_store.release(oid)  # creator drops its ref -> evictable
        oids.append(oid)
    extra = ObjectID.from_random()
    shm_store.create_buffer(extra, 16 * 1024 * 1024)
    shm_store.seal(extra)
    # The oldest object(s) are gone; the newest survives.
    assert shm_store.get_buffer(oids[0], timeout=-1) is None
    assert shm_store.contains(extra)


def test_referenced_objects_not_evicted(shm_store):
    pinned = ObjectID.from_random()
    buf = shm_store.create_buffer(pinned, 30 * 1024 * 1024)
    buf[:4] = b"pin!"
    shm_store.seal(pinned)  # creator still holds a ref
    with pytest.raises(ObjectStoreFullError):
        shm_store.create_buffer(ObjectID.from_random(), 50 * 1024 * 1024)
    assert bytes(shm_store.get_buffer(pinned)[:4]) == b"pin!"


def _child_reader(name, oid_bytes, q):
    store = ObjectStore.attach(name)
    buf = store.get_buffer(ObjectID(oid_bytes), timeout=10)
    q.put(bytes(buf[:16]))
    store.close()


def test_cross_process_get():
    name = f"/ray_tpu_test_xp_{os.getpid()}"
    store = ObjectStore.create(name, capacity=16 * 1024 * 1024, table_size=256)
    try:
        oid = ObjectID.from_random()
        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        # Reader starts BEFORE the object exists: exercises blocking get.
        proc = ctx.Process(target=_child_reader, args=(name, oid.binary(), q))
        proc.start()
        buf = store.create_buffer(oid, 1024)
        buf[:16] = b"cross-proc-data!"
        store.seal(oid)
        assert q.get(timeout=20) == b"cross-proc-data!"
        proc.join(timeout=10)
    finally:
        store.destroy()


def test_stats_expose_lock_and_eviction_counters(shm_store):
    st = shm_store.stats()
    for key in ("lock_wait_ns", "lock_contended", "evicted_objects",
                "referenced"):
        assert key in st
    assert shm_store.num_shards >= 1
    rows = shm_store.shard_stats()
    assert len(rows) == shm_store.num_shards
    assert all("lock_acquisitions" in r for r in rows)
    # force evictions; the aggregate and per-shard counters must move
    for _ in range(9):
        _put(shm_store, ObjectID.from_random(), 8 * 1024 * 1024)
    assert shm_store.stats()["evicted_objects"] > 0
    assert sum(r["evicted_objects"] for r in shm_store.shard_stats()) > 0


def _put(store, oid, nbytes):
    buf = store.create_buffer(oid, nbytes)
    buf[:4] = b"xxxx"
    store.seal(oid)
    store.release(oid)


@pytest.fixture
def sharded_store():
    """A store with 8 forced index/allocator shards (a production-sized
    arena would pick this up automatically from its capacity)."""
    name = f"/ray_tpu_test_sh_{os.getpid()}_{os.urandom(4).hex()}"
    store = ObjectStore.create(name, capacity=32 * 1024 * 1024,
                               table_size=4096, shards=8)
    yield store
    store.destroy()


def test_sharded_store_basics(sharded_store):
    assert sharded_store.num_shards == 8
    oids = [ObjectID.from_random() for _ in range(64)]
    for i, oid in enumerate(oids):
        buf = sharded_store.create_buffer(oid, 4096)
        buf[:] = bytes([i % 251]) * 4096
        sharded_store.seal(oid)
        sharded_store.release(oid)
    for i, oid in enumerate(oids):
        out = sharded_store.get_buffer(oid)
        assert bytes(out) == bytes([i % 251]) * 4096
    # objects landed across multiple stripes, not one hot shard
    populated = sum(1 for r in sharded_store.shard_stats()
                    if r["num_objects"] > 0)
    assert populated > 1


def test_sharded_spanning_allocation(sharded_store):
    # 32 MB / 8 shards = 4 MB regions: a 10 MB object cannot fit any
    # single region free list and must take the spanning (all-region
    # locks) path — and still read back intact.
    oid = ObjectID.from_random()
    buf = sharded_store.create_buffer(oid, 10 * 1024 * 1024)
    buf[:8] = b"spanning"
    buf[-8:] = b"tail-ok!"
    sharded_store.seal(oid)
    out = sharded_store.get_buffer(oid)
    assert bytes(out[:8]) == b"spanning"
    assert bytes(out[-8:]) == b"tail-ok!"


def test_sharded_cross_shard_eviction(sharded_store):
    # fill every stripe with small evictable objects, then create one
    # object larger than any stripe's share: the eviction sweep must
    # reclaim across shards (taking only the shards it touches)
    for _ in range(100):
        _put(sharded_store, ObjectID.from_random(), 256 * 1024)
    big = ObjectID.from_random()
    buf = sharded_store.create_buffer(big, 24 * 1024 * 1024)
    assert buf.nbytes == 24 * 1024 * 1024
    assert sharded_store.stats()["evicted_objects"] > 0


# -- concurrent correctness (tentpole gate): 4 threads + 2 processes
# interleave create/write/seal/get/delete/evict on one sharded store;
# no torn reads, exact refcount accounting at quiesce. Runs under
# RAY_TPU_LOCKDEP=1 via the module-wide conftest fixture. ----------------

def _det_oid(seed: int, i: int) -> ObjectID:
    return ObjectID(bytes([seed % 256]) + i.to_bytes(4, "little") + b"\0" * 11)


def _mixed_ops(store, seed, iters, peers):
    """One worker's op mix. Shared ids are written with a uniform tag
    byte and never force-deleted (readers hold refcounts, so eviction
    cannot touch them mid-read — any non-uniform read is a torn read).
    Delete churn runs on a private id namespace nobody else reads."""
    import random

    rng = random.Random(seed)
    errors = []
    for i in range(iters):
        oid = _det_oid(seed, i)
        tag = (seed * 31 + i) % 251
        size = rng.choice([512, 4096, 65536])
        try:
            buf = store.create_buffer(oid, size)
        except ObjectStoreError:  # full under pressure: acceptable
            buf = None
        if buf is not None:
            buf[:] = bytes([tag]) * size
            del buf
            store.seal(oid)
            store.release(oid)
        # read a peer's recent object (may be evicted — both outcomes
        # legal, but a present object must be untorn)
        p = peers[rng.randrange(len(peers))]
        view = store.get_buffer(_det_oid(p, rng.randrange(i + 1)),
                                timeout=-1)
        if view is not None:
            data = bytes(view)
            if data and any(b != data[0] for b in data):
                errors.append(f"torn read by {seed} at iter {i}")
            del view
        if i % 16 == 0:
            store.evict(64 * 1024)
        if i % 7 == 0:
            # private create/delete churn (ids offset far from shared)
            priv = _det_oid(seed + 100, i)
            try:
                store.create_buffer(priv, 2048)
                store.delete(priv)
            except ObjectStoreError:
                pass
    return errors


def _mixed_proc(name, seed, iters, peers, q):
    from ray_tpu._private.object_store import ObjectStore as _OS

    store = _OS.attach(name)
    try:
        q.put(_mixed_ops(store, seed, iters, peers))
    finally:
        store.close()


def test_concurrent_mixed_ops_no_torn_reads_exact_refcounts():
    import gc
    import threading

    name = f"/ray_tpu_test_mix_{os.getpid()}"
    store = ObjectStore.create(name, capacity=32 * 1024 * 1024,
                               table_size=4096, shards=8)
    try:
        thread_seeds = [1, 2, 3, 4]
        proc_seeds = [5, 6]
        peers = thread_seeds + proc_seeds
        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        procs = [ctx.Process(target=_mixed_proc,
                             args=(name, s, 250, peers, q))
                 for s in proc_seeds]
        for p in procs:
            p.start()
        results = []
        threads = [threading.Thread(
            target=lambda s=s: results.append(
                _mixed_ops(store, s, 400, peers)))
            for s in thread_seeds]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for p in procs:
            results.append(q.get(timeout=120))
        for p in procs:
            p.join(timeout=30)
        errors = [e for r in results for e in r]
        assert not errors, errors[:5]

        # exact refcount accounting at quiesce: every creator released,
        # every reader view dropped -> nothing is referenced, and a full
        # eviction sweep must drain the store to zero objects/bytes
        gc.collect()
        st = store.stats()
        assert st["referenced"] == 0, st
        store.evict(2 ** 62)
        st = store.stats()
        assert st["num_objects"] == 0, st
        assert st["allocated"] == 0, st
    finally:
        store.destroy()


def test_close_drops_handle_refs_before_detach():
    """Regression (use-after-detach): close() must null _lib/_h BEFORE
    detaching so a late PlasmaBuffer.__del__ cannot ss_release on a
    handle index a newer store reuses."""
    import gc

    name = f"/ray_tpu_test_close_{os.getpid()}"
    store = ObjectStore.create(name, capacity=4 * 1024 * 1024,
                               table_size=256)
    oid = ObjectID.from_random()
    buf = store.create_buffer(oid, 1024)
    buf[:4] = b"live"
    del buf
    store.seal(oid)
    view = store.get_buffer(oid)  # holds a PlasmaBuffer store ref
    store.destroy()
    assert store._h == -1 and store._lib is None
    # a second store that reuses the freed handle index must be immune
    # to the stale view's __del__
    store2 = ObjectStore.create(name, capacity=4 * 1024 * 1024,
                                table_size=256)
    try:
        oid2 = ObjectID.from_random()
        buf2 = store2.create_buffer(oid2, 1024)
        del buf2
        store2.seal(oid2)  # creator ref still held -> referenced > 0
        before = store2.stats()["referenced"]
        del view
        gc.collect()  # stale PlasmaBuffer.__del__ fires: must be a no-op
        assert store2.stats()["referenced"] == before
    finally:
        store2.destroy()


def test_coalescing_allocator(shm_store):
    # Allocate the entire region in chunks, free them all, then allocate one
    # object nearly the full size: only works if free blocks coalesce.
    oids = [ObjectID.from_random() for _ in range(8)]
    for oid in oids:
        shm_store.create_buffer(oid, 7 * 1024 * 1024)
    for oid in oids:
        shm_store.delete(oid)
    big = ObjectID.from_random()
    buf = shm_store.create_buffer(big, 55 * 1024 * 1024)
    assert buf.nbytes == 55 * 1024 * 1024
