"""Native shm object store: create/seal/get/evict across processes.

Covers the behavior the reference exercises in
`src/ray/object_manager/plasma/test/` (create/seal/get lifecycle, eviction,
aborts) plus zero-copy numpy reads.
"""

import multiprocessing
import os

import numpy as np
import pytest

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import (
    ObjectStore,
    ObjectStoreError,
    ObjectStoreFullError,
)


def test_create_seal_get_roundtrip(shm_store):
    oid = ObjectID.from_random()
    payload = b"hello world" * 100
    buf = shm_store.create_buffer(oid, len(payload))
    buf[:] = payload
    shm_store.seal(oid)
    out = shm_store.get_buffer(oid)
    assert bytes(out) == payload
    assert shm_store.contains(oid)


def test_get_missing_returns_none(shm_store):
    assert shm_store.get_buffer(ObjectID.from_random()) is None


def test_unsealed_invisible(shm_store):
    oid = ObjectID.from_random()
    shm_store.create_buffer(oid, 128)
    assert not shm_store.contains(oid)
    assert shm_store.get_buffer(oid) is None
    shm_store.seal(oid)
    assert shm_store.contains(oid)


def test_duplicate_create_rejected(shm_store):
    oid = ObjectID.from_random()
    shm_store.create_buffer(oid, 64)
    with pytest.raises(ObjectStoreError):
        shm_store.create_buffer(oid, 64)


def test_serialized_numpy_zero_copy(shm_store):
    oid = ObjectID.from_random()
    arr = np.arange(100000, dtype=np.float32)
    pickled, buffers = serialization.serialize(arr)
    shm_store.put_serialized(oid, pickled, buffers)
    out = shm_store.get(oid)
    np.testing.assert_array_equal(out, arr)
    # The deserialized array must be a view over shared memory, not a copy.
    assert not out.flags["OWNDATA"]


def test_delete_frees_space(shm_store):
    oid = ObjectID.from_random()
    shm_store.create_buffer(oid, 1024 * 1024)
    shm_store.seal(oid)
    before = shm_store.stats()["allocated"]
    shm_store.delete(oid)
    after = shm_store.stats()["allocated"]
    assert after < before
    assert shm_store.get_buffer(oid) is None


def test_lru_eviction_on_full(shm_store):
    # Fill the 64MB store with 8MB objects, then create one more: the least
    # recently used unreferenced object must be evicted to make room.
    oids = []
    for _ in range(7):
        oid = ObjectID.from_random()
        buf = shm_store.create_buffer(oid, 8 * 1024 * 1024)
        buf[:4] = b"abcd"
        shm_store.seal(oid)
        shm_store.release(oid)  # creator drops its ref -> evictable
        oids.append(oid)
    extra = ObjectID.from_random()
    shm_store.create_buffer(extra, 16 * 1024 * 1024)
    shm_store.seal(extra)
    # The oldest object(s) are gone; the newest survives.
    assert shm_store.get_buffer(oids[0], timeout=-1) is None
    assert shm_store.contains(extra)


def test_referenced_objects_not_evicted(shm_store):
    pinned = ObjectID.from_random()
    buf = shm_store.create_buffer(pinned, 30 * 1024 * 1024)
    buf[:4] = b"pin!"
    shm_store.seal(pinned)  # creator still holds a ref
    with pytest.raises(ObjectStoreFullError):
        shm_store.create_buffer(ObjectID.from_random(), 50 * 1024 * 1024)
    assert bytes(shm_store.get_buffer(pinned)[:4]) == b"pin!"


def _child_reader(name, oid_bytes, q):
    store = ObjectStore.attach(name)
    buf = store.get_buffer(ObjectID(oid_bytes), timeout=10)
    q.put(bytes(buf[:16]))
    store.close()


def test_cross_process_get():
    name = f"/ray_tpu_test_xp_{os.getpid()}"
    store = ObjectStore.create(name, capacity=16 * 1024 * 1024, table_size=256)
    try:
        oid = ObjectID.from_random()
        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        # Reader starts BEFORE the object exists: exercises blocking get.
        proc = ctx.Process(target=_child_reader, args=(name, oid.binary(), q))
        proc.start()
        buf = store.create_buffer(oid, 1024)
        buf[:16] = b"cross-proc-data!"
        store.seal(oid)
        assert q.get(timeout=20) == b"cross-proc-data!"
        proc.join(timeout=10)
    finally:
        store.destroy()


def test_coalescing_allocator(shm_store):
    # Allocate the entire region in chunks, free them all, then allocate one
    # object nearly the full size: only works if free blocks coalesce.
    oids = [ObjectID.from_random() for _ in range(8)]
    for oid in oids:
        shm_store.create_buffer(oid, 7 * 1024 * 1024)
    for oid in oids:
        shm_store.delete(oid)
    big = ObjectID.from_random()
    buf = shm_store.create_buffer(big, 55 * 1024 * 1024)
    assert buf.nbytes == 55 * 1024 * 1024
