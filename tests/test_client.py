"""Ray-Client-mode tests: a thin driver proxied through a ClientServer.

Reference ground: `python/ray/tests/test_client.py` — connect via a
client address, run the full task/actor/object surface with no local
daemons, disconnect cleanly.
"""

import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu._private.node import Cluster


@pytest.fixture(scope="module")
def client_cluster():
    cluster = Cluster(head_resources={"CPU": 4.0, "TPU": 0.0},
                      object_store_memory=128 * 1024 * 1024)
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "client-server",
         "--address", cluster.gcs_addr, "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    addr = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("CLIENT_SERVER_READY"):
            addr = line.split()[1]
            break
    assert addr, "client server never became ready"
    yield addr
    proc.terminate()
    proc.wait(timeout=10)
    cluster.shutdown()


@pytest.fixture
def client(client_cluster):
    ray_tpu.init(address=f"client://{client_cluster}")
    yield ray_tpu
    ray_tpu.shutdown()


def test_client_objects_tasks_actors(client):
    import numpy as np

    assert ray_tpu.is_initialized()

    # objects: put/get roundtrip incl. numpy payloads
    ref = ray_tpu.put({"a": np.arange(5)})
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out["a"], np.arange(5))

    # tasks: args, kwargs, ref args, multiple returns
    @ray_tpu.remote
    def add(x, y=0):
        return x + y

    assert ray_tpu.get(add.remote(1, y=2)) == 3
    assert ray_tpu.get(add.remote(ray_tpu.put(10), y=5)) == 15

    @ray_tpu.remote(num_returns=2)
    def pair():
        return "a", "b"

    r1, r2 = pair.remote()
    assert ray_tpu.get([r1, r2]) == ["a", "b"]

    # wait
    refs = [add.remote(i) for i in range(4)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=4, timeout=60)
    assert len(ready) == 4 and not not_ready

    # actors: create, method calls, state, named lookup, kill
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

    c = Counter.options(name="client-counter").remote(100)
    assert ray_tpu.get(c.inc.remote()) == 101
    assert ray_tpu.get(c.inc.remote(9)) == 110

    c2 = ray_tpu.get_actor("client-counter")
    assert ray_tpu.get(c2.inc.remote()) == 111
    ray_tpu.kill(c)

    # cluster introspection proxied
    res = ray_tpu.cluster_resources()
    assert res.get("CPU", 0) >= 4.0


def test_client_task_error_propagates(client):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(Exception, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_client_cancel(client):
    """ray_tpu.cancel proxies through the client server (no local core
    worker in client mode)."""
    import time as time_mod

    @ray_tpu.remote
    def busy():
        d = time_mod.monotonic() + 60
        while time_mod.monotonic() < d:
            time_mod.sleep(0.02)

    ref = busy.remote()
    time_mod.sleep(0.8)
    ray_tpu.cancel(ref)
    with pytest.raises(Exception, match="cancel"):
        ray_tpu.get(ref, timeout=30)


def test_client_unknown_actor_raises(client):
    with pytest.raises(ValueError):
        ray_tpu.get_actor("does-not-exist")


def test_client_nested_refs_and_handles(client):
    """Refs nested in containers and actor handles passed as args
    resolve server-side via the persistent-id pickle protocol."""
    @ray_tpu.remote
    def total(refs):
        return sum(ray_tpu.get(refs))

    nested = [ray_tpu.put(i) for i in (1, 2, 3)]
    assert ray_tpu.get(total.remote(nested)) == 6

    @ray_tpu.remote
    class Store:
        def __init__(self):
            self.v = None

        def set(self, v):
            self.v = v
            return "ok"

        def get(self):
            return self.v

    s = Store.remote()

    @ray_tpu.remote
    def write_through(handle, value):
        return ray_tpu.get(handle.set.remote(value))

    assert ray_tpu.get(write_through.remote(s, 42)) == "ok"
    assert ray_tpu.get(s.get.remote()) == 42
    ray_tpu.kill(s)


def test_client_timeout_error_type(client):
    """Server-side GetTimeoutError surfaces with its real type."""
    @ray_tpu.remote
    def slow():
        import time

        time.sleep(30)

    with pytest.raises(ray_tpu.GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=1.0)


def test_client_reconnect_reuses_module_functions(client_cluster):
    """A module-level remote function keeps working across
    shutdown + re-init (no stale-context cache)."""
    @ray_tpu.remote
    def echo(x):
        return x

    ray_tpu.init(address=f"client://{client_cluster}")
    try:
        assert ray_tpu.get(echo.remote(1)) == 1
    finally:
        ray_tpu.shutdown()
    ray_tpu.init(address=f"client://{client_cluster}")
    try:
        assert ray_tpu.get(echo.remote(2)) == 2
    finally:
        ray_tpu.shutdown()


def test_client_rejects_local_cluster_kwargs(client_cluster):
    with pytest.raises(ValueError, match="does not accept"):
        ray_tpu.init(address=f"client://{client_cluster}", num_cpus=2)
