"""Zero-pickle channel frame plane (ray_tpu/experimental/channel.py).

Direct coverage for the raw-header frame protocol the compiled-DAG hot
loop rides: header-only stale-frame skipping, FrameScratch reuse,
FIFO-token wakeups, and cross-process round trips.
"""

import os
import pickle
import threading
import time

import pytest

from ray_tpu.experimental.channel import (
    TAG_ERR,
    TAG_OK,
    ChannelClosedError,
    FrameScratch,
    ShmChannel,
)


@pytest.fixture
def chan():
    ch = ShmChannel.create(ShmChannel.make_name(0), 1 << 16)
    yield ch
    ch.destroy()
    ch.close()


def test_frame_roundtrip_and_zero_copy_view(chan):
    scratch = FrameScratch()
    value = {"x": list(range(50)), "tag": "hello"}
    chan.write_frame(TAG_OK, 7, scratch.pack(value))
    tag, seq, view = chan.read_frame(timeout=5)
    assert (tag, seq) == (TAG_OK, 7)
    assert isinstance(view, memoryview)  # aliases the shm segment
    assert pickle.loads(view) == value
    del view
    chan.release_frame()


def test_stale_frames_skipped_without_deserializing(chan):
    class Bomb:
        """Deserializing this object is the bug being tested for."""
        def __reduce__(self):
            return (_explode, ())

    chan.write_frame(TAG_OK, 1, pickle.dumps(Bomb()))
    tag, seq, _view = chan.read_frame(timeout=5)
    assert seq == 1
    _view = None
    chan.release_frame()  # stale: dropped from the header alone
    chan.write_frame(TAG_OK, 2, pickle.dumps("fresh"))
    tag, seq, view = chan.read_frame(timeout=5)
    assert (tag, seq) == (TAG_OK, 2)
    assert pickle.loads(view) == "fresh"
    del view
    chan.release_frame()


def _explode():
    raise AssertionError("stale frame payload was deserialized")


def test_frame_scratch_reuses_buffer():
    scratch = FrameScratch(initial=16)
    v1 = scratch.pack(b"a" * 100)     # grows
    buf_id = id(scratch._buf)
    assert pickle.loads(v1) == b"a" * 100
    v2 = scratch.pack(b"b" * 80)      # reuse, no regrow
    assert id(scratch._buf) == buf_id
    assert pickle.loads(v2) == b"b" * 80


def test_oversize_frame_raises(chan):
    with pytest.raises(ValueError, match="exceeds channel capacity"):
        chan.write_frame(TAG_OK, 1, b"x" * (1 << 17))


def test_err_tag_travels(chan):
    chan.write_frame(TAG_ERR, 3, pickle.dumps("boom"))
    tag, seq, view = chan.read_frame(timeout=5)
    assert tag == TAG_ERR and pickle.loads(view) == "boom"
    del view
    chan.release_frame()


def test_depth_one_backpressure_and_fifo_wakeup(chan):
    chan.write_frame(TAG_OK, 1, b"first")
    # slot occupied: a second write must time out quickly
    with pytest.raises(TimeoutError):
        chan.write_frame(TAG_OK, 2, b"second", timeout=0.05)

    # a blocked writer wakes as soon as the reader releases
    done = []

    def release_later():
        time.sleep(0.1)
        chan.read_frame(timeout=5)
        chan.release_frame()
        done.append(True)

    t = threading.Thread(target=release_later)
    t.start()
    start = time.monotonic()
    chan.write_frame(TAG_OK, 2, b"second", timeout=5)
    assert time.monotonic() - start < 2.0
    t.join()
    assert done


def test_shutdown_wakes_blocked_reader(chan):
    errs = []

    def reader():
        try:
            chan.read_frame(timeout=30)
        except ChannelClosedError:
            errs.append("closed")

    t = threading.Thread(target=reader)
    t.start()
    time.sleep(0.1)
    start = time.monotonic()
    chan.signal_shutdown()
    t.join(timeout=5)
    assert not t.is_alive()
    # the FIFO token (or the bounded select slice) delivers the flag
    # promptly — not after a long poll cap
    assert time.monotonic() - start < 2.0
    assert errs == ["closed"]


def test_cross_process_roundtrip_latency(chan):
    """Echo child: parent->child->parent round trips must be far below
    the old ~1 ms/hop polling regime (FIFO wakeups are kernel-directed;
    generous bound for busy CI boxes)."""
    back = ShmChannel.create(ShmChannel.make_name(1), 1 << 16)
    n = 300
    pid = os.fork()
    if pid == 0:  # child: echo loop
        try:
            for _ in range(n):
                tag, seq, view = chan.read_frame(timeout=30)
                payload = bytes(view)
                del view
                chan.release_frame()
                back.write_frame(tag, seq, payload, timeout=30)
        finally:
            os._exit(0)
    try:
        payload = b"z" * 128
        for i in range(50):  # warm
            chan.write_frame(TAG_OK, i, payload, timeout=30)
            back.read_frame(timeout=30)
            back.release_frame()
        t0 = time.perf_counter()
        for i in range(50, n):
            chan.write_frame(TAG_OK, i, payload, timeout=30)
            back.read_frame(timeout=30)
            back.release_frame()
        rtt = (time.perf_counter() - t0) / (n - 50)
        os.waitpid(pid, 0)
        assert rtt < 0.002, f"round trip {rtt * 1e6:.0f} µs"
    finally:
        back.destroy()
        back.close()


def test_fifo_fallback_polling_still_works(chan, monkeypatch):
    """A channel without FIFO fds degrades to the spin/sleep fallback
    and stays correct."""
    for fd in (chan._rdy_fd, chan._fre_fd):
        if fd is not None:
            os.close(fd)
    chan._rdy_fd = chan._fre_fd = None
    chan.write_frame(TAG_OK, 9, b"polled")
    tag, seq, view = chan.read_frame(timeout=5)
    assert (tag, seq, bytes(view)) == (TAG_OK, 9, b"polled")
    del view
    chan.release_frame()
