"""Sanitizer gate for the native store (SURVEY.md §5 race detection).

Builds and runs the multi-threaded create/seal/get/evict stress driver
under AddressSanitizer — the reference's TSAN/ASAN bazel-config
equivalent for `src/ray/object_manager/plasma/`.
"""

import os
import subprocess
import sys

import pytest

_NATIVE = os.path.join(os.path.dirname(__file__), "..", "ray_tpu",
                       "native")


def test_shm_store_stress_under_asan():
    build = subprocess.run(
        ["make", "-C", _NATIVE, "build/stress_asan"],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]
    run = subprocess.run(
        [os.path.join(_NATIVE, "build", "stress_asan")],
        capture_output=True, text=True, timeout=300)
    assert run.returncode == 0, \
        f"ASAN stress failed:\n{run.stdout[-1000:]}\n{run.stderr[-3000:]}"
    assert "stress OK" in run.stdout
