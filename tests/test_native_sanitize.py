"""Sanitizer gates for the native store (SURVEY.md §5 race detection).

Builds and runs the multi-threaded create/seal/get/evict stress driver
under AddressSanitizer and ThreadSanitizer — the reference's TSAN/ASAN
bazel-config equivalent for `src/ray/object_manager/plasma/`. TSAN is
the native-side counterpart of the Python-side lockdep + raylint gates:
ASAN catches lifetime bugs, TSAN the data races and lock inversions.

The driver runs three phases and each must print its OK line: the
single-shard (v1-shaped) store, an 8-way-sharded store that hammers
the sharded create/seal/evict paths, the lock-free contains/release
probes, cross-shard eviction sweeps, and the all-region-locks spanning
allocator — and the dispatch request ring (request_ring.cc), where
producers race native pow-2 enqueue against batch-draining consumers
under replica-snapshot churn (publish / mark_dead / stale rr_done).
"""

import os
import subprocess
import sys

import pytest

_NATIVE = os.path.join(os.path.dirname(__file__), "..", "ray_tpu",
                       "native")


def _build_and_stress(target: str, label: str,
                      extra_env: dict = None) -> None:
    build = subprocess.run(
        ["make", "-C", _NATIVE, f"build/{target}"],
        capture_output=True, text=True, timeout=300)
    if build.returncode != 0 and "unrecognized" in (build.stderr or ""):
        pytest.skip(f"toolchain lacks {label} support")
    assert build.returncode == 0, build.stderr[-2000:]
    env = dict(os.environ)
    env.update(extra_env or {})
    run = subprocess.run(
        [os.path.join(_NATIVE, "build", target)],
        capture_output=True, text=True, timeout=300, env=env)
    assert run.returncode == 0, \
        f"{label} stress failed:\n{run.stdout[-1000:]}\n{run.stderr[-3000:]}"
    assert "stress OK (single-shard)" in run.stdout
    assert "stress OK (sharded)" in run.stdout
    assert "stress OK (request-ring)" in run.stdout


def test_shm_store_stress_under_asan():
    _build_and_stress("stress_asan", "ASAN")


def test_shm_store_stress_under_tsan():
    # halt_on_error so the first race fails the gate instead of
    # scrolling past; second_deadlock_stack mirrors lockdep's
    # both-witness-stacks reporting for pthread mutex inversions
    _build_and_stress(
        "stress_tsan", "TSAN",
        {"TSAN_OPTIONS": "halt_on_error=1 second_deadlock_stack=1"})
