"""Parallelism primitives on the 8-device virtual CPU mesh: mesh building,
sharding rules, collectives, ring attention, Ulysses, pipeline, MoE."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import (
    MeshConfig,
    ShardingStrategy,
    build_mesh,
    mesh_shape_for,
)
from ray_tpu.parallel import collectives
from ray_tpu.parallel.moe import apply_moe
from ray_tpu.parallel.pipeline import (
    bubble_fraction,
    pipeline_apply,
    pipeline_loss,
    pipeline_train_step,
    stack_stage_params,
)
from ray_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ulysses_attention,
)


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_mesh_config_inference():
    assert MeshConfig({"dp": -1, "tp": 2}).resolved(8) == {"dp": 4, "tp": 2}
    assert mesh_shape_for(8, tp=2, sp=2) == {"dp": 2, "tp": 2, "sp": 2}
    with pytest.raises(ValueError):
        MeshConfig({"dp": 3}).resolved(8)


def test_build_mesh_axes():
    mesh = build_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    assert mesh.shape == {"dp": 2, "fsdp": 2, "tp": 2}


def test_device_allreduce():
    mesh = build_mesh({"dp": 8})
    x = jnp.arange(8.0)
    out = collectives.device_allreduce(mesh, x, axis="dp")
    # Each dp member holds one element; psum yields the total, replicated.
    assert float(np.asarray(out)[0]) == 28.0


def test_strategy_data_axes():
    s = ShardingStrategy(dp=2, fsdp=2, tp=2)
    assert s.data_axes == ("dp", "fsdp")
    assert ShardingStrategy(dp=8).data_axes == ("dp",)


def _reference_attention(q, k, v, causal):
    return full_attention(q, k, v, causal=causal)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    mesh = build_mesh({"dp": 2, "sp": 4})
    b, t, h, d = 2, 32, 4, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    with mesh:
        out = ring_attention(q, k, v, mesh, causal=causal, head_axis=None)
    expected = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_jit_grad():
    mesh = build_mesh({"sp": 8})
    b, t, h, d = 1, 64, 2, 4
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)

    def loss(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True, head_axis=None,
                              batch_axes=()).sum()

    def ref_loss(q, k, v):
        return full_attention(q, k, v, causal=True).sum()

    g = jax.jit(jax.grad(loss))(q, k, v)
    g_ref = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    mesh = build_mesh({"sp": 4, "dp": 2})
    b, t, h, d = 2, 16, 4, 8
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    with mesh:
        out = ulysses_attention(q, k, v, mesh, causal=causal)
    expected = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


def test_pipeline_matches_sequential():
    mesh = build_mesh({"pp": 4}, devices=jax.devices()[:4])
    n_stages, batch, dim = 4, 8, 16
    rng = np.random.RandomState(3)
    stage_ws = [jnp.asarray(rng.randn(dim, dim) * 0.1, jnp.float32)
                for _ in range(n_stages)]
    params = stack_stage_params([{"w": w} for w in stage_ws])
    x = jnp.asarray(rng.randn(batch, dim), jnp.float32)

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    out = pipeline_apply(stage_fn, params, x, mesh, num_microbatches=4)
    expected = x
    for w in stage_ws:
        expected = jnp.tanh(expected @ w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=1e-5)


def test_pipeline_grad():
    mesh = build_mesh({"pp": 2}, devices=jax.devices()[:2])
    rng = np.random.RandomState(4)
    params = stack_stage_params([
        {"w": jnp.asarray(rng.randn(8, 8) * 0.1, jnp.float32)}
        for _ in range(2)
    ])
    x = jnp.asarray(rng.randn(4, 8), jnp.float32)

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    def loss(params):
        return pipeline_apply(stage_fn, params, x, mesh,
                              num_microbatches=2).sum()

    g = jax.jit(loss)(params), jax.grad(loss)(params)
    assert float(jnp.abs(g[1]["w"]).sum()) > 0


def test_pipeline_fused_loss_and_grads_match_single_device():
    """VERDICT r2 item 9 'done' criterion: the fused-loss pipeline's
    loss AND per-stage grads equal a plain single-device forward/backward
    of the same stack — with remat on (the 1F1B-equivalent memory mode)
    and gradient accumulation over microbatches built in."""
    n_stages, batch, dim, n_mb = 4, 16, 8, 8
    mesh = build_mesh({"pp": n_stages}, devices=jax.devices()[:n_stages])
    rng = np.random.RandomState(7)
    stage_ws = [jnp.asarray(rng.randn(dim, dim) * 0.3, jnp.float32)
                for _ in range(n_stages)]
    params = stack_stage_params([{"w": w} for w in stage_ws])
    x = jnp.asarray(rng.randn(batch, dim), jnp.float32)
    y = jnp.asarray(rng.randn(batch, dim), jnp.float32)

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    def loss_fn(out, tgt):
        return jnp.mean(jnp.square(out - tgt))

    loss, grads = jax.jit(
        lambda ps: pipeline_train_step(
            stage_fn, loss_fn, ps, x, y, mesh,
            num_microbatches=n_mb))(params)

    # single-device reference: same microbatch averaging (mean of
    # per-microbatch MSE == global MSE here since equal sizes)
    def ref_loss(ps):
        h = x
        for i in range(n_stages):
            h = jnp.tanh(h @ ps["w"][i])
        return jnp.mean(jnp.square(h - y))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["w"]),
                               np.asarray(ref_g["w"]),
                               atol=1e-5, rtol=1e-4)
    # remat (the 1F1B-equivalent memory mode) is bit-stable vs no-remat
    loss2, grads2 = jax.jit(
        lambda ps: pipeline_train_step(
            stage_fn, loss_fn, ps, x, y, mesh,
            num_microbatches=n_mb, remat=False))(params)
    np.testing.assert_allclose(float(loss2), float(loss), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grads2["w"]),
                               np.asarray(grads["w"]), rtol=1e-5)


def test_pipeline_loss_scalar_only_psum():
    """pipeline_loss returns a replicated scalar; raising microbatches
    shrinks the structural bubble."""
    mesh = build_mesh({"pp": 2}, devices=jax.devices()[:2])
    rng = np.random.RandomState(8)
    params = stack_stage_params([
        {"w": jnp.asarray(rng.randn(4, 4) * 0.1, jnp.float32)}
        for _ in range(2)])
    x = jnp.asarray(rng.randn(8, 4), jnp.float32)
    y = jnp.asarray(rng.randn(8, 4), jnp.float32)
    l = pipeline_loss(
        lambda p, h: h @ p["w"], lambda o, t: jnp.mean((o - t) ** 2),
        params, x, y, mesh, num_microbatches=4)
    assert l.shape == ()
    assert bubble_fraction(2, 4) == pytest.approx(1 / 5)
    assert bubble_fraction(4, 16) < bubble_fraction(4, 4)


def test_moe_dispatch_combines():
    mesh = build_mesh({"ep": 4, "dp": 2})
    b, s, d, n_experts = 2, 16, 8, 4
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(b, s, d), jnp.float32)
    router_w = jnp.asarray(rng.randn(d, n_experts) * 0.1, jnp.float32)
    expert_w = jnp.asarray(rng.randn(n_experts, d, d) * 0.1, jnp.float32)

    def expert_fn(w, tokens):
        return tokens @ w

    with mesh:
        y, aux = apply_moe(
            x, router_w, expert_w, expert_fn, mesh,
            capacity_factor=8.0,  # ample capacity: no token dropped
        )
    assert y.shape == x.shape
    assert float(aux) > 0

    # Compare against dense single-shard dispatch.
    mesh1 = build_mesh({"dp": 8})
    y_ref, _ = apply_moe(x, router_w, expert_w, expert_fn, mesh1,
                         capacity_factor=8.0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-4)


@pytest.mark.parametrize("variant", ["ring", "ulysses"])
def test_sequence_parallel_attention_gqa(variant):
    """GQA (fewer KV heads) through the sequence-parallel paths: ring
    rotates KV at its narrow h_kv width (expanding per-block); Ulysses
    all_to_alls the narrow KV then expands post-split. Both must match
    dense attention over query-side-expanded KV."""
    import numpy as np

    from ray_tpu.parallel.mesh import build_mesh
    from ray_tpu.parallel.ring_attention import (full_attention,
                                                 ring_attention,
                                                 ulysses_attention)

    mesh = build_mesh({"dp": 2, "sp": 4})
    rng = np.random.default_rng(0)
    b, t, h, h_kv, d = 2, 64, 8, 2, 16
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, h_kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, h_kv, d)), jnp.float32)
    ref = full_attention(q, jnp.repeat(k, h // h_kv, axis=2),
                         jnp.repeat(v, h // h_kv, axis=2), causal=True)
    if variant == "ring":
        with mesh:
            got = ring_attention(q, k, v, mesh, causal=True,
                                 head_axis=None)
    else:
        # h_kv=2 not divisible by sp=4 -> pre-expansion fallback; also
        # exercise the narrow path with h_kv=4
        with mesh:
            got = ulysses_attention(q, k, v, mesh, causal=True)
        k4 = jnp.asarray(rng.standard_normal((b, t, 4, d)), jnp.float32)
        v4 = jnp.asarray(rng.standard_normal((b, t, 4, d)), jnp.float32)
        ref4 = full_attention(q, jnp.repeat(k4, 2, axis=2),
                              jnp.repeat(v4, 2, axis=2), causal=True)
        with mesh:
            got4 = ulysses_attention(q, k4, v4, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got4), np.asarray(ref4),
                                   atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_hybrid_mesh_multislice():
    """Hybrid dcn x ici mesh (VERDICT r4 item 2): 2 virtual slices x 4
    devices; dcn outermost; each ici column stays within one slice's
    device block."""
    import jax
    from ray_tpu.parallel.mesh import build_hybrid_mesh

    mesh = build_hybrid_mesh({"fsdp": 4}, {"dcn": 2})
    assert mesh.axis_names == ("dcn", "fsdp")
    assert mesh.shape == {"dcn": 2, "fsdp": 4}
    devs = jax.devices()
    arr = mesh.devices
    # virtual slices are contiguous device blocks
    assert [d.id for d in arr[0]] == [d.id for d in devs[:4]]
    assert [d.id for d in arr[1]] == [d.id for d in devs[4:8]]


def test_multislice_strategy_allreduce():
    """A dcn-data-parallel + in-slice fsdp strategy trains identically to
    the unsharded computation: psum over ('dcn','fsdp') sums all 8 data
    shards."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel import ShardingStrategy

    strategy = ShardingStrategy(dcn_dp=2, fsdp=4)
    assert strategy.data_axes == ("dcn", "fsdp")
    mesh = strategy.build_mesh()
    x = np.arange(16, dtype=np.float32).reshape(8, 2)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "fsdp"), None)))

    @jax.jit
    def global_sum(x):
        return jnp.sum(x)

    np.testing.assert_allclose(float(global_sum(xs)), x.sum())


def test_multislice_scaling_config_bundles():
    from ray_tpu.air.config import ScalingConfig

    sc = ScalingConfig(num_workers=4, num_slices=2)
    assert sc.workers_per_slice == 2
    assert len(sc.bundles()) == 2      # one slice's gang
    assert len(sc.total_bundles()) == 4
    import pytest as _pytest

    with _pytest.raises(ValueError):
        ScalingConfig(num_workers=3, num_slices=2).workers_per_slice
