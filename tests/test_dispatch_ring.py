"""Dispatch plane v2: native request ring + snapshot table (ISSUE 19).

Lockdep-gated (conftest `_LOCKDEP_SUITES`) concurrency suite for the
zero-Python dispatch path:

- ring semantics: mint/deadline/pow-2 choice happen natively; the
  rejection codes (FULL / DEADLINE / TOO_BIG / NO_REPLICA) map to
  shed-vs-fallback in Python; generation-checked `done` drops stale
  completions (the native twin of the Router's positional-aliasing fix)
- thread + process races: producers hammer `rr_enqueue` against
  batch-draining consumers while a churn thread bumps the snapshot
  version / marks replicas dead / fires stale dones — no torn frames,
  every successful enqueue drains exactly once, and the inflight
  counters balance to zero at quiesce
- Router satellites: stable replica keying across `mark_dead`
  compaction (regression for the old positional-index aliasing),
  in-flight counts preserved across version bumps, the
  `serve_router_empty_waits_total` counter (one per empty episode, not
  one per poll slice), and per-site seeded pow-2 picks under an armed
  FaultPlan.
"""

import os
import random
import subprocess
import sys
import threading
import time

import pytest

from ray_tpu.serve import dispatch as _dispatch

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_NATIVE_OK = _dispatch._load() is not None
needs_native = pytest.mark.skipif(
    not _NATIVE_OK, reason="native dispatch library unavailable")


def _fresh_segment() -> str:
    return f"/rtds.t{os.getpid():x}{os.urandom(3).hex()}"


@pytest.fixture
def ring():
    seg = _fresh_segment()
    r = _dispatch.DispatchRing(seg, table_cap=4, slots=256, slot_bytes=256)
    yield r
    r.close(unlink=True)


def _inflight_sum(r: _dispatch.DispatchRing) -> int:
    _ver, rows = r.snapshot()
    return sum(row[2] for row in rows)


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

@needs_native
class TestRingSemantics:
    def test_no_replica_rejected(self, ring):
        with pytest.raises(_dispatch.DispatchRejected) as e:
            ring.enqueue(b"x")
        assert e.value.code == _dispatch.ERR_NO_REPLICA
        assert ring.stats()["no_replica"] >= 1

    def test_expired_deadline_shed_natively(self, ring):
        ring.publish(1, [7])
        with pytest.raises(_dispatch.DispatchRejected) as e:
            ring.enqueue(b"x", deadline_ns=1)  # long past
        assert e.value.code == _dispatch.ERR_DEADLINE
        assert ring.stats()["deadline_shed"] >= 1

    def test_oversized_payload_rejected(self, ring):
        ring.publish(1, [7])
        with pytest.raises(_dispatch.DispatchRejected) as e:
            ring.enqueue(b"x" * (ring.slot_bytes + 1))
        assert e.value.code == _dispatch.ERR_TOO_BIG

    def test_frame_roundtrip_and_inflight(self, ring):
        ring.publish(1, [7])
        trace, rid, gen = ring.enqueue(b"hello", client=0xabc)
        assert rid == 7
        assert trace != 0
        # natively-minted trace ids stitch into the recorder wire format
        tid = _dispatch.format_trace(trace)
        assert len(tid) == 16 and int(tid, 16) == trace
        assert _inflight_sum(ring) == 1
        frames = ring.drain(ring.ring_of(7))
        assert len(frames) == 1
        f = frames[0]
        assert (f.trace, f.rid, f.gen) == (trace, rid, gen)
        assert f.client == 0xabc
        assert f.tag == _dispatch.TAG_REQUEST
        assert f.payload == b"hello"
        assert ring.done(rid, gen)
        assert _inflight_sum(ring) == 0

    def test_stale_generation_done_dropped(self, ring):
        ring.publish(1, [7])
        _trace, rid, gen = ring.enqueue(b"x")
        # wrong generation: the completion belongs to a previous tenant
        # of the slot — it must NOT decrement the current counter
        assert not ring.done(rid, gen + 1)
        assert ring.stats()["done_stale"] >= 1
        assert _inflight_sum(ring) == 1
        assert ring.done(rid, gen)
        assert _inflight_sum(ring) == 0

    def test_retire_and_readd_bumps_generation(self, ring):
        # the ABA shape the packed gen<<32|inflight word exists for:
        # replica 7 leaves, its slot is re-issued to 7 again (scale
        # down/up) — a completion from the FIRST tenancy must not touch
        # the second's counter
        ring.publish(1, [7])
        _t, rid, old_gen = ring.enqueue(b"x")
        ring.drain(ring.ring_of(7))
        ring.publish(2, [8])        # 7 retired: gen bump + inflight zeroed
        ring.publish(3, [7, 8])     # 7 re-added under a fresh generation
        assert not ring.done(rid, old_gen)
        assert _inflight_sum(ring) == 0

    def test_full_ring_rejected(self, ring):
        ring.publish(1, [7])
        for _ in range(ring.slots):
            ring.enqueue(b"x")
        with pytest.raises(_dispatch.DispatchRejected) as e:
            ring.enqueue(b"x")
        assert e.value.code == _dispatch.ERR_FULL
        assert ring.stats()["full_rejects"] >= 1

    def test_pow2_choice_balances(self, ring):
        ring.publish(1, [11, 22, 33, 44])
        for _ in range(200):
            ring.enqueue(b"x")
        pend = [ring.pending(r) for r in range(4)]
        assert sum(pend) == 200
        # two-choice against live inflight counters: no ring starves
        assert min(pend) >= 20, pend

    def test_metrics_text_renders_counters(self, ring):
        ring.publish(1, [7])
        ring.enqueue(b"x")
        ring.drain(ring.ring_of(7))
        text = ring.metrics_text("demo")
        assert 'serve_dispatch_enqueued_total{domain="demo"} 1' in text
        assert 'serve_dispatch_drained_total{domain="demo"} 1' in text


# ---------------------------------------------------------------------------
# thread + process races under snapshot churn
# ---------------------------------------------------------------------------

_IDS = (11, 22, 33, 44)


def _uniform(n: int) -> bytes:
    return bytes([n % 251]) * (n % 96 + 1)


def _is_torn(payload: bytes) -> bool:
    return payload != payload[:1] * len(payload)


@needs_native
class TestRaces:
    def test_threads_race_enqueue_drain_under_churn(self):
        seg = _fresh_segment()
        ring = _dispatch.DispatchRing(seg, table_cap=4, slots=256,
                                      slot_bytes=256)
        ring.publish(1, list(_IDS))
        stop_churn = threading.Event()
        producers_done = threading.Event()
        enq_ok = []          # per-producer success counts
        drained = [0, 0]
        torn = [0]
        errors = []

        def producer(n):
            ok = 0
            for i in range(500):
                try:
                    ring.enqueue(_uniform(n * 1000 + i))
                    ok += 1
                except _dispatch.DispatchRejected:
                    pass      # FULL under churn is expected shed
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    break
            enq_ok.append(ok)

        def consumer(slot, rings):
            # own attachment: drain buffers are per-object
            mine = _dispatch.DispatchRing(seg, create=False)
            try:
                while True:
                    got = 0
                    for r in rings:
                        for f in mine.drain(r, 64):
                            got += 1
                            if _is_torn(f.payload):
                                torn[0] += 1
                            mine.done(f.rid, f.gen)
                    drained[slot] += got
                    if got == 0:
                        if producers_done.is_set() and \
                                all(mine.pending(r) == 0 for r in rings):
                            return
                        time.sleep(0.001)
            finally:
                mine.close()

        def churn():
            mine = _dispatch.DispatchRing(seg, create=False)
            rng = random.Random(19)
            ver = 2
            try:
                while not stop_churn.is_set():
                    keep = rng.sample(_IDS, rng.randint(1, 4))
                    mine.publish(ver, keep)
                    ver += 1
                    mine.mark_dead(rng.choice(_IDS))
                    mine.done(rng.choice(_IDS), 0)   # stale: must drop
                    mine.snapshot()
                    time.sleep(0.002)
                mine.publish(ver, list(_IDS))        # restore for quiesce
            finally:
                mine.close()

        threads = ([threading.Thread(target=producer, args=(n,))
                    for n in range(6)]
                   + [threading.Thread(target=consumer, args=(0, (0, 1))),
                      threading.Thread(target=consumer, args=(1, (2, 3)))])
        ct = threading.Thread(target=churn)
        ct.start()
        for t in threads:
            t.start()
        for t in threads[:6]:
            t.join(60)
        stop_churn.set()
        ct.join(10)
        producers_done.set()
        for t in threads[6:]:
            t.join(60)
        try:
            assert not errors, errors
            assert torn[0] == 0
            # zero leaked frames: every successful enqueue drained once
            assert sum(drained) == sum(enq_ok)
            assert sum(enq_ok) > 0
            assert _inflight_sum(ring) == 0
        finally:
            ring.close(unlink=True)

    def test_processes_race_enqueue_against_local_drain(self):
        seg = _fresh_segment()
        ring = _dispatch.DispatchRing(seg, table_cap=4, slots=256,
                                      slot_bytes=256)
        ring.publish(1, list(_IDS))
        child_src = (
            "import sys\n"
            f"sys.path.insert(0, {ROOT!r})\n"
            "from ray_tpu.serve import dispatch as d\n"
            f"ring = d.DispatchRing({seg!r}, create=False)\n"
            "ok = 0\n"
            "for i in range(2000):\n"
            "    try:\n"
            "        ring.enqueue(bytes([i % 251]) * 64)\n"
            "        ok += 1\n"
            "    except d.DispatchRejected:\n"
            "        pass\n"
            "print('CHILD', ok)\n"
        )
        procs = [subprocess.Popen(
            [sys.executable, "-c", child_src],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for _ in range(2)]

        stop = threading.Event()
        drained = [0]
        torn = [0]

        def consumer():
            mine = _dispatch.DispatchRing(seg, create=False)
            try:
                while True:
                    got = 0
                    for r in range(4):
                        for f in mine.drain(r, 64):
                            got += 1
                            if _is_torn(f.payload):
                                torn[0] += 1
                            mine.done(f.rid, f.gen)
                    drained[0] += got
                    if got == 0:
                        if stop.is_set() and \
                                all(mine.pending(r) == 0 for r in range(4)):
                            return
                        time.sleep(0.001)
            finally:
                mine.close()

        def churn():
            rng = random.Random(7)
            ver = 2
            while not stop.is_set():
                ring.publish(ver, rng.sample(_IDS, rng.randint(2, 4)))
                ver += 1
                time.sleep(0.005)
            ring.publish(ver, list(_IDS))

        ct1 = threading.Thread(target=consumer)
        ct2 = threading.Thread(target=churn)
        ct1.start()
        ct2.start()
        child_ok = 0
        try:
            for p in procs:
                out, err = p.communicate(timeout=120)
                assert p.returncode == 0, err[-2000:]
                child_ok += int(out.split()[-1])
        finally:
            stop.set()
            ct2.join(10)
            ct1.join(60)
        try:
            assert torn[0] == 0
            assert drained[0] == child_ok
            assert child_ok > 0
            assert _inflight_sum(ring) == 0
        finally:
            ring.close(unlink=True)


# ---------------------------------------------------------------------------
# Router satellites: stable keying, empty-wait wakeup, seeded picks
# ---------------------------------------------------------------------------

class _FakeActor:
    """Enough surface for dispatch.replica_key: a stable actor id."""

    def __init__(self, tag: int):
        self._actor_id = bytes([tag]) * 8


class _FakeController:
    """Duck-typed controller: `.get_replicas.remote(name)` returns the
    payload itself; ray_tpu.get is patched to pass it through."""

    def __init__(self, replicas):
        self.version = 1
        self.replicas = list(replicas)
        outer = self

        class _Method:
            @staticmethod
            def remote(_name):
                return {"version": outer.version,
                        "replicas": list(outer.replicas)}

        self.get_replicas = _Method()


@pytest.fixture
def passthrough_get(monkeypatch):
    import ray_tpu
    monkeypatch.setattr(ray_tpu, "get",
                        lambda ref, timeout=None: ref)


def _mk_router(ctrl, name):
    from ray_tpu.serve.handle import Router
    r = Router(ctrl, name)
    return r


class TestRouterKeying:
    def test_done_after_compaction_hits_the_right_replica(
            self, passthrough_get):
        # Regression for the positional-index aliasing: with the old
        # list keying, mark_dead compacted the list and a done(idx)
        # from a request dispatched BEFORE the compaction decremented
        # whichever replica slid into that slot. Stable keys: the late
        # completion hits its own replica or (replica gone) nothing.
        a, b, c = _FakeActor(1), _FakeActor(2), _FakeActor(3)
        ka, kb, kc = (_dispatch.replica_key(x) for x in (a, b, c))
        ctrl = _FakeController([a, b, c])
        r = _mk_router(ctrl, f"dr-{os.urandom(3).hex()}")
        try:
            for _ in range(3):
                r.choose()
            before = dict(r._inflight)
            assert sum(before.values()) == 3
            r.mark_dead(ka)
            # late completion for the dead replica: decrements NOBODY
            r.done(ka)
            assert r._inflight.get(kb) == before[kb]
            assert r._inflight.get(kc) == before[kc]
            # survivor completions land on their own counter
            r._inflight[kb] = 2
            r.done(kb)
            assert r._inflight[kb] == 1
            assert r._inflight[kc] == before[kc]
        finally:
            r._wake.close(unlink=True)

    def test_counts_preserved_across_version_bump(self, passthrough_get):
        a, b, c = _FakeActor(1), _FakeActor(2), _FakeActor(3)
        ka, kb, _kc = (_dispatch.replica_key(x) for x in (a, b, c))
        ctrl = _FakeController([a, b])
        r = _mk_router(ctrl, f"dr-{os.urandom(3).hex()}")
        try:
            r._refresh(force=True)
            r._inflight[ka] = 4
            r._inflight[kb] = 2
            ctrl.version = 2
            ctrl.replicas = [b, c]   # a departs, c arrives
            r._last_refresh = 0.0
            r._refresh(force=True)
            assert ka not in r._inflight          # departed: count drops
            assert r._inflight[kb] == 2           # survivor: preserved
            assert r._inflight[_dispatch.replica_key(c)] == 0
        finally:
            r._wake.close(unlink=True)

    def test_empty_wait_counts_once_and_wakes_on_publish(
            self, passthrough_get):
        from ray_tpu.serve.handle import ROUTER_EMPTY_WAITS
        name = f"dr-{os.urandom(3).hex()}"
        ctrl = _FakeController([])
        r = _mk_router(ctrl, name)
        before = ROUTER_EMPTY_WAITS._values.get((name,), 0.0)
        out = []

        def run():
            out.append(r.choose())

        t = threading.Thread(target=run)
        t.start()
        try:
            time.sleep(0.6)  # several wait slices while the view is empty
            assert not out
            # replica arrives; the controller posts the wake FIFO on the
            # version bump (dispatch-agnostic: plain mkfifo token)
            ctrl.version = 2
            ctrl.replicas = [_FakeActor(9)]
            r._last_refresh = 0.0
            _dispatch._Wakeup(_dispatch.router_wake_path(name)).post()
            t.join(10)
            assert not t.is_alive()
            assert out and out[0][1] is ctrl.replicas[0]
            after = ROUTER_EMPTY_WAITS._values.get((name,), 0.0)
            # one empty EPISODE == one count, however many slices it took
            assert after - before == 1.0
        finally:
            r._wake.close(unlink=True)

    def test_pow2_picks_replay_under_armed_fault_plan(
            self, passthrough_get):
        from ray_tpu._private import fault_injection as _fi
        actors = [_FakeActor(i + 1) for i in range(5)]

        def pick_sequence():
            plan = _fi.install(_fi.FaultPlan("seed=7"))
            assert plan.rng_for("serve.router") is not None
            ctrl = _FakeController(actors)
            r = _mk_router(ctrl, f"dr-{os.urandom(3).hex()}")
            try:
                seq = []
                for _ in range(24):
                    key, _actor = r.choose()
                    r.done(key)   # keep the inflight view flat
                    seq.append(key)
                return seq
            finally:
                r._wake.close(unlink=True)
                _fi.uninstall()

        assert pick_sequence() == pick_sequence()


# ---------------------------------------------------------------------------
# recorder stitching for natively-minted trace ids
# ---------------------------------------------------------------------------

class TestAdoptContext:
    def test_adopted_context_shape(self):
        from ray_tpu.util import request_recorder as _rr
        tid = _dispatch.format_trace(0xdeadbeef)
        ctx = _rr.adopt_context(tid, "echo", job="jobA")
        assert ctx["req_id"] == "00000000deadbeef"
        assert ctx["deployment"] == "echo"
        assert ctx["job"] == "jobA"
        assert "sampled" in ctx

    def test_domain_segment_is_stable_shm_name(self):
        s1 = _dispatch.domain_segment("echo")
        s2 = _dispatch.domain_segment("echo")
        assert s1 == s2
        assert s1.startswith("/rtds.") and "/" not in s1[1:]
        assert _dispatch.domain_segment("other") != s1
