"""Request-path flight recorder tests (ISSUE 12): per-request ring,
context propagation handle->replica->engine, phase attribution,
histogram export, scrape hardening, the tsdb time-series plane, and
the `ray_tpu requests` CLI.

Reference ground: the step-profiler suite (ISSUE 5) pins the training
plane's flight recorder; this suite pins its inference twin.
"""

import json
import os
import time

import pytest

from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import request_recorder as rr
from ray_tpu.util import tsdb as tsdb_mod


@pytest.fixture(autouse=True)
def _clean_recorder():
    rr.refresh()
    rr.clear()
    yield
    rr.refresh()
    rr.clear()


# ---------------------------------------------------------------------------
# ring semantics + knobs
# ---------------------------------------------------------------------------

def test_ring_bounds_and_eviction(monkeypatch):
    """Sustained serving must hold steady memory: the ring keeps the
    newest `RAY_TPU_REQ_RING` records and the total keeps counting."""
    monkeypatch.setenv("RAY_TPU_REQ_RING", "16")
    rr.refresh()
    for i in range(3 * 16 + 5):
        rr.record_engine(None, ts=float(i), total_ms=1.0 + i)
    assert len(rr.ring()) == 16
    assert rr.ring().total_recorded == 3 * 16 + 5
    totals = [r.total_ms for r in rr.ring().recent()]
    assert totals == [1.0 + i for i in range(37, 53)]  # newest kept


def test_sample_knob_records_one_in_n(monkeypatch):
    monkeypatch.setenv("RAY_TPU_REQ_SAMPLE", "4")
    rr.refresh()
    for i in range(16):
        rr.record_engine(None, ts=0.0, total_ms=1.0)
    assert len(rr.ring()) == 4  # 1 in 4
    # the sampled bit is minted ONCE at the handle: client and engine
    # agree on whether the request exists
    ctxs = [rr.new_context("d") for _ in range(16)]
    assert sum(1 for c in ctxs if c["sampled"]) == 4


def test_disabled_recorder_is_inert():
    rr.set_enabled(False)
    try:
        assert rr.record_engine(None, ts=0.0, total_ms=1.0) is None
        ctx = rr.new_context("d")
        assert ctx["sampled"] is False
        assert rr.record_client(ctx, ts=0.0, total_ms=1.0) is None
        assert len(rr.ring()) == 0
    finally:
        rr.set_enabled(True)


# ---------------------------------------------------------------------------
# context plane + record merge
# ---------------------------------------------------------------------------

def test_serving_region_carries_context_to_engine_role():
    ctx = rr.new_context("chat", job="tenant-a")
    assert rr.current() is None
    with rr.serving(ctx):
        assert rr.current() is ctx
        rec = rr.record_engine(rr.current(), ts=1.0, total_ms=10.0,
                               queue_ms=1.0, admission_ms=2.0,
                               prefill_ms=3.0, decode_ms=4.0,
                               ttft_ms=6.0, tpot_ms=1.0,
                               tokens_in=8, tokens_out=5)
    assert rr.current() is None
    assert rec.req_id == ctx["req_id"]
    assert rec.deployment == "chat" and rec.job == "tenant-a"
    assert rec.phase_sum_ms() == pytest.approx(10.0)


def test_merge_by_request_joins_client_and_engine_rows():
    ctx = rr.new_context("chat", job="tenant-a")
    eng = rr.record_engine(ctx, ts=1.0, total_ms=9.0, queue_ms=1.0,
                           admission_ms=1.0, prefill_ms=3.0,
                           decode_ms=4.0, ttft_ms=5.0, tpot_ms=1.0,
                           tokens_out=5)
    cli = rr.record_client(ctx, ts=1.0, total_ms=11.0, queue_ms=0.5,
                           ttft_ms=6.0, tpot_ms=1.2, tokens_out=5,
                           replayed_tokens=2, outcome="failed_over")
    merged = rr.merge_by_request([eng.as_dict(), cli.as_dict()])
    assert len(merged) == 1
    m = merged[0]
    assert m["req_id"] == ctx["req_id"]
    # engine phases are authoritative; client total/TTFT/outcome win
    assert m["prefill_ms"] == pytest.approx(3.0)
    assert m["total_ms"] == pytest.approx(11.0)
    assert m["ttft_ms"] == pytest.approx(6.0)
    assert m["outcome"] == "failed_over"
    assert m["replayed_tokens"] == 2


def test_summary_and_slowest():
    for i in range(10):
        rr.record_engine(None, ts=float(i), total_ms=10.0 * (i + 1),
                         prefill_ms=6.0 * (i + 1),
                         decode_ms=4.0 * (i + 1), ttft_ms=7.0,
                         tpot_ms=1.5)
    s = rr.summary()
    assert s["n"] == 10
    assert s["total_ms_p50"] == pytest.approx(50.0)
    assert s["ttft_ms_p50"] == pytest.approx(7.0)
    assert s["outcomes"] == {"ok": 10}
    # phases tile 100% of total in this synthetic set
    assert sum(s["attribution"].values()) == pytest.approx(1.0)
    worst = rr.slowest([r.as_dict() for r in rr.ring().recent()], 3)
    assert [w["total_ms"] for w in worst] == [100.0, 90.0, 80.0]


# ---------------------------------------------------------------------------
# live engine: phases tile the measured end-to-end latency
# ---------------------------------------------------------------------------

def test_engine_phase_sum_matches_e2e():
    """The ISSUE 12 attribution contract: queue + admission + prefill +
    decode reconstruct the engine-observed e2e latency (within 5%)."""
    from ray_tpu.serve.llm import EngineConfig, LLMEngine

    eng = LLMEngine(model="llama",
                    engine_config=EngineConfig(batch_buckets=(1, 2),
                                               prefill_buckets=(8,)),
                    seed=0)
    eng.warmup()
    eng.start()
    try:
        reqs = [eng.submit([3, 4, 5], 4) for _ in range(4)]
        for r in reqs:
            r.result(timeout=120)
    finally:
        eng.quiesce(timeout=60)
        assert eng.shutdown() == 0

    recs = [r for r in rr.ring().recent()
            if r.role == "engine" and r.outcome == "ok"]
    assert len(recs) == 4
    for rec in recs:
        assert rec.ttft_ms is not None and rec.ttft_ms > 0
        assert rec.tokens_out == 4
        assert rec.tpot_ms is not None  # 4 tokens -> 3 decode gaps
        ratio = rec.phase_sum_ms() / rec.total_ms
        assert 0.95 <= ratio <= 1.05, rec.as_dict()


# ---------------------------------------------------------------------------
# metrics export
# ---------------------------------------------------------------------------

def test_histograms_carry_phase_deployment_job_labels():
    ctx = rr.new_context("chat", job="tenant-a")
    rr.record_engine(ctx, ts=0.0, total_ms=9.0, queue_ms=0.5,
                     admission_ms=0.5, prefill_ms=4.0, decode_ms=4.0,
                     ttft_ms=4.5, tpot_ms=1.3, tokens_out=4)
    text = metrics_mod.DEFAULT_REGISTRY.prometheus_text()
    # the module registers its callback at import: the family arrives
    # through the shared registry scrape, fully labelled
    assert ('serve_request_phase_ms_bucket{phase="queue",'
            'deployment="chat",job="tenant-a",le="1.0"} 1') in text
    assert ('serve_request_phase_ms_bucket{phase="decode",'
            'deployment="chat",job="tenant-a",le="5.0"} 1') in text
    assert 'serve_ttft_ms_bucket{deployment="chat",job="tenant-a"' \
        in text
    assert 'serve_tpot_ms_sum{deployment="chat",job="tenant-a"} 1.3' \
        in text
    assert 'serve_request_outcomes_total{outcome="ok"} 1' in text
    assert "serve_requests_recorded_total 1" in text


def test_raising_source_degrades_to_scrape_error_comment():
    """Satellite 2: scrape assembly is all-or-nothing PER SOURCE — a
    raising metric or callback must leave a `# scrape_error` comment,
    not a torn body (headers without samples), and must not take the
    other sources down with it."""
    reg = metrics_mod._Registry()
    metrics_mod.Counter("ok_total", "fine", registry=reg).inc()
    bad = metrics_mod.Counter("bad_total", "boom", registry=reg)

    def _boom():
        raise RuntimeError("mid-render")

    bad.samples = _boom
    reg.register_callback("bad_cb", lambda: 1 / 0)
    reg.register_callback("good_cb", lambda: "extra_metric 1\n")
    text = reg.prometheus_text()
    assert "ok_total 1.0" in text
    assert "extra_metric 1" in text
    assert '# scrape_error source="bad_total" error="RuntimeError"' \
        in text
    assert '# scrape_error source="bad_cb" error="ZeroDivisionError"' \
        in text
    # no torn chunk: the failed metric contributed NOTHING but the
    # comment (no dangling HELP/TYPE header)
    assert "# HELP bad_total" not in text
    assert "# TYPE bad_total" not in text


# ---------------------------------------------------------------------------
# two-process serve app: one req_id spans handle + replica
# ---------------------------------------------------------------------------

def test_request_spans_stitch_across_processes(tmp_path):
    """The handle's producer span (driver pid) and the replica's
    consumer span (worker pid) must share one `req:<id>` flow id, and
    collect()+to_chrome() must emit the s->f arrow pair across the
    process boundary."""
    trace_dir = str(tmp_path / "traces")
    os.environ["RAY_TPU_TRACE"] = "1"
    os.environ["RAY_TPU_TRACE_DIR"] = trace_dir
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.util import tracing

    tracing._reset_writer()
    rr._reset_shard_writer()
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        @serve.deployment
        def echo(x):
            return x

        handle = serve.run(echo.bind())
        assert handle.remote(7).result(timeout=60) == 7
        time.sleep(0.5)  # line-buffered shard flush
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_TRACE", None)
        os.environ.pop("RAY_TPU_TRACE_DIR", None)
        tracing._reset_writer()
        rr._reset_shard_writer()

    spans = tracing.collect(trace_dir)
    prod = [s for s in spans if s["name"] == "serve.echo.request"]
    cons = [s for s in spans if s["name"] == "replica.handle_request"]
    assert prod and cons, [s["name"] for s in spans]
    flow = prod[0]["attrs"]["flow_id"]
    assert flow.startswith("req:")
    assert cons[0]["attrs"]["flow_id"] == flow
    assert cons[0]["attrs"]["req_id"] == prod[0]["attrs"]["req_id"]
    assert prod[0]["pid"] != cons[0]["pid"]  # crossed processes

    events = tracing.to_chrome(spans)
    starts = [e for e in events
              if e.get("ph") == "s" and e.get("id") == flow]
    finishes = [e for e in events
                if e.get("ph") == "f" and e.get("id") == flow]
    assert len(starts) == 1 and len(finishes) >= 1
    assert starts[0]["pid"] != finishes[0]["pid"]

    # the handle also shed a client record shard for the same request
    recs = rr.collect(trace_dir)
    mine = [r for r in recs
            if r["req_id"] == prod[0]["attrs"]["req_id"]]
    assert mine and mine[0]["role"] == "client"
    assert mine[0]["outcome"] == "ok"
    assert mine[0]["deployment"] == "echo"

    # and the unified timeline carries the serve-request row
    from ray_tpu.util.timeline import unified_timeline

    merged = unified_timeline(trace_dir=trace_dir, include_tasks=False)
    assert any(e.get("cat") == "serve_request" for e in merged)


# ---------------------------------------------------------------------------
# tsdb: the metrics time-series plane
# ---------------------------------------------------------------------------

def test_parse_prometheus_text_labels_and_escapes():
    text = (
        "# HELP x about\n"
        "# TYPE x counter\n"
        "serve_x_total 3\n"
        'serve_y{job="a,b",name="quo\\"te"} 1.5\n'
        "malformed line without value x\n"
    )
    samples = tsdb_mod.parse_prometheus_text(text)
    assert ("serve_x_total", {}, 3.0) in samples
    assert ("serve_y", {"job": "a,b", "name": 'quo"te'}, 1.5) in samples
    assert len(samples) == 2  # comments + malformed dropped


def test_tsdb_bounded_series_and_points():
    db = tsdb_mod.TSDB(max_series=2, max_points=3, prefixes=("serve_",))
    for i in range(5):
        db.ingest(f"serve_a 1\nserve_b 2\nserve_c 3\nother {i}\n",
                  source="t", ts=float(i))
    # third serve_ series dropped (bound), non-prefixed never admitted
    assert len(db.series()) == 2
    assert db.dropped_series == 5
    # per-series ring trimmed to max_points, newest kept
    assert [t for t, _ in db.points("serve_a", source="t")] == \
        [2.0, 3.0, 4.0]
    assert db.latest("serve_b") == 2.0


def test_rate_computes_per_second_and_clamps_resets():
    db = tsdb_mod.TSDB(max_series=4, max_points=16, prefixes=("serve_",))
    for i, v in enumerate((0, 10, 20, 30)):
        db.ingest(f"serve_reqs_total {v}\n", source="t", ts=float(i))
    assert db.rate("serve_reqs_total", window_s=10.0) == \
        pytest.approx(10.0)
    # counter reset (daemon restart) reads as quiet, never negative
    db.ingest("serve_reqs_total 0\n", source="t", ts=4.0)
    assert db.rate("serve_reqs_total", window_s=10.0) == 0.0


def test_histogram_quantile_interpolates():
    db = tsdb_mod.TSDB(max_series=8, max_points=4, prefixes=())
    db.ingest(
        'lat_bucket{le="1.0"} 0\n'
        'lat_bucket{le="2.0"} 5\n'
        'lat_bucket{le="+Inf"} 10\n',
        source="t", ts=1.0)
    # q=0.5 -> target 5 falls exactly at the le=2.0 bucket edge
    assert tsdb_mod.histogram_quantile(db, "lat", 0.5) == \
        pytest.approx(2.0)
    # mass beyond the last finite bound reports that bound
    assert tsdb_mod.histogram_quantile(db, "lat", 0.99) == \
        pytest.approx(2.0)
    # q=0.25 -> target 2.5, linear inside (1.0, 2.0]
    assert tsdb_mod.histogram_quantile(db, "lat", 0.25) == \
        pytest.approx(1.5)


def test_scrape_local_feeds_request_histograms():
    rr.record_engine(None, ts=0.0, total_ms=9.0, prefill_ms=5.0,
                     decode_ms=4.0, ttft_ms=5.5, tpot_ms=1.3)
    db = tsdb_mod.TSDB(max_series=128, max_points=8)
    assert tsdb_mod.scrape_local(db, ts=1.0) > 0
    q50 = tsdb_mod.histogram_quantile(db, "serve_ttft_ms", 0.5,
                                      source="local")
    assert q50 is not None and 0 < q50 <= 10.0
    snap = db.snapshot()
    assert snap["scrapes"] == 1
    assert any(s["name"].startswith("serve_") for s in snap["series"])


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------

def test_cli_requests_offline(tmp_path, capsys):
    trace_dir = str(tmp_path / "traces")
    os.environ["RAY_TPU_TRACE"] = "1"
    os.environ["RAY_TPU_TRACE_DIR"] = trace_dir
    rr._reset_shard_writer()
    try:
        for i in range(5):
            ctx = rr.new_context("chat", job="tenant-a")
            rr.record_engine(ctx, ts=float(i),
                             total_ms=10.0 * (i + 1),
                             prefill_ms=6.0 * (i + 1),
                             decode_ms=4.0 * (i + 1),
                             ttft_ms=7.0, tpot_ms=1.5, tokens_out=4)
    finally:
        os.environ.pop("RAY_TPU_TRACE", None)
        os.environ.pop("RAY_TPU_TRACE_DIR", None)
        rr._reset_shard_writer()

    from ray_tpu.scripts.cli import main

    main(["requests", "--trace-dir", trace_dir, "--last", "3"])
    out = capsys.readouterr().out
    assert "phase attribution" in out
    assert "chat" in out and "tenant-a" in out

    main(["requests", "--trace-dir", trace_dir, "--slow", "2",
          "--json"])
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    assert len(lines) == 2
    assert json.loads(lines[0])["total_ms"] == 50.0  # worst first
