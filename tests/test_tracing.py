"""Tracing tests: spans around submit/execute stitch into one trace.

Reference ground: `python/ray/tests/test_tracing.py` — remote task and
actor-method calls produce `.remote` (producer) and `.execute`
(consumer) spans that share a trace id across processes.
"""

import os

import pytest


def test_task_and_actor_spans(tmp_path):
    trace_dir = str(tmp_path / "traces")
    os.environ["RAY_TPU_TRACE"] = "1"
    os.environ["RAY_TPU_TRACE_DIR"] = trace_dir
    import ray_tpu
    from ray_tpu.util import tracing

    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def traced_fn(x):
            return x * 2

        assert ray_tpu.get(traced_fn.remote(21)) == 42

        @ray_tpu.remote
        class TracedActor:
            def method(self, x):
                return x + 1

        a = TracedActor.remote()
        assert ray_tpu.get(a.method.remote(1)) == 2
        ray_tpu.kill(a)
        import time

        time.sleep(0.5)  # line-buffered shard flush
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_TRACE", None)
        os.environ.pop("RAY_TPU_TRACE_DIR", None)

    spans = tracing.collect(trace_dir)
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)

    # producer span on the driver, consumer span in the worker process,
    # linked by trace_id + parent_id
    assert "traced_fn.remote" in by_name
    assert "traced_fn.execute" in by_name
    sub = by_name["traced_fn.remote"][0]
    ex = by_name["traced_fn.execute"][0]
    assert ex["trace_id"] == sub["trace_id"]
    assert ex["parent_id"] == sub["span_id"]
    assert ex["pid"] != sub["pid"]  # crossed a process boundary
    assert ex["attrs"]["task_type"] == "normal"

    # actor method call traced the same way
    assert "method.remote" in by_name and "method.execute" in by_name
    m_sub = by_name["method.remote"][0]
    m_ex = by_name["method.execute"][0]
    assert m_ex["trace_id"] == m_sub["trace_id"]
    assert m_ex["attrs"]["task_type"] == "actor"

    # chrome export is well-formed
    events = tracing.to_chrome(spans)
    assert any(e["ph"] == "X" for e in events)
    assert any(e["ph"] == "s" for e in events)  # flow arrows


def test_tracing_disabled_is_free(tmp_path):
    """With tracing off, no shard files appear and spans are no-ops."""
    from ray_tpu.util import tracing

    os.environ.pop("RAY_TPU_TRACE", None)
    os.environ["RAY_TPU_TRACE_DIR"] = str(tmp_path / "none")
    try:
        with tracing.span("x") as s:
            assert s == {}
        assert tracing.current_context() is None
        assert not os.path.exists(str(tmp_path / "none"))
    finally:
        os.environ.pop("RAY_TPU_TRACE_DIR", None)
