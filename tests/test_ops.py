"""Pallas kernel correctness (interpret mode on the CPU mesh).

The flash-attention kernel must agree with the dense XLA reference
(`full_attention`) in both forward and backward — same contract the
sharded attention variants are held to in test_parallel.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.flash_attention import _flash
from ray_tpu.parallel.ring_attention import full_attention


def _qkv(b=2, t=256, h=4, d=64, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    return mk(), mk(), mk()


def _flash_bthd(q, k, v, causal, block_q=128, block_k=128):
    # test through the raw kernel with interpret=True (public wrapper
    # only engages the kernel on real TPU)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    group = q.shape[2] // k.shape[2]
    out = _flash(qt, kt, vt, q.shape[-1] ** -0.5, causal, block_q,
                 block_k, group, True)
    return out.transpose(0, 2, 1, 3)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_matches_dense(causal):
    q, k, v = _qkv()
    ref = full_attention(q, k, v, causal=causal)
    got = _flash_bthd(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grads_match_dense(causal):
    q, k, v = _qkv()

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    def loss_fl(q, k, v):
        return jnp.sum(_flash_bthd(q, k, v, causal) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        scale = float(jnp.abs(a).max())
        np.testing.assert_allclose(np.asarray(b) / scale,
                                   np.asarray(a) / scale, atol=1e-5)


@pytest.mark.parametrize("h_kv", [1, 2])
def test_flash_gqa_matches_expanded_dense(h_kv):
    """Grouped-query attention through the kernel's KV index map must
    equal dense attention over query-side-expanded KV — forward and
    both KV gradients (dK/dV accumulate across each head group)."""
    rng = np.random.default_rng(3)
    b, t, h, d = 2, 256, 4, 64
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, h_kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, h_kv, d)), jnp.float32)
    group = h // h_kv

    def expand(x):
        return jnp.repeat(x, group, axis=2)

    ref = full_attention(q, expand(k), expand(v), causal=True)
    got = _flash_bthd(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, expand(k), expand(v),
                                      causal=True) ** 2)

    def loss_fl(q, k, v):
        return jnp.sum(_flash_bthd(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gf):
        scale = float(jnp.abs(a).max())
        np.testing.assert_allclose(np.asarray(b_) / scale,
                                   np.asarray(a) / scale, atol=1e-5)


def test_gqa_autoexpand_in_dense_path():
    """full_attention accepts unexpanded GQA KV directly (the Llama
    block passes n_kv_head KV to any attention_fn)."""
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((2, 64, 4, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 64, 2, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 64, 2, 32)), jnp.float32)
    ref = full_attention(q, jnp.repeat(k, 2, axis=2),
                         jnp.repeat(v, 2, axis=2), causal=True)
    got = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref))


def test_flash_block_q_shapes():
    # uneven T falls back to the dense path inside the public wrapper
    from ray_tpu.ops import flash_attention
    q, k, v = _qkv(t=192)  # not divisible by 128
    ref = full_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_flash_in_gpt_model():
    # the model accepts the kernel as its attention_fn (bench wiring)
    from functools import partial
    from ray_tpu.models import GPT, GPTConfig
    from ray_tpu.ops.flash_attention import flash_attention as fa

    cfg = GPTConfig.tiny()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 128)))
    dense = GPT(cfg)
    params = dense.init(jax.random.PRNGKey(0), tokens)
    out_dense = dense.apply(params, tokens)
    flash = GPT(cfg, attention_fn=partial(fa, causal=True))
    out_flash = flash.apply(params, tokens)
    # off-TPU the wrapper falls back to dense — outputs must be identical
    np.testing.assert_allclose(np.asarray(out_flash),
                               np.asarray(out_dense), atol=1e-5)


# --------------------------------------------------------------------------
# fused LM-head cross-entropy
# --------------------------------------------------------------------------

def test_fused_ce_matches_reference():
    from ray_tpu.models.gpt import cross_entropy_loss
    from ray_tpu.ops import fused_cross_entropy

    rng = np.random.default_rng(1)
    B, T, D, V = 2, 64, 32, 512
    h = jnp.asarray(rng.standard_normal((B, T, D)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((V, D)), jnp.float32)
    y = np.asarray(rng.integers(0, V, (B, T)), np.int32)
    y[0, :5] = -1  # ignored positions
    y = jnp.asarray(y)

    ref_fn = lambda h, w: cross_entropy_loss(  # noqa: E731
        jnp.einsum("btd,vd->btv", h, w), y)
    fus_fn = lambda h, w: fused_cross_entropy(h, w, y)  # noqa: E731
    np.testing.assert_allclose(float(fus_fn(h, w)), float(ref_fn(h, w)),
                               rtol=1e-5)
    gr = jax.grad(ref_fn, (0, 1))(h, w)
    gf = jax.grad(fus_fn, (0, 1))(h, w)
    for a, b in zip(gr, gf):
        scale = float(jnp.abs(a).max())
        np.testing.assert_allclose(np.asarray(b) / scale,
                                   np.asarray(a) / scale, atol=1e-5)


def test_fused_ce_in_train_step():
    # end-to-end: a tiny GPT trains through the fused head and the loss
    # decreases (the bench.py wiring)
    import optax
    from functools import partial
    from ray_tpu.models import GPT, GPTConfig
    from ray_tpu.ops import fused_cross_entropy

    cfg = GPTConfig.tiny()
    model = GPT(cfg)
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 65)))
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    params = model.init(jax.random.PRNGKey(0), inputs)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state):
        def loss_fn(p):
            hidden, wte = model.apply(p, inputs, return_hidden=True)
            return fused_cross_entropy(hidden, wte, targets)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, first = step(params, opt_state)
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state)
    assert float(loss) < float(first)


@pytest.mark.parametrize("bq,bk", [(128, 256), (256, 128)])
def test_flash_asymmetric_blocks(bq, bk):
    """Chunked-KV online softmax with block_q != block_k (the causal
    chunk-skip predicate must be right for partial diagonal overlaps)."""
    q, k, v = _qkv(t=512, seed=9)

    ref = full_attention(q, k, v, causal=True)
    got = _flash_bthd(q, k, v, causal=True, block_q=bq, block_k=bk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    def loss_fl(q, k, v):
        return jnp.sum(_flash_bthd(q, k, v, causal=True,
                                   block_q=bq, block_k=bk) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gf):
        scale = float(jnp.abs(a).max())
        np.testing.assert_allclose(np.asarray(b_) / scale,
                                   np.asarray(a) / scale, atol=1e-5)


def test_flash_gqa_with_asymmetric_blocks():
    """The riskiest composition: GQA head-group folding in the dK/dV
    kernel (hk*group + jj//nq index arithmetic) together with
    block_q != block_k causal skipping."""
    rng = np.random.default_rng(11)
    b, t, h, h_kv, d = 2, 512, 4, 2, 64
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, h_kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, h_kv, d)), jnp.float32)

    def expand(x):
        return jnp.repeat(x, h // h_kv, axis=2)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, expand(k), expand(v),
                                      causal=True) ** 2)

    def loss_fl(q, k, v):
        return jnp.sum(_flash_bthd(q, k, v, causal=True,
                                   block_q=128, block_k=256) ** 2)

    ref = full_attention(q, expand(k), expand(v), causal=True)
    got = _flash_bthd(q, k, v, causal=True, block_q=128, block_k=256)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gf):
        scale = float(jnp.abs(a).max())
        np.testing.assert_allclose(np.asarray(b_) / scale,
                                   np.asarray(a) / scale, atol=1e-5)
