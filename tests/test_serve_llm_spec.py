"""serve.llm perf-plane tests: copy-on-write prefix caching, chunked
prefill, and speculative decoding.

The load-bearing properties:
  * shared pages are refcounted — a sequence freeing aliased pages can
    never force-free pages the prefix cache (or a sibling sequence)
    still references, and a page re-enters the free list only at
    refcount zero;
  * only FULL pages are ever aliased (a partial page's tail is still
    appended to), and the page holding the last prompt token is never
    aliased (its forward pass produces the first output token);
  * chunked prefill and speculative decoding are INVISIBLE in the
    output: token streams bit-match plain one-shot greedy for both
    model families, and accept-length variation never retraces.
"""

import numpy as np
import pytest


def _cache(**kw):
    from ray_tpu.serve.llm import PagedKVCache
    base = dict(num_pages=16, n_layer=2, block_size=4, n_kv_head=2,
                head_dim=4)
    base.update(kw)
    return PagedKVCache(**base)


def _prefix(kv):
    from ray_tpu.serve.llm import PrefixCache
    return PrefixCache(kv)


# ---------------------------------------------------------------------------
# prefix cache: aliasing + refcount accounting (no jax, no cluster)
# ---------------------------------------------------------------------------


def test_prefix_cache_hit_and_miss():
    kv = _cache()
    pc = _prefix(kv)
    prompt = list(range(100, 110))  # 10 tokens, block 4 -> 2 full pages
    a = object()
    pages_a, cached = pc.acquire(prompt, a, kv.pages_for_tokens(10))
    assert cached == 0  # cold cache: pure miss
    pc.insert(prompt, pages_a)
    assert pc.stats()["misses"] == 1 and pc.stats()["hits"] == 0
    # same prompt again: both full pages alias, only the tail page is new
    b = object()
    pages_b, cached = pc.acquire(prompt, b, kv.pages_for_tokens(10))
    assert cached == 8
    assert pages_b[:2] == pages_a[:2]      # aliased page ids
    assert pages_b[2] != pages_a[2]        # private tail page
    # page 0 backs BOTH registered sub-prefixes (4- and 8-token) plus
    # the two sequences — every hold is an independent refcount
    assert kv.page_refcount(pages_a[0]) == 4
    # a different prompt with the same first page: 1-page hit
    other = prompt[:4] + [999] * 6
    c = object()
    pages_c, cached = pc.acquire(other, c, kv.pages_for_tokens(10))
    assert cached == 4 and pages_c[0] == pages_a[0]
    st = pc.stats()
    assert st["hits"] == 2 and st["hit_tokens"] == 12
    assert st["miss_tokens"] == 10 + 2 + 6


def test_prefix_partial_page_boundary_never_aliased():
    kv = _cache()
    pc = _prefix(kv)
    a = object()
    prompt = list(range(7))  # 1 full page + 3 tokens
    pages, cached = pc.acquire(prompt, a, kv.pages_for_tokens(7))
    pc.insert(prompt, pages)
    # only the full page was registered — the partial page is mutable
    # (its tail is still appended to) and must stay private
    assert pc.entries == 1
    b = object()
    pages_b, cached = pc.acquire(prompt, b, kv.pages_for_tokens(7))
    assert cached == 4
    assert pages_b[1] != pages[1]
    # a prompt that IS page-aligned never aliases its own last page:
    # at least one suffix token must run prefill for next-logits
    aligned = list(range(50, 58))  # exactly 2 pages
    c, d = object(), object()
    pages_c, _ = pc.acquire(aligned, c, kv.pages_for_tokens(8))
    pc.insert(aligned, pages_c)
    pages_d, cached = pc.acquire(aligned, d, kv.pages_for_tokens(8))
    assert cached == 4  # NOT 8: the last page holds the last token
    assert pages_d[1] != pages_c[1]


def test_aliased_free_keeps_shared_pages():
    """The bugfix: freeing a sequence that aliased cached pages must
    not force-free pages still referenced by the prefix cache or by
    another running sequence (the pre-refcount free path released a
    page to the free list unconditionally — a sibling's next alloc
    would then scribble over live cached K/V)."""
    from ray_tpu.serve.llm import KVCacheError
    kv = _cache()
    pc = _prefix(kv)
    a, b = object(), object()
    prompt = list(range(10))
    pages_a, _ = pc.acquire(prompt, a, 3)
    pc.insert(prompt, pages_a)
    pages_b, cached = pc.acquire(prompt, b, 3)
    assert cached == 8
    shared = pages_b[:2]
    kv.write_prefill(pages_a, np.ones((8, 2, 2, 4), np.float32),
                     np.ones((8, 2, 2, 4), np.float32), 8)
    free_before = kv.free_pages
    kv.free(pages_a, a)
    # shared pages survive a's free (cache + b still hold them) and the
    # bytes are untouched; only a's private tail page was released
    assert kv.free_pages == free_before + 1
    for p in shared:
        assert kv.page_refcount(p) >= 2  # b + at least one cache entry
        assert float(kv.k_pages[p].sum()) > 0
    # double free by the same (gone) owner raises, releases nothing
    with pytest.raises(KVCacheError, match="not held by owner"):
        kv.free(pages_a, a)
    assert kv.free_pages == free_before + 1
    kv.free(pages_b, b)
    assert kv.page_refcount(shared[0]) == 2  # the 2 cache entries pin it
    kv.assert_quiesced()  # cached pages are not leaks
    pc.drain()
    assert kv.free_pages == kv.num_pages
    assert kv.close() == 0


def test_refcount_zero_reuse():
    """A page re-enters the free list only when its LAST holder lets
    go — in either order (sequence first or cache first)."""
    kv = _cache(num_pages=4)
    pc = _prefix(kv)
    a = object()
    prompt = list(range(8))
    pages, _ = pc.acquire(prompt, a, 2)
    pc.insert(prompt, pages)
    page0 = pages[0]
    # cache entry evicted while the sequence still runs: page survives
    pc._evict_for_locked  # (exercised via drain below on live refs)
    pc.drain()
    assert kv.page_refcount(page0) == 1
    assert page0 not in kv._free
    kv.free(pages, a)
    assert page0 in kv._free


def test_lru_eviction_under_arena_pressure():
    """Allocation shortfall evicts COLD prefixes oldest-first; a
    just-hit prefix is MRU and survives; pages a live sequence shares
    survive their entry's eviction."""
    kv = _cache(num_pages=6)
    pc = _prefix(kv)
    owners = [object(), object()]
    p1 = list(range(0, 8))     # 2 pages
    p2 = list(range(100, 108))  # 2 pages
    pages1, _ = pc.acquire(p1, owners[0], 2)
    pc.insert(p1, pages1)
    kv.free(pages1, owners[0])
    pages2, _ = pc.acquire(p2, owners[1], 2)
    pc.insert(p2, pages2)
    kv.free(pages2, owners[1])
    assert kv.free_pages == 2 and pc.entries >= 2
    # touch p2 (a hit) so p1 becomes LRU
    toucher = object()
    pt, cached = pc.acquire(p2, toucher, 2)
    assert cached == 4
    kv.free(pt, toucher)
    # demand 4 pages: only 2-3 free -> the p1 entries evict, p2 stays
    big = kv.alloc(4, "big")
    assert len(big) == 4
    assert pc.stats()["evicted"] >= 1
    survivor = object()
    _, cached = pc.acquire(p2, survivor, 2)
    assert cached == 4  # MRU entry survived the pressure


def test_assert_quiesced_with_cached_prefixes():
    """A populated prefix cache is quiesced state, not a leak — but a
    live sequence holder still trips the gate; close() after drain
    reports zero."""
    from ray_tpu.serve.llm import KVCacheError
    kv = _cache()
    pc = _prefix(kv)
    a = object()
    prompt = list(range(12))
    pages, _ = pc.acquire(prompt, a, 3)
    pc.insert(prompt, pages)
    with pytest.raises(KVCacheError, match="leak"):
        kv.assert_quiesced()  # the sequence itself is live
    kv.free(pages, a)
    kv.assert_quiesced()      # cache-only holds: quiesced
    # 12 tokens = 3 full pages, all cache-pinned (4/8/12-token entries)
    assert kv.cached_pages == 3 and kv.live_pages == 0
    pc.drain()
    assert kv.close() == 0


# ---------------------------------------------------------------------------
# engine: chunked prefill + speculative decoding equivalence (jax cpu)
# ---------------------------------------------------------------------------


def _perturbed_draft(params, seed=99, scale=1.0):
    """A draft that mostly-but-not-always agrees with the target:
    target weights + noise. (Two independently-initialized tiny
    tied-head models agree on argmax almost everywhere — the embedding
    similarity term dominates — so disagreement has to be injected
    around the target's own weights to scatter accept lengths.)"""
    import jax
    import jax.numpy as jnp
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(leaves))
    pert = [l + scale * jnp.std(l) * jax.random.normal(k, l.shape)
            for l, k in zip(leaves, keys)]
    return jax.tree_util.tree_unflatten(treedef, pert)


def _adversarial_draft(params):
    """A draft that structurally DISAGREES with the target: the
    embedding table is rolled one row, so the draft's tied head scores
    a shifted vocabulary — rejection-heavy rounds exercise the
    accept-length-0 path (one target token per round, like plain
    decode but through the verify window)."""
    import jax
    import jax.numpy as jnp

    def roll_wte(path, leaf):
        if any(getattr(p, "key", None) == "wte" for p in path):
            return jnp.roll(leaf, 1, axis=0)
        return leaf

    return jax.tree_util.tree_map_with_path(roll_wte, params)


def _reference_greedy(engine, prompt, max_new):
    import jax.numpy as jnp
    mod = engine._mod
    cfg = engine.model_cfg
    net = (mod.Llama if engine.model_name == "llama" else mod.GPT)(cfg)
    toks = list(prompt)
    out = []
    for _ in range(max_new):
        logits = net.apply(engine.params,
                           jnp.asarray([toks], jnp.int32))
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
    return out


def _engine(model="llama", **cfg_kw):
    from ray_tpu.serve.llm import EngineConfig, LLMEngine
    base = dict(batch_buckets=(1, 2), prefill_buckets=(8, 16),
                block_size=4)
    base.update(cfg_kw)
    eng = LLMEngine(model=model, engine_config=EngineConfig(**base),
                    seed=0)
    eng.warmup()
    return eng


def test_chunked_prefill_matches_oneshot():
    """A prompt longer than every prefill bucket windows in chunk by
    chunk and yields exactly the one-shot math's tokens (the chunk
    kernel attends cached pages + the causal window — same einsums,
    same mask floor). Short prompts on the same engine still take the
    one-shot bucket path."""
    rng = np.random.RandomState(3)
    eng = _engine(prefill_chunk=8, prefix_cache=0)
    try:
        long_p = list(rng.randint(1, 500, size=27))   # > max bucket 16
        short_p = list(rng.randint(1, 500, size=5))
        r_long = eng.submit(long_p, 6)
        r_short = eng.submit(short_p, 6)
        eng.run_until_idle(timeout=120)
        assert r_long.result(timeout=10) == \
            _reference_greedy(eng, long_p, 6)
        assert r_short.result(timeout=10) == \
            _reference_greedy(eng, short_p, 6)
        m = eng.metrics()
        assert m["chunk_steps"] >= 4  # 27 tokens / 8-wide windows
        eng.quiesce()
    finally:
        assert eng.shutdown() == 0


def test_prefix_cache_reuse_in_engine():
    """Requests sharing a long prefix prefill only their suffix after
    the first; outputs are identical to the cold path and the arena
    quiesces with the cache still populated (then drains at
    shutdown)."""
    rng = np.random.RandomState(4)
    shared = list(rng.randint(1, 500, size=13))
    prompts = [shared + list(rng.randint(1, 500, size=3))
               for _ in range(3)]
    cold = _engine(prefix_cache=0)
    try:
        reqs = [cold.submit(p, 5) for p in prompts]
        cold.run_until_idle(timeout=120)
        want = [r.result(timeout=10) for r in reqs]
        cold.quiesce()
    finally:
        assert cold.shutdown() == 0
    eng = _engine(prefix_cache=1)
    try:
        reqs = [eng.submit(p, 5) for p in prompts]
        eng.run_until_idle(timeout=120)
        assert [r.result(timeout=10) for r in reqs] == want
        m = eng.metrics()
        # 13-token shared prefix = 3 full pages (block 4): requests 2+3
        # alias them instead of recomputing
        assert m["prefix_cache_hits"] == 2
        assert m["prefix_cache_hit_tokens"] == 24
        assert m["kv_pages_cached"] > 0
        eng.quiesce()                       # cached pages != leaks
        assert m["kv_pages_live"] == 0
        text = eng._metrics_text()
        assert "serve_llm_prefix_cache_hit_tokens_total" in text
        assert "serve_llm_kv_pages_cached" in text
        assert "serve_llm_compiled_step_calls_total" in text
    finally:
        assert eng.shutdown() == 0          # drain happens here


@pytest.mark.parametrize("model", ["llama", "gpt"])
def test_speculative_bitmatch_plain_greedy(model):
    """Greedy speculative output == plain greedy token-for-token, for
    both a self-draft (accepts everything) and an INDEPENDENT draft
    (random weights — most proposals rejected), for both families."""
    rng = np.random.RandomState(5)
    prompts = [list(rng.randint(1, 500, size=n)) for n in (4, 9, 14)]
    plain = _engine(model=model, spec_k=0, prefix_cache=0)
    try:
        reqs = [plain.submit(p, 7) for p in prompts]
        plain.run_until_idle(timeout=120)
        want = [r.result(timeout=10) for r in reqs]
        plain.quiesce()
    finally:
        assert plain.shutdown() == 0

    for perturbed in (False, True):  # False -> self-draft
        from ray_tpu.serve.llm import EngineConfig, LLMEngine
        eng = LLMEngine(model=model, engine_config=EngineConfig(
            batch_buckets=(1, 2), prefill_buckets=(8, 16),
            block_size=4, spec_k=3, prefix_cache=0), seed=0)
        if perturbed:
            # structurally-disagreeing draft (rolled embedding):
            # proposals diverge from the target's argmaxes, so rounds
            # run rejection-heavy — the accept-length-0 path
            eng.draft_params = _adversarial_draft(eng.params)
        eng.warmup()
        try:
            reqs = [eng.submit(p, 7) for p in prompts]
            eng.run_until_idle(timeout=180)
            got = [r.result(timeout=10) for r in reqs]
            assert got == want, f"perturbed={perturbed}"
            m = eng.metrics()
            assert m["spec_rounds"] > 0
            if not perturbed:
                # self-draft proposals are the target's own argmaxes
                assert m["spec_accepted"] == m["spec_proposed"]
            else:
                assert m["spec_accepted"] < m["spec_proposed"]
            eng.quiesce()
        finally:
            assert eng.shutdown() == 0


def test_spec_zero_retrace_across_accept_lengths():
    """Accept-length variation must bucket, never retrace: after
    warmup, a burst whose accept lengths scatter (independent draft)
    adds ZERO compile-cache misses and zero retraces — the draft loop
    varies only its host-side dispatch count, and the verify window is
    always K+1 wide."""
    from ray_tpu import parallel
    from ray_tpu.serve.llm import EngineConfig, LLMEngine

    eng = LLMEngine(
        model="llama",
        engine_config=EngineConfig(
            batch_buckets=(1, 2), prefill_buckets=(8, 16),
            block_size=4, spec_k=3, prefix_cache=1),
        seed=0)
    eng.draft_params = _perturbed_draft(eng.params, seed=77)
    eng.warmup()
    try:
        rng = np.random.RandomState(6)
        # shapes seen once -> compiled
        warm = [eng.submit(list(rng.randint(1, 500, size=5)), 6)
                for _ in range(3)]
        eng.run_until_idle(timeout=180)
        [r.result(timeout=10) for r in warm]
        before = parallel.cache_stats()
        reqs = [eng.submit(list(rng.randint(1, 500, size=n)), 8)
                for n in (3, 7, 6, 4)]
        eng.run_until_idle(timeout=180)
        [r.result(timeout=10) for r in reqs]
        after = parallel.cache_stats()
        assert after["retraces"] == before["retraces"]
        assert after["misses"] == before["misses"]
        assert after["hits"] > before["hits"]
        m = eng.metrics()
        # the burst's rounds really did scatter accept lengths
        assert 0 < m["spec_accepted"] < m["spec_proposed"]
        eng.quiesce()
    finally:
        assert eng.shutdown() == 0
