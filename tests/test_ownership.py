"""Ownership GC + lineage recovery: the distributed ref-counting plane.

The submitting worker owns its returns (reference: `reference_count.h:61`,
ownership design from the NSDI '21 paper): local refs pin the object,
tasks borrow their by-ref args for their lifetime, remote workers that
deserialize a ref register as borrowers, and the owner frees the primary
shm copy the moment every count hits zero. Loss of the primary copy
re-executes the producing task from recorded lineage
(`task_manager.h:208`), recursively for missing upstream inputs, with
`ObjectLostError` on the unreconstructable paths. This suite runs under
lockdep (see conftest `_LOCKDEP_SUITES`): the ref-table lock joins the
order graph in every test.
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.node import Cluster
from ray_tpu._private.object_ref import get_core_worker

# this machine populates big shm arenas slowly; small stores keep the
# cluster spin-up inside the suite budget without changing semantics
_STORE = 64 * 1024 * 1024


def _poll(pred, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return pred()


def _ref_table_empty(cw):
    with cw._ref_lock:
        return (not cw._local_refs and not cw._task_arg_refs
                and not any(cw._borrowers.values())
                and not cw._borrowed_refs)


# ---------------------------------------------------------------------------
# ref-count lifecycle
# ---------------------------------------------------------------------------


def test_local_ref_release_frees_store_copy():
    """Dropping the last local handle drives the owner's count to zero:
    the pin is released and the raylet force-deletes the shm slot (not
    leak-or-LRU — the owner decides)."""
    ray_tpu.init(num_cpus=2, object_store_memory=_STORE)
    try:
        cw = get_core_worker()
        freed_before = cw._stats_objects_freed
        ref = ray_tpu.put(np.arange(1_000_000, dtype=np.uint8))
        oid = ref.binary()
        assert _poll(lambda: oid in cw._pinned_at, 10), \
            "pin never recorded at the owner"
        del ref
        gc.collect()
        assert _poll(lambda: oid not in cw._pinned_at
                     and oid not in cw._local_refs), \
            "owner never released the zero-ref object"
        assert _poll(lambda: cw._stats_objects_freed > freed_before)
    finally:
        ray_tpu.shutdown()


def test_task_return_release_frees_store_copy():
    """Task plasma returns follow the same lifecycle: owner frees the
    executor-pinned copy when the driver's last handle dies."""
    ray_tpu.init(num_cpus=2, object_store_memory=_STORE)
    try:
        cw = get_core_worker()

        @ray_tpu.remote
        def produce():
            return np.full(500_000, 7, np.uint8)

        ref = produce.remote()
        assert ray_tpu.get(ref, timeout=30)[0] == 7
        oid = ref.binary()
        del ref
        gc.collect()
        assert _poll(lambda: oid not in cw._pinned_at
                     and oid not in cw._local_refs), \
            "task-return pin leaked after the last deref"
        # lineage goes with the last reference
        assert _poll(lambda: oid not in cw._lineage_oids)
    finally:
        ray_tpu.shutdown()


def test_borrower_keeps_object_alive_across_worker(ray_start):
    """A ref pickled into another worker's args registers that worker as
    a borrower with the owner; the object survives the owner dropping
    its own handle until the borrower's last deref releases the edge."""
    cw = get_core_worker()

    @ray_tpu.remote
    class Holder:
        def hold(self, refs):
            self.ref = refs[0]  # keep the deserialized borrow alive
            return True

        def read(self):
            return int(ray_tpu.get(self.ref)[123])

        def drop(self):
            self.ref = None
            gc.collect()
            return True

    holder = Holder.remote()
    ref = ray_tpu.put(np.arange(600_000, dtype=np.uint8) % 251)
    oid = ref.binary()
    expected = int((np.arange(600_000, dtype=np.uint8) % 251)[123])
    # nested in a list → rides the borrower protocol, not top-level
    # arg resolution
    assert ray_tpu.get(holder.hold.remote([ref]), timeout=30)
    assert _poll(lambda: cw._borrowers.get(oid), 15), \
        "borrower edge never registered with the owner"

    del ref
    gc.collect()
    time.sleep(1.0)  # give a buggy release a chance to fire
    # the borrow must keep the object readable
    assert ray_tpu.get(holder.read.remote(), timeout=30) == expected

    assert ray_tpu.get(holder.drop.remote(), timeout=30)
    assert _poll(lambda: not cw._borrowers.get(oid)
                 and oid not in cw._pinned_at), \
        "owner never freed after the last borrower released"


def test_zero_leaked_refs_at_quiesce(ray_start):
    """After a workload of puts, ref args, nested refs and chains, the
    owner's entire ref table drains to zero — no leaked counts, no
    stranded pins, no lineage for dead objects."""
    cw = get_core_worker()

    @ray_tpu.remote
    def produce(i):
        return np.full(300_000, i, np.uint8)

    @ray_tpu.remote
    def consume(x):
        return int(x.astype(np.uint64).sum())

    @ray_tpu.remote
    def consume_nested(d):
        return int(ray_tpu.get(d["ref"]).astype(np.uint64).sum())

    puts = [ray_tpu.put(np.full(200_000, i, np.uint8)) for i in range(3)]
    stage1 = [produce.remote(i) for i in range(4)]
    stage2 = [consume.remote(r) for r in stage1]
    nested = [consume_nested.remote({"ref": r}) for r in puts]
    assert ray_tpu.get(stage2, timeout=60) == [300_000 * i
                                               for i in range(4)]
    assert ray_tpu.get(nested, timeout=60) == [200_000 * i
                                               for i in range(3)]
    del puts, stage1, stage2, nested
    gc.collect()
    assert _poll(lambda: _ref_table_empty(cw)), (
        "leaked refs at quiesce: locals=%d task_args=%d borrowers=%d"
        % (len(cw._local_refs), len(cw._task_arg_refs),
           sum(1 for v in cw._borrowers.values() if v)))
    assert _poll(lambda: not cw._pinned_at), "stranded pins at quiesce"
    assert _poll(lambda: not cw._lineage and cw._lineage_bytes == 0), \
        "lineage retained for fully-released objects"


# ---------------------------------------------------------------------------
# loss + reconstruction
# ---------------------------------------------------------------------------


@pytest.fixture
def two_node():
    cluster = Cluster(object_store_memory=_STORE)
    cluster.add_node({"CPU": 2.0})
    victim = cluster.add_node({"CPU": 2.0, "scratch": 1.0})
    ray_tpu.init(address=cluster.gcs_addr)
    yield cluster, victim
    ray_tpu.shutdown()
    cluster.shutdown()


def test_recursive_reconstruction_bit_identical(two_node):
    """Both stages of a chain lived on the dead node: recovering the
    downstream object first re-executes its upstream input, and the
    recovered bytes are identical to a local recompute."""
    cluster, victim = two_node
    affinity = ray_tpu.NodeAffinitySchedulingStrategy(
        victim.node_id_hex, soft=True)

    @ray_tpu.remote(scheduling_strategy=affinity)
    def produce():
        return (np.arange(400_000, dtype=np.uint64) * 2654435761) \
            .astype(np.uint8)

    @ray_tpu.remote(scheduling_strategy=affinity)
    def transform(x):
        return (x.astype(np.uint16) * 3 + 1).astype(np.uint8)

    a = produce.remote()
    b = transform.remote(a)
    ready, _ = ray_tpu.wait([b], timeout=60)  # wait, don't localize
    assert ready

    cluster.remove_node(victim)
    time.sleep(1.0)

    base = (np.arange(400_000, dtype=np.uint64) * 2654435761) \
        .astype(np.uint8)
    expect_b = (base.astype(np.uint16) * 3 + 1).astype(np.uint8)
    out_b = ray_tpu.get(b, timeout=180)
    assert np.array_equal(out_b, expect_b), \
        "reconstructed downstream value is not bit-identical"
    out_a = ray_tpu.get(a, timeout=180)
    assert np.array_equal(out_a, base), \
        "reconstructed upstream value is not bit-identical"
    cw = get_core_worker()
    assert cw._stats_reconstructions >= 2, \
        "chain recovery should have re-executed both stages"


def test_get_lost_object_without_lineage_fails_fast(two_node):
    """Regression (pre-fix: get() on an object whose node died blocked
    until the full timeout with no diagnostic): actor-method returns
    carry no lineage, so loss must raise ObjectLostError promptly —
    well before the caller's timeout — naming why recovery is
    impossible."""
    cluster, victim = two_node
    affinity = ray_tpu.NodeAffinitySchedulingStrategy(
        victim.node_id_hex, soft=False)

    @ray_tpu.remote(scheduling_strategy=affinity)
    class Producer:
        def make(self):
            return np.full(400_000, 5, np.uint8)

    prod = Producer.remote()
    ref = prod.make.remote()
    ready, _ = ray_tpu.wait([ref], timeout=60)
    assert ready

    cluster.remove_node(victim)
    time.sleep(1.0)

    start = time.monotonic()
    with pytest.raises(ray_tpu.ObjectLostError,
                       match="lost|not reconstructable"):
        ray_tpu.get(ref, timeout=120)
    elapsed = time.monotonic() - start
    assert elapsed < 60, (
        f"lost-object get took {elapsed:.0f}s — should fail fast, "
        "not block toward the timeout")


def test_lineage_cap_eviction_marks_unreconstructable():
    """Past max_lineage_bytes the owner evicts oldest lineage and marks
    its returns permanently unreconstructable: loss of such an object
    raises ObjectLostError naming the eviction, while younger objects
    (lineage intact) still recover."""
    cluster = Cluster(object_store_memory=_STORE)
    cluster.add_node({"CPU": 2.0})
    victim = cluster.add_node({"CPU": 2.0, "scratch": 1.0})
    # cap small enough that a handful of specs (~300B each) overflow it
    ray_tpu.init(address=cluster.gcs_addr,
                 _system_config={"max_lineage_bytes": 2048})
    try:
        cw = get_core_worker()
        affinity = ray_tpu.NodeAffinitySchedulingStrategy(
            victim.node_id_hex, soft=True)

        @ray_tpu.remote(scheduling_strategy=affinity)
        def produce(i):
            return np.full(200_000, i, np.uint8)

        refs = [produce.remote(i) for i in range(16)]
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=90)
        assert len(ready) == len(refs)
        assert cw._stats_lineage_evictions > 0, \
            "16 specs against a 2KB cap must evict"
        assert cw._lineage_bytes <= 2048

        cluster.remove_node(victim)
        time.sleep(1.0)

        # oldest spec was evicted → permanent loss, named as such
        with pytest.raises(ray_tpu.ObjectLostError, match="evicted"):
            ray_tpu.get(refs[0], timeout=120)
        # youngest still has lineage → full recovery
        out = ray_tpu.get(refs[-1], timeout=180)
        assert out[0] == 15 and out.shape == (200_000,)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_reconstruction_metrics_exported():
    """The ownership plane lands on /metrics: owned/borrowed gauges and
    reconstruction counters render with # TYPE lines (tsdb plane keys
    off them)."""
    ray_tpu.init(num_cpus=2, object_store_memory=_STORE)
    try:
        from ray_tpu.util.metrics import DEFAULT_REGISTRY

        keep = ray_tpu.put(np.arange(100_000, dtype=np.uint8))
        text = DEFAULT_REGISTRY.prometheus_text()
        for name in ("ray_tpu_owned_refs", "ray_tpu_lineage_bytes",
                     "ray_tpu_reconstructions_total",
                     "ray_tpu_reconstruction_failures_total",
                     "ray_tpu_objects_freed_total"):
            assert f"# TYPE {name}" in text, f"{name} missing # TYPE"
            assert f"\n{name}" in text or text.startswith(name), \
                f"{name} has no sample row"
        del keep
    finally:
        ray_tpu.shutdown()
