"""raylint checker fixtures + the tier-1 repo gate + runtime lockdep.

Each checker gets a known-bad snippet (must be detected) and a known-good
twin (must stay silent) so the analysis can't rot in either direction.
The repo gate (marked `lint`) runs the real CLI over `ray_tpu/` against
the committed baseline — any new violation fails tier-1.
"""

import os
import textwrap
import threading
import time

import pytest

from tools.raylint import analyze_source
from tools.raylint.__main__ import main as raylint_main

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(src, relpath="ray_tpu/serve/fake.py", checks=None):
    kwargs = {"checks": checks} if checks else {}
    return analyze_source(textwrap.dedent(src), relpath, **kwargs)


def checks_of(findings):
    return {f.check for f in findings}


# ---------------------------------------------------------------------------
# checker 1: lock-discipline
# ---------------------------------------------------------------------------

class TestLockDiscipline:
    BAD = """
        import threading

        class Router:
            def __init__(self):
                self._lock = threading.Lock()
                self._replicas = []

            def add(self, r):
                with self._lock:
                    self._replicas.append(r)

            def reset(self):
                self._replicas = []          # write outside the lock
    """

    def test_unguarded_write_detected(self):
        findings = run(self.BAD)
        assert any(f.check == "lock-discipline"
                   and f.detail == "attr:_replicas"
                   and f.scope == "Router.reset" for f in findings), findings

    def test_guarded_write_ok(self):
        findings = run("""
            import threading

            class Router:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._replicas = []

                def add(self, r):
                    with self._lock:
                        self._replicas.append(r)

                def reset(self):
                    with self._lock:
                        self._replicas = []
        """)
        assert "lock-discipline" not in checks_of(findings)

    def test_mutator_call_is_a_write(self):
        findings = run("""
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = []

                def put(self, x):
                    with self._lock:
                        self._q.append(x)

                def drop(self):
                    self._q.clear()
        """)
        assert any(f.detail == "attr:_q" and f.scope == "Q.drop"
                   for f in findings), findings

    def test_init_exempt_until_self_escapes(self):
        src = """
            import threading

            def register(obj):
                pass

            class M:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._state = {}         # fine: pre-publication
                    register(self)           # self escapes here
                    self._state = {"x": 1}   # visible to other threads
                def touch(self):
                    with self._lock:
                        self._state = {}
        """
        findings = run(src)
        bad = [f for f in findings if f.check == "lock-discipline"]
        assert len(bad) == 1 and bad[0].scope == "M.__init__", findings

    def test_locked_suffix_contract_exempt(self):
        findings = run("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def _bump_locked(self):
                    self._n += 1
        """)
        assert "lock-discipline" not in checks_of(findings)

    def test_module_global_guarded(self):
        findings = run("""
            import threading

            _LOCK = threading.Lock()
            _CACHE = {}

            def put(k, v):
                with _LOCK:
                    _CACHE[k] = v

            def clear():
                _CACHE = {}
        """)
        # clear() rebinds a local, not the global — but a global statement
        # or subscript write outside the lock must flag
        findings = run("""
            import threading

            _LOCK = threading.Lock()
            _CACHE = {}

            def put(k, v):
                with _LOCK:
                    _CACHE[k] = v

            def poison(k):
                _CACHE[k] = None
        """)
        assert any(f.detail == "global:_CACHE" and f.scope == "poison"
                   for f in findings), findings


# ---------------------------------------------------------------------------
# checker 2: blocking-under-lock
# ---------------------------------------------------------------------------

class TestBlockingUnderLock:
    def test_sleep_under_lock(self):
        findings = run("""
            import threading
            import time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def spin(self):
                    with self._lock:
                        time.sleep(1)
        """)
        assert any(f.check == "blocking-under-lock"
                   and f.detail == "time.sleep" for f in findings), findings

    def test_transitive_chain_reported(self):
        findings = run("""
            import threading
            import subprocess

            class B:
                def __init__(self):
                    self._lock = threading.Lock()

                def _build(self):
                    subprocess.run(["make"])

                def ensure(self):
                    with self._lock:
                        self._build()
        """)
        hit = [f for f in findings if f.check == "blocking-under-lock"
               and f.scope == "B.ensure"]
        assert hit and "B._build" in hit[0].message, findings

    def test_rpc_and_result_under_lock(self):
        findings = run("""
            import threading
            import ray_tpu

            class P:
                def __init__(self):
                    self._lock = threading.Lock()

                def poll(self, actor, fut):
                    with self._lock:
                        ref = actor.get_metrics.remote()
                        out = ray_tpu.get(ref)
                        val = fut.result()
        """)
        details = {f.detail for f in findings
                   if f.check == "blocking-under-lock"}
        assert {".remote() [RPC send]", "ray_tpu.get",
                ".result()"} <= details, findings

    def test_condition_wait_on_held_lock_ok(self):
        findings = run("""
            import threading

            class W:
                def __init__(self):
                    self._cond = threading.Condition()

                def wait(self):
                    with self._cond:
                        self._cond.wait(1.0)
        """)
        assert "blocking-under-lock" not in checks_of(findings)

    def test_nested_function_body_not_under_lock(self):
        # a closure defined under a lock runs later (often another
        # thread): its body is not a held-lock region
        findings = run("""
            import threading
            import time

            class D:
                def __init__(self):
                    self._lock = threading.Lock()

                def arm(self):
                    with self._lock:
                        def later():
                            time.sleep(5)
                        return later
        """)
        assert "blocking-under-lock" not in checks_of(findings)


class TestCoalescerPattern:
    """The write-coalescer idiom (`rpc._WriteCoalescer`, same shape as
    PR-2's pubsub batching fix): enqueue under the lock, flush started
    by a timer / loop callback and draining OUTSIDE any lock. The good
    twin must stay silent; folding the blocking drain back under the
    lock must flag — that exact regression is what these fixtures pin.
    """

    def test_timer_started_flush_outside_lock_clean(self):
        findings = run("""
            import threading

            class Coalescer:
                def __init__(self, writer):
                    self._lock = threading.Lock()
                    self._writer = writer
                    self._pending = []
                    self._timer = None

                def send(self, body):
                    with self._lock:
                        self._pending.append(body)
                        if self._timer is None:
                            self._timer = threading.Timer(
                                0.005, self._flush)
                            self._timer.start()

                def _flush(self):
                    with self._lock:
                        batch, self._pending = self._pending, []
                        self._timer = None
                    # the drain round-trip happens outside the lock
                    self._writer.write_batch(batch).result()
        """)
        assert "blocking-under-lock" not in checks_of(findings), findings
        assert "lock-discipline" not in checks_of(findings), findings

    def test_flush_under_lock_flagged(self):
        # the regression PR-2 fixed: drain performed while still
        # holding the enqueue lock — every sender stalls behind I/O
        findings = run("""
            import threading

            class Coalescer:
                def __init__(self, writer):
                    self._lock = threading.Lock()
                    self._writer = writer
                    self._pending = []

                def send(self, body):
                    with self._lock:
                        self._pending.append(body)
                        self._flush()

                def _flush(self):
                    batch, self._pending = self._pending, []
                    self._writer.write_batch(batch).result()
        """)
        hit = [f for f in findings if f.check == "blocking-under-lock"
               and f.scope == "Coalescer.send"]
        assert hit and "Coalescer._flush" in hit[0].message, findings

    def test_blocking_drain_inline_under_lock_flagged(self):
        findings = run("""
            import threading
            import time

            class Coalescer:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._pending = []

                def send(self, body):
                    with self._lock:
                        self._pending.append(body)
                        time.sleep(0.005)  # "wait for batchmates"
        """)
        assert any(f.check == "blocking-under-lock"
                   and f.detail == "time.sleep"
                   and f.scope == "Coalescer.send"
                   for f in findings), findings


class TestTimedSchedulePattern:
    """The timed fault-schedule idiom (`fault_injection.arm_timed`):
    partition the due entries while holding the schedule lock, then
    hand them to a daemon thread that sleeps out each offset and fires
    OUTSIDE any lock. The good twin must stay silent; sleeping out the
    offsets while still holding the schedule lock (which would stall
    every other arm/record for the whole schedule) must flag.
    """

    def test_timer_fire_outside_lock_clean(self):
        findings = run("""
            import threading
            import time

            class Plan:
                def __init__(self, entries):
                    self._lock = threading.Lock()
                    self._entries = entries
                    self._armed = []

                def arm(self, role, base):
                    with self._lock:
                        due = [e for e in self._entries
                               if e.role in (None, role)
                               and e not in self._armed]
                        self._armed.extend(due)
                    t = threading.Thread(
                        target=self._run, args=(due, base), daemon=True)
                    t.start()

                def _run(self, due, base):
                    # waits + firing happen on the timer thread with no
                    # lock held; only bookkeeping re-takes the lock
                    for e in due:
                        remaining = base + e.offset - time.time()
                        if remaining > 0:
                            time.sleep(remaining)
                        e.fire()
                        with self._lock:
                            self._armed.remove(e)
        """)
        assert "blocking-under-lock" not in checks_of(findings), findings
        assert "lock-discipline" not in checks_of(findings), findings

    def test_timer_fire_under_lock_flagged(self):
        # the shape the clean twin exists to prevent: sleeping out the
        # schedule while holding the lock serializes every arm/record
        # behind the full fault schedule's wall-clock span
        findings = run("""
            import threading
            import time

            class Plan:
                def __init__(self, entries):
                    self._lock = threading.Lock()
                    self._entries = entries

                def arm(self, role, base):
                    with self._lock:
                        for e in self._entries:
                            remaining = base + e.offset - time.time()
                            if remaining > 0:
                                time.sleep(remaining)
                            e.fire()
        """)
        assert any(f.check == "blocking-under-lock"
                   and f.detail == "time.sleep"
                   and f.scope == "Plan.arm"
                   for f in findings), findings


class TestQuotaReservePattern:
    """The quota check-and-reserve idiom (`shm_store.cc ss_create_job`,
    mirrored by the pure-Python quota paths): the quota read and the
    `used` reservation must happen under ONE lock acquisition — a
    single RMW in the native store. The good twin must stay silent;
    checking under the lock and reserving after it is released is the
    classic TOCTOU (two racing jobs both pass the check, both reserve,
    and the tenant sails past its byte quota) and must flag.
    """

    def test_read_and_reserve_under_one_lock_clean(self):
        findings = run("""
            import threading

            class JobQuota:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._used = 0
                    self._quota = 1 << 23

                def try_reserve(self, want):
                    with self._lock:
                        # check and reserve are one critical section
                        if self._used + want > self._quota:
                            return False
                        self._used += want
                    return True

                def release(self, n):
                    with self._lock:
                        self._used -= n
        """)
        assert "lock-discipline" not in checks_of(findings), findings
        assert "blocking-under-lock" not in checks_of(findings), findings

    def test_check_then_reserve_across_release_flagged(self):
        # the forbidden shape: the admission decision is made under the
        # lock, but the reservation lands after it was released — a
        # concurrent create can pass the same check in the window
        findings = run("""
            import threading

            class JobQuota:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._used = 0
                    self._quota = 1 << 23

                def try_reserve(self, want):
                    with self._lock:
                        ok = self._used + want <= self._quota
                    if ok:
                        self._used += want   # TOCTOU: lock was released
                    return ok

                def release(self, n):
                    with self._lock:
                        self._used -= n
        """)
        assert any(f.check == "lock-discipline"
                   and f.detail == "attr:_used"
                   and f.scope == "JobQuota.try_reserve"
                   for f in findings), findings


# ---------------------------------------------------------------------------
# checker 3: jit-purity
# ---------------------------------------------------------------------------

class TestJitPurity:
    def test_print_in_decorated_jit(self):
        findings = run("""
            import jax

            @jax.jit
            def step(x):
                print("tracing", x)
                return x * 2
        """)
        assert any(f.check == "jit-purity" and f.detail == "print"
                   for f in findings), findings

    def test_time_and_rng_in_scan_body(self):
        findings = run("""
            import time
            import numpy as np
            from jax import lax

            def roll(carry, x):
                t = time.time()
                noise = np.random.normal()
                return carry, x

            def run(xs):
                return lax.scan(roll, 0.0, xs)
        """)
        details = {f.detail for f in findings if f.check == "jit-purity"}
        assert "time.time" in details and "np.random.normal" in details, \
            findings

    def test_tracer_escape_via_self_store(self):
        findings = run("""
            import jax

            class Model:
                def update(self, x):
                    self.last = x        # leaks a tracer
                    return x + 1

                def jitted(self):
                    return jax.jit(self.update)
        """)
        assert any(f.detail == "self-store:last" for f in findings), findings

    def test_logging_in_partial_jit(self):
        findings = run("""
            import functools
            import jax
            import logging

            logger = logging.getLogger(__name__)

            @functools.partial(jax.jit, static_argnums=0)
            def fwd(n, x):
                logger.info("fwd %s", n)
                return x
        """)
        assert any(f.detail == "logging" for f in findings), findings

    def test_jax_debug_print_sanctioned(self):
        findings = run("""
            import jax

            @jax.jit
            def step(x):
                jax.debug.print("x={x}", x=x)
                return x * 2
        """)
        assert "jit-purity" not in checks_of(findings)

    def test_unstaged_function_untouched(self):
        findings = run("""
            def helper(x):
                print(x)
                return x
        """)
        assert "jit-purity" not in checks_of(findings)


# ---------------------------------------------------------------------------
# checker 4: seeded-rng
# ---------------------------------------------------------------------------

class TestSeededRng:
    BAD = """
        import random

        def jitter():
            return random.random() * 0.1
    """

    def test_bare_random_in_private_flagged(self):
        findings = run(self.BAD, relpath="ray_tpu/_private/fake.py")
        assert any(f.check == "seeded-rng" and f.detail == "random.random"
                   for f in findings), findings

    def test_outside_private_not_flagged(self):
        findings = run(self.BAD, relpath="ray_tpu/serve/fake.py")
        assert "seeded-rng" not in checks_of(findings)

    def test_np_random_flagged(self):
        findings = run("""
            import numpy as np

            def pick(n):
                return np.random.randint(n)
        """, relpath="ray_tpu/_private/fake.py")
        assert any(f.check == "seeded-rng" for f in findings), findings

    def test_seeded_stream_construction_ok(self):
        findings = run("""
            import random

            def stream(seed):
                rng = random.Random(seed)
                return rng.random()
        """, relpath="ray_tpu/_private/fake.py")
        assert "seeded-rng" not in checks_of(findings)


# ---------------------------------------------------------------------------
# checker 5: jit-cache-stability
# ---------------------------------------------------------------------------

class TestJitCacheStability:
    def test_jit_in_for_loop_flagged(self):
        findings = run("""
            import jax

            def train(batches):
                for b in batches:
                    f = jax.jit(lambda x: x + 1)
                    f(b)
        """)
        assert any(f.check == "jit-cache-stability"
                   and f.detail == "in-loop:jit"
                   and f.scope == "train" for f in findings), findings

    def test_shard_map_in_while_loop_flagged(self):
        findings = run("""
            from jax.experimental.shard_map import shard_map

            def pump(mesh, spec):
                while True:
                    fn = shard_map(body, mesh=mesh, in_specs=spec,
                                   out_specs=spec)
                    fn(0)
        """)
        assert any(f.check == "jit-cache-stability"
                   and f.detail == "in-loop:shard_map"
                   for f in findings), findings

    def test_construct_and_call_flagged(self):
        findings = run("""
            import jax

            def once(x):
                return jax.jit(lambda v: v * 2)(x)
        """)
        assert any(f.check == "jit-cache-stability"
                   and f.detail == "construct-and-call:jit"
                   for f in findings), findings

    def test_fresh_closure_inside_loop_flagged(self):
        findings = run("""
            import jax

            def build(stages):
                fns = []
                for s in stages:
                    def stage_fn(x, s=s):
                        return jax.jit(lambda v: v + s)(x)
                    fns.append(stage_fn)
                return fns
        """)
        assert any(f.check == "jit-cache-stability"
                   for f in findings), findings

    def test_hoisted_jit_called_in_loop_ok(self):
        findings = run("""
            import jax

            def train(batches):
                f = jax.jit(lambda x: x + 1)
                for b in batches:
                    f(b)
        """)
        assert "jit-cache-stability" not in checks_of(findings)

    def test_compiled_step_is_the_sanctioned_form(self):
        findings = run("""
            from ray_tpu.parallel import compiled_step

            @compiled_step(donate_argnums=(0,))
            def step(w, b):
                return w + b, None

            def train(w, batches):
                for b in batches:
                    w, _ = step(w, b)
                return w
        """)
        assert "jit-cache-stability" not in checks_of(findings)

    def test_inline_suppression_applies(self):
        findings = run("""
            import jax

            def train(batches):
                for b in batches:
                    f = jax.jit(lambda x: x + 1)  # raylint: disable=jit-cache-stability
                    f(b)
        """)
        assert "jit-cache-stability" not in checks_of(findings)


# ---------------------------------------------------------------------------
# checker 6: metric-in-hot-loop
# ---------------------------------------------------------------------------

class TestMetricInHotLoop:
    def test_counter_in_loop_flagged(self):
        findings = run("""
            from ray_tpu.util.metrics import Counter

            def scan(items):
                for item in items:
                    c = Counter("item_total", "per item")
                    c.inc()
        """)
        assert any(f.check == "metric-in-hot-loop"
                   and f.detail == "in-loop:Counter"
                   and f.scope == "scan" for f in findings), findings

    def test_histogram_in_per_call_function_flagged(self):
        findings = run("""
            from ray_tpu.util import metrics

            class Replica:
                def handle_request(self, req):
                    h = metrics.Histogram("latency_s", "per request")
                    h.observe(req.latency)
        """)
        assert any(f.check == "metric-in-hot-loop"
                   and f.detail == "per-call:Histogram"
                   and f.scope == "Replica.handle_request"
                   for f in findings), findings

    def test_module_scope_and_init_ok(self):
        findings = run("""
            from ray_tpu.util.metrics import Counter, Gauge

            REQUESTS = Counter("req_total", "requests")

            class Replica:
                def __init__(self):
                    self._inflight = Gauge("inflight", "in flight")

                def handle(self, req):
                    REQUESTS.inc()
                    self._inflight.set(1)
        """)
        assert "metric-in-hot-loop" not in checks_of(findings)

    def test_setup_function_ok(self):
        findings = run("""
            from ray_tpu.util.metrics import Gauge

            def _init_metrics():
                return Gauge("depth", "queue depth")

            def setup_daemon():
                return Gauge("up", "daemon up")
        """)
        assert "metric-in-hot-loop" not in checks_of(findings)

    def test_collections_counter_not_a_metric(self):
        findings = run("""
            import collections
            from collections import Counter

            def tally(items):
                for item in items:
                    c = Counter(item)           # collections.Counter
                    d = collections.Counter(item)
        """)
        assert "metric-in-hot-loop" not in checks_of(findings)

    def test_def_inside_loop_is_per_iteration(self):
        findings = run("""
            from ray_tpu.util.metrics import Counter

            def build(names):
                fns = []
                for name in names:
                    def make():
                        return Counter(name, "fresh per iteration")
                    fns.append(make)
                return fns
        """)
        assert any(f.check == "metric-in-hot-loop"
                   and f.detail == "in-loop:Counter"
                   for f in findings), findings

    def test_inline_suppression_applies(self):
        findings = run("""
            from ray_tpu.util.metrics import Counter

            def per_call():
                return Counter("x", "y")  # raylint: disable=metric-in-hot-loop
        """)
        assert "metric-in-hot-loop" not in checks_of(findings)


# ---------------------------------------------------------------------------
# span-leak: manually-opened spans must close on exception paths
# ---------------------------------------------------------------------------

class TestSpanLeak:
    def test_happy_path_close_flagged(self):
        findings = run("""
            from ray_tpu.util import tracing

            def handle(req):
                s = tracing.start_span("serve.request")
                do_work(req)
                s.end()
        """)
        assert any(f.check == "span-leak" and f.detail == "span:s"
                   and f.scope == "handle"
                   and "happy path" in f.message
                   for f in findings), findings

    def test_manual_enter_never_closed_flagged(self):
        findings = run("""
            from ray_tpu.util.tracing import span

            class Router:
                def choose(self, req):
                    s = span("router.choose").__enter__()
                    return self.pick(req)
        """)
        assert any(f.check == "span-leak"
                   and f.scope == "Router.choose"
                   and "never closed" in f.message
                   for f in findings), findings

    def test_finally_close_ok(self):
        findings = run("""
            from ray_tpu.util import tracing

            def handle(req):
                s = tracing.start_span("serve.request")
                try:
                    do_work(req)
                finally:
                    s.end()
        """)
        assert "span-leak" not in checks_of(findings)

    def test_with_span_ok(self):
        findings = run("""
            from ray_tpu.util import tracing

            def handle(req):
                with tracing.span("serve.request"):
                    do_work(req)
        """)
        assert "span-leak" not in checks_of(findings)

    def test_suppression_comment(self):
        findings = run("""
            from ray_tpu.util import tracing

            def handle(req):
                s = tracing.start_span("x")  # raylint: disable=span-leak
                do_work(req)
                s.end()
        """)
        assert "span-leak" not in checks_of(findings)


# ---------------------------------------------------------------------------
# snapshot-read: dispatch-plane snapshot rows are read-time facts
# ---------------------------------------------------------------------------

class TestSnapshotRead:
    """Rows from ``ring.snapshot()`` are validated by the seqlock
    generation check at read time only. The bad twin reuses a row
    after ``ring.done()`` advanced the table — the slot may have been
    retired and re-issued, so the stale row sails past the ABA guard.
    The good twin finishes every use under the single hold."""

    BAD = """
        def route_and_ack(ring, rid, gen):
            rows = ring.snapshot()
            target = rows[0]
            ring.done(rid, gen)      # version/generation may advance here
            return send(target)      # stale: validated before done()
    """

    def test_reuse_after_release_flagged(self):
        findings = run(self.BAD)
        assert any(f.check == "snapshot-read"
                   and f.detail == "snap:target"
                   and f.scope == "route_and_ack"
                   for f in findings), findings

    def test_derived_value_carries_the_taint(self):
        findings = run("""
            def pick(ring, rid, gen):
                rows = ring.snapshot()
                alive = rows[1]
                best = alive
                ring.mark_dead(rid)
                return best
        """)
        assert any(f.check == "snapshot-read" and f.detail == "snap:best"
                   for f in findings), findings

    def test_single_hold_read_clean(self):
        findings = run("""
            def route_and_ack(ring, rid, gen):
                rows = ring.snapshot()
                target = rows[0]
                send(target)         # every use lands before the release
                ring.done(rid, gen)
        """)
        assert "snapshot-read" not in checks_of(findings), findings

    def test_fresh_snapshot_after_release_clean(self):
        findings = run("""
            def ack_then_route(ring, rid, gen):
                ring.done(rid, gen)
                rows = ring.snapshot()   # fresh read after the release
                return rows[0]
        """)
        assert "snapshot-read" not in checks_of(findings), findings

    def test_other_receiver_mutation_clean(self):
        findings = run("""
            def route(ring_a, ring_b, rid, gen):
                rows = ring_a.snapshot()
                ring_b.done(rid, gen)    # a different table entirely
                return rows[0]
        """)
        assert "snapshot-read" not in checks_of(findings), findings

    def test_inline_suppression(self):
        src = self.BAD.replace(
            "return send(target)      # stale: validated before done()",
            "return send(target)  # raylint: disable=snapshot-read")
        findings = run(src)
        assert "snapshot-read" not in checks_of(findings), findings


# ---------------------------------------------------------------------------
# jit-purity over the AOT-cache stagers (compiled_step / fold_steps)
# ---------------------------------------------------------------------------

class TestJitPurityOverCompiledStep:
    def test_print_in_compiled_step_flagged(self):
        findings = run("""
            from ray_tpu.parallel import compiled_step

            @compiled_step(donate_argnums=(0,))
            def step(w, b):
                print(w)
                return w + b, None
        """)
        assert any(f.check == "jit-purity" and f.detail == "print"
                   and f.scope == "step" for f in findings), findings

    def test_sleep_in_fold_steps_body_flagged(self):
        findings = run("""
            import time
            from ray_tpu.parallel import fold_steps

            def make(step_count):
                def body(c, b):
                    time.sleep(0.1)
                    return c, b
                return fold_steps(body, step_count)
        """)
        assert any(f.check == "jit-purity" and f.detail == "time.sleep"
                   for f in findings), findings

    def test_pure_compiled_step_silent(self):
        findings = run("""
            from ray_tpu.parallel import compiled_step

            @compiled_step(donate_argnums=(0,))
            def step(w, b):
                return w + b, None
        """)
        assert "jit-purity" not in checks_of(findings)


# ---------------------------------------------------------------------------
# ownership ref-table lock discipline
# ---------------------------------------------------------------------------

class TestRefTableLockDiscipline:
    """Pins the ownership plane's ref-table contract (core_worker
    `_ref_lock`): count mutation and the free decision must happen under
    one lock hold. A check-then-delete that releases the lock between
    the read and the write races a concurrent `register_ref` — the
    classic lost-resurrection bug distributed ref counting must not
    have."""

    BAD = """
        import threading

        class RefTable:
            def __init__(self):
                self._lock = threading.Lock()
                self._local_refs = {}

            def register(self, oid):
                with self._lock:
                    self._local_refs[oid] = \\
                        self._local_refs.get(oid, 0) + 1

            def deregister(self, oid):
                with self._lock:
                    gone = self._local_refs.get(oid, 0) <= 1
                if gone:
                    # raced: a register between release and here is lost
                    self._local_refs.pop(oid, None)
    """

    GOOD = """
        import threading

        class RefTable:
            def __init__(self):
                self._lock = threading.Lock()
                self._local_refs = {}

            def register(self, oid):
                with self._lock:
                    self._local_refs[oid] = \\
                        self._local_refs.get(oid, 0) + 1

            def deregister(self, oid):
                with self._lock:
                    n = self._local_refs.get(oid, 0) - 1
                    if n <= 0:
                        self._local_refs.pop(oid, None)
                    else:
                        self._local_refs[oid] = n
    """

    def test_check_then_delete_across_release_flagged(self):
        findings = run(self.BAD)
        assert any(f.check == "lock-discipline"
                   and f.detail == "attr:_local_refs"
                   and f.scope == "RefTable.deregister"
                   for f in findings), findings

    def test_mutation_under_one_hold_clean(self):
        findings = run(self.GOOD)
        assert "lock-discipline" not in checks_of(findings), findings


class TestPrefixCacheRefcountLockDiscipline:
    """Pins the serve.llm prefix-cache aliasing contract: the cache
    lookup and the page incref must happen under ONE hold of the arena
    lock. A lookup that releases the lock before aliasing races
    eviction — the LRU can free the matched pages in the gap, and the
    new sequence increfs (and then reads) pages already handed to
    another owner. Same TOCTOU shape as the ref-table pair above, on
    the KV-page refcount table."""

    BAD = """
        import threading

        class PrefixCache:
            def __init__(self):
                self._lock = threading.Lock()
                self._entries = {}
                self._refs = {}

            def insert(self, key, pages):
                with self._lock:
                    self._entries[key] = pages
                    for p in pages:
                        self._refs[p] = self._refs.get(p, 0) + 1

            def acquire(self, key):
                with self._lock:
                    pages = self._entries.get(key)
                if pages is None:
                    return None
                # raced: eviction between release and here freed pages
                for p in pages:
                    self._refs[p] = self._refs.get(p, 0) + 1
                return pages
    """

    GOOD = """
        import threading

        class PrefixCache:
            def __init__(self):
                self._lock = threading.Lock()
                self._entries = {}
                self._refs = {}

            def insert(self, key, pages):
                with self._lock:
                    self._entries[key] = pages
                    for p in pages:
                        self._refs[p] = self._refs.get(p, 0) + 1

            def acquire(self, key):
                with self._lock:
                    pages = self._entries.get(key)
                    if pages is None:
                        return None
                    for p in pages:
                        self._refs[p] = self._refs.get(p, 0) + 1
                    return pages
    """

    def test_check_then_alias_across_release_flagged(self):
        findings = run(self.BAD)
        assert any(f.check == "lock-discipline"
                   and f.detail == "attr:_refs"
                   and f.scope == "PrefixCache.acquire"
                   for f in findings), findings

    def test_lookup_and_incref_under_one_hold_clean(self):
        findings = run(self.GOOD)
        assert "lock-discipline" not in checks_of(findings), findings


# ---------------------------------------------------------------------------
# checker 9: watchdog-probe
# ---------------------------------------------------------------------------

class TestWatchdogProbeDiscipline:
    """Pins the health-plane deadman invariant: a loop's liveness beat
    must be lock-free. A `probe.beat()` taken inside the watched loop's
    lock freezes together with that lock — the exact wedge the watchdog
    exists to catch (a thread stuck on the loop's mutex) then also
    silences the liveness signal, and the stall is never reported."""

    BAD = """
        import threading

        class Dispatcher:
            def __init__(self, probe):
                self._lock = threading.Lock()
                self._queue = []
                self._probe = probe

            def drain(self):
                with self._lock:
                    self._probe.beat()
                    while self._queue:
                        self._queue.pop()
    """

    GOOD = """
        import threading

        class Dispatcher:
            def __init__(self, probe):
                self._lock = threading.Lock()
                self._queue = []
                self._probe = probe

            def drain(self):
                self._probe.beat()
                with self._lock:
                    while self._queue:
                        self._queue.pop()
    """

    def test_beat_under_watched_lock_flagged(self):
        findings = run(self.BAD)
        assert any(f.check == "watchdog-probe"
                   and f.detail == "beat:self._probe.beat"
                   and f.scope == "Dispatcher.drain"
                   for f in findings), findings

    def test_beat_outside_loop_lock_clean(self):
        findings = run(self.GOOD)
        assert "watchdog-probe" not in checks_of(findings), findings


# ---------------------------------------------------------------------------
# suppression + baseline
# ---------------------------------------------------------------------------

class TestSuppressionAndBaseline:
    BAD = """
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def spin(self):
                with self._lock:
                    time.sleep(1)
    """

    def test_inline_suppression(self):
        src = self.BAD.replace(
            "time.sleep(1)",
            "time.sleep(1)  # raylint: disable=blocking-under-lock")
        assert run(src) == []

    def test_suppression_line_above(self):
        src = self.BAD.replace(
            "time.sleep(1)",
            "# raylint: disable=all\n                    time.sleep(1)")
        assert run(src) == []

    def test_wrong_check_does_not_suppress(self):
        src = self.BAD.replace(
            "time.sleep(1)",
            "time.sleep(1)  # raylint: disable=jit-purity")
        assert run(src) != []

    def test_baseline_freezes_then_gates(self, tmp_path, capsys):
        mod = tmp_path / "mod.py"
        base = tmp_path / "baseline.txt"
        mod.write_text(textwrap.dedent(self.BAD))
        args = [str(mod), "--root", str(tmp_path),
                "--baseline", str(base)]
        # new finding, no baseline: gate fails
        assert raylint_main(args) == 1
        # freeze, then the same finding passes
        assert raylint_main(args + ["--write-baseline"]) == 0
        assert raylint_main(args) == 0
        # a NEW violation on top of the frozen one fails again
        mod.write_text(mod.read_text().replace(
            "time.sleep(1)", "time.sleep(1)\n                fut.result()"))
        assert raylint_main(args) == 1
        # fixing everything reports the stale entries but stays green
        mod.write_text("x = 1\n")
        capsys.readouterr()
        assert raylint_main(args) == 0
        assert "stale" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# whole-program pass: the call graph itself
# ---------------------------------------------------------------------------

def wp(sources, checks=None, aux=()):
    """Run the whole-program checkers over {relpath: source}."""
    from tools.raylint.whole_program import (WP_CHECKS,
                                             analyze_program_sources)
    return analyze_program_sources(
        {rel: textwrap.dedent(src) for rel, src in sources.items()},
        checks or WP_CHECKS, aux=aux)


def program_of(sources):
    from tools.raylint.callgraph import Program, extract_module_facts
    return Program([extract_module_facts(textwrap.dedent(src), rel)
                    for rel, src in sources.items()])


class TestCallGraph:
    def test_async_coloring(self):
        from tools.raylint.callgraph import extract_module_facts
        mf = extract_module_facts(textwrap.dedent("""
            async def handler(): ...

            def helper(): ...

            class Svc:
                async def rpc_go(self): ...
                def sync_part(self): ...
        """), "ray_tpu/a.py")
        assert mf.functions["handler"].is_async
        assert not mf.functions["helper"].is_async
        assert mf.functions["Svc.rpc_go"].is_async
        assert not mf.functions["Svc.sync_part"].is_async

    def test_self_method_resolution(self):
        prog = program_of({"ray_tpu/a.py": """
            class Svc:
                def top(self):
                    self.bottom()

                def bottom(self): ...
        """})
        edges = prog.edges_of("ray_tpu.a::Svc.top")
        assert [t for t, _l, _c in edges] == ["ray_tpu.a::Svc.bottom"]

    def test_cross_module_resolution(self):
        prog = program_of({
            "ray_tpu/a.py": """
                def leaf(): ...
            """,
            "ray_tpu/b.py": """
                from ray_tpu import a

                def caller():
                    a.leaf()
            """,
        })
        edges = prog.edges_of("ray_tpu.b::caller")
        assert [t for t, _l, _c in edges] == ["ray_tpu.a::leaf"]

    def test_attr_type_dispatch(self):
        # self._store = Store() in __init__, then self._store.get()
        prog = program_of({"ray_tpu/a.py": """
            class Store:
                def get(self): ...

            class Worker:
                def __init__(self):
                    self._store = Store()

                def fetch(self):
                    return self._store.get()
        """})
        edges = prog.edges_of("ray_tpu.a::Worker.fetch")
        assert [t for t, _l, _c in edges] == ["ray_tpu.a::Store.get"]

    def test_inherited_method_resolution(self):
        prog = program_of({"ray_tpu/a.py": """
            class Base:
                def shared(self): ...

            class Child(Base):
                def go(self):
                    self.shared()
        """})
        edges = prog.edges_of("ray_tpu.a::Child.go")
        assert [t for t, _l, _c in edges] == ["ray_tpu.a::Base.shared"]


# ---------------------------------------------------------------------------
# whole-program checker 1: async-blocking
# ---------------------------------------------------------------------------

class TestAsyncBlocking:
    def one(self, sources):
        return wp(sources, checks=("async-blocking",))

    def test_direct_sleep_in_async_def(self):
        fs = self.one({"ray_tpu/a.py": """
            import time

            async def handler():
                time.sleep(1)
        """})
        assert [(f.check, f.detail) for f in fs] == \
            [("async-blocking", "time.sleep")]

    def test_asyncio_sleep_is_clean(self):
        fs = self.one({"ray_tpu/a.py": """
            import asyncio

            async def handler():
                await asyncio.sleep(1)
        """})
        assert fs == []

    def test_transitive_chain_flagged_at_boundary(self):
        fs = self.one({"ray_tpu/a.py": """
            import time

            def backoff():
                time.sleep(0.5)

            def retry():
                backoff()

            async def handler():
                retry()
        """})
        assert len(fs) == 1
        f = fs[0]
        assert f.scope == "handler" and f.detail == "retry->time.sleep"
        # the chain rides in the message for the fix-it trail
        assert "ray_tpu.a.retry -> ray_tpu.a.backoff" in f.message

    def test_cross_module_chain(self):
        fs = self.one({
            "ray_tpu/io.py": """
                def read_all(path):
                    with open(path) as fh:
                        return fh.read()
            """,
            "ray_tpu/srv.py": """
                from ray_tpu import io

                async def handler(req):
                    return io.read_all(req)
            """,
        })
        assert [(f.path, f.detail) for f in fs] == \
            [("ray_tpu/srv.py", "io.read_all->open() [sync file I/O]")]

    def test_executor_hop_is_clean(self):
        fs = self.one({"ray_tpu/a.py": """
            import asyncio
            import time

            def backoff():
                time.sleep(0.5)

            async def handler():
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, backoff)
        """})
        assert fs == []

    def test_to_thread_is_clean(self):
        fs = self.one({"ray_tpu/a.py": """
            import asyncio

            def load(path):
                return open(path).read()

            async def handler(path):
                return await asyncio.to_thread(load, path)
        """})
        assert fs == []

    def test_thread_target_is_clean(self):
        fs = self.one({"ray_tpu/a.py": """
            import threading
            import time

            def pump():
                time.sleep(1)

            async def handler():
                threading.Thread(target=pump, daemon=True).start()
        """})
        assert fs == []

    def test_awaited_queue_get_is_not_blocking(self):
        # asyncio.Queue.get is a coroutine; `await q.get()` must not
        # trip the queue-ish `.get` blocking heuristic
        fs = self.one({"ray_tpu/a.py": """
            import asyncio

            async def consume(q):
                return await q.get()
        """})
        assert fs == []

    def test_wait_for_wrapped_call_is_not_blocking(self):
        fs = self.one({"ray_tpu/a.py": """
            import asyncio

            async def consume(q):
                return await asyncio.wait_for(q.get(), timeout=5)
        """})
        assert fs == []

    def test_unawaited_queue_get_in_async_def_flagged(self):
        fs = self.one({"ray_tpu/a.py": """
            async def consume(q):
                return q.get()
        """})
        assert [f.detail for f in fs] == [".get() [queue]"]

    def test_async_callee_flagged_at_itself_not_caller(self):
        # boundary rule: one finding per root cause
        fs = self.one({"ray_tpu/a.py": """
            import time

            async def inner():
                time.sleep(1)

            async def outer():
                await inner()
        """})
        assert [(f.scope, f.detail) for f in fs] == \
            [("inner", "time.sleep")]

    def test_sync_only_chain_is_clean(self):
        # blocking is fine off-loop: no async root, no finding
        fs = self.one({"ray_tpu/a.py": """
            import time

            def a():
                time.sleep(1)

            def b():
                a()
        """})
        assert fs == []

    def test_sink_suppression_sanctions_every_chain(self):
        fs = self.one({"ray_tpu/a.py": """
            import subprocess

            def build():
                subprocess.run(["make"])  # raylint: disable=async-blocking

            def ensure_built():
                build()

            async def handler():
                ensure_built()

            async def other_handler():
                ensure_built()
        """})
        assert fs == []

    def test_boundary_suppression_is_local_to_one_caller(self):
        fs = self.one({"ray_tpu/a.py": """
            import time

            def backoff():
                time.sleep(1)

            async def sanctioned():
                backoff()  # raylint: disable=async-blocking

            async def unsanctioned():
                backoff()
        """})
        assert [f.scope for f in fs] == ["unsanctioned"]

    def test_lock_acquire_and_future_result(self):
        fs = self.one({"ray_tpu/a.py": """
            async def handler(lock, fut):
                lock.acquire()
                return fut.result(timeout=5)
        """})
        assert sorted(f.detail for f in fs) == \
            [".result(timeout) [concurrent future]", "Lock.acquire"]

    def test_nonblocking_acquire_is_clean(self):
        fs = self.one({"ray_tpu/a.py": """
            async def handler(lock):
                return lock.acquire(blocking=False)
        """})
        assert fs == []


# ---------------------------------------------------------------------------
# whole-program checker 2: rpc-surface
# ---------------------------------------------------------------------------

class TestRpcSurface:
    def one(self, sources, aux=()):
        return wp(sources, checks=("rpc-surface",), aux=aux)

    def test_unregistered_call_flagged(self):
        fs = self.one({"ray_tpu/a.py": """
            async def go(client):
                await client.call("get_sturf", {})
        """})
        assert [(f.check, f.detail) for f in fs] == \
            [("rpc-surface", "call:get_sturf")]

    def test_registered_literal_satisfies_call(self):
        fs = self.one({"ray_tpu/a.py": """
            def setup(server):
                server.register("get_stuff", handle_get_stuff)

            async def handle_get_stuff(req): ...

            async def go(client):
                await client.call("get_stuff", {})
        """})
        assert fs == []

    def test_register_all_sweep_satisfies_call(self):
        fs = self.one({"ray_tpu/a.py": """
            class Gcs:
                async def rpc_get_nodes(self, req): ...

                def start(self, server):
                    server.register_all(self)

            async def go(client):
                await client.call("get_nodes", {})
        """})
        assert fs == []

    def test_register_all_sweeps_base_classes(self):
        fs = self.one({
            "ray_tpu/base.py": """
                class KvMixin:
                    async def rpc_kv_get(self, req): ...
            """,
            "ray_tpu/gcs.py": """
                from ray_tpu.base import KvMixin

                class Gcs(KvMixin):
                    def start(self, server):
                        server.register_all(self)

                async def go(client):
                    await client.call("kv_get", {})
            """,
        })
        assert fs == []

    def test_dead_handler_flagged(self):
        fs = self.one({"ray_tpu/a.py": """
            class Svc:
                async def rpc_orphan(self, req): ...
                async def rpc_used(self, req): ...

                def start(self, server):
                    server.register_all(self)

            async def go(client):
                await client.call("used", {})
        """})
        assert [(f.detail, f.scope) for f in fs] == \
            [("handler:orphan", "Svc.rpc_orphan")]

    def test_str_mention_rescues_dynamic_dispatch(self):
        # the handler name appearing as a literal anywhere else means
        # a variable-method path may reach it — not provably dead
        fs = self.one({"ray_tpu/a.py": """
            class Svc:
                async def rpc_add_borrower(self, req): ...

                def start(self, server):
                    server.register_all(self)

            def kick(client, oid):
                notify_later(client, "add_borrower", oid)
        """})
        assert fs == []

    def test_wrapper_call_literal_counts(self):
        # ClientContext-style `self._call("connect", ...)` thin wrapper
        fs = self.one({"ray_tpu/a.py": """
            def setup(server):
                server.register("connect", on_connect)

            async def on_connect(req): ...

            class Ctx:
                def connect(self):
                    return self._call("connect", {})
        """})
        assert fs == []

    def test_aux_registration_satisfies_but_aux_dead_skipped(self):
        # bench registers its own echo handler: the bench call site is
        # satisfied, and bench-local dead surface is not our report
        fs = self.one({
            "ray_tpu/a.py": """
                def noop(): ...
            """,
            "bench.py": """
                def setup(server):
                    server.register("echo", on_echo)
                    server.register("bench_only", on_bench_only)

                async def on_echo(req): ...
                async def on_bench_only(req): ...

                async def go(client):
                    await client.call("echo", {})
            """,
        }, aux=("bench.py",))
        assert fs == []

    def test_notify_verb_counts_as_call_site(self):
        fs = self.one({"ray_tpu/a.py": """
            async def go(client):
                await client.notify("free_sturf", {})
        """})
        assert [f.detail for f in fs] == ["call:free_sturf"]


# ---------------------------------------------------------------------------
# whole-program checker 3: surface-drift
# ---------------------------------------------------------------------------

class TestSurfaceDrift:
    def one(self, sources, aux=()):
        return wp(sources, checks=("surface-drift",), aux=aux)

    def test_unresolved_tsdb_query_flagged(self):
        fs = self.one({"ray_tpu/a.py": """
            def panel(tsdb):
                return tsdb.rate("serve_requests_totall", 60)
        """})
        assert [(f.check, f.detail) for f in fs] == \
            [("surface-drift", "metric:serve_requests_totall")]

    def test_ctor_export_resolves_query(self):
        fs = self.one({
            "ray_tpu/m.py": """
                from ray_tpu.util.metrics import Counter

                REQS = Counter("serve_requests_total", "requests")
            """,
            "ray_tpu/d.py": """
                def panel(tsdb):
                    return tsdb.rate("serve_requests_total", 60)
            """,
        })
        assert fs == []

    def test_histogram_quantile_resolves_bucket_family(self):
        fs = self.one({
            "ray_tpu/m.py": """
                from ray_tpu.util.metrics import Histogram

                LAT = Histogram("serve_latency_seconds", "latency")
            """,
            "ray_tpu/d.py": """
                def panel(q):
                    return q.histogram_quantile(
                        0.99, "serve_latency_seconds")
            """,
        })
        assert fs == []

    def test_histogram_quantile_without_histogram_flagged(self):
        fs = self.one({
            "ray_tpu/m.py": """
                from ray_tpu.util.metrics import Counter

                REQS = Counter("serve_latency_seconds", "not a histogram")
            """,
            "ray_tpu/d.py": """
                def panel(q):
                    return q.histogram_quantile(
                        0.99, "serve_latency_seconds")
            """,
        })
        assert [f.detail for f in fs] == \
            ["metric:serve_latency_seconds_bucket"]

    def test_exposition_row_prefix_export_resolves(self):
        # f"rpc_{name}_total {v}" callback rows export the rpc_ prefix
        fs = self.one({
            "ray_tpu/m.py": """
                def rows(counts):
                    return "".join(
                        f"rpc_{name}_total {v}\\n"
                        for name, v in counts.items())
            """,
            "ray_tpu/d.py": """
                def panel(tsdb):
                    return tsdb.latest("rpc_calls_total")
            """,
        })
        assert fs == []

    def test_prefix_tuple_elements_must_match_an_exporter(self):
        fs = self.one({
            "ray_tpu/m.py": """
                from ray_tpu.util.metrics import Gauge

                G = Gauge("serve_replicas", "replica count")
            """,
            "ray_tpu/top.py": """
                DEFAULT_PREFIXES = ("serve_", "raylet_")
            """,
        })
        assert [(f.detail, f.scope) for f in fs] == \
            [("prefix:raylet_", "DEFAULT_PREFIXES")]

    def test_aux_value_keys_checked_against_ray_tpu_surface(self):
        # bench REGRESSION value-keys must resolve against ray_tpu/
        # exporters — bench's own exposition rows don't count
        fs = self.one({
            "ray_tpu/m.py": """
                from ray_tpu.util.metrics import Counter

                C = Counter("serve_requests_total", "requests")
            """,
            "bench.py": """
                def check(tsdb):
                    tsdb.rate("serve_requests_total", 60)   # resolves
                    tsdb.rate("bench_gone_metric", 60)      # drifted
            """,
        }, aux=("bench.py",))
        assert [(f.path, f.detail) for f in fs] == \
            [("bench.py", "metric:bench_gone_metric")]


# ---------------------------------------------------------------------------
# unused-suppression audit (full-gate only)
# ---------------------------------------------------------------------------

class TestUnusedSuppressionAudit:
    def gate(self, tmp_path, text):
        (tmp_path / "mod.py").write_text(textwrap.dedent(text))
        return raylint_main([str(tmp_path), "--root", str(tmp_path),
                             "--no-baseline"])

    def test_rotted_suppression_is_a_finding(self, tmp_path, capsys):
        rc = self.gate(tmp_path, """
            import time

            def fine():
                x = 1  # raylint: disable=async-blocking
                return x
        """)
        out = capsys.readouterr().out
        assert rc == 1
        assert "unused-suppression" in out

    def test_live_suppression_is_not_flagged(self, tmp_path, capsys):
        rc = self.gate(tmp_path, """
            import time

            async def handler():
                time.sleep(1)  # raylint: disable=async-blocking
        """)
        assert rc == 0, capsys.readouterr().out

    def test_sink_suppression_counts_as_used(self, tmp_path, capsys):
        # consumed inside the sync-summary fixpoint, not at a finding:
        # must still register as a hit for the audit
        rc = self.gate(tmp_path, """
            import subprocess

            def build():
                subprocess.run(["make"])  # raylint: disable=async-blocking

            async def handler():
                build()
        """)
        assert rc == 0, capsys.readouterr().out

    def test_partial_select_skips_the_audit(self, tmp_path, capsys):
        (tmp_path / "mod.py").write_text(textwrap.dedent("""
            def fine():
                return 1  # raylint: disable=jit-purity
        """))
        rc = raylint_main([str(tmp_path), "--root", str(tmp_path),
                           "--no-baseline", "--select",
                           "async-blocking,unused-suppression"])
        assert rc == 0, capsys.readouterr().out


# ---------------------------------------------------------------------------
# --json CLI output
# ---------------------------------------------------------------------------

class TestJsonOutput:
    def test_json_findings_shape(self, tmp_path, capsys):
        import json as _json
        (tmp_path / "mod.py").write_text(textwrap.dedent("""
            import time

            async def handler():
                time.sleep(1)
        """))
        rc = raylint_main([str(tmp_path), "--root", str(tmp_path),
                           "--no-baseline", "--json"])
        assert rc == 1
        doc = _json.loads(capsys.readouterr().out)
        assert set(doc) == {"findings", "new", "stale"}
        [f] = doc["findings"]
        assert f["check"] == "async-blocking"
        assert f["path"] == "mod.py" and f["detail"] == "time.sleep"
        assert "::" in f["key"]

    def test_json_baseline_mode_reports_new(self, tmp_path, capsys):
        import json as _json
        mod = tmp_path / "mod.py"
        base = tmp_path / "baseline.txt"
        mod.write_text("import time\n\nasync def h():\n    time.sleep(1)\n")
        args = [str(tmp_path), "--root", str(tmp_path),
                "--baseline", str(base)]
        assert raylint_main(args + ["--write-baseline"]) == 0
        capsys.readouterr()
        assert raylint_main(args + ["--json"]) == 0
        doc = _json.loads(capsys.readouterr().out)
        assert doc["new"] == [] and len(doc["findings"]) == 1


# ---------------------------------------------------------------------------
# the tier-1 repo gate
# ---------------------------------------------------------------------------

@pytest.mark.lint
def test_ray_tpu_clean_against_baseline():
    """`python -m tools.raylint ray_tpu/` must exit 0: every finding is
    either fixed, inline-suppressed with a justification, or frozen in
    tools/raylint/baseline.txt. New violations fail tier-1 here."""
    rc = raylint_main([os.path.join(ROOT, "ray_tpu"), "--root", ROOT])
    assert rc == 0, "raylint found new violations (see captured output)"


@pytest.mark.lint
def test_burned_down_files_stay_clean():
    """The burn-down targets must never re-enter the baseline."""
    with open(os.path.join(ROOT, "tools", "raylint", "baseline.txt")) as fh:
        entries = [ln for ln in fh
                   if ln.strip() and not ln.startswith("#")]
    for banned in ("serve/batching.py", "serve/controller.py",
                   "util/metrics.py"):
        assert not any(banned in e for e in entries), entries


@pytest.mark.lint
def test_whole_program_baseline_is_empty():
    """The three whole-program checkers burned down to zero: no
    async-blocking / rpc-surface / surface-drift entry may be frozen —
    new violations must be fixed or inline-suppressed with a reason."""
    with open(os.path.join(ROOT, "tools", "raylint", "baseline.txt")) as fh:
        entries = [ln for ln in fh
                   if ln.strip() and not ln.startswith("#")]
    for check in ("async-blocking", "rpc-surface", "surface-drift",
                  "unused-suppression"):
        assert not any(f"::{check}::" in e for e in entries), entries


@pytest.mark.lint
def test_observability_surface_resolves():
    """Every metric name consumed by tsdb queries, the dashboard,
    `ray_tpu top`, and bench REGRESSION value-keys must resolve to a
    registered or callback-exported metric — zero drift, no baseline."""
    rc = raylint_main([os.path.join(ROOT, "ray_tpu"), "--root", ROOT,
                       "--select", "surface-drift", "--no-baseline"])
    assert rc == 0, "surface-drift found unresolved metric names"


@pytest.mark.lint
def test_rpc_surface_resolves():
    """Every call/notify literal has a registered handler and every
    non-aux handler has a caller (or a dynamic-dispatch mention)."""
    rc = raylint_main([os.path.join(ROOT, "ray_tpu"), "--root", ROOT,
                       "--select", "rpc-surface", "--no-baseline"])
    assert rc == 0, "rpc-surface found mismatches"


@pytest.mark.lint
def test_repo_gate_is_fast_enough():
    """The full gate (per-module + whole-program + audit) must stay a
    pre-commit-friendly <10s; the facts cache keeps warm runs cheap."""
    start = time.monotonic()
    raylint_main([os.path.join(ROOT, "ray_tpu"), "--root", ROOT])
    elapsed = time.monotonic() - start
    assert elapsed < 10.0, f"repo gate took {elapsed:.1f}s"


# ---------------------------------------------------------------------------
# runtime lockdep
# ---------------------------------------------------------------------------

class TestLockdep:
    @pytest.fixture(autouse=True)
    def _installed(self):
        from ray_tpu._private import lockdep
        was = lockdep.enabled()
        if not was:
            lockdep.install()
        yield lockdep
        if not was:
            lockdep.uninstall()

    def test_abba_cycle_reported_with_both_stacks(self, _installed):
        lockdep = _installed
        a = threading.Lock()
        b = threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        t = threading.Thread(target=ab)
        t.start()
        t.join()

        caught = []

        def ba():
            try:
                with b:
                    with a:
                        pass
            except lockdep.LockOrderError as e:
                caught.append(str(e))

        t = threading.Thread(target=ba)
        t.start()
        t.join()
        assert caught, "B->A after A->B must raise LockOrderError"
        report = caught[0]
        assert "cycle" in report
        # both witness stacks: the new B->A acquisition and the prior A->B
        assert report.count("acquired here") >= 2, report
        assert "in ab" in report and "in ba" in report, report
        assert lockdep.cycle_reports(), "report must also be recorded"

    def test_consistent_order_is_clean(self, _installed):
        lockdep = _installed
        before = len(lockdep.cycle_reports())
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
        assert len(lockdep.cycle_reports()) == before

    def test_rlock_reentrancy_is_not_an_edge(self, _installed):
        lockdep = _installed
        r = threading.RLock()
        edges = lockdep.edge_count()
        with r:
            with r:      # re-entrant: no self edge, no crash
                pass
        assert lockdep.edge_count() == edges

    def test_condition_wait_keeps_bookkeeping(self, _installed):
        cond = threading.Condition()
        done = []

        def waiter():
            with cond:
                cond.wait(timeout=5)
                done.append(True)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)
        with cond:
            cond.notify()
        t.join(timeout=5)
        assert done == [True]

    def test_env_install(self):
        import subprocess
        import sys
        code = ("import ray_tpu; from ray_tpu._private import lockdep; "
                "assert lockdep.enabled(); print('installed')")
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            env={**os.environ, "RAY_TPU_LOCKDEP": "1",
                 "JAX_PLATFORMS": "cpu"}, cwd=ROOT, timeout=120)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "installed" in out.stdout


def test_record_only_mode():
    """The conftest gate installs with raise_on_cycle=False: cycles are
    recorded for the teardown assert instead of raised mid-test."""
    from ray_tpu._private import lockdep
    if lockdep.enabled():
        pytest.skip("lockdep already active in raising mode")
    lockdep.install(raise_on_cycle=False)
    try:
        a = threading.Lock()
        b = threading.Lock()

        def run_order(x, y):
            def go():
                with x:
                    with y:
                        pass
            t = threading.Thread(target=go)
            t.start()
            t.join()

        run_order(a, b)
        run_order(b, a)   # must record, not raise
        assert lockdep.cycle_reports()
    finally:
        lockdep.uninstall()
