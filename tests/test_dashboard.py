"""Dashboard + timeline tests.

Reference ground: `python/ray/dashboard/tests/` and the
`ray timeline` chrome-trace dump — compressed.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_timeline_chrome_trace(tmp_path):
    from ray_tpu.util.timeline import timeline

    @ray_tpu.remote
    def traced(x):
        time.sleep(0.05)
        return x

    ray_tpu.get([traced.remote(i) for i in range(3)])
    time.sleep(1.5)  # event flush

    out = tmp_path / "trace.json"
    events = timeline(str(out))
    traced_events = [e for e in events if e["name"] == "traced"]
    assert len(traced_events) >= 3
    for e in traced_events:
        assert e["ph"] == "X"
        assert e["dur"] >= 0.04 * 1e6  # spans the 50ms body
    # the file is valid chrome-trace JSON
    loaded = json.loads(out.read_text())
    assert isinstance(loaded, list) and loaded


def test_dashboard_rest_and_html():
    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    class Visible:
        def ping(self):
            return 1

    v = Visible.options(name="dash_actor").remote()
    ray_tpu.get(v.ping.remote())

    dash = start_dashboard(port=18265)
    base = "http://127.0.0.1:18265"

    html = urllib.request.urlopen(base + "/", timeout=30).read().decode()
    assert "ray_tpu" in html

    nodes = json.loads(urllib.request.urlopen(
        base + "/api/nodes", timeout=30).read())
    assert any(n["Alive"] for n in nodes)

    actors = json.loads(urllib.request.urlopen(
        base + "/api/actors", timeout=30).read())
    assert any(a["name"] == "dash_actor" for a in actors)

    res = json.loads(urllib.request.urlopen(
        base + "/api/cluster_resources", timeout=30).read())
    assert res["total"].get("CPU", 0) >= 2

    tl = json.loads(urllib.request.urlopen(
        base + "/api/timeline", timeout=30).read())
    assert isinstance(tl, list)

    jobs = json.loads(urllib.request.urlopen(
        base + "/api/jobs", timeout=30).read())
    assert len(jobs) >= 1  # this driver's job
    assert all(not jb["finished"] or jb["end_time"] for jb in jobs)

    events = json.loads(urllib.request.urlopen(
        base + "/api/events", timeout=30).read())
    assert isinstance(events, list)  # GCS/raylet lifecycle events

    # steps panel (flight recorder): records + attribution + summary
    from ray_tpu.util import step_profiler

    step_profiler.record_step(7, 11.0, host_dispatch_ms=2.0)
    try:
        steps = json.loads(urllib.request.urlopen(
            base + "/api/steps", timeout=30).read())
        assert any(r["step"] == 7 for r in steps["records"])
        assert "attribution" in steps and "summary" in steps
    finally:
        step_profiler.clear()
    ray_tpu.kill(v)
