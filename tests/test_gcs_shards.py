"""Sharded GCS tables: concurrent register/list consistency.

The actor directory and the bounded task-event log are `ShardedTable`s
(keyed shards + per-shard counters + shard-routed write-through
persistence). These tests pin the dict contract the GCS code relies on,
the recency/cap semantics the task-event log needs, and end-to-end
consistency when many clients register and list concurrently over RPC.
"""

import asyncio
import os
import pickle

import pytest

from ray_tpu._private import task as task_mod
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.rpc import RpcClient
from ray_tpu._private.sharded_table import ShardedTable, shard_index


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# ---------------------------------------------------------------------------
# table semantics
# ---------------------------------------------------------------------------


def test_sharded_table_dict_contract():
    t = ShardedTable(name="t")
    keys = [os.urandom(16) for _ in range(256)]
    for i, k in enumerate(keys):
        t[k] = {"i": i}
    assert len(t) == 256
    assert set(t) == set(keys)
    assert t[keys[3]] == {"i": 3}
    assert t.get(b"\x00missing") is None
    assert keys[5] in t
    t.pop(keys[5])
    assert keys[5] not in t and len(t) == 255
    # every key routes to the same shard every time
    for k in keys:
        assert shard_index(k, t.num_shards) == t.shard_of(k)
    assert sum(t.shard_sizes()) == 255
    assert sum(t.shard_ops()) >= 256


def test_sharded_table_recency_and_eviction():
    t = ShardedTable(name="ev")
    keys = [os.urandom(16) for _ in range(100)]
    for i, k in enumerate(keys):
        t[k] = i
    # newest-first across shards
    assert list(t.iter_recent()) == list(range(99, -1, -1))
    # global-oldest eviction, regardless of which shard holds it
    for expect in range(10):
        _, v = t.popitem_oldest()
        assert v == expect
    # an update does not change recency bookkeeping's membership
    t[keys[50]] = "updated"
    assert len(t) == 90


def test_sharded_table_pickle_roundtrip_preserves_recency():
    t = ShardedTable(name="snap")
    keys = [os.urandom(16) for _ in range(64)]
    for i, k in enumerate(keys):
        t[k] = i
    t2 = pickle.loads(pickle.dumps(t))
    assert isinstance(t2, ShardedTable)
    assert dict(t2) == dict(t)
    assert list(t2.iter_recent()) == list(t.iter_recent())
    _, oldest = t2.popitem_oldest()
    assert oldest == 0


def test_from_mapping_wraps_plain_dict():
    plain = {os.urandom(16): i for i in range(32)}
    t = ShardedTable.from_mapping(plain, name="restored")
    assert dict(t) == plain
    assert list(t.iter_recent())[-1] == 0  # insertion order = recency


# ---------------------------------------------------------------------------
# GCS end-to-end: concurrent registration + listing over RPC
# ---------------------------------------------------------------------------


def _creation_spec(i: int) -> dict:
    return task_mod.TaskSpec(
        task_id=os.urandom(16),
        job_id=b"job0",
        name=f"Actor{i}",
        task_type=task_mod.ACTOR_CREATION_TASK,
        owner_addr="127.0.0.1:0",
        owner_worker_id=b"w0",
        actor_id=os.urandom(16),
        resources={"CPU": 1.0},
    ).to_wire()


def test_gcs_concurrent_register_and_list(loop):
    """N clients registering actors while others list must observe a
    consistent directory: every registration lands exactly once and the
    per-shard counters account for all of them."""

    async def main():
        gcs = GcsServer()
        await gcs.server.start()
        gcs.server.register_all(gcs)
        clients = [await RpcClient(gcs.server.address).connect()
                   for _ in range(4)]
        n_per_client = 50

        async def register_burst(client):
            return await asyncio.gather(*[
                client.call("register_actor", {"spec": _creation_spec(i)})
                for i in range(n_per_client)])

        async def list_loop(client):
            listings = []
            for _ in range(10):
                listings.append(await client.call("list_actors", {}))
                await asyncio.sleep(0)
            return listings

        results = await asyncio.gather(
            register_burst(clients[0]), register_burst(clients[1]),
            register_burst(clients[2]), list_loop(clients[3]))
        for replies in results[:3]:
            assert all(r["ok"] for r in replies)
        final = await clients[3].call("list_actors", {})
        assert len(final) == 3 * n_per_client
        assert len({a["actor_id"] for a in final}) == 3 * n_per_client
        # interleaved listings saw monotonically growing prefixes
        sizes = [len(l) for l in results[3]]
        assert sizes == sorted(sizes)
        # shard accounting covers the whole directory
        assert sum(gcs.actors.shard_sizes()) == 3 * n_per_client
        text = gcs._metrics_text()
        assert 'gcs_table_shard_size{table="actors"' in text
        assert 'gcs_table_shard_ops{table="task_events"' in text
        for c in clients:
            await c.close()
        await gcs.server.stop()

    loop.run_until_complete(main())


def test_gcs_task_events_sharded_cap_and_recency(loop):
    """Event ingestion through the vectorized add_task_events handler:
    the bounded log evicts globally-oldest and lists newest-first across
    shards."""

    async def main():
        gcs = GcsServer()
        gcs._TASK_EVENTS_CAP = 100  # shrink the cap for the test
        await gcs.server.start()
        gcs.server.register_all(gcs)
        client = await RpcClient(gcs.server.address).connect()
        ids = [os.urandom(16) for _ in range(150)]
        # two list payloads (one decode + one pass each), overlapping
        await client.call("add_task_events", {"events": [
            (tid, f"task{i}", "NORMAL_TASK", "RUNNING", float(i))
            for i, tid in enumerate(ids[:100])]})
        await client.call("add_task_events", {"events": [
            (tid, f"task{i + 100}", "NORMAL_TASK", "FINISHED",
             float(i + 100)) for i, tid in enumerate(ids[100:])]})
        assert len(gcs.task_events) == 100  # cap held
        listed = await client.call("list_task_events", {"limit": 1000})
        # newest-first: the most recent insertion leads
        assert listed[0]["name"] == "task149"
        names = [r["name"] for r in listed]
        assert names == [f"task{i}" for i in range(149, 49, -1)]
        await client.close()
        await gcs.server.stop()

    loop.run_until_complete(main())


def test_gcs_snapshot_roundtrip_with_sharded_tables(tmp_path, loop):
    """Snapshot → restart keeps sharded tables sharded (and a plain-dict
    snapshot from before sharding still loads via the rewrap path)."""

    async def main():
        path = str(tmp_path / "gcs_snapshot.pkl")
        gcs = GcsServer(persist_path=path)
        await gcs.server.start()
        gcs.server.register_all(gcs)
        client = await RpcClient(gcs.server.address).connect()
        for i in range(20):
            await client.call("register_actor", {"spec": _creation_spec(i)})
        gcs._write_snapshot()
        await client.close()
        await gcs.server.stop()

        revived = GcsServer(persist_path=path)
        assert isinstance(revived.actors, ShardedTable)
        assert len(revived.actors) == 20
        assert len(revived._pending_actors) == 20  # PENDING resumes

        # pre-shard snapshot shape: plain dicts get rewrapped on load
        legacy = {name: (dict(getattr(revived, name))
                         if name in ("actors", "task_events")
                         else getattr(revived, name))
                  for name in GcsServer._SNAPSHOT_TABLES}
        with open(path, "wb") as f:
            pickle.dump(legacy, f)
        revived2 = GcsServer(persist_path=path)
        assert isinstance(revived2.actors, ShardedTable)
        assert len(revived2.actors) == 20

    loop.run_until_complete(main())
