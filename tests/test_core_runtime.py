"""End-to-end core runtime tests: tasks, objects, actors on a live cluster.

Covers the reference's `python/ray/tests/test_basic*.py` ground: submission,
fan-out, plasma arg passing, put/get, error propagation, wait, nested tasks,
actor lifecycle/ordering, named + async actors.

One module-scoped cluster (this box has one CPU core; per-test clusters are
too slow) — tests are written to be order-independent.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
def square(x):
    return x * x


def test_basic_task():
    assert ray_tpu.get(square.remote(7), timeout=60) == 49


def test_fanout_tasks():
    refs = [square.remote(i) for i in range(16)]
    assert ray_tpu.get(refs, timeout=120) == [i * i for i in range(16)]


def test_kwargs_and_multiple_returns():
    @ray_tpu.remote(num_returns=2)
    def divmod_task(a, b=3):
        return a // b, a % b

    q, r = divmod_task.remote(17, b=5)
    assert ray_tpu.get([q, r], timeout=60) == [3, 2]


def test_plasma_roundtrip():
    @ray_tpu.remote
    def make(n):
        return np.ones(n, dtype=np.float32)

    @ray_tpu.remote
    def total(a):
        return float(a.sum())

    ref = make.remote(2_000_000)  # 8MB -> plasma
    assert ray_tpu.get(total.remote(ref), timeout=120) == 2_000_000.0


def test_put_get_small_and_large():
    small = ray_tpu.put({"a": 1})
    assert ray_tpu.get(small, timeout=30) == {"a": 1}
    arr = np.arange(1_000_000)
    large = ray_tpu.put(arr)
    np.testing.assert_array_equal(ray_tpu.get(large, timeout=60), arr)


def test_error_propagation():
    @ray_tpu.remote
    def boom():
        raise ValueError("intentional-failure")

    with pytest.raises(ray_tpu.RayTaskError, match="intentional-failure"):
        ray_tpu.get(boom.remote(), timeout=60)


def test_get_timeout():
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    with pytest.raises(ray_tpu.GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.5)


def test_wait():
    refs = [square.remote(i) for i in range(6)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=2, timeout=60)
    assert len(ready) >= 2
    assert len(ready) + len(not_ready) == 6


def test_wait_drain_loop():
    """The reference `wait_multiple_refs` pattern: drain a batch one
    wait() at a time. Exercises both the caller-thread ready fast path
    and the scan-and-pulse slow path; every ref must surface exactly
    once."""
    refs = [square.remote(i) for i in range(200)]
    seen = []
    not_ready = refs
    while not_ready:
        ready, not_ready = ray_tpu.wait(not_ready, timeout=60)
        assert ready, "wait timed out with tasks still pending"
        seen.extend(ready)
    assert len(seen) == 200
    assert {r.binary() for r in seen} == {r.binary() for r in refs}
    assert sorted(ray_tpu.get(seen)) == sorted(i * i for i in range(200))


def test_wait_timeout_none_ready():
    @ray_tpu.remote
    def sleepy():
        import time as _t
        _t.sleep(5)
        return 1

    ref = sleepy.remote()
    ready, not_ready = ray_tpu.wait([ref], timeout=0.3)
    assert ready == [] and not_ready == [ref]
    assert ray_tpu.get(ref, timeout=60) == 1


def test_task_burst_with_ref_dependencies():
    """A burst where later tasks depend on earlier ones' returns must not
    deadlock in the batched push pipeline (dependent specs ride their own
    frame — the batch reply would otherwise withhold the upstream value
    the executor is blocked on)."""
    @ray_tpu.remote
    def add_one(x):
        return x + 1

    ref = add_one.remote(0)
    refs = [ref]
    for _ in range(20):
        ref = add_one.remote(ref)
        refs.append(ref)
    assert ray_tpu.get(refs[-1], timeout=120) == 21

    # interleaved: independent + dependent specs in one burst
    base = [add_one.remote(i) for i in range(10)]
    chained = [add_one.remote(b) for b in base]
    assert ray_tpu.get(chained, timeout=120) == [i + 2 for i in range(10)]


def test_task_burst_batched_pipeline():
    """A burst bigger than the lease-pipeline window rides batch frames;
    results and errors must still map back per-task."""
    @ray_tpu.remote
    def may_fail(i):
        if i % 17 == 0:
            raise ValueError(f"boom {i}")
        return i

    refs = [may_fail.remote(i) for i in range(300)]
    ok, errs = 0, 0
    for i, r in enumerate(refs):
        try:
            assert ray_tpu.get(r, timeout=120) == i
            ok += 1
        except ray_tpu.RayTaskError as e:
            assert f"boom {i}" in str(e)
            errs += 1
    assert ok == 282 and errs == 18


def test_nested_tasks():
    @ray_tpu.remote
    def outer(n):
        inner = [square.remote(i) for i in range(n)]
        return sum(ray_tpu.get(inner))

    assert ray_tpu.get(outer.remote(4), timeout=120) == 14


def test_actor_state_and_ordering():
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.v = start

        def incr(self, n=1):
            self.v += n
            return self.v

    c = Counter.remote(100)
    refs = [c.incr.remote() for _ in range(50)]
    # Sequence ordering: the 50th increment sees all prior ones.
    assert ray_tpu.get(refs[-1], timeout=120) == 150


def test_named_actor():
    @ray_tpu.remote
    class Registry:
        def who(self):
            return "registry"

    Registry.options(name="test_named_actor").remote()
    h = ray_tpu.get_actor("test_named_actor")
    assert ray_tpu.get(h.who.remote(), timeout=60) == "registry"


def test_actor_handle_passing():
    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.v = 41

        def bump(self):
            self.v += 1
            return self.v

    @ray_tpu.remote
    def call_through(handle):
        return ray_tpu.get(handle.bump.remote())

    h = Holder.remote()
    assert ray_tpu.get(call_through.remote(h), timeout=120) == 42


def test_async_actor_concurrency():
    @ray_tpu.remote
    class AsyncWorker:
        async def work(self, x):
            import asyncio

            await asyncio.sleep(0.2)
            return x

    aw = AsyncWorker.options(max_concurrency=8).remote()
    # warm: actor creation spawns a worker (~2s JAX import) that must
    # not land inside the timed window
    ray_tpu.get(aw.work.remote(-1), timeout=120)
    start = time.time()
    out = ray_tpu.get([aw.work.remote(i) for i in range(8)], timeout=120)
    elapsed = time.time() - start
    assert out == list(range(8))
    # 8 concurrent 0.2s sleeps must overlap (serial would be 1.6s+).
    assert elapsed < 1.4


def test_actor_death_raises():
    @ray_tpu.remote
    class Mortal:
        def die(self):
            os._exit(1)

        def ping(self):
            return "pong"

    m = Mortal.remote()
    assert ray_tpu.get(m.ping.remote(), timeout=60) == "pong"
    with pytest.raises(Exception):
        ray_tpu.get(m.die.remote(), timeout=60)
    time.sleep(1)
    with pytest.raises(Exception):
        ray_tpu.get(m.ping.remote(), timeout=30)


def test_cluster_resources():
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 4.0


def test_actor_seq_epoch_resync():
    """Executor-side (epoch, seq) reorder buffer: a newer epoch flushes and
    resyncs at seq 0 (reconnect after connection loss); an older epoch runs
    immediately instead of wedging the stream."""
    import asyncio

    from ray_tpu._private.core_worker import CoreWorker
    from ray_tpu._private.task import TaskSpec, ACTOR_TASK

    class Stub:
        _actor_seq_state = {}
        dispatched = []
        _enqueue_ordered_collect = CoreWorker._enqueue_ordered_collect

        def _dispatch_actor_task(self, spec, fut):
            self.dispatched.append((spec.seq_epoch, spec.seq_no))

    stub = Stub()

    def spec(epoch, seq):
        return TaskSpec(task_id=b"t", job_id=b"j", name="m",
                        task_type=ACTOR_TASK, owner_worker_id=b"caller",
                        seq_no=seq, seq_epoch=epoch)

    async def run():
        enq = CoreWorker._enqueue_ordered
        # Epoch 1: seq 0 runs, seq 2 buffers (seq 1 lost with the wire).
        await enq(stub, spec(1, 0), None)
        await enq(stub, spec(1, 2), None)
        assert stub.dispatched == [(1, 0)]
        # Epoch 2 arrives: buffered (1,2) flushes, numbering resyncs at 0.
        await enq(stub, spec(2, 0), None)
        assert stub.dispatched == [(1, 0), (1, 2), (2, 0)]
        # In-order epoch 2 traffic flows normally.
        await enq(stub, spec(2, 1), None)
        assert stub.dispatched[-1] == (2, 1)
        # A stray old-epoch orphan executes immediately.
        await enq(stub, spec(1, 5), None)
        assert stub.dispatched[-1] == (1, 5)
        # Epoch 2 stream is unaffected by the orphan.
        await enq(stub, spec(2, 2), None)
        assert stub.dispatched[-1] == (2, 2)

    asyncio.run(run())


def test_runtime_context():
    """ray_tpu.get_runtime_context() exposes job/node/worker identity on
    the driver and task/actor ids inside workers (reference
    `python/ray/runtime_context.py`)."""
    import ray_tpu

    ctx = ray_tpu.get_runtime_context()
    assert ctx.get_worker_mode() == "driver"
    assert ctx.get_task_id() is None
    assert len(ctx.get_job_id()) > 0
    assert len(ctx.get_node_id()) > 0
    assert len(ctx.get_worker_id()) > 0
    assert ":" in ctx.gcs_address
    d = ctx.get()
    assert d["worker_mode"] == "driver"
    assert d["job_id"] == ctx.get_job_id()

    @ray_tpu.remote
    def task_ctx():
        c = ray_tpu.get_runtime_context()
        return {"mode": c.get_worker_mode(), "task_id": c.get_task_id(),
                "actor_id": c.get_actor_id(), "job_id": c.get_job_id()}

    info = ray_tpu.get(task_ctx.remote())
    assert info["mode"] == "worker"
    assert info["task_id"] is not None
    assert info["actor_id"] is None
    assert info["job_id"] == ctx.get_job_id()

    @ray_tpu.remote
    class A:
        def ctx(self):
            c = ray_tpu.get_runtime_context()
            return {"actor_id": c.get_actor_id(),
                    "task_id": c.get_task_id()}

    a = A.remote()
    info = ray_tpu.get(a.ctx.remote())
    assert info["actor_id"] is not None
    assert info["task_id"] is not None
    ray_tpu.kill(a)


def test_actor_burst_with_intra_burst_ref_dependency():
    """A burst where a later call's argument is an earlier call's ref —
    submitted back-to-back so they land in ONE submit-buffer flush. The
    batching fast path must not put both in one batched frame (the
    batch's single reply would withhold the first result the second
    task's argument resolution is waiting on — deadlock)."""

    @ray_tpu.remote
    class Chain:
        def produce(self, x):
            return x + 1

        def consume(self, v):
            return v * 10

    c = Chain.remote()
    ray_tpu.get(c.produce.remote(0))  # resolve actor (enable fast path)
    r1 = c.produce.remote(41)
    r2 = c.consume.remote(r1)  # same burst, depends on r1
    assert ray_tpu.get(r2, timeout=30) == 420
    # interleaved bursts keep working and stay ordered
    refs = []
    for i in range(20):
        a = c.produce.remote(i)
        refs.append(c.consume.remote(a))
    assert ray_tpu.get(refs, timeout=60) == [(i + 1) * 10
                                             for i in range(20)]


def test_actor_burst_with_nested_ref_dependency():
    """Same deadlock shape, but the dependency ref is buried inside a
    container arg (a supported pattern — nested refs arrive as refs and
    the body get()s them). Top-level entries are all by-value then, so
    the batch guard must detect the ref during pickling, not by wire
    tag."""

    @ray_tpu.remote
    class Chain:
        def produce(self, x):
            return x + 1

        def consume_nested(self, lst):
            return ray_tpu.get(lst[0], timeout=20) * 10

    c = Chain.remote()
    ray_tpu.get(c.produce.remote(0))  # resolve actor (enable fast path)
    r1 = c.produce.remote(41)
    r2 = c.consume_nested.remote([r1])  # same burst, nested dependency
    assert ray_tpu.get(r2, timeout=30) == 420
    # dict-nested too, in a burst loop
    refs = []
    for i in range(5):
        a = c.produce.remote(i)
        refs.append(c.consume_nested.remote({0: a}))
    assert ray_tpu.get(refs, timeout=60) == [(i + 1) * 10
                                             for i in range(5)]


def test_threaded_actor_concurrency_groups():
    """Named concurrency groups (reference
    concurrency_group_manager.h): per-group thread pools — 'io' (2) runs
    its methods concurrently while 'compute' (1) serializes, without
    either stealing the other's threads."""
    import time as time_mod

    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class Worker:
        @ray_tpu.method(concurrency_group="io")
        def fetch(self):
            time_mod.sleep(0.5)
            return time_mod.monotonic()

        @ray_tpu.method(concurrency_group="compute")
        def crunch(self):
            time_mod.sleep(0.5)
            return time_mod.monotonic()

        def plain(self):
            return "default"

    w = Worker.remote()
    ray_tpu.get(w.plain.remote(), timeout=60)  # actor up

    t0 = time_mod.monotonic()
    ray_tpu.get([w.fetch.remote(), w.fetch.remote()], timeout=60)
    io_elapsed = time_mod.monotonic() - t0
    assert io_elapsed < 0.95, f"io group did not run concurrently: {io_elapsed}"

    t0 = time_mod.monotonic()
    ray_tpu.get([w.crunch.remote(), w.crunch.remote()], timeout=60)
    compute_elapsed = time_mod.monotonic() - t0
    assert compute_elapsed > 0.95, \
        f"compute group (size 1) overlapped: {compute_elapsed}"

    # per-call override routes a method into another group
    t0 = time_mod.monotonic()
    ray_tpu.get([w.crunch.options(concurrency_group="io").remote(),
                 w.crunch.options(concurrency_group="io").remote()],
                timeout=60)
    assert time_mod.monotonic() - t0 < 0.95

    # unknown group fails loudly, not silently-default
    with pytest.raises(Exception, match="unknown concurrency group"):
        ray_tpu.get(w.plain.options(concurrency_group="nope").remote(),
                    timeout=60)
    ray_tpu.kill(w)


def test_async_actor_concurrency_groups():
    """Async actors get per-group semaphores on one event loop."""
    import time as time_mod

    @ray_tpu.remote(concurrency_groups={"io": 4}, max_concurrency=1)
    class AsyncWorker:
        @ray_tpu.method(concurrency_group="io")
        async def fetch(self):
            import asyncio
            await asyncio.sleep(0.4)
            return 1

        async def slow_default(self):
            import asyncio
            await asyncio.sleep(0.4)
            return 2

    w = AsyncWorker.remote()
    ray_tpu.get(w.slow_default.remote(), timeout=60)

    t0 = time_mod.monotonic()
    ray_tpu.get([w.fetch.remote() for _ in range(4)], timeout=60)
    assert time_mod.monotonic() - t0 < 1.1  # 4-deep io group overlaps

    t0 = time_mod.monotonic()
    ray_tpu.get([w.slow_default.remote(), w.slow_default.remote()],
                timeout=60)
    assert time_mod.monotonic() - t0 > 0.75  # default group is 1-deep
    ray_tpu.kill(w)
