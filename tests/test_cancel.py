"""ray_tpu.cancel() — pending, running, actor, recursive, and force
cancellation (reference: `ray.cancel`, `python/ray/_private/worker.py:2932`;
protocol `src/ray/protobuf/core_worker.proto:252-270`).

Covers VERDICT r3 item 5: cancel pending (dequeue), running (interrupt in
worker), and actor tasks, with recursive child cancel.
"""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def cluster():
    ray_tpu.init(num_cpus=2, num_tpus=0,
                 object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
def busy(seconds):
    # cooperative loop: async thread interrupts land at bytecode
    # boundaries, so a single long C-level sleep would not see them
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        time.sleep(0.02)
    return "done"


def _occupy_all_workers(n=2, seconds=8):
    """Saturate the worker pool so further tasks stay queued."""
    return [busy.remote(seconds) for _ in range(n)]


def test_cancel_pending_task():
    blockers = _occupy_all_workers()
    queued = busy.remote(0.1)
    time.sleep(0.3)  # let it reach a queue, not a worker
    ray_tpu.cancel(queued)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(queued, timeout=30)
    # blockers unaffected
    assert ray_tpu.get(blockers, timeout=60) == ["done", "done"]


def test_cancel_running_task():
    ref = busy.remote(30)
    time.sleep(1.0)  # ensure it is executing
    start = time.monotonic()
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    # the interrupt must beat the 30s run time by a wide margin
    assert time.monotonic() - start < 15


def test_cancel_finished_task_is_noop():
    ref = busy.remote(0.05)
    assert ray_tpu.get(ref, timeout=30) == "done"
    ray_tpu.cancel(ref)  # best-effort: already done
    assert ray_tpu.get(ref, timeout=30) == "done"


def test_cancel_async_actor_task():
    @ray_tpu.remote
    class AsyncWorker:
        async def slow(self):
            import asyncio

            await asyncio.sleep(60)
            return "never"

        async def ping(self):
            return "pong"

    a = AsyncWorker.options(max_concurrency=4).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    ref = a.slow.remote()
    time.sleep(1.0)
    start = time.monotonic()
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    assert time.monotonic() - start < 15
    # the actor itself survives the cancellation
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"


def test_cancel_recursive_children():
    @ray_tpu.remote
    def parent():
        children = [busy.remote(30)]
        ray_tpu.get(children)  # blocks until cancelled
        return "done"

    ref = parent.remote()
    time.sleep(2.0)  # parent running, child submitted
    ray_tpu.cancel(ref, recursive=True)
    with pytest.raises(ray_tpu.RayTaskError):
        ray_tpu.get(ref, timeout=30)
    # the child's worker frees up quickly: a fresh task must not wait
    # out the child's 30s run time
    start = time.monotonic()
    assert ray_tpu.get(busy.remote(0.05), timeout=30) == "done"
    assert time.monotonic() - start < 20


def test_cancel_force_kills_worker():
    @ray_tpu.remote
    def stuck():
        time.sleep(600)  # non-cooperative: only force can stop it

    ref = stuck.remote()
    time.sleep(1.0)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=60)
    # the pool replaces the killed worker; the cluster still works
    assert ray_tpu.get(busy.remote(0.05), timeout=60) == "done"
