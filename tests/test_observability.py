"""Observability tests: task events, state API, metrics, CLI.

Reference ground: `python/ray/tests/test_state_api.py`,
`test_metrics_agent.py`, `test_cli.py` — compressed.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import state as state_api


@pytest.fixture(scope="module", autouse=True)
def cluster(tmp_path_factory):
    # isolate this module's structured-event shards so
    # test_gcs_emits_lifecycle_events asserts on THIS cluster's events,
    # not stale machine-global state
    import os

    event_dir = str(tmp_path_factory.mktemp("cluster_events"))
    os.environ["RAY_TPU_EVENT_DIR"] = event_dir
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_EVENT_DIR", None)


def test_task_events_and_state_api():
    @ray_tpu.remote
    def tracked(x):
        return x + 1

    @ray_tpu.remote
    def exploder():
        raise ValueError("boom")

    assert ray_tpu.get(tracked.remote(1)) == 2
    with pytest.raises(ray_tpu.RayTaskError):
        ray_tpu.get(exploder.remote())

    deadline = time.monotonic() + 10
    tasks = []
    while time.monotonic() < deadline:
        tasks = state_api.list_tasks()
        names = {t["name"]: t["state"] for t in tasks}
        if names.get("tracked") == "FINISHED" and \
                names.get("exploder") == "FAILED":
            break
        time.sleep(0.5)
    names = {t["name"]: t["state"] for t in tasks}
    assert names.get("tracked") == "FINISHED"
    assert names.get("exploder") == "FAILED"
    # every record carries its (state, ts) transitions
    rec = next(t for t in tasks if t["name"] == "tracked")
    states = [s for s, _ in rec["events"]]
    assert "SUBMITTED" in states and "FINISHED" in states

    summary = state_api.summarize_tasks()
    assert summary["tracked"]["FINISHED"] >= 1


def test_list_actors_and_objects():
    import numpy as np

    @ray_tpu.remote
    class Holder:
        def ping(self):
            return 1

    h = Holder.options(name="state_holder").remote()
    ray_tpu.get(h.ping.remote())
    actors = state_api.list_actors()
    assert any(a["name"] == "state_holder" and a["state"] == "ALIVE"
               for a in actors)

    ref = ray_tpu.put(np.ones(500_000, np.uint8))  # plasma + pinned
    time.sleep(0.3)
    objs = state_api.list_objects()
    assert any(o["object_id"] == ref.hex() for o in objs)
    del ref
    ray_tpu.kill(h)


def test_metrics_registry_prometheus_text():
    reg = metrics_mod._Registry()
    c = metrics_mod.Counter("req_total", "requests", ("route",),
                            registry=reg)
    g = metrics_mod.Gauge("inflight", "in flight", registry=reg)
    hist = metrics_mod.Histogram("latency_s", "latency",
                                 boundaries=(0.1, 1.0), registry=reg)
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/b"})
    g.set(7)
    hist.observe(0.05)
    hist.observe(5.0)
    text = reg.prometheus_text()
    assert 'req_total{route="/a"} 1.0' in text
    assert 'req_total{route="/b"} 2.0' in text
    assert "inflight 7.0" in text
    assert 'latency_s_bucket{le="0.1"} 1' in text
    assert 'latency_s_bucket{le="+Inf"} 2' in text
    assert "latency_s_count 2" in text


def test_daemon_metrics_endpoint():
    """A cluster started with metrics ports serves Prometheus text."""
    from ray_tpu._private.node import Cluster

    cluster = Cluster()
    try:
        # spawn a raylet with a metrics port via CLI-style args
        import os

        session = cluster.session_dir
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.raylet",
             "--gcs-addr", cluster.gcs_addr,
             "--resources", '{"CPU": 1.0}',
             "--session-dir", session,
             "--labels", "{}",
             "--metrics-port", "18123",
             "--log-file", f"{session}/logs/mraylet.log"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            line = proc.stdout.readline().decode()
            if line.startswith("RAYLET_READY"):
                break
        body = urllib.request.urlopen(
            "http://127.0.0.1:18123/metrics", timeout=10).read().decode()
        assert "object_store_capacity_bytes" in body
        assert 'raylet_resource_available{resource="CPU"} 1.0' in body
        # flight-recorder plane: sharded-store contention + scheduler
        # queue depth ride the same scrape
        assert "object_store_lock_wait_ns_total" in body
        assert "object_store_shards" in body
        assert "scheduler_queue_depth" in body
        assert "scheduler_pick_node_total" in body
        assert body.endswith("# EOF\n")
        proc.terminate()
        proc.wait(timeout=10)
    finally:
        cluster.shutdown()


def test_one_scrape_sees_the_whole_system():
    """ISSUE 5 acceptance: one /metrics scrape of a process that
    exercised the dispatch plane exposes compile-cache, channel-hop,
    compiled-DAG and per-step training metrics from DEFAULT_REGISTRY
    (store-shard + scheduler families ride the daemon scrape, covered
    above)."""
    import asyncio

    import jax.numpy as jnp

    from ray_tpu import dag as dag_mod  # registers the DAG histogram
    from ray_tpu.experimental.channel import ShmChannel
    from ray_tpu.parallel.compile_cache import (ExecutableCache,
                                                compiled_step)
    from ray_tpu.util import step_profiler as sp

    # exercise: the compile cache ...
    tick = compiled_step(lambda x: x + 1, cache=ExecutableCache())
    tick(jnp.zeros(()))
    # ... the channel frame plane ...
    ch = ShmChannel.create(ShmChannel.make_name(990), capacity=4096)
    try:
        ch.write_frame(0, 1, b"payload")
        tag, seq, view = ch.read_frame()
        assert (tag, seq, bytes(view)) == (0, 1, b"payload")
        del view
        ch.release_frame()
    finally:
        ch.destroy()
        ch.close()
    # ... and the step recorder
    sp.record_step(1, 5.0, host_dispatch_ms=1.0, tokens=8,
                   flops=1e6, peak=1e12)

    async def scrape():
        server, port = await metrics_mod.serve_metrics()
        try:
            return await asyncio.get_event_loop().run_in_executor(
                None,
                lambda: urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=10).read().decode())
        finally:
            server.close()

    body = asyncio.run(scrape())
    assert "compile_cache_hits_total" in body
    assert "compile_cache_lowering_ms_total" in body
    assert 'channel_frames_total{op="write"}' in body
    assert "channel_stale_skips_total" in body
    # registered at dag-module import; series appear once a compiled
    # DAG executes (Prometheus histograms emit no samples at zero)
    assert "# TYPE compiled_dag_execute_seconds histogram" in body
    assert "train_steps_recorded_total" in body
    assert "train_step_mfu" in body
    assert body.endswith("# EOF\n")


def _cli_env(tmp_path):
    """Isolated CLI environment (the PR-4-era suite-load flakes came
    from every CLI test sharing the machine-global
    /tmp/ray_tpu/cli_node.json state file — and from same-second
    session-dir collisions): each test tracks its daemons in its OWN
    tmpdir state file, so concurrent/leftover clusters can't collide."""
    env = dict(os.environ)
    env.pop("RAY_TPU_ADDRESS", None)
    env["RAY_TPU_CLI_STATE_FILE"] = str(tmp_path / "cli_node.json")
    return env


def test_cli_status_and_list(tmp_path):
    """The operator CLI forms a standalone cluster, reports status, and
    tears it down."""
    env = _cli_env(tmp_path)
    state_file = env["RAY_TPU_CLI_STATE_FILE"]

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "start", "--head",
         "--port", "0", "--resources", '{"CPU": 2.0}'],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stderr
    assert "GCS started at" in out.stdout

    with open(state_file) as f:
        gcs_addr = json.load(f)["gcs_addr"]

    status = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "status",
         "--address", gcs_addr],
        capture_output=True, text=True, env=env, timeout=300)
    assert status.returncode == 0, status.stderr
    assert "alive node(s)" in status.stdout

    nodes = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "list", "nodes",
         "--address", gcs_addr],
        capture_output=True, text=True, env=env, timeout=300)
    assert nodes.returncode == 0, nodes.stderr
    assert gcs_addr.split(":")[0] in nodes.stdout  # host appears

    stop = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "stop"],
        capture_output=True, text=True, env=env, timeout=60)
    assert stop.returncode == 0
    assert "stopped pid" in stop.stdout


def test_structured_export_events(tmp_path, monkeypatch):
    """Structured events (reference src/ray/util/event.h): emitted by
    daemons at lifecycle transitions, merged + filtered by
    list_events. The running cluster's GCS wrote NODE_ADDED to the
    default dir at bring-up; this test uses an isolated dir."""
    from ray_tpu.util import events as export_events

    monkeypatch.setenv("RAY_TPU_EVENT_DIR", str(tmp_path / "ev"))
    # reset the per-process writer cache so the env change applies
    export_events._files.clear()
    try:
        export_events.report("GCS", "INFO", "NODE_ADDED",
                             "node abc joined", node_id="abc")
        export_events.report("RAYLET", "WARNING", "WORKER_DIED",
                             "worker 7 exited", pid=7)
        export_events.report("GCS", "ERROR", "NODE_DEAD",
                             "node abc dead", node_id="abc")

        evs = export_events.list_events()
        assert [e["label"] for e in evs] == [
            "NODE_ADDED", "WORKER_DIED", "NODE_DEAD"]
        assert export_events.list_events(source="GCS")[-1]["severity"] \
            == "ERROR"
        assert export_events.list_events(severity="WARNING")[0][
            "pid"] == 7
        assert export_events.list_events(label="NODE_DEAD")[0][
            "node_id"] == "abc"
    finally:
        export_events._files.clear()


def test_gcs_emits_lifecycle_events():
    """The live cluster's GCS daemon wrote NODE_ADDED events for its
    node registration to the default event dir."""
    from ray_tpu.util.events import list_events

    evs = list_events(source="GCS", label="NODE_ADDED")
    assert evs, "GCS should have recorded node registrations"
    assert all(e["severity"] == "INFO" for e in evs)


def test_events_export_otlp(tmp_path):
    """The structured event log exports as a valid OTLP/JSON Logs
    payload (resourceLogs -> scopeLogs -> logRecords), one resource per
    (source, pid) shard."""
    import json

    from ray_tpu.util import events as ev

    d = str(tmp_path / "events")
    old = os.environ.get("RAY_TPU_EVENT_DIR")
    os.environ["RAY_TPU_EVENT_DIR"] = d
    ev._files.clear()
    try:
        ev.report("GCS", "INFO", "NODE_ADDED", "node up", node_id="n1")
        ev.report("GCS", "ERROR", "NODE_DEAD", "node lost", node_id="n1")
        out = str(tmp_path / "logs.otlp.json")
        n = ev.export_otlp(out, path=d)
        assert n == 2
        payload = json.load(open(out))
        rl = payload["resourceLogs"]
        assert len(rl) == 1  # one (source, pid)
        svc = {a["key"]: a["value"] for a in rl[0]["resource"]["attributes"]}
        assert svc["service.name"]["stringValue"] == "ray_tpu.gcs"
        recs = rl[0]["scopeLogs"][0]["logRecords"]
        assert [r["severityText"] for r in recs] == ["INFO", "ERROR"]
        assert recs[1]["body"]["stringValue"] == "node lost"
        attrs = {a["key"]: a["value"]["stringValue"]
                 for a in recs[0]["attributes"]}
        assert attrs["node_id"] == "n1"
        assert attrs["label"] == "NODE_ADDED"
        assert int(recs[0]["timeUnixNano"]) > 1e18
    finally:
        ev._files.clear()
        if old is None:
            os.environ.pop("RAY_TPU_EVENT_DIR", None)
        else:
            os.environ["RAY_TPU_EVENT_DIR"] = old


def test_cli_memory(tmp_path):
    """`memory` reports per-node object-store usage and largest objects
    (reference `ray memory`'s primary-copy view)."""
    env = _cli_env(tmp_path)

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "start", "--head",
         "--port", "0", "--resources", '{"CPU": 2.0}'],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stderr
    with open(env["RAY_TPU_CLI_STATE_FILE"]) as f:
        gcs_addr = json.load(f)["gcs_addr"]
    try:
        driver = (
            "import numpy as np\n"
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import ray_tpu\n"
            f"ray_tpu.init(address={gcs_addr!r})\n"
            "refs = [ray_tpu.put(np.ones(1 << 20, np.uint8))"
            " for _ in range(3)]\n"
            "import time; time.sleep(0.5)\n"
        )
        r = subprocess.run([sys.executable, "-c", driver],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0, r.stderr
        mem = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "memory",
             "--address", gcs_addr],
            capture_output=True, text=True, env=env, timeout=300)
        assert mem.returncode == 0, mem.stderr
        assert "MB shm" in mem.stdout
        assert "primary copies" in mem.stdout
    finally:
        subprocess.run([sys.executable, "-m", "ray_tpu", "stop"],
                       capture_output=True, env=env, timeout=120)


def test_cli_serve_status_and_shutdown(tmp_path):
    """`serve status` observes a live Serve instance without starting
    one, and `serve shutdown` stops it (reference serve CLI)."""
    env = _cli_env(tmp_path)

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "start", "--head",
         "--port", "0", "--resources", '{"CPU": 4.0}'],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stderr
    with open(env["RAY_TPU_CLI_STATE_FILE"]) as f:
        gcs_addr = json.load(f)["gcs_addr"]
    try:
        # status with no serve instance: observer must not start one
        st = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "serve", "status",
             "--address", gcs_addr],
            capture_output=True, text=True, env=env, timeout=300)
        assert st.returncode == 0, st.stderr
        assert "no serve instance" in st.stdout

        driver = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import ray_tpu\n"
            "from ray_tpu import serve\n"
            f"ray_tpu.init(address={gcs_addr!r})\n"
            "@serve.deployment\n"
            "def echo(x):\n"
            "    return x\n"
            "serve.run(echo.bind())\n"
            "import time; time.sleep(30)\n"
        )
        drv = subprocess.Popen([sys.executable, "-c", driver], env=env,
                               stdout=subprocess.DEVNULL,
                               stderr=subprocess.DEVNULL)
        try:
            deadline = time.time() + 60
            while time.time() < deadline:
                st = subprocess.run(
                    [sys.executable, "-m", "ray_tpu", "serve", "status",
                     "--address", gcs_addr],
                    capture_output=True, text=True, env=env, timeout=300)
                if '"echo"' in st.stdout:
                    break
                time.sleep(2)
            assert '"echo"' in st.stdout, st.stdout

            down = subprocess.run(
                [sys.executable, "-m", "ray_tpu", "serve", "shutdown",
                 "--address", gcs_addr],
                capture_output=True, text=True, env=env, timeout=300)
            assert down.returncode == 0, down.stderr
            assert "shut down" in down.stdout
        finally:
            drv.terminate()
    finally:
        subprocess.run([sys.executable, "-m", "ray_tpu", "stop"],
                       capture_output=True, env=env, timeout=120)
