"""Benchmark suite — prints ONE JSON line.

Headline: GPT-2-125M single-chip training throughput (tokens/sec/chip)
with computed MFU — BASELINE.json's north-star metric ("Ray Train GPT-2
tokens/sec/chip"). The reference repo has no checked-in tokens/sec number
(BASELINE.md "Not in-repo"), so vs_baseline for the headline is derived
from hardware peaks: the north star asks for >=0.9x of an A100+NCCL
baseline, and at the commonly reported ~30% MFU for GPT-2-class DDP
training an A100 (312 bf16 TFLOP/s) yields `0.30 * 312e12 /
flops_per_token` tokens/s/chip. vs_baseline = ours / (0.9 * that).
On CPU (no TPU attached) the headline falls back to the control-plane
metric so the line is still comparable.

The `suite` field carries the rest of the reference's microbenchmark
shapes (`python/ray/_private/ray_perf.py`,
`release/perf_metrics/microbenchmark.json`), each with its own
vs_baseline against BASELINE.md:
- 1:1 sync actor calls        (baseline 2,097/s)
- 1:1 async actor calls       (baseline 9,063/s)
- n:n async actor calls       (baseline 27,688/s)
- single-client async tasks   (baseline 8,194/s)
- single-client put GB/s      (baseline 20.1 GB/s)
- single-client plasma get/s  (baseline 10,270/s)
"""

from __future__ import annotations

import json
import os
import threading
import time

BASELINES = {
    "1_1_actor_calls_sync": 2097.0,
    "1_1_actor_calls_async": 9063.0,
    "n_n_actor_calls_async": 27688.0,
    "single_client_tasks_sync": 971.0,
    "single_client_tasks_async": 8194.0,
    "multi_client_tasks_async": 21744.0,
    "single_client_put_gigabytes": 20.1,
    "multi_client_put_gigabytes": 35.9,
    "single_client_get_calls": 10270.0,
    "single_client_wait_1k_refs": 5.0,
    "single_client_get_object_containing_10k_refs": 13.3,
    "placement_group_create_removal": 839.0,
}

A100_BF16_PEAK = 312e12
A100_ASSUMED_MFU = 0.30
NORTH_STAR_FACTOR = 0.9

# any metric that dropped more than this vs the previous BENCH_r*.json
# is flagged in a REGRESSION block (ROADMAP item #5)
REGRESSION_DROP_FRACTION = 0.15


def _host_metadata() -> dict:
    """Box provenance for every row (VERDICT r3 weak #5: %-of-ceiling
    claims must be auditable — cpu model, core count, /dev/shm size and
    library versions pin down what 'this box' was)."""
    import platform

    meta = {
        "cpu_count": os.cpu_count(),
        "python": platform.python_version(),
        "platform": platform.platform(),
    }
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    meta["cpu_model"] = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    try:
        st = os.statvfs("/dev/shm")
        meta["dev_shm_bytes"] = st.f_frsize * st.f_blocks
    except OSError:
        pass
    for mod in ("jax", "numpy"):
        try:
            meta[f"{mod}_version"] = __import__(mod).__version__
        except Exception:  # noqa: BLE001
            pass
    return meta


def _scale_overrides() -> dict:
    """RAY_TPU_SCALE_SIZES decouples bench sizes from os.cpu_count()
    (ROADMAP item #5). Comma-separated ints, all optional, defaulting to
    the current host-scaled behavior, e.g.:

        RAY_TPU_SCALE_SIZES=raylets=50,actors=5000,tasks=20000,pgs=200,\
putters=8,put_mb=64
    """
    out = {}
    for part in os.environ.get("RAY_TPU_SCALE_SIZES", "").split(","):
        if "=" not in part:
            continue
        k, v = part.split("=", 1)
        try:
            out[k.strip()] = int(v)
        except ValueError:
            pass
    return out


def _store_stats() -> dict:
    """Lock/eviction counters of the live node's object store, emitted
    beside each phase-A row so contention claims are auditable."""
    try:
        from ray_tpu._private import worker_api

        store = worker_api._global_state.core_worker.store
        st = store.stats()
        st["num_shards"] = store.num_shards
        st["shards"] = store.shard_stats()
        return st
    except Exception as e:  # noqa: BLE001
        return {"error": repr(e)[:200]}


def _rpc_stats_snapshot() -> dict:
    """Driver-process RPC coalescing counters (rpc.RPC_STATS)."""
    from ray_tpu._private import rpc as rpc_mod

    st = rpc_mod.RPC_STATS
    return {k: getattr(st, k) for k in type(st).__slots__}


def _control_plane_attrib(before: dict) -> dict:
    """Where a control-plane number came from: the phase's driver-side
    frame-coalescing deltas plus the GCS/raylet scheduler + shard
    counters, scraped over RPC (`metrics_text`) from the live daemons.
    Driver-process counters only — worker subprocesses keep their own
    RPC_STATS — so msgs_per_frame understates cluster-wide coalescing.
    """
    now = _rpc_stats_snapshot()
    delta = {k: now[k] - before.get(k, 0) for k in now}
    delta["msgs_per_frame"] = round(
        delta["messages_sent"] / max(1, delta["frames_sent"]), 3)
    out = {"driver_rpc_delta": delta}
    try:
        from ray_tpu._private import worker_api

        cw = worker_api._global_state.core_worker

        async def scrape():
            gcs = await cw.gcs.call("metrics_text", {}, timeout=10.0)
            raylet = await cw._clients.get(cw.raylet_addr)
            ray = await raylet.call("metrics_text", {}, timeout=10.0)
            return gcs["text"], ray["text"]

        gcs_text, raylet_text = cw._run_sync(scrape())
        prefixes = ("scheduler_", "raylet_leases_granted",
                    "raylet_workers_returned", "raylet_pending_leases",
                    "gcs_table_shard_", "rpc_")

        def agg(text: str) -> dict:
            # sum labeled series per bare metric name — the artifact
            # wants attributable totals, not 8 shard rows per table
            rows = {}
            for ln in text.splitlines():
                if not ln or ln.startswith("#"):
                    continue
                name, _, val = ln.rpartition(" ")
                bare = name.split("{", 1)[0]
                if bare.startswith(prefixes):
                    try:
                        rows[bare] = round(
                            rows.get(bare, 0.0) + float(val), 3)
                    except ValueError:
                        pass
            return rows

        out["gcs"] = agg(gcs_text)
        out["raylet"] = agg(raylet_text)
    except Exception as e:  # noqa: BLE001
        out["scrape_error"] = repr(e)[:200]
    return out


def _check_regressions(suite: dict) -> list | None:
    """Self-comparison gate: load the newest BENCH_r*.json and flag any
    metric that dropped >15% (ROADMAP item #5). Returns the regression
    rows (also printed as a REGRESSION block on stderr) or None."""
    import glob
    import sys

    here = os.path.dirname(os.path.abspath(__file__))
    files = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    if not files:
        return None
    prev_path = files[-1]
    try:
        with open(prev_path) as f:
            prev = json.load(f)
        if "suite" in prev:
            prev_suite = prev["suite"]
        else:
            # driver-written artifact: the bench JSON line is embedded
            # (possibly truncated at the head) in the "tail" field —
            # raw-decode the suite object from its opening brace
            tail = prev.get("tail", "")
            key = tail.find('"suite"')
            brace = tail.find("{", key) if key != -1 else -1
            if brace == -1:
                return None
            prev_suite, _ = json.JSONDecoder().raw_decode(tail[brace:])
    except (OSError, ValueError):
        return None
    regressions = []
    for key, cur in suite.items():
        if not isinstance(cur, dict):
            continue
        now = cur.get("value")
        old = prev_suite.get(key)
        was = old.get("value") if isinstance(old, dict) else None
        if not isinstance(now, (int, float)) \
                or not isinstance(was, (int, float)) or was <= 0:
            continue
        if now < (1.0 - REGRESSION_DROP_FRACTION) * was:
            regressions.append({
                "metric": key,
                "prev": was,
                "now": now,
                "drop_pct": round(100 * (1 - now / was), 1),
                "baseline_file": os.path.basename(prev_path),
            })
    if regressions:
        print("REGRESSION (>15% drop vs "
              f"{os.path.basename(prev_path)}):", file=sys.stderr)
        for r in regressions:
            print(f"  {r['metric']}: {r['prev']} -> {r['now']} "
                  f"(-{r['drop_pct']}%)", file=sys.stderr)
    return regressions or None


# --------------------------------------------------------------------------
# Model benchmark (runs directly on the local accelerator, no cluster —
# matching the reference's release/train_tests harnesses which measure the
# framework's compute path, not the control plane).
# --------------------------------------------------------------------------

def _tpu_peak_bf16_flops(dev) -> float:
    """Per-chip bf16 peak by device generation (public spec sheets)."""
    kind = getattr(dev, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind or "v5litepod" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v6" in kind:
        return 918e12
    return 275e12  # v4 default

def _bench_train(model, loss_fn, vocab_size: int, batch: int, seq: int,
                 steps: int = 20):
    """Shared model-training bench harness: synth tokens, adamw, donated
    jitted step, then a timed loop.

    Sync note: on the axon-tunneled TPU platform block_until_ready does
    not actually wait, so pulling the scalar loss to the host
    (`float(loss)`) is the only reliable fence — it's a tiny transfer
    that depends on the final step.
    Returns (tokens_per_sec, n_params).
    """
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, vocab_size, (batch, seq + 1), np.int32))
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    params = jax.jit(model.init)(jax.random.PRNGKey(0), inputs)
    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, inputs, targets):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(model, p, inputs, targets))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, loss = train_step(params, opt_state, inputs,
                                         targets)
    float(loss)  # compile + warm + fence
    start = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, inputs,
                                             targets)
    float(loss)
    elapsed = time.perf_counter() - start
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    return batch * seq * steps / elapsed, n_params


def bench_gpt2_tokens_per_sec(steps: int = 20, batch: int = None,
                              seq: int = None):
    from functools import partial

    import jax

    from ray_tpu.models import GPT, GPTConfig
    from ray_tpu.models.gpt import flops_per_token as gpt_flops_per_token
    from ray_tpu.ops import flash_attention, fused_cross_entropy

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    # sized for one chip; on CPU shrink so the bench stays fast
    if on_tpu:
        batch, seq = batch or 16, seq or 1024
        cfg = GPTConfig.gpt2_125m(remat=False, max_seq_len=seq)
        peak_flops = _tpu_peak_bf16_flops(dev)
    else:
        cfg = GPTConfig.tiny()
        batch, seq = batch or 4, seq or 128
        peak_flops = None

    # single-chip hot path: pallas flash attention (scores never touch
    # HBM) + fused LM-head CE (bf16 logits, hand-written backward)
    model = GPT(cfg, attention_fn=partial(flash_attention, causal=True))

    def loss_fn(model, p, inputs, targets):
        hidden, wte = model.apply(p, inputs, return_hidden=True)
        return fused_cross_entropy(hidden, wte, targets)

    tokens_per_sec, n_params = _bench_train(
        model, loss_fn, cfg.vocab_size, batch, seq, steps)

    # PaLM appendix-B accounting (6N + attention term), shared with the
    # model module so the two can't drift
    fpt = gpt_flops_per_token(cfg, seq)
    result = {
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "platform": dev.platform,
        "params": int(n_params),
        "batch": batch,
        "seq": seq,
    }
    if peak_flops is not None:
        mfu = tokens_per_sec * fpt / peak_flops
        a100_tokens = A100_ASSUMED_MFU * A100_BF16_PEAK / fpt
        result["mfu"] = round(mfu, 4)
        result["vs_baseline"] = round(
            tokens_per_sec / (NORTH_STAR_FACTOR * a100_tokens), 3)
    return result


def bench_gpt2_long_context(steps: int = 10):
    """Single-chip long-context: GPT-2 at seq 4096 through the flash
    kernel (dense attention's f32 scores would be ~3.2 GB per layer at
    this shape). Multi-chip long context is ring/Ulysses attention —
    exercised by the driver's dryrun, not benchable on one chip."""
    import jax

    if jax.devices()[0].platform != "tpu":
        return {"skipped": "no TPU"}
    out = bench_gpt2_tokens_per_sec(steps=steps, batch=4, seq=4096)
    # vs_baseline is the seq-1024 north-star comparison; at 4096 the
    # per-token flops differ, so only throughput + MFU are meaningful
    out.pop("vs_baseline", None)
    return out


def bench_llama_tokens_per_sec(steps: int = 20):
    """Secondary model bench: Llama-125M (RMSNorm/RoPE/SwiGLU/GQA 12q:4kv)
    through the flash kernel's native grouped-KV path. TPU only."""
    from functools import partial

    import jax

    from ray_tpu.models.llama import Llama, LlamaConfig, flops_per_token
    from ray_tpu.ops import flash_attention, fused_cross_entropy

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        return {"skipped": "no TPU"}
    cfg = LlamaConfig.llama_125m(remat=False, max_seq_len=1024)
    batch, seq = 16, 1024
    model = Llama(cfg, attention_fn=partial(flash_attention, causal=True))

    # same hot path as the GPT-2 bench: fused LM-head CE (bf16 hidden x
    # tied embedding, logits never hit HBM)
    def loss_fn(model, p, inputs, targets):
        hidden, wte = model.apply(p, inputs, return_hidden=True)
        return fused_cross_entropy(hidden, wte, targets)

    tokens_per_sec, _ = _bench_train(
        model, loss_fn, cfg.vocab_size, batch, seq, steps)
    mfu = tokens_per_sec * flops_per_token(cfg, seq) / \
        _tpu_peak_bf16_flops(dev)
    return {
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "mfu": round(mfu, 4),
        "batch": batch,
        "seq": seq,
    }


# --------------------------------------------------------------------------
# Control-plane microbenchmarks (reference ray_perf.py shapes).
# --------------------------------------------------------------------------

def bench_pipeline_bubble():
    """Measured pipeline-schedule overhead on the 4-stage host mesh
    (VERDICT r2 item 9, r4 item 5; ROADMAP r5 #3): times the fused-loss
    pipeline train step through the AOT executable cache
    (`ray_tpu.parallel.fold_steps`) — params donated, grads applied
    in-jit, K=4 optimizer steps folded into ONE dispatch via lax.scan
    over prefetched on-device batches — which is how a dispatch-bound
    training loop should invoke it. Fits the structural model
    t(M) = a + c*(M + S - 1) by least squares over four microbatch
    counts and validates on a held-out fifth; `a` is the PER-STEP fixed
    driver overhead (the r5 #3 "< 2 ms" number) and the executable
    cache counters ride along for the dispatch_overhead phase.
    bubble = (S-1)/(M+S-1) (identical for GPipe and 1F1B in the
    single-jit formulation — see ray_tpu/parallel/pipeline.py). Runs in
    a forced-CPU subprocess so it never competes with the TPU phases
    for the chip."""
    import subprocess
    import sys

    code = r"""
import json, time
import jax
# a sitecustomize may import jax before this code runs; force the
# platform on the live config (mirrors __graft_entry__.dryrun_multichip)
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from ray_tpu.parallel.mesh import build_mesh
from ray_tpu.parallel.compile_cache import (
    ExecutableCache, fold_steps, stack_batches)
from ray_tpu.parallel.pipeline import (
    bubble_fraction, pipeline_train_step, stack_stage_params)

S, DIM, MB_ROWS, K = 4, 256, 8, 4   # K = steps_per_call (one dispatch)
mesh = build_mesh({"pp": S}, devices=jax.devices()[:S])
rng = np.random.RandomState(0)
params = stack_stage_params([
    {"w": jnp.asarray(rng.randn(DIM, DIM) * 0.05, jnp.float32)}
    for _ in range(S)])

def stage_fn(p, h):
    for _ in range(4):
        h = jnp.tanh(h @ p["w"])
    return h

def loss_fn(o, t):
    return jnp.mean(jnp.square(o - t))

def train_step(ps, batch):
    x, y = batch
    loss, g = pipeline_train_step(
        stage_fn, loss_fn, ps, x, y, mesh,
        num_microbatches=batch_microbatches(x))
    return jax.tree_util.tree_map(
        lambda p, gg: p - 1e-3 * gg, ps, g), loss

def batch_microbatches(x):
    return x.shape[0] // MB_ROWS

cache = ExecutableCache()
multi = fold_steps(train_step, K, cache=cache)
_batches = {}

def _get_batches(M):
    # K prefetched on-device batches, stacked on a leading axis
    if M not in _batches:
        _batches[M] = stack_batches([
            (jnp.asarray(rng.randn(MB_ROWS * M, DIM), jnp.float32),
             jnp.asarray(rng.randn(MB_ROWS * M, DIM), jnp.float32))
            for _ in range(K)])
    return _batches[M]

def timed(M):
    batches = _get_batches(M)
    ps = jax.tree_util.tree_map(lambda p: p.copy(), params)
    ps, losses = multi(ps, batches)   # compile (first pass) + warm
    jax.block_until_ready(losses)
    n, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < 1.5:
        ps, losses = multi(ps, batches)  # ONE dispatch per K steps
        jax.block_until_ready(losses)
        n += K
    return (time.perf_counter() - t0) / n

# palindromic double pass cancels slow drift on shared hosts
FIT_MS, HOLD_M = (4, 8, 24, 32), 16
order = FIT_MS + (HOLD_M,)
acc = {M: [] for M in order}
for M in order + order[::-1]:
    acc[M].append(timed(M))
ts = {M: sum(v) / len(v) for M, v in acc.items()}
# least-squares t = a + c*(M+S-1) over the fit points
xs = np.array([M + S - 1 for M in FIT_MS], np.float64)
ys = np.array([ts[M] for M in FIT_MS], np.float64)
c, a = np.polyfit(xs, ys, 1)
hold_pred = a + c * (HOLD_M + S - 1)
t1, t3 = ts[4], ts[32]
pred = ((4 + S - 1) / 4) / ((32 + S - 1) / 32)
meas = (t1 / 4) / (t3 / 32)
print(json.dumps({
    "bubble_m4": round(bubble_fraction(S, 4), 4),
    "bubble_m32": round(bubble_fraction(S, 32), 4),
    "step_s_m4": round(t1, 4), "step_s_m32": round(t3, 4),
    "per_microbatch_ratio_measured": round(meas, 3),
    "per_microbatch_ratio_predicted_no_overhead": round(pred, 3),
    "fixed_dispatch_overhead_s": round(float(a), 5),
    "per_microbatch_cost_s": round(float(c), 5),
    "steps_per_call": K,
    "executable_cache": cache.stats.as_dict() | {
        "entries": cache.size()},
    "holdout_m16_measured_s": round(ts[HOLD_M], 4),
    "holdout_m16_model_s": round(float(hold_pred), 4),
    "holdout_residual_pct": round(
        100 * abs(ts[HOLD_M] - hold_pred) / ts[HOLD_M], 2),
}))
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=4")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=420,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env)
    except subprocess.TimeoutExpired:
        return {"error": "pipeline bench subprocess timed out"}
    if proc.returncode != 0:
        return {"error": proc.stderr[-300:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_dispatch_overhead(pipeline_bubble: dict | None = None):
    """Driver-dispatch overhead phase (ROADMAP r5 #3, twice missed).

    Reports the three numbers that define the sub-2 ms dispatch plane:
    (a) the fitted per-step fixed overhead `a` from
    `bench_pipeline_bubble` (AOT cached executable, donated carries,
    K-step folding) plus its executable-cache hit/miss counters, (b)
    the AOT dispatch cost in isolation — µs per call of a cached
    trivial executable, the floor any training step pays — and (c)
    compiled-DAG round-trip latency over the zero-pickle channel plane
    (3-stage actor chain, raw-header frames, FIFO-token wakeups).
    `compiled_dag_roundtrips_per_s` is emitted value-style so the >15%
    REGRESSION self-comparison gates it like every other rate."""
    import statistics

    out: dict = {"dispatch_overhead": {}}
    detail = out["dispatch_overhead"]
    if isinstance(pipeline_bubble, dict) and \
            "fixed_dispatch_overhead_s" in pipeline_bubble:
        detail["fixed_dispatch_overhead_s"] = \
            pipeline_bubble["fixed_dispatch_overhead_s"]
        detail["meets_2ms_target"] = \
            pipeline_bubble["fixed_dispatch_overhead_s"] < 0.002
        detail["steps_per_call"] = pipeline_bubble.get("steps_per_call")
        detail["executable_cache"] = pipeline_bubble.get(
            "executable_cache")

    # (b) bare AOT dispatch: cached-executable call overhead in µs
    import jax.numpy as jnp

    from ray_tpu.parallel.compile_cache import (ExecutableCache,
                                                compiled_step)

    cache = ExecutableCache()
    tick = compiled_step(lambda x: x + 1, cache=cache)
    x = jnp.zeros((), jnp.float32)
    for _ in range(50):
        x = tick(x)  # 1 miss + warm hits
    n, start = 0, time.perf_counter()
    while time.perf_counter() - start < 1.0:
        x = tick(x)
        n += 1
    x.block_until_ready()
    detail["aot_dispatch_us"] = round(
        1e6 * (time.perf_counter() - start) / n, 1)
    detail["aot_cache"] = cache.stats.as_dict()

    # (c) compiled-DAG round trip on the zero-pickle channel plane
    import ray_tpu
    from ray_tpu import dag as dag_mod

    ray_tpu.init(num_cpus=4, object_store_memory=64 << 20)
    try:
        @ray_tpu.remote
        class Stage:
            def __init__(self, add):
                self.add = add

            def f(self, x):
                return x + self.add

        a, b, c = Stage.remote(1), Stage.remote(10), Stage.remote(100)
        ray_tpu.get([a.f.remote(0), b.f.remote(0), c.f.remote(0)],
                    timeout=60)
        node = dag_mod.bind(
            c.f, dag_mod.bind(b.f, dag_mod.bind(
                a.f, dag_mod.InputNode())))
        compiled = node.experimental_compile()
        for i in range(100):
            compiled.execute(i)
        lat = []
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 2.0:
            t0 = time.perf_counter()
            compiled.execute(n)
            lat.append(time.perf_counter() - t0)
            n += 1
        out["compiled_dag_roundtrips_per_s"] = n / (
            time.perf_counter() - start)
        detail["compiled_dag_rtt_us_p50"] = round(
            1e6 * statistics.median(lat), 1)
        compiled.teardown()
    finally:
        ray_tpu.shutdown()
    return out


def bench_observability_overhead():
    """Cost ceiling of the passive observability plane. ISSUE 20 widens
    the measured configuration: the interleaves below now run with the
    WHOLE health/alert plane live — a tsdb Sampler scraping at 1s, the
    SLO AlertEvaluator riding its scrape tick, and a Watchdog sweeping
    the registered loop probes (the engine pump registers one on
    start()) — so `observability_dispatch_per_s` /
    `observability_serve_req_per_s` and the <1% targets price
    recorder + evaluator + watchdog together, not the recorders alone.
    """
    from ray_tpu._private import health as health_mod
    from ray_tpu.util import slo as slo_mod
    from ray_tpu.util import tsdb as tsdb_mod

    sampler = tsdb_mod.Sampler(interval_s=1.0)
    evaluator = slo_mod.AlertEvaluator(sampler.db,
                                       register_metrics=False)
    evaluator.attach(sampler)
    sampler.start()
    watchdog = health_mod.Watchdog(source="BENCH",
                                   interval_s=0.5).start()
    try:
        out = _bench_observability_measured()
    finally:
        sampler.stop()
        watchdog.stop()
    out["observability_overhead"].update({
        "alert_plane_active": True,
        "alert_evaluations": evaluator.evaluations,
        "watchdog_checks": watchdog.checks,
        "alerts_fired_during_bench": evaluator.firing(),
    })
    return out


def _bench_observability_measured():
    """Cost ceiling of the flight-recorder plane (ISSUE 5): the step
    profiler is ALWAYS ON, so its price on the sub-2 ms dispatch path
    PR 4 bought must stay under 1%. Times the same cached-executable
    dispatch loop with the recorder disabled and enabled (palindromic
    interleave, medians — slow drift on shared hosts cancels), reports
    the delta, and emits `observability_dispatch_per_s` value-style so
    the >15% REGRESSION self-comparison gates the *absolute* dispatch
    rate with the recorder on. Also measures the raw record_step cost
    and proves the ring stays bounded under sustained stepping.

    ISSUE 12 extends the phase with the serve-path twin: the same
    on/off interleave over a closed-loop LLM engine holds the REQUEST
    recorder (per-request phase stamps + histogram folds) under 1% of
    serve req/s, and `observability_serve_req_per_s` rides the same
    >15% REGRESSION gate."""
    import statistics

    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.parallel.compile_cache import (ExecutableCache,
                                                compiled_step)
    from ray_tpu.serve.llm import EngineConfig, LLMEngine
    from ray_tpu.util import request_recorder as rr
    from ray_tpu.util import step_profiler as sp

    cache = ExecutableCache()
    w = jnp.asarray(np.random.RandomState(0).randn(192, 192),
                    jnp.float32)

    def step(x):
        for _ in range(8):
            x = jnp.tanh(x @ w)
        return x

    tick = compiled_step(step, cache=cache)
    x = jnp.ones((192, 192), jnp.float32)
    x = tick(x)  # compile
    x.block_until_ready()

    def per_call_us() -> float:
        nonlocal x
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 0.35:
            x = tick(x)
            n += 1
        x.block_until_ready()
        return 1e6 * (time.perf_counter() - start) / n

    was_enabled = sp.enabled()
    dis, en = [], []
    try:
        per_call_us()  # warm both code paths before measuring
        # strict alternation, min-of-passes: min is robust against the
        # scheduler-noise spikes a shared/1-core box injects (the
        # recorder's cost is deterministic; the noise is one-sided)
        for on in (False, True) * 6:
            sp.set_enabled(on)
            (en if on else dis).append(per_call_us())
    finally:
        sp.set_enabled(was_enabled)
    dis_us = min(dis)
    en_us = min(en)
    overhead_pct = 100.0 * (en_us - dis_us) / dis_us

    # raw recorder costs, in isolation
    t0 = time.perf_counter()
    reps = 20000
    for i in range(reps):
        sp.record_step(i, 1.0, host_dispatch_ms=0.5, tokens=1)
    record_us = 1e6 * (time.perf_counter() - t0) / reps
    ring_len_after = len(sp.ring().recent())
    bounded = ring_len_after <= sp.ring().capacity

    # -- serve-path twin (ISSUE 12): the request recorder's price on
    # engine req/s. Two measurements: (a) an on/off interleave over a
    # closed-loop engine — empirical but noise-bounded on a 1-core box
    # (pass-to-pass scheduler/GC noise is ±2-3%, an order of magnitude
    # above the recorder's true cost; a fully STUBBED recorder still
    # reads 2-4% on this estimator), and (b) the isolated per-record
    # cost — the serve analog of `record_step_us` above — multiplied
    # by the measured steady req/s. The <1% target keys on (b): it is
    # deterministic and is exactly the recorder's share of request
    # wall time; (a) rides along as the empirical cross-check.
    import gc

    eng = LLMEngine(model="llama",
                    engine_config=EngineConfig(batch_buckets=(1, 2, 4),
                                               prefill_buckets=(8,)),
                    seed=0)
    eng.warmup()
    eng.start()

    def serve_req_per_s() -> float:
        gc.collect()  # cross-pass GC bleed dominates at this grain
        n = 0
        t0 = time.perf_counter()
        stop_at = t0 + 0.75
        while time.perf_counter() < stop_at:
            req = eng.submit([3, 4, 5], 4)
            req.result(timeout=60)
            n += 1
        return n / (time.perf_counter() - t0)

    rr_was = rr.enabled()
    srv_off, srv_on, deltas = [], [], []
    try:
        # warm to steady state FIRST: the engine's closed-loop rate
        # climbs for several seconds after start (allocator/dispatch
        # warmup), and drift inside the interleave biases whichever
        # side runs later
        prev = 0.0
        for _ in range(16):
            cur = serve_req_per_s()
            if prev and abs(cur - prev) / cur < 0.02:
                break
            prev = cur
        # adjacent-pair estimator: compare each on-pass against the
        # off-pass RIGHT NEXT to it, alternate which side goes first
        # (residual drift cancels across pairs), median pairwise delta
        for i in range(6):
            order = (False, True) if i % 2 == 0 else (True, False)
            pair = {}
            for on in order:
                rr.set_enabled(on)
                pair[on] = serve_req_per_s()
            srv_off.append(pair[False])
            srv_on.append(pair[True])
            deltas.append(100.0 * (pair[False] - pair[True])
                          / pair[False])
    finally:
        rr.set_enabled(rr_was)
        eng.quiesce(timeout=60)
        eng.shutdown()
    off_rps = max(srv_off)
    on_rps = max(srv_on)
    serve_interleave_pct = statistics.median(deltas)

    # (b) isolated per-record cost at this request shape x measured
    # req/s -> the recorder's share of request wall time
    rr.set_enabled(True)
    try:
        t0 = time.perf_counter()
        for i in range(reps):
            rr.record_engine(None, ts=0.0, total_ms=2.0, queue_ms=0.1,
                             admission_ms=0.1, prefill_ms=1.0,
                             decode_ms=0.8, ttft_ms=1.2, tpot_ms=0.3,
                             tokens_in=3, tokens_out=4, job="bench")
        record_req_us = 1e6 * (time.perf_counter() - t0) / reps
        rr.clear()
    finally:
        rr.set_enabled(rr_was)
    serve_cost_pct = record_req_us * on_rps / 1e4  # us/req * req/s

    detail = {
        "dispatch_us_recorder_off": round(dis_us, 2),
        "dispatch_us_recorder_on": round(en_us, 2),
        "overhead_pct": round(overhead_pct, 2),
        "meets_1pct_target": overhead_pct < 1.0,
        "record_step_us": round(record_us, 3),
        "dispatch_sample_interval": sp.dispatch_stats()[
            "sample_interval"],
        "ring_capacity": sp.ring().capacity,
        "ring_bounded_after_sustained_stepping": bounded,
        "serve_req_per_s_recorder_off": round(off_rps, 1),
        "serve_req_per_s_recorder_on": round(on_rps, 1),
        # empirical cross-check; on a 1-core box its noise floor is
        # ±2-3% (a stubbed recorder reads the same), so the target
        # keys on the deterministic cost share below
        "serve_interleave_pct": round(serve_interleave_pct, 2),
        "record_request_us": round(record_req_us, 3),
        "serve_recorder_cost_pct": round(serve_cost_pct, 3),
        "serve_meets_1pct_target": serve_cost_pct < 1.0,
    }
    return {
        "observability_overhead": detail,
        # value-keyed: the >15% REGRESSION gate compares these rates
        # like every other suite metric
        "observability_dispatch_per_s": 1e6 / en_us,
        "observability_serve_req_per_s": on_rps,
    }


def bench_scale_envelope():
    """Scale-envelope rows (reference `release/benchmarks/README.md`:
    2k+ nodes / 40k+ actors / 10k+ simultaneous tasks / 1k+ PGs across
    a 64-node cluster; harnesses `distributed/test_many_{actors,tasks,
    pgs}.py`). Scaled to one box: the raylets run in virtual-worker
    mode (`RAY_TPU_VIRTUAL_WORKERS` — in-process stub workers, real
    GCS/scheduler/gossip/lease machinery, the same trivial workload the
    reference envelope uses). Sizes scale with the host so the 1-core
    build box smoke-runs the same phase the driver box runs big."""
    import ray_tpu
    from ray_tpu._private.node import Cluster

    ncpu = os.cpu_count() or 1
    # RAY_TPU_SCALE_SIZES (raylets=/actors=/tasks=/pgs=) decouples the
    # envelope from os.cpu_count() so a 50-raylet/5k-actor run can be
    # recorded on any box; defaults preserve the host-scaled behavior
    scale = _scale_overrides()
    n_raylets = scale.get("raylets", max(8, min(50, 3 * ncpu)))
    n_actors = scale.get("actors", max(300, min(5000, 100 * ncpu)))
    n_tasks = scale.get("tasks", max(2000, min(20000, 400 * ncpu)))
    n_pgs = scale.get("pgs", max(20, min(200, 4 * ncpu)))
    out = {}
    os.environ["RAY_TPU_VIRTUAL_WORKERS"] = "1"
    cluster = None
    try:
        cluster = Cluster(head_resources={"CPU": 16.0},
                          object_store_memory=16 << 20)
        for _ in range(n_raylets - 1):
            cluster.add_node({"CPU": 16.0},
                             object_store_memory=16 << 20)
        ray_tpu.init(address=cluster.gcs_addr)

        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if len([n for n in ray_tpu.nodes() if n["Alive"]]) \
                    == n_raylets:
                break
            time.sleep(0.5)
        out["scale_num_raylets"] = len(
            [n for n in ray_tpu.nodes() if n["Alive"]])

        @ray_tpu.remote(num_cpus=0.1)
        class A:
            def ping(self):
                return None

        start = time.perf_counter()
        actors = [A.remote() for _ in range(n_actors)]
        ray_tpu.get([a.ping.remote() for a in actors], timeout=900)
        out["scale_actors_launched_per_sec"] = n_actors / (
            time.perf_counter() - start)
        out["scale_num_actors"] = n_actors

        @ray_tpu.remote(num_cpus=1.0)
        def noop():
            return None

        start = time.perf_counter()
        refs = [noop.remote() for _ in range(n_tasks)]
        ray_tpu.get(refs, timeout=900)
        out["scale_tasks_per_sec"] = n_tasks / (
            time.perf_counter() - start)
        out["scale_num_tasks"] = n_tasks

        start = time.perf_counter()
        pgs = [ray_tpu.placement_group([{"CPU": 0.5}, {"CPU": 0.5}],
                                       strategy="PACK")
               for _ in range(n_pgs)]
        created = sum(1 for pg in pgs if pg.ready(timeout=300))
        for pg in pgs:
            ray_tpu.remove_placement_group(pg)
        # only PGs that actually reached CREATED count toward the rate
        out["scale_pgs_per_sec"] = created / (time.perf_counter() - start)
        out["scale_num_pgs"] = created
        if created != n_pgs:
            out["scale_pgs_failed"] = n_pgs - created
        return out
    finally:
        os.environ.pop("RAY_TPU_VIRTUAL_WORKERS", None)
        try:
            ray_tpu.shutdown()
        except Exception:  # noqa: BLE001
            pass
        if cluster is not None:
            cluster.shutdown()


def bench_rpc_fanin():
    """Transport-level microbench, no cluster: 4 clients × 256-deep
    concurrent echo bursts against one RpcServer — the pure fan-in
    shape the write coalescer exists for — plus a serial ping-pong
    row pinning that coalescing adds no latency to request/response
    traffic. Runs in-process, so it is the one control-plane row
    that is stable on the 1-core build box."""
    import asyncio

    from ray_tpu._private import rpc as rpc_mod
    from ray_tpu._private.rpc import RpcClient, RpcServer

    async def run():
        server = RpcServer()

        async def echo(payload):
            return payload

        server.register("echo", echo)
        await server.start()
        clients = [await RpcClient(server.address).connect()
                   for _ in range(4)]

        async def burst(client, n):
            await asyncio.gather(
                *[client.call("echo", i) for i in range(n)])

        await asyncio.gather(*[burst(c, 64) for c in clients])  # warm
        before = _rpc_stats_snapshot()
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 4.0:
            await asyncio.gather(*[burst(c, 256) for c in clients])
            n += 4 * 256
        fanin = n / (time.perf_counter() - start)
        now = _rpc_stats_snapshot()
        msgs = now["messages_sent"] - before["messages_sent"]
        frames = now["frames_sent"] - before["frames_sent"]

        c = clients[0]
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 2.0:
            for _ in range(100):
                await c.call("echo", 1)
            n += 100
        serial = n / (time.perf_counter() - start)
        for c in clients:
            await c.close()
        await server.stop()
        return fanin, serial, msgs, frames

    fanin, serial, msgs, frames = asyncio.run(run())
    return {
        "rpc_fanin_calls_async": fanin,
        "rpc_serial_calls_sync": serial,
        "rpc_fanin_coalescing": {
            "messages_sent": msgs,
            "frames_sent": frames,
            "msgs_per_frame": round(msgs / max(1, frames), 3),
        },
    }


def bench_control_plane():
    """Each phase gets an isolated cluster sized to the machine: worker
    processes beyond the core count thrash instead of pipelining, and a
    phase's leftover actors would steal cycles from the next phase's
    measurement."""

    import numpy as np

    import ray_tpu

    ncpu = os.cpu_count() or 1
    scale = _scale_overrides()
    out = {}

    # -- phase A: object plane (no task workers at all) -----------------
    ray_tpu.init(num_cpus=1, object_store_memory=1 << 30)
    try:
        arr = np.ones(64 * 1024 * 1024, np.uint8)  # 64 MiB
        # the raw-memory ceiling `put` is up against on THIS box: a
        # single-thread copy of the same buffer (VERDICT r3 weak #5 —
        # the claimed %-of-ceiling must be measured, not asserted)
        dst = np.empty_like(arr)
        np.copyto(dst, arr)
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 1.5:
            np.copyto(dst, arr)
            n += 1
        out["host_memcpy_gigabytes"] = (
            n * arr.nbytes / (time.perf_counter() - start) / 1e9)

        ray_tpu.put(arr)  # warm
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 3.0:
            ray_tpu.put(arr)
            n += 1
        out["single_client_put_gigabytes"] = (
            n * arr.nbytes / (time.perf_counter() - start) / 1e9)
        out["single_client_put_store"] = _store_stats()

        small_ref = ray_tpu.put(np.ones(1024, np.uint8))
        for _ in range(100):
            ray_tpu.get(small_ref)
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 3.0:
            for _ in range(100):
                ray_tpu.get(small_ref)
            n += 100
        out["single_client_get_calls"] = n / (time.perf_counter() - start)
    finally:
        ray_tpu.shutdown()

    # -- phase A2: multi-client puts (reference `put_multi`: 10 tasks
    # each putting 10 x 80 MB). Recorded as a writer-count scaling
    # curve (1/2/4 writers by default) so the sharded store's scaling —
    # not just one aggregate number — lands in the bench artifact.
    # RAY_TPU_SCALE_SIZES putters=/put_mb= decouple the shape from
    # os.cpu_count(). -----------------------------------------------------
    curve_counts = [1, 2, 4]
    if scale.get("putters"):
        curve_counts = sorted({1, 2, 4, scale["putters"]})
    nbytes = scale.get("put_mb", 32) << 20
    count = 4
    max_w = max(curve_counts)
    ray_tpu.init(num_cpus=max_w,
                 object_store_memory=min(8 << 30, (8 * nbytes) * max_w))
    try:
        @ray_tpu.remote
        def do_put(nbytes, count):
            import numpy as _np

            block = _np.ones(nbytes, _np.uint8)
            for _ in range(count):
                ray_tpu.put(block)
            return None

        ray_tpu.get([do_put.remote(nbytes, 1)
                     for _ in range(max_w)])  # warm workers
        curve = {}
        for writers in curve_counts:
            n, start = 0, time.perf_counter()
            while time.perf_counter() - start < 4.0:
                ray_tpu.get([do_put.remote(nbytes, count)
                             for _ in range(writers)])
                n += writers * count
            curve[str(writers)] = round(
                n * nbytes / (time.perf_counter() - start) / 1e9, 3)
        out["multi_client_put_scaling"] = {
            "writers_gigabytes": curve,
            "put_mb": nbytes >> 20,
        }
        # the headline multi-client number is the best multi-writer
        # aggregate (>=2 writers), matching the reference's
        # many-putters shape
        out["multi_client_put_gigabytes"] = max(
            v for w, v in curve.items() if int(w) > 1)
        out["multi_client_put_store"] = _store_stats()
    finally:
        ray_tpu.shutdown()

    # -- phase B: tasks --------------------------------------------------
    ray_tpu.init(num_cpus=min(4, ncpu), object_store_memory=256 << 20)
    try:
        @ray_tpu.remote
        def noop():
            return None

        ray_tpu.get(noop.remote())
        ray_tpu.get([noop.remote() for _ in range(64)])
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 3.0:
            refs = [noop.remote() for _ in range(1000)]
            ray_tpu.get(refs)
            n += 1000
        out["single_client_tasks_async"] = n / (time.perf_counter() - start)

        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 3.0:
            ray_tpu.get(noop.remote())
            n += 1
        out["single_client_tasks_sync"] = n / (time.perf_counter() - start)

        # reference `wait_multiple_refs`: submit 1k tasks, then ray.wait
        # them out one at a time (1k wait calls per op)
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 4.0:
            not_ready = [noop.remote() for _ in range(1000)]
            while not_ready:
                _ready, not_ready = ray_tpu.wait(not_ready)
            n += 1
        out["single_client_wait_1k_refs"] = (
            n / (time.perf_counter() - start))

        # reference `get_containing_object_ref`: one object holding 10k
        # refs, repeatedly fetched (exercises nested-ref deserialization
        # + borrower registration)
        @ray_tpu.remote
        def create_object_containing_refs():
            return [ray_tpu.put(1) for _ in range(10000)]

        obj = create_object_containing_refs.remote()
        ray_tpu.get(obj)
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 4.0:
            ray_tpu.get(obj)
            n += 1
        out["single_client_get_object_containing_10k_refs"] = (
            n / (time.perf_counter() - start))

        # placement-group create+remove cycle (reference
        # `placement_group_create/removal`: 10 trivial PGs per loop).
        # Create the batch first so the GCS scheduler pass overlaps the
        # ready-polling (polling serially per PG would measure the 50 ms
        # poll granularity, not the control plane), and fail loudly if a
        # PG never schedules instead of counting it as done.
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 3.0:
            pgs = [ray_tpu.placement_group([{"CPU": 0.01}])
                   for _ in range(10)]
            for pg in pgs:
                if not pg.ready(timeout=30.0):
                    raise RuntimeError("placement group never scheduled")
            for pg in pgs:
                ray_tpu.remove_placement_group(pg)
            n += 20  # 10 creations + 10 removals, reference accounting
        out["placement_group_create_removal"] = (
            n / (time.perf_counter() - start))
    finally:
        ray_tpu.shutdown()

    # -- phase C: actors -------------------------------------------------
    # reference actor_multi2 shape (`ray_perf.py:222`): cpu_count()//2
    # actors, 4 caller worker processes — the cluster must actually hold
    # them all or the callers starve on leases and the row measures the
    # self-imposed cap instead of the dispatch path
    n_actors = max(1, ncpu // 2)
    ray_tpu.init(num_cpus=max(2, n_actors + 6),
                 object_store_memory=256 << 20)
    try:
        @ray_tpu.remote
        class Sink:
            def ping(self):
                return None

        actor = Sink.remote()
        ray_tpu.get(actor.ping.remote())
        for _ in range(100):
            ray_tpu.get(actor.ping.remote())
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 3.0:
            for _ in range(100):
                ray_tpu.get(actor.ping.remote())
            n += 100
        out["1_1_actor_calls_sync"] = n / (time.perf_counter() - start)

        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 3.0:
            refs = [actor.ping.remote() for _ in range(1000)]
            ray_tpu.get(refs)
            n += 1000
        out["1_1_actor_calls_async"] = n / (time.perf_counter() - start)

        # n:n — the reference's `actor_multi2` shape
        # (`python/ray/_private/ray_perf.py:227-232`): m=4 caller WORKER
        # PROCESSES, each async-calling n_cpu actors round-robin. The
        # callers parallelize submission exactly as the baseline run did;
        # a driver-only loop would measure one submitter thread instead.
        actors = [Sink.remote() for _ in range(n_actors)]
        ray_tpu.get([a.ping.remote() for a in actors])

        @ray_tpu.remote
        def caller_work(actors, n):
            ray_tpu.get([actors[i % len(actors)].ping.remote()
                         for i in range(n)])
            return None

        m, calls = 4, 1000
        ray_tpu.get([caller_work.remote(actors, 8) for _ in range(m)])
        rpc_before = _rpc_stats_snapshot()
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 4.0:
            ray_tpu.get([caller_work.remote(actors, calls)
                         for _ in range(m)])
            n += m * calls
        out["n_n_actor_calls_async"] = n / (time.perf_counter() - start)
        out["n_n_actor_calls_attrib"] = _control_plane_attrib(rpc_before)
    finally:
        ray_tpu.shutdown()

    # -- phase D: multi-client task submission (reference `multi_task`:
    # m=4 actor clients each submitting n noop tasks, on a cluster with
    # every core available — the reference baseline ran uncapped) -------
    ray_tpu.init(num_cpus=max(4, ncpu),
                 object_store_memory=256 << 20)
    try:
        @ray_tpu.remote
        def small_value():
            return b"ok"

        @ray_tpu.remote
        class Client:
            def small_value_batch(self, n):
                ray_tpu.get([small_value.remote() for _ in range(n)])
                return 0

        m, calls = 4, 1000
        clients = [Client.remote() for _ in range(m)]
        ray_tpu.get([c.small_value_batch.remote(8) for c in clients])
        rpc_before = _rpc_stats_snapshot()
        n, start = 0, time.perf_counter()
        while time.perf_counter() - start < 4.0:
            ray_tpu.get([c.small_value_batch.remote(calls)
                         for c in clients])
            n += m * calls
        out["multi_client_tasks_async"] = n / (time.perf_counter() - start)
        out["multi_client_tasks_attrib"] = _control_plane_attrib(rpc_before)
    finally:
        ray_tpu.shutdown()
    return out


def bench_serve_llm():
    """Inference-plane phase (ISSUE 9): closed-loop load over the
    continuous-batching engine — `llm_clients` threads each keep one
    request in flight until `llm_requests` complete. Measures request
    throughput, tokens/s/chip and p50/p99 request latency, and holds
    the plane to its two hard gates: ZERO executable-cache retraces in
    steady state (every shape is a warmup-compiled bucket) and ZERO
    leaked KV pages at quiesce. Scale with
    RAY_TPU_SCALE_SIZES=llm_requests=1000000,llm_clients=32 (the
    full-scale artifact run; defaults keep the bench budget on a small
    box and are noted in the detail row)."""
    import statistics

    import jax

    from ray_tpu import parallel
    from ray_tpu.serve.llm import EngineConfig, LLMEngine
    from ray_tpu.util import request_recorder as rr

    ncpu = os.cpu_count() or 1
    scale = _scale_overrides()
    n_requests = scale.get("llm_requests", min(4000, 1000 * ncpu))
    n_clients = scale.get("llm_clients", min(16, 4 * ncpu))
    max_new = 8

    eng = LLMEngine(
        model="llama",
        engine_config=EngineConfig(batch_buckets=(1, 2, 4, 8, 16),
                                   prefill_buckets=(8, 16)),
        seed=0)
    t0 = time.perf_counter()
    eng.warmup()
    warmup_s = time.perf_counter() - t0
    eng.start()

    stats_before = parallel.cache_stats()
    # isolate this run's flight-recorder records (ISSUE 12): the ring
    # keeps the tail of the run; TTFT/TPOT and phase attribution below
    # come from these engine-role records
    rec_was_enabled = rr.enabled()
    rr.set_enabled(True)
    rr.clear()
    prompts = [[3 + (i % 5)] * (1 + i % 8) for i in range(16)]
    latencies = []
    lat_lock = threading.Lock()
    issued = iter(range(n_requests))

    def client(cid):
        mine = []
        while True:
            if next(issued, None) is None:  # GIL-atomic claim
                break
            req = eng.submit(prompts[cid % len(prompts)], max_new)
            req.result(timeout=300)
            mine.append(req.finish_ts - req.submit_ts)
        with lat_lock:
            latencies.extend(mine)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(n_clients)]
    start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - start

    eng.quiesce(timeout=60)
    stats_after = parallel.cache_stats()
    m = eng.metrics()
    leaked = eng.shutdown()
    retraces = stats_after["retraces"] - stats_before["retraces"]
    if retraces:
        raise RuntimeError(
            f"{retraces} retraces in steady-state decode")
    if leaked:
        raise RuntimeError(f"{leaked} KV pages leaked at quiesce")

    # -- request-path attribution (ISSUE 12 acceptance gate) -------------
    # Engine records stamp queue -> admission -> prefill -> decode so the
    # phases TILE the request: their sum must reconstruct the measured
    # end-to-end latency to within 5% for the p50 request.
    recs = [r for r in rr.ring().recent()
            if r.role == "engine" and r.outcome == "ok"
            and r.total_ms > 0]
    rr.set_enabled(rec_was_enabled)
    if not recs:
        raise RuntimeError("request recorder captured no engine records")

    def _q(vals, q):
        s = sorted(vals)
        return s[int(q * (len(s) - 1))]

    ratios = [r.phase_sum_ms() / r.total_ms for r in recs]
    p50_ratio = statistics.median(ratios)
    if abs(p50_ratio - 1.0) > 0.05:
        raise RuntimeError(
            "phase attribution broken: median phase-sum/e2e ratio "
            f"{p50_ratio:.3f} outside [0.95, 1.05]")
    ttfts = [r.ttft_ms for r in recs if r.ttft_ms is not None]
    tpots = [r.tpot_ms for r in recs if r.tpot_ms is not None]
    phase_ms = {
        ph: {"p50": round(_q([getattr(r, ph) for r in recs], 0.50), 3),
             "p99": round(_q([getattr(r, ph) for r in recs], 0.99), 3)}
        for ph in rr.PHASES}

    n_done = len(latencies)
    lat_sorted = sorted(latencies)
    chips = max(1, jax.device_count())
    detail = {
        "requests": n_done,
        "clients": n_clients,
        "max_new_tokens": max_new,
        "warmup_s": round(warmup_s, 2),
        "elapsed_s": round(elapsed, 2),
        "latency_p50_ms": round(1e3 * statistics.median(lat_sorted), 2),
        "latency_p99_ms": round(
            1e3 * lat_sorted[int(0.99 * (n_done - 1))], 2),
        "tokens_generated": int(m["tokens_generated"]),
        "prefill_steps": int(m["prefill_steps"]),
        "decode_steps": int(m["decode_steps"]),
        "retraces_steady_state": retraces,
        "kv_pages_leaked": leaked,
        "cache_hits_delta": stats_after["hits"] - stats_before["hits"],
        "full_scale": n_requests >= 1_000_000,
        # flight-recorder attribution (engine-role records, ring tail)
        "recorded_requests": len(recs),
        "ttft_ms_p50": round(_q(ttfts, 0.50), 3) if ttfts else None,
        "ttft_ms_p99": round(_q(ttfts, 0.99), 3) if ttfts else None,
        "tpot_ms_p50": round(_q(tpots, 0.50), 3) if tpots else None,
        "tpot_ms_p99": round(_q(tpots, 0.99), 3) if tpots else None,
        "phase_ms": phase_ms,
        "phase_sum_over_e2e_p50": round(p50_ratio, 4),
    }
    # -- shared-prefix + speculative A/B (ISSUE 18) ----------------------
    ab = _serve_llm_shared_prefix_ab(scale)
    detail["shared_prefix_ab"] = ab["detail"]
    # -- native-intake sub-phase (ISSUE 19) ------------------------------
    detail["native_intake"] = _serve_llm_native_intake(scale)

    return {
        "serve_llm": detail,
        # value-keyed: the >15% REGRESSION gate watches all four rates
        "serve_llm_requests_per_s": n_done / elapsed,
        "serve_llm_tokens_per_s_per_chip":
            m["tokens_generated"] / elapsed / chips,
        "serve_llm_shared_prefix_tokens_per_s":
            ab["cache_tokens_per_s"],
        "serve_llm_shared_prefix_spec_tokens_per_s":
            ab["spec_tokens_per_s"],
    }


def _serve_llm_shared_prefix_ab(scale: dict) -> dict:
    """Shared-prefix workload A/B (ISSUE 18): every request carries the
    same long prompt prefix with a private suffix — the RAG /
    system-prompt shape the COW prefix cache exists for. Three arms run
    the IDENTICAL workload in one process:

        base        prefix_cache=0, spec_k=0  (the PR-7 engine)
        cache       prefix_cache=1, spec_k=0  (COW prefix reuse)
        cache+spec  prefix_cache=1, spec_k=K  (reuse + speculation)

    Greedy determinism makes the three token streams comparable: the
    arms must EMIT identical tokens (asserted), so tokens/s is an
    apples-to-apples rate. The spec arm self-drafts (draft == target
    weights) — accept length is always K, the upper bound of the
    speculative win; a production draft supplies its own accept rate.
    Zero retraces and zero leaked pages are hard gates in every arm.
    Shape knobs via RAY_TPU_SCALE_SIZES: llm_prefix=96,llm_suffix=16,
    llm_ab_requests=48,llm_ab_clients=4,llm_spec_k=4."""
    import numpy as np

    from ray_tpu import parallel
    from ray_tpu.serve.llm import EngineConfig, LLMEngine
    from ray_tpu.util import request_recorder as rr

    prefix_len = scale.get("llm_prefix", 96)
    suffix_len = scale.get("llm_suffix", 16)
    n_requests = scale.get("llm_ab_requests", 48)
    n_clients = scale.get("llm_ab_clients", 4)
    spec_k = scale.get("llm_spec_k", 4)
    max_new = 8

    rng = np.random.RandomState(7)
    prefix = [int(t) for t in rng.randint(3, 500, size=prefix_len)]
    prompts = [prefix + [int(t) for t in rng.randint(3, 500,
                                                     size=suffix_len)]
               for _ in range(min(n_requests, 16))]

    # the chunk window matches the suffix: a prefix-cache hit prefills
    # ONLY the private suffix, in one suffix-sized chunk (without it
    # the suffix pads to the widest prefill bucket and the win drowns)
    arms = {
        "base": dict(prefix_cache=0, spec_k=0),
        "cache": dict(prefix_cache=1, spec_k=0,
                      prefill_chunk=suffix_len),
        "cache_spec": dict(prefix_cache=1, spec_k=spec_k,
                           prefill_chunk=suffix_len),
    }
    out_detail: dict = {
        "prefix_tokens": prefix_len, "suffix_tokens": suffix_len,
        "requests_per_arm": n_requests, "clients": n_clients,
        "spec_k": spec_k, "max_new_tokens": max_new,
    }
    emitted: dict = {}
    rates: dict = {}
    rec_was_enabled = rr.enabled()
    rr.set_enabled(True)
    for arm, knobs in arms.items():
        eng = LLMEngine(
            model="llama",
            engine_config=EngineConfig(
                batch_buckets=(1, 2, 4),
                prefill_buckets=(16, 32, 64, 128), **knobs),
            seed=0)
        eng.warmup()
        eng.start()
        stats_before = parallel.cache_stats()
        rr.clear()
        results: dict = {}
        res_lock = threading.Lock()
        issued = iter(range(n_requests))

        def client():
            while True:
                i = next(issued, None)  # GIL-atomic claim
                if i is None:
                    break
                req = eng.submit(prompts[i % len(prompts)], max_new)
                toks = req.result(timeout=300)
                with res_lock:
                    results[i % len(prompts)] = toks
        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(n_clients)]
        start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - start
        eng.quiesce(timeout=60)
        m = eng.metrics()
        retraces = parallel.cache_stats()["retraces"] - \
            stats_before["retraces"]
        leaked = eng.shutdown()
        if retraces:
            raise RuntimeError(
                f"{arm}: {retraces} retraces in steady state")
        if leaked:
            raise RuntimeError(f"{arm}: {leaked} KV pages leaked")
        emitted[arm] = results
        rates[arm] = m["tokens_generated"] / elapsed

        recs = [r for r in rr.ring().recent()
                if r.role == "engine" and r.outcome == "ok"]
        ttfts = sorted(r.ttft_ms for r in recs
                       if r.ttft_ms is not None)
        tpots = sorted(r.tpot_ms for r in recs
                       if r.tpot_ms is not None)

        def _q(vals, q):
            return round(vals[int(q * (len(vals) - 1))], 3) \
                if vals else None
        arm_detail = {
            "tokens_per_s": round(rates[arm], 2),
            "elapsed_s": round(elapsed, 2),
            "ttft_ms_p50": _q(ttfts, 0.50),
            "ttft_ms_p99": _q(ttfts, 0.99),
            "tpot_ms_p50": _q(tpots, 0.50),
            "tpot_ms_p99": _q(tpots, 0.99),
        }
        if knobs.get("prefix_cache"):
            hit = m["prefix_cache_hit_tokens"]
            miss = m["prefix_cache_miss_tokens"]
            arm_detail["prefix_cache_hit_rate"] = round(
                hit / (hit + miss), 4) if hit + miss else 0.0
            arm_detail["prefix_cache_hit_tokens"] = int(hit)
        if knobs.get("spec_k"):
            arm_detail["spec_mean_accept"] = round(
                m["spec_accepted"] / m["spec_rounds"], 3) \
                if m["spec_rounds"] else None
            arm_detail["spec_proposed"] = int(m["spec_proposed"])
            arm_detail["spec_accepted"] = int(m["spec_accepted"])
        out_detail[arm] = arm_detail
    rr.set_enabled(rec_was_enabled)

    # greedy determinism: all three arms emit the SAME streams
    for arm in ("cache", "cache_spec"):
        if emitted[arm] != emitted["base"]:
            raise RuntimeError(
                f"{arm} arm diverged from plain greedy output")

    ncpu = os.cpu_count() or 1
    best = max(rates["cache"], rates["cache_spec"])
    out_detail["speedup_cache"] = round(rates["cache"] / rates["base"], 3)
    out_detail["speedup_cache_spec"] = round(
        rates["cache_spec"] / rates["base"], 3)
    out_detail["two_x_target_met"] = best >= 2.0 * rates["base"]
    if not out_detail["two_x_target_met"] and ncpu <= 2:
        # the 2x acceptance target assumes real accelerator decode
        # (prefill FLOPs dominate); on the 1-core CPU box dispatch
        # overhead dominates and caps the cache win — noted, not fatal
        out_detail["note"] = (
            f"{ncpu}-core CPU box: dispatch-bound, 2x target waived "
            "(see README 1-core caveat)")
    return {
        "detail": out_detail,
        "cache_tokens_per_s": rates["cache"],
        "spec_tokens_per_s": rates["cache_spec"],
    }


def _serve_llm_native_intake(scale: dict) -> dict:
    """Native-intake sub-phase (ISSUE 19): the serve.llm zero-Python
    dispatch path in one process — raw token-id request frames enqueued
    through the native ring (mint + deadline + choice in C), the engine
    pump draining them batch-at-a-time ahead of step(), token frames
    flowing back through the client response plane. Gates: recorder
    attribution must survive the native path (engine records carry the
    NATIVELY-minted 16-hex trace ids and their phase sums tile e2e to
    within 5%), the native streams are bit-identical to the same
    engine's direct submit() path (greedy determinism), and the ring's
    inflight counters balance to zero at quiesce."""
    import statistics

    import numpy as np

    from ray_tpu.serve import dispatch as _dispatch
    from ray_tpu.serve.llm import EngineConfig, LLMEngine
    from ray_tpu.util import request_recorder as rr

    if _dispatch._load() is None:
        return {"skipped": "native dispatch library unavailable"}

    n_requests = scale.get("llm_native_requests", 24)
    max_new = 8
    rng = np.random.RandomState(11)
    prompts = [[int(t) for t in rng.randint(3, 500, size=1 + i % 8)]
               for i in range(8)]

    eng = LLMEngine(
        model="llama",
        engine_config=EngineConfig(batch_buckets=(1, 2, 4),
                                   prefill_buckets=(8, 16)),
        seed=0)
    eng.warmup()
    eng.start()
    # reference streams: the ordinary Python submit() path on the SAME
    # engine — greedy decode makes each prompt's stream deterministic
    expect = [eng.submit(p, max_new).result(timeout=300) for p in prompts]

    seg = f"/rtds.bench{os.getpid():x}"
    ring = _dispatch.DispatchRing(seg, table_cap=2, slots=256,
                                  slot_bytes=1024)
    rec_was = rr.enabled()
    rr.set_enabled(True)
    rr.clear()
    try:
        cookie = 0x5eed
        ring.publish(1, [cookie])
        eng.attach_intake(ring, ring.ring_of(cookie), "llm-native")
        plane = _dispatch.ClientPlane.get()
        traces = []
        native: dict = {}
        start = time.perf_counter()
        for i in range(n_requests):
            payload = _dispatch.encode_llm_request(
                prompts[i % len(prompts)], max_new, "bench")
            trace, _rid, _gen = ring.enqueue(payload, client=plane.cookie)
            mailbox = plane.register(trace)
            traces.append(trace)
            toks = []
            while True:
                f = mailbox.q.get(timeout=300)
                if f.tag == _dispatch.TAG_TOKEN:
                    toks.append(_dispatch._LLM_TOK.unpack(f.payload)[1])
                elif f.tag == _dispatch.TAG_DONE:
                    break
                else:
                    raise RuntimeError(
                        f.payload.decode("utf-8", "replace"))
            plane.unregister(trace)
            native[i % len(prompts)] = toks
        elapsed = time.perf_counter() - start
        eng.quiesce(timeout=60)

        for j, toks in native.items():
            if toks != expect[j]:
                raise RuntimeError(
                    "native intake stream diverged from the Python "
                    f"submit() path for prompt {j}")

        # recorder attribution: every native request's engine record is
        # keyed by the natively-minted trace id (16-hex wire format)
        native_ids = {_dispatch.format_trace(t) for t in traces}
        recs = [r for r in rr.ring().recent()
                if r.role == "engine" and r.outcome == "ok"
                and r.total_ms > 0 and r.req_id in native_ids]
        if len(recs) < n_requests:
            raise RuntimeError(
                f"only {len(recs)}/{n_requests} native requests "
                "stitched into engine-role records")
        ratio = statistics.median(
            r.phase_sum_ms() / r.total_ms for r in recs)
        if abs(ratio - 1.0) > 0.05:
            raise RuntimeError(
                "native-path phase attribution broken: median "
                f"phase-sum/e2e ratio {ratio:.3f} outside [0.95, 1.05]")

        _ver, rows = ring.snapshot()
        inflight = sum(row[2] for row in rows)
        if inflight:
            raise RuntimeError(
                f"{inflight} inflight frames leaked at quiesce")
        s = ring.stats()
        tokens = sum(len(t) for t in native.values()) * (
            n_requests // len(prompts))
        return {
            "requests": n_requests,
            "elapsed_s": round(elapsed, 2),
            "tokens_per_s": round(
                n_requests * max_new / elapsed, 2),
            "frames_enqueued": int(s["enqueued"]),
            "frames_per_drain_batch": round(
                s["drained"] / max(1, s["drain_batches"]), 2),
            "recorded_native_requests": len(recs),
            "phase_sum_over_e2e_p50": round(ratio, 4),
            "tokens_checked": tokens,
        }
    finally:
        rr.set_enabled(rec_was)
        eng.shutdown()
        ring.close(unlink=True)


def _dispatch_ring_frames(deployment: str) -> int:
    """Frames natively enqueued for a deployment's dispatch domain (0
    when the domain segment does not exist — the Python-path arm)."""
    from ray_tpu.serve import dispatch as _dispatch

    try:
        ring = _dispatch.DispatchRing(
            _dispatch.domain_segment(deployment), create=False)
    except Exception:  # noqa: BLE001
        return 0
    try:
        return int(ring.stats()["enqueued"])
    finally:
        ring.close()


def bench_serve_dispatch():
    """Dispatch plane v2 A/B (ISSUE 19): the same echo deployment and
    closed-loop clients, once over the native request ring
    (RAY_TPU_NATIVE_DISPATCH=1: mint + deadline + pow-2 choice on raw
    frames in C, Python entered once per batch) and once over the
    Python handle path (flag off — bit-for-bit the pre-PR path, kept as
    the fallback). Gates: the native arm must actually go native (the
    domain ring's frame counter advances), a fixed probe set returns
    bit-identical outputs in both arms, and on a multi-core box the
    native arm clears >=5x the Python-path request rate at p99 parity.
    On a 1-core box both arms timeshare one core with the replicas and
    the controller, so the ring's syscall/pickle wins drown in
    scheduler churn — the 5x target is noted, not fatal (README 1-core
    caveat); the full-scale artifact run proves it on real hardware."""
    import statistics

    import ray_tpu
    from ray_tpu import serve

    scale = _scale_overrides()
    ncpu = os.cpu_count() or 1
    duration = scale.get("dispatch_ab_seconds", 4)
    n_clients = scale.get("dispatch_ab_clients", min(8, 2 * ncpu))
    probe_n = 32

    def run_arm(native: bool) -> dict:
        os.environ["RAY_TPU_NATIVE_DISPATCH"] = "1" if native else "0"
        ray_tpu.init(num_cpus=max(4, ncpu), num_tpus=0,
                     object_store_memory=128 * 1024 * 1024)
        try:
            @serve.deployment(num_replicas=2, max_ongoing_requests=64)
            class DispatchEcho:
                def __call__(self, x):
                    return x * 2

            handle = serve.run(DispatchEcho.bind())
            for i in range(64):  # warm: replicas up, rings attached
                handle.remote(i).result(timeout=60)
            probe = [handle.remote(i).result(timeout=60)
                     for i in range(probe_n)]
            frames0 = _dispatch_ring_frames("DispatchEcho")
            lat: list = []
            lat_lock = threading.Lock()
            stop = time.perf_counter() + duration

            def client():
                mine = []
                while time.perf_counter() < stop:
                    t0 = time.perf_counter()
                    handle.remote(1).result(timeout=60)
                    mine.append(time.perf_counter() - t0)
                with lat_lock:
                    lat.extend(mine)

            threads = [threading.Thread(target=client, daemon=True)
                       for _ in range(n_clients)]
            t_start = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t_start
            frames = _dispatch_ring_frames("DispatchEcho") - frames0
            lat.sort()
            return {
                "probe": probe,
                "requests": len(lat),
                "per_s": len(lat) / elapsed,
                "p50_ms": 1e3 * statistics.median(lat),
                "p99_ms": 1e3 * lat[int(0.99 * (len(lat) - 1))],
                "native_frames": frames,
            }
        finally:
            serve.shutdown()
            ray_tpu.shutdown()
            os.environ.pop("RAY_TPU_NATIVE_DISPATCH", None)

    py = run_arm(native=False)
    nat = run_arm(native=True)

    if nat["probe"] != py["probe"]:
        raise RuntimeError(
            "native and Python dispatch arms returned different outputs")
    if nat["native_frames"] <= 0:
        raise RuntimeError(
            "native arm never used the request ring — the 5x claim "
            "would be vacuous (is the native library building?)")
    if py["native_frames"] != 0:
        raise RuntimeError(
            "Python arm touched the native ring with the flag off")

    speedup = nat["per_s"] / max(1e-9, py["per_s"])
    p99_parity = nat["p99_ms"] <= 1.25 * py["p99_ms"]
    detail = {
        "clients": n_clients,
        "seconds_per_arm": duration,
        "native": {k: round(v, 2) for k, v in nat.items()
                   if k not in ("probe",)},
        "python": {k: round(v, 2) for k, v in py.items()
                   if k not in ("probe",)},
        "speedup": round(speedup, 2),
        "p99_parity": p99_parity,
        "five_x_target_met": speedup >= 5.0 and p99_parity,
    }
    if not detail["five_x_target_met"]:
        if ncpu > 2:
            raise RuntimeError(
                f"native dispatch {speedup:.2f}x vs Python path "
                f"(p99 parity={p99_parity}) — below the 5x-at-parity "
                "acceptance gate")
        detail["note"] = (
            f"{ncpu}-core CPU box: arms timeshare one core with the "
            "replicas, 5x target waived (see README 1-core caveat)")
    return {
        "serve_dispatch": detail,
        # value-keyed: the >15% REGRESSION gate watches both arms, so
        # neither the native path nor the guarded fallback can rot
        "serve_dispatch_native_per_s": nat["per_s"],
        "serve_dispatch_python_per_s": py["per_s"],
    }


def bench_soak():
    """Elastic-recovery soak (ISSUE 10): a wall-clock-budgeted
    continuous-pretraining campaign — streaming ingest -> fold-steps ->
    gang-durable checkpoints on a real multi-raylet cluster — under a
    seeded timed fault schedule spanning every plane (raylet kill +
    autoscaler replacement, GCS heartbeat brownout, checkpoint-persist
    failure, data stall). The recovery ledger measures MTTR per fault
    class and the phase holds the run to its hard gates EVERY time:
    zero non-injected failures, zero resume-accounting mismatches, zero
    batch-watermark violations, every fault recovered. Scale with
    RAY_TPU_SCALE_SIZES=soak_budget_s=600,soak_faults_per_class=2 (the
    >=10-min artifact run; defaults keep the bench budget on a small
    box and are noted in the detail row)."""
    from ray_tpu.soak import SoakConfig, run_soak

    scale = _scale_overrides()
    budget = float(scale.get("soak_budget_s", 90))
    per_class = int(scale.get("soak_faults_per_class",
                              1 if budget < 300 else 2))
    cfg = SoakConfig(
        budget_s=budget,
        mode="cluster",
        seed=1,
        fault_classes=("kill@raylet", "hb_brownout@gcs",
                       "ckpt_fail@train", "data_stall@train",
                       "drop_objects@raylet"),
        faults_per_class=per_class,
    )
    result = run_soak(cfg)
    ledger = result["ledger"]

    # hard gates: a soak whose failures weren't all injected, whose
    # restores don't match the commit ledger, or whose resumed shards
    # replayed/skipped a batch is a FAILED run, not a slow one
    if ledger["non_injected_failures"]:
        raise RuntimeError("non-injected failures during soak: "
                           f"{ledger['non_injected_failures']}")
    if ledger["resume_mismatches"]:
        raise RuntimeError("resume accounting mismatches: "
                           f"{ledger['resume_mismatches']}")
    if result["watermark_errors"]:
        raise RuntimeError("batch-watermark violations: "
                           f"{result['watermark_errors']}")
    if ledger["recovered_count"] < ledger["faults_injected"]:
        raise RuntimeError(
            f"only {ledger['recovered_count']}/"
            f"{ledger['faults_injected']} faults recovered")

    mttrs = sorted(m["mttr_s"] for m in ledger["recoveries"]
                   if m["recovered"])
    p50 = mttrs[int(0.50 * (len(mttrs) - 1))] if mttrs else None
    p95 = mttrs[int(0.95 * (len(mttrs) - 1))] if mttrs else None
    down = ledger["downtime_breakdown_s"]
    avail = 100.0 * (1.0 - down["dead_s"] / result["elapsed_s"])
    detail = {
        "budget_s": budget,
        "elapsed_s": result["elapsed_s"],
        "seed": cfg.seed,
        "fault_classes": len(ledger["mttr_by_class"]),
        "faults_injected": ledger["faults_injected"],
        "recovered": ledger["recovered_count"],
        "attempts": result["attempts"],
        "final_step": result["final_step"],
        "ingest_tokens_per_s": result["ingest_tokens_per_s"],
        "commits": ledger["commits"],
        "restores": ledger["restores"],
        "watermark_checks": result["watermark_checks"],
        "mttr_p50_s": round(p50, 3) if p50 is not None else None,
        "mttr_p95_s": round(p95, 3) if p95 is not None else None,
        "mttr_by_class": ledger["mttr_by_class"],
        "downtime_breakdown_s": down,
        "non_injected_failures": 0,
        "resume_mismatches": 0,
        "full_scale": budget >= 600,
    }
    out = {
        "soak": detail,
        # value-keyed: the >15% REGRESSION gate watches throughput and
        # availability directly; MTTR gates as its inverse (recoveries
        # per second of outage) so a >15% DROP flags MTTR growth
        "soak_steps_per_s": result["steps_per_s"],
        "soak_ingest_tokens_per_s": result["ingest_tokens_per_s"],
        "soak_availability_pct": avail,
    }
    if p95:
        out["soak_recovery_speed_p95_per_s"] = 1.0 / p95
    return out


def bench_reconstruction():
    """Lineage reconstruction (ISSUE 16): when the node holding an
    object's primary copy dies, the owner re-executes the producing
    task from recorded lineage through the normal lease path. Per
    object size (64 KiB -> 64 MiB) the phase pins one task return to a
    victim raylet, kills the raylet, and times the driver's get() until
    the recovered bytes land — death detection is excluded (polled out
    before the timer starts), so small sizes show lease + re-execution
    latency and large ones add the store write — then measures
    sustained recovery rate over a batch of lost objects. Every recovered value is checked bit-identical
    against a local recompute. Scale with
    RAY_TPU_SCALE_SIZES=reconstruction_max_mib=64,reconstruction_batch=32."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private.node import Cluster

    scale = _scale_overrides()
    max_mib = int(scale.get("reconstruction_max_mib", 64))
    batch = int(scale.get("reconstruction_batch", 16))
    sizes = [64 * 1024]
    while sizes[-1] < (max_mib << 20):
        sizes.append(min(sizes[-1] * 8, max_mib << 20))
    # headroom for the largest object + its re-executed copy
    store = max(192 << 20, 3 * (max_mib << 20))

    cluster = None
    curve = []
    try:
        cluster = Cluster(head_resources={"CPU": 2.0},
                          object_store_memory=store)
        ray_tpu.init(address=cluster.gcs_addr)

        @ray_tpu.remote
        def produce(n, mult):
            return (np.arange(n, dtype=np.uint64) * mult).astype(np.uint8)

        def lose_and_time(make_refs):
            """Spin up a victim raylet, pin make_refs(affinity) to it,
            kill it, and time localizing every ref at the driver."""
            victim = cluster.add_node({"CPU": 2.0, "scratch": 1.0},
                                      object_store_memory=store)
            affinity = ray_tpu.NodeAffinitySchedulingStrategy(
                victim.node_id_hex, soft=True)
            refs = make_refs(affinity)
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                    timeout=180)
            if len(ready) != len(refs):
                raise RuntimeError("producer batch never became ready")
            cluster.remove_node(victim)
            # exclude death-detection latency (heartbeat period x
            # failure threshold — constant per cluster config, already
            # measured by the soak MTTR rows) so the curve shows the
            # re-execute + store-write cost that actually scales with
            # object size
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if not any(n["Alive"] and
                           n["NodeID"] == victim.node_id_hex
                           for n in ray_tpu.nodes()):
                    break
                time.sleep(0.05)
            start = time.perf_counter()
            vals = ray_tpu.get(refs, timeout=300)
            return time.perf_counter() - start, vals

        for size in sizes:
            elapsed, vals = lose_and_time(
                lambda aff, n=size: [produce.options(
                    scheduling_strategy=aff).remote(n, 7)])
            expect = (np.arange(size, dtype=np.uint64) * 7) \
                .astype(np.uint8)
            if not np.array_equal(vals[0], expect):
                raise RuntimeError(
                    f"reconstructed {size}-byte object not bit-identical")
            del vals, expect
            curve.append({
                "size_bytes": size,
                "latency_ms": round(elapsed * 1e3, 2),
                "mib_per_s": round((size / (1 << 20)) / elapsed, 3),
            })

        small = 256 * 1024
        elapsed, vals = lose_and_time(
            lambda aff: [produce.options(scheduling_strategy=aff)
                         .remote(small, i + 1) for i in range(batch)])
        for i, v in enumerate(vals):
            if int(v[1]) != ((i + 1) & 0xFF):
                raise RuntimeError("batch-recovered object corrupted")
        del vals
        rate = batch / elapsed

        largest = curve[-1]
        return {
            "reconstruction": {
                "sizes": len(curve),
                "curve": curve,
                "batch_objects": batch,
                "batch_object_bytes": small,
                "batch_s": round(elapsed, 3),
            },
            # value-keyed into the >15% REGRESSION gate: both are
            # higher-is-better, so latency growth flags as a drop
            "reconstructions_per_s": rate,
            "reconstruction_mib_per_s": largest["mib_per_s"],
        }
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:  # noqa: BLE001
            pass
        if cluster is not None:
            cluster.shutdown()


# Fairness submitter: one competing tenant. SPREAD tasks take one lease
# each, so the raylet's weighted-fair queue arbitrates EVERY task (the
# default pipelining would drain a whole backlog through one lease and
# hide the queue). Completions are counted by worker-side timestamp
# inside the shared [t0, t0+window] measurement interval — same-machine
# clocks, so no cross-process skew.
_MT_SUBMITTER = """
import json, sys, time
import ray_tpu

addr, weight, t0, window = (sys.argv[1], float(sys.argv[2]),
                            float(sys.argv[3]), float(sys.argv[4]))
ray_tpu.init(address=addr, job_quotas={"weight": weight})

@ray_tpu.remote(scheduling_strategy="SPREAD")
def work():
    import time as _t
    _t.sleep(0.005)
    return _t.time()

late_start = time.time() >= t0
refs = [work.remote() for _ in range(8)]
count = warm = 0
end = t0 + window
while time.time() < end:
    done, refs = ray_tpu.wait(refs, num_returns=1, timeout=30)
    for r in done:
        ts = ray_tpu.get(r)
        if t0 <= ts <= end:
            count += 1
        elif ts < t0:
            warm += 1
        refs.append(work.remote())
print(json.dumps({"job": ray_tpu.get_runtime_context().get_job_id(),
                  "weight": weight, "count": count, "warm": warm,
                  "late_start": late_start}))
ray_tpu.shutdown()
"""

# Overload offender: registers a byte quota at init, waits until the
# raylet has stamped it into the shared arena (the pubsub propagation
# under test), then fires the chaos `quota_flood` fault in-process. The
# flood hammers the CoreWorker-registered put target for the window; the
# store must cap the job at its quota (self-eviction first, then
# SS_QUOTA) without touching any other job's bytes.
_MT_OFFENDER = """
import sys, time
import ray_tpu
from ray_tpu._private import fault_injection as _fi
from ray_tpu._private.worker_api import _require_state

addr, jobfile, quota, flood_s = (sys.argv[1], sys.argv[2],
                                 int(sys.argv[3]), float(sys.argv[4]))
ray_tpu.init(address=addr,
             job_quotas={"weight": 1.0, "object_store_bytes": quota})
cw = _require_state().core_worker
with open(jobfile, "w") as f:
    f.write(cw.job_id.hex())
deadline = time.time() + 30
while time.time() < deadline:
    st = cw.store.job_stats(cw.job_id.binary())
    if st is not None and st["quota"] == quota:
        break
    time.sleep(0.05)
else:
    raise RuntimeError("byte quota never reached the store arena")
plan = _fi.install(_fi.FaultPlan(f"at=0.2:quota_flood:{flood_s}@driver"))
_fi.set_role("driver")  # arm the driver-scoped timed entry
deadline = time.time() + flood_s + 5
while time.time() < deadline and not any(
        s[0] == "timed.quota_flood.done" for s in plan.schedule):
    time.sleep(0.05)
done = [s for s in plan.schedule if s[0] == "timed.quota_flood.done"]
print("FLOOD=" + (done[0][2] if done else "missing"))
ray_tpu.shutdown()
"""


def bench_multitenant():
    """Multi-tenant isolation (ISSUE 11): three competing jobs with
    fair-share weights 1/2/4 submit backlogged SPREAD tasks against one
    1-CPU cluster — per-job throughput shares must land within 10%
    (relative) of the weight ratio. Then a quota-flood variant: an
    offender job with a byte quota floods the shared object store via
    the `quota_flood` chaos fault while the head job probes put latency
    — the offender stays capped at its quota, zero bytes are evicted
    from any other job, and the victim's put p99 regresses <15% vs its
    pre-flood window. Scale with RAY_TPU_SCALE_SIZES=
    mt_window_s=30,mt_flood_s=10 for the full artifact run."""
    import subprocess
    import sys
    import tempfile

    import ray_tpu
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.worker_api import _require_state
    from ray_tpu.util import state as state_api

    scale = _scale_overrides()
    window = float(scale.get("mt_window_s", 10))
    warmup = float(scale.get("mt_warmup_s", 10))
    flood_s = float(scale.get("mt_flood_s", 4))
    quota = int(scale.get("mt_quota_mb", 8)) * 1024 * 1024
    weights = (1.0, 2.0, 4.0)

    ray_tpu.init(num_cpus=1, num_tpus=0,
                 object_store_memory=128 * 1024 * 1024,
                 job_quotas={"weight": 1.0})
    try:
        from ray_tpu._private import worker_api

        gcs_addr = worker_api._global_state.cluster.gcs_addr
        cw = _require_state().core_worker
        store = cw.store
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        here = os.path.dirname(os.path.abspath(__file__))

        # -- phase 1: weighted-fair throughput shares -------------------
        t0 = time.time() + warmup
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _MT_SUBMITTER, gcs_addr, str(w),
                 str(t0), str(window)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, cwd=here, env=env)
            for w in weights
        ]
        tenants = []
        for p in procs:
            out, err = p.communicate(timeout=warmup + window + 120)
            if p.returncode != 0:
                raise RuntimeError(f"submitter failed: {err[-500:]}")
            tenants.append(json.loads(out.strip().splitlines()[-1]))
        total = sum(t["count"] for t in tenants)
        total_w = sum(weights)
        if total < 20 * len(weights):
            raise RuntimeError(
                f"undersampled fairness window: {total} grants")
        fairness = []
        worst = 0.0
        for t in tenants:
            expected = t["weight"] / total_w
            share = t["count"] / total
            rel_err = abs(share / expected - 1.0)
            worst = max(worst, rel_err)
            fairness.append({
                "job": t["job"][:8], "weight": t["weight"],
                "tasks": t["count"], "warmup_tasks": t["warm"],
                "share": round(share, 4),
                "expected_share": round(expected, 4),
                "rel_err": round(rel_err, 4),
            })
        if worst > 0.10:
            raise RuntimeError(
                "fairness: share deviates >10% from weight: "
                f"{fairness}")

        # -- phase 2: quota-flood containment ---------------------------
        def put_p99(n):
            # victim probe: 64 KiB put+delete round trips on the shared
            # arena, p99 over the window
            lat = []
            payload = b"\x00" * 65536
            for _ in range(n):
                oid = ObjectID.from_random()
                t = time.perf_counter()
                store.put_value(oid, payload)
                lat.append(time.perf_counter() - t)
                store.delete(oid)
            lat.sort()
            return lat[int(0.99 * (len(lat) - 1))], len(lat)

        base_p99, base_n = put_p99(400)
        victim_before = store.job_stats(cw.job_id.binary()) or {}

        jobfile = tempfile.mktemp(prefix="ray_tpu_mt_job_")
        offender = subprocess.Popen(
            [sys.executable, "-c", _MT_OFFENDER, gcs_addr, jobfile,
             str(quota), str(flood_s)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=here, env=env)
        deadline = time.time() + 30
        offender_job = None
        while time.time() < deadline and offender_job is None:
            try:
                with open(jobfile) as f:
                    offender_job = bytes.fromhex(f.read().strip())
            except (OSError, ValueError):
                time.sleep(0.05)
        if offender_job is None:
            offender.kill()
            raise RuntimeError("offender never registered its job")

        # probe while the flood runs, sampling the offender's usage
        max_used = 0
        flood_lat = []
        payload = b"\x00" * 65536
        end = time.time() + flood_s + 1.0
        while time.time() < end:
            oid = ObjectID.from_random()
            t = time.perf_counter()
            store.put_value(oid, payload)
            flood_lat.append(time.perf_counter() - t)
            store.delete(oid)
            st = store.job_stats(offender_job)
            if st is not None:
                max_used = max(max_used, st["used"])
        out, err = offender.communicate(timeout=flood_s + 60)
        if offender.returncode != 0:
            raise RuntimeError(f"offender failed: {err[-500:]}")
        flood_line = [ln for ln in out.splitlines()
                      if ln.startswith("FLOOD=")][0]
        try:
            os.unlink(jobfile)
        except OSError:
            pass

        flood_lat.sort()
        flood_p99 = flood_lat[int(0.99 * (len(flood_lat) - 1))]
        off_stats = store.job_stats(offender_job) or {}
        victim_after = store.job_stats(cw.job_id.binary()) or {}

        # hard gates: containment must hold EVERY run, not on average.
        # The store reserves `used` with a fetch_add BEFORE admission
        # (check-and-reserve is one RMW), so a concurrent sample may
        # read up to one in-flight reservation over quota while a
        # create is inside its self-evict/recheck window; the quiesced
        # value is the strict cap.
        slack = 128 * 1024  # one aligned 64 KiB flood frame in flight
        if max_used > quota + slack:
            raise RuntimeError(
                f"offender exceeded its byte quota: {max_used} > {quota}")
        if off_stats.get("used", 0) > quota:
            raise RuntimeError(
                "offender over quota at quiesce: "
                f"{off_stats.get('used')} > {quota}")
        if off_stats.get("evicted_bytes", 0) + \
                off_stats.get("quota_rejects", 0) <= 0:
            raise RuntimeError(
                f"flood never hit the quota boundary: {off_stats}")
        if victim_after.get("evicted_bytes", 0) != \
                victim_before.get("evicted_bytes", 0):
            raise RuntimeError(
                "cross-job eviction: victim bytes were reclaimed for "
                f"the offender: {victim_before} -> {victim_after}")
        # latency floor guards micro-noise on sub-ms p99s
        p99_floor = max(base_p99, 0.0005)
        if flood_p99 > 1.15 * p99_floor:
            raise RuntimeError(
                f"victim put p99 regressed >15% under flood: "
                f"{base_p99 * 1e3:.3f}ms -> {flood_p99 * 1e3:.3f}ms")

        # per-job accounting rows as the dashboard /api/jobs serves them
        job_rows = []
        for jb in state_api.list_jobs():
            job_rows.append({
                "job_id": jb["job_id"][:8],
                "quotas": jb.get("quotas"),
                "finished": jb["finished"],
                "object_store": store.job_stats(
                    bytes.fromhex(jb["job_id"])),
            })

        detail = {
            "window_s": window,
            "tenants": fairness,
            "fairness_worst_rel_err": round(worst, 4),
            "flood": {
                "quota_bytes": quota,
                "flood_s": flood_s,
                "result": flood_line.split("=", 1)[1],
                "offender_max_used": max_used,
                "offender_stats": off_stats,
                "victim_put_p99_ms_base": round(base_p99 * 1e3, 3),
                "victim_put_p99_ms_flood": round(flood_p99 * 1e3, 3),
                "victim_probe_puts": base_n + len(flood_lat),
                "victim_evicted_bytes": victim_after.get(
                    "evicted_bytes", 0),
            },
            "jobs": job_rows,
            "full_scale": window >= 30,
        }
        return {
            "multitenant": detail,
            # value-keyed: the >15% REGRESSION gate watches the fairness
            # score (1.0 = shares exactly track weights), aggregate
            # fair-queue throughput, and victim put speed under flood
            # (1/p99 — a drop flags p99 growth)
            "multitenant_fairness_score": round(1.0 - worst, 4),
            "multitenant_tasks_per_s": round(total / window, 2),
            "multitenant_victim_put_speed_under_flood_per_s":
                round(1.0 / flood_p99, 1),
        }
    finally:
        ray_tpu.shutdown()


def main():
    suite = {}
    started = time.perf_counter()
    # the headline must always print: secondary phases are skipped once
    # the soft budget is spent (each TPU bench costs a 1-3 min compile)
    budget = float(os.environ.get("RAY_TPU_BENCH_BUDGET_S", "900"))

    try:
        gpt2 = bench_gpt2_tokens_per_sec()
    except Exception as e:  # noqa: BLE001
        gpt2 = {"error": repr(e)[:300]}
    suite["gpt2_125m_train"] = gpt2
    on_tpu = gpt2.get("platform") == "tpu"

    def remaining():
        return budget - (time.perf_counter() - started)

    if remaining() > 240:
        try:
            suite["llama_125m_train"] = bench_llama_tokens_per_sec()
        except Exception as e:  # noqa: BLE001
            suite["llama_125m_train"] = {"error": repr(e)[:300]}
    else:
        suite["llama_125m_train"] = {"skipped": "budget"}

    if remaining() > 240:
        try:
            suite["gpt2_long_context_4096"] = bench_gpt2_long_context()
        except Exception as e:  # noqa: BLE001
            suite["gpt2_long_context_4096"] = {"error": repr(e)[:300]}
    else:
        suite["gpt2_long_context_4096"] = {"skipped": "budget"}

    if remaining() > 120:
        try:
            suite["pipeline_bubble"] = bench_pipeline_bubble()
        except Exception as e:  # noqa: BLE001
            suite["pipeline_bubble"] = {"error": repr(e)[:300]}
    else:
        suite["pipeline_bubble"] = {"skipped": "budget"}

    # the dispatch plane is cheap to measure and gates r5 #3 — run it
    # whenever the pipeline phase ran (its fit feeds this phase)
    if remaining() > 60 or not on_tpu:
        try:
            do = bench_dispatch_overhead(suite.get("pipeline_bubble"))
            for k, v in do.items():
                suite[k] = v if isinstance(v, dict) else {
                    "value": round(v, 2), "vs_baseline": None}
        except Exception as e:  # noqa: BLE001
            suite["dispatch_overhead_error"] = repr(e)[:300]
    else:
        suite["dispatch_overhead"] = {"skipped": "budget"}

    # the flight recorder's cost ceiling rides with the dispatch plane:
    # cheap to measure, gates the always-on recorder at <1%
    if remaining() > 45 or not on_tpu:
        try:
            oo = bench_observability_overhead()
            for k, v in oo.items():
                suite[k] = v if isinstance(v, dict) else {
                    "value": round(v, 2), "vs_baseline": None}
        except Exception as e:  # noqa: BLE001
            suite["observability_overhead_error"] = repr(e)[:300]
    else:
        suite["observability_overhead"] = {"skipped": "budget"}

    # off-TPU the control-plane phase IS the headline — never gate it
    if remaining() > 120 or not on_tpu:
        try:
            rf = bench_rpc_fanin()
            for k, v in rf.items():
                suite[k] = v if isinstance(v, dict) else {
                    "value": round(v, 2), "vs_baseline": None}
        except Exception as e:  # noqa: BLE001
            suite["rpc_fanin_error"] = repr(e)[:300]
        try:
            cp = bench_control_plane()
            for k, v in cp.items():
                if isinstance(v, dict):  # store stats / scaling curves
                    suite[k] = v
                    continue
                suite[k] = {
                    "value": round(v, 2),
                    "vs_baseline": round(v / BASELINES[k], 3)
                    if k in BASELINES else None,
                }
        except Exception as e:  # noqa: BLE001
            suite["control_plane_error"] = repr(e)[:300]
    else:
        suite["control_plane"] = {"skipped": "budget"}

    if remaining() > 90 or not on_tpu:
        try:
            sc = bench_scale_envelope()
            for k, v in sc.items():
                suite[k] = {"value": round(v, 2), "vs_baseline": None} \
                    if isinstance(v, float) else v
        except Exception as e:  # noqa: BLE001
            suite["scale_envelope_error"] = repr(e)[:300]
    else:
        suite["scale_envelope"] = {"skipped": "budget"}

    # inference plane (ISSUE 9): cheap on CPU at default scale; the
    # full 1M-request artifact run sets RAY_TPU_SCALE_SIZES
    if remaining() > 60 or not on_tpu:
        try:
            sl = bench_serve_llm()
            for k, v in sl.items():
                suite[k] = v if isinstance(v, dict) else {
                    "value": round(v, 2), "vs_baseline": None}
        except Exception as e:  # noqa: BLE001
            suite["serve_llm_error"] = repr(e)[:300]
    else:
        suite["serve_llm"] = {"skipped": "budget"}

    # dispatch plane v2 (ISSUE 19): native request ring vs the Python
    # handle path, A/B on every run so the fallback arm can't rot
    if remaining() > 60 or not on_tpu:
        try:
            sd = bench_serve_dispatch()
            for k, v in sd.items():
                suite[k] = v if isinstance(v, dict) else {
                    "value": round(v, 2), "vs_baseline": None}
        except Exception as e:  # noqa: BLE001
            suite["serve_dispatch_error"] = repr(e)[:300]
    else:
        suite["serve_dispatch"] = {"skipped": "budget"}

    # elastic-recovery soak (ISSUE 10): cluster-mode fault schedule with
    # MTTR accounting; the full >=10-min SOAK_r*.json artifact run sets
    # RAY_TPU_SCALE_SIZES=soak_budget_s=600,soak_faults_per_class=2
    if remaining() > 150 or not on_tpu:
        try:
            sk = bench_soak()
            for k, v in sk.items():
                suite[k] = v if isinstance(v, dict) else {
                    "value": round(v, 3), "vs_baseline": None}
        except Exception as e:  # noqa: BLE001
            suite["soak_error"] = repr(e)[:300]
    else:
        suite["soak"] = {"skipped": "budget"}

    # lineage reconstruction (ISSUE 16): latency-vs-size curve + batch
    # recovery rate after a raylet death, bit-identity checked
    if remaining() > 90 or not on_tpu:
        try:
            rc = bench_reconstruction()
            for k, v in rc.items():
                suite[k] = v if isinstance(v, dict) else {
                    "value": round(v, 3), "vs_baseline": None}
        except Exception as e:  # noqa: BLE001
            suite["reconstruction_error"] = repr(e)[:300]
    else:
        suite["reconstruction"] = {"skipped": "budget"}

    # multi-tenant fairness + quota-flood containment; the full
    # MULTITENANT_r*.json artifact run sets
    # RAY_TPU_SCALE_SIZES=mt_window_s=30,mt_flood_s=10
    if remaining() > 90 or not on_tpu:
        try:
            mt = bench_multitenant()
            for k, v in mt.items():
                suite[k] = v if isinstance(v, dict) else {
                    "value": round(v, 3), "vs_baseline": None}
        except Exception as e:  # noqa: BLE001
            suite["multitenant_error"] = repr(e)[:300]
    else:
        suite["multitenant"] = {"skipped": "budget"}

    if "tokens_per_sec_per_chip" in gpt2 and gpt2.get("platform") == "tpu":
        headline = {
            "metric": "gpt2_125m_tokens_per_sec_per_chip",
            "value": gpt2["tokens_per_sec_per_chip"],
            "unit": "tokens/s",
            "vs_baseline": gpt2.get("vs_baseline"),
            "mfu": gpt2.get("mfu"),
        }
    else:
        # no TPU attached: headline falls back to the control-plane number
        cp_sync = suite.get("1_1_actor_calls_sync", {})
        headline = {
            "metric": "1_1_actor_calls_sync",
            "value": cp_sync.get("value"),
            "unit": "calls/s",
            "vs_baseline": cp_sync.get("vs_baseline"),
        }
    headline["host"] = _host_metadata()
    # self-comparison gate BEFORE this run is written as the new
    # baseline: any suite metric down >15% vs the latest BENCH_r*.json
    # prints a REGRESSION block and rides along in the artifact
    regressions = _check_regressions(suite)
    if regressions:
        headline["regressions"] = regressions
    headline["suite"] = suite
    print(json.dumps(headline))


if __name__ == "__main__":
    main()
