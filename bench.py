"""Benchmark harness — prints ONE JSON line.

Headline metric: 1:1 sync actor call throughput, the reference's own
microbenchmark headline (`release/perf_metrics/microbenchmark.json`
`1_1_actor_calls_sync` = 2,097/s on m5.16xlarge; harness
`python/ray/_private/ray_perf.py`). Same shape here: one driver, one actor,
round-trip method calls, wall-clocked.
"""

from __future__ import annotations

import json
import time


BASELINE_ACTOR_CALLS_SYNC = 2097.0  # release/perf_metrics/microbenchmark.json


def bench_actor_calls_sync(duration_s: float = 5.0) -> float:
    import ray_tpu

    ray_tpu.init(num_cpus=4)
    try:
        @ray_tpu.remote
        class Sink:
            def ping(self):
                return None

        actor = Sink.remote()
        ray_tpu.get(actor.ping.remote())  # warm-up / actor creation

        # Warm loop.
        for _ in range(100):
            ray_tpu.get(actor.ping.remote())

        n = 0
        start = time.perf_counter()
        while True:
            for _ in range(100):
                ray_tpu.get(actor.ping.remote())
            n += 100
            elapsed = time.perf_counter() - start
            if elapsed >= duration_s:
                return n / elapsed
    finally:
        ray_tpu.shutdown()


def main():
    value = bench_actor_calls_sync()
    print(json.dumps({
        "metric": "1_1_actor_calls_sync",
        "value": round(value, 1),
        "unit": "calls/s",
        "vs_baseline": round(value / BASELINE_ACTOR_CALLS_SYNC, 3),
    }))


if __name__ == "__main__":
    main()
