"""RL: multi-agent PPO — two cooperating agents sharing one policy.

Each agent sees a 4-state one-hot observation and earns +1 per step for
matching its action to state % 2. `policy_mapping_fn` routes both agents
onto one shared module (change it to route each agent to its own module
for independent policies).
"""
import _bootstrap  # noqa: F401  (repo-checkout import shim)
# sim-env RL is latency-bound: tiny MLP forwards gain nothing from an
# accelerator (in a cluster, env-runner actors have no TPU chips bound
# anyway). Force CPU so a tunneled/remote TPU doesn't add per-step RTTs.
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from ray_tpu.rllib import MultiAgentEnv, MultiAgentPPOConfig


class MatchingEnv(MultiAgentEnv):
    possible_agents = ["a0", "a1"]

    def __init__(self):
        import gymnasium as gym

        obs_sp = gym.spaces.Box(0.0, 1.0, (4,), np.float32)
        act_sp = gym.spaces.Discrete(2)
        self.observation_spaces = {a: obs_sp for a in self.possible_agents}
        self.action_spaces = {a: act_sp for a in self.possible_agents}
        self._rng = np.random.default_rng(0)
        self._t = 0
        self._state = {}

    def _obs(self):
        out = {}
        for a in self.possible_agents:
            s = int(self._rng.integers(0, 4))
            self._state[a] = s
            onehot = np.zeros(4, np.float32)
            onehot[s] = 1.0
            out[a] = onehot
        return out

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._obs(), {}

    def step(self, actions):
        rewards = {a: float(int(actions[a]) == self._state[a] % 2)
                   for a in self.possible_agents}
        self._t += 1
        done = self._t >= 8
        terms = {a: done for a in self.possible_agents}
        terms["__all__"] = done
        truncs = {a: False for a in self.possible_agents}
        truncs["__all__"] = False
        return self._obs(), rewards, terms, truncs, {}


if __name__ == "__main__":
    algo = (
        MultiAgentPPOConfig()
        .environment(env=lambda: MatchingEnv())
        .multi_agent(policies={"shared": None},
                     policy_mapping_fn=lambda agent_id: "shared")
        .training(train_batch_size=512, minibatch_size=128,
                  num_epochs=4, lr=3e-3, entropy_coeff=0.01)
        .build_algo()
    )
    for i in range(8):
        r = algo.train()
        print(f"iter {i}: return={r['episode_return_mean']:.1f} "
              f"(optimal 16.0)")
    algo.stop()
