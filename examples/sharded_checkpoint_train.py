"""Elastic training with sharded-array checkpoints.

Demonstrates the r5 checkpoint story: a JaxTrainer gang whose training
state is a NamedSharding pytree, saved with each worker writing only
its shards (`train.array_checkpoint`) and restored bit-identically
after a failure — onto whatever topology the restarted gang has.

Run: python examples/sharded_checkpoint_train.py
(uses a CPU mesh so it works on any machine; on TPU hosts drop the
JaxConfig platform/xla_flags overrides)
"""
import _bootstrap  # noqa: F401  (repo-checkout import shim)
import numpy as np

import ray_tpu
from ray_tpu import train
from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.backend import JaxConfig


def train_loop(config):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu import train
    from ray_tpu.train import array_checkpoint as ac

    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(len(devs)), ("dp",))
    w0 = np.zeros((8, 4), np.float32)
    state = {
        "w": jax.make_array_from_callback(
            (8, 4), NamedSharding(mesh, P("dp")), lambda idx: w0[idx]),
        "step": jax.make_array_from_callback(
            (), NamedSharding(mesh, P()),
            lambda idx: np.zeros((), np.int32)),
    }

    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None and ac.is_sharded_checkpoint(ckpt):
        state = ac.restore_sharded(ckpt, state)   # any topology
        start = int(np.asarray(state["step"].addressable_shards[0].data))

    @jax.jit
    def update(s):
        return {"w": s["w"] + 0.1, "step": s["step"] + 1}

    for i in range(start, config["steps"]):
        state = update(state)
        # local mean over this host's replica-0 shards (they are
        # equally sized here, so the mean of shard-means is exact)
        shard_means = [np.asarray(s.data).mean()
                       for s in state["w"].addressable_shards
                       if s.replica_id == 0]
        train.report(
            {"step": i + 1,
             "w_mean": float(np.mean(shard_means))},
            checkpoint=ac.save_to_checkpoint(state))


def main():
    # explicit CPU count: the trial controller + 2 train workers need 3
    # slots, which auto-detection under-provisions on small machines
    ray_tpu.init(num_cpus=4)
    trainer = train.JaxTrainer(
        train_loop,
        train_loop_config={"steps": 5},
        backend_config=JaxConfig(
            distributed="on", platform="cpu",
            xla_flags="--xla_force_host_platform_device_count=2"),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path="/tmp/ray_tpu_results", name="sharded_ckpt",
            failure_config=FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    print("final:", result.metrics)
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
