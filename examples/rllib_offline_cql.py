"""Offline RL: record a behavior dataset, train CQL from it, evaluate.

The pipeline the reference documents for offline RL: (1) log episodes
with an output writer, (2) train a conservative Q-learner purely from
the logged data, (3) evaluate the learned policy on the real env.
"""
import _bootstrap  # noqa: F401  (repo-checkout import shim)
# sim-env RL is latency-bound; see rllib_ppo.py
import jax
jax.config.update("jax_platforms", "cpu")
import tempfile

import numpy as np

from ray_tpu.rllib import CQL, CQLConfig
from ray_tpu.rllib.env.env_runner import Episode
from ray_tpu.rllib.offline.io import JsonWriter

if __name__ == "__main__":
    import gymnasium as gym

    # 1) behavior dataset: random torques on Pendulum
    data_dir = tempfile.mkdtemp(prefix="pendulum_offline_")
    env = gym.make("Pendulum-v1")
    writer, rng, episodes = JsonWriter(data_dir), np.random.default_rng(0), []
    for i in range(30):
        obs, _ = env.reset(seed=i)
        ep = Episode()
        for _ in range(60):
            a = rng.uniform(-2.0, 2.0, size=(1,)).astype(np.float32)
            nxt, r, term, trunc, _ = env.step(a)
            ep.obs.append(np.asarray(obs, np.float32))
            ep.actions.append(a)
            ep.rewards.append(float(r))
            ep.logps.append(0.0)
            ep.vf_preds.append(0.0)
            obs = nxt
        ep.truncated = True
        ep.last_obs = np.asarray(obs, np.float32)
        episodes.append(ep)
    writer.write(episodes)
    env.close()
    print(f"recorded {len(episodes)} episodes to {data_dir}")

    # 2) offline training + 3) greedy eval on the real env
    algo = (
        CQLConfig()
        .environment("Pendulum-v1")
        .offline_data(input_=data_dir)
        .training(train_batch_size=64, num_updates_per_iteration=32,
                  cql_alpha=5.0, num_sampled_actions=4)
        .evaluation(evaluation_interval=2, evaluation_duration=400)
        .build_algo()
    )
    for i in range(4):
        r = algo.train()
        line = (f"iter {i}: q_loss={r['q_loss']:.2f} "
                f"cql_gap={r['cql_loss']:.2f}")
        if "evaluation" in r:
            line += f" eval_return={r['evaluation']['episode_return_mean']:.0f}"
        print(line)
    algo.stop()
