"""Make the in-repo ray_tpu importable when examples run from a source
checkout (no-op once the package is on PYTHONPATH)."""
import os
import sys

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _repo not in sys.path:
    sys.path.insert(0, _repo)
