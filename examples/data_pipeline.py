"""Streaming data: read -> transform -> shuffle -> batched iteration."""
import _bootstrap  # noqa: F401  (repo-checkout import shim)
import numpy as np

import ray_tpu
from ray_tpu import data

if __name__ == "__main__":
    ray_tpu.init(num_cpus=4)
    ds = (
        data.range(1000)
        .map_batches(lambda b: {"x": b["id"], "y": b["id"] * 2})
        .random_shuffle(seed=0)
    )
    total = 0
    for batch in ds.iter_batches(batch_size=128):
        total += int(np.sum(batch["y"]))
    print("sum of y:", total)  # 2 * sum(0..999) = 999000
    ray_tpu.shutdown()
