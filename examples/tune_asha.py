"""Hyperparameter search: ASHA early-stops bad lr choices."""
import _bootstrap  # noqa: F401  (repo-checkout import shim)
import ray_tpu
from ray_tpu import tune
from ray_tpu.air.config import RunConfig
from ray_tpu.tune.schedulers import ASHAScheduler


def objective(config):
    x = 1.0
    for i in range(20):
        x = x - config["lr"] * (2 * x)  # minimize x^2
        tune.report({"loss": x * x})


if __name__ == "__main__":
    import tempfile

    ray_tpu.init(num_cpus=4)
    tuner = tune.Tuner(
        objective,
        param_space={"lr": tune.loguniform(1e-3, 1.0)},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=8,
            scheduler=ASHAScheduler(max_t=20)),
        run_config=RunConfig(storage_path=tempfile.mkdtemp(),
                             name="asha_demo"),
    )
    results = tuner.fit()
    best = results.get_best_result()
    print("best lr:", best.config["lr"], "loss:", best.metrics["loss"])
    ray_tpu.shutdown()
