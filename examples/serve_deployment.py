"""Online serving: deploy, call through the handle and over HTTP."""
import _bootstrap  # noqa: F401  (repo-checkout import shim)
import json
import urllib.request

import ray_tpu
from ray_tpu import serve


@serve.deployment(num_replicas=2)
class Doubler:
    def __call__(self, x):
        return {"doubled": x * 2}


if __name__ == "__main__":
    ray_tpu.init(num_cpus=4)
    handle = serve.run(Doubler.bind(), route_prefix="/double",
                       http_port=8123)
    print("handle:", handle.remote(21).result(timeout=60))
    req = urllib.request.Request(
        "http://127.0.0.1:8123/double", data=b"4",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        print("http:", json.loads(resp.read()))
    serve.shutdown()
    ray_tpu.shutdown()
