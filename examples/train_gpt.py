"""Distributed LM training: JaxTrainer runs a data-parallel GPT loop on
a placement-grouped worker fleet; metrics/checkpoints stream back
through train.report."""
import _bootstrap  # noqa: F401  (repo-checkout import shim)
import numpy as np

import ray_tpu
from ray_tpu import train
from ray_tpu.air import ScalingConfig
from ray_tpu.train.backend import JaxConfig


def train_loop(config):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import GPT, GPTConfig
    from ray_tpu.models.gpt import cross_entropy_loss

    cfg = GPTConfig.tiny(dtype=jnp.float32)
    model = GPT(cfg)
    rng = np.random.default_rng(train.get_context().get_world_rank())
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (8, 65), np.int32))
    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, tokens):
        def loss_fn(p):
            return cross_entropy_loss(
                model.apply(p, tokens[:, :-1]), tokens[:, 1:])

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for i in range(config.get("steps", 5)):
        params, opt_state, loss = step(params, opt_state, tokens)
        train.report({"step": i, "loss": float(loss)})


if __name__ == "__main__":
    ray_tpu.init(num_cpus=4)
    trainer = train.JaxTrainer(
        train_loop,
        train_loop_config={"steps": 5},
        scaling_config=ScalingConfig(num_workers=2),
        # each demo worker is an independent jax process; "auto" forms
        # one jax.distributed slice per multi-worker TPU run instead
        backend_config=JaxConfig(distributed="off"),
    )
    result = trainer.fit()
    print("final loss:", result.metrics["loss"])
    ray_tpu.shutdown()
