"""RL: a few PPO iterations on CartPole."""
import _bootstrap  # noqa: F401  (repo-checkout import shim)
# sim-env RL is latency-bound: tiny MLP forwards gain nothing from an
# accelerator (in a cluster, env-runner actors have no TPU chips bound
# anyway). Force CPU so a tunneled/remote TPU doesn't add per-step RTTs.
import jax
jax.config.update("jax_platforms", "cpu")
import ray_tpu
from ray_tpu.rllib import PPOConfig

if __name__ == "__main__":
    ray_tpu.init(num_cpus=4)
    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_envs_per_env_runner=4)
        .training(train_batch_size=1024, minibatch_size=128,
                  num_epochs=4)
        .debugging(seed=0)
        .build_algo()
    )
    for i in range(3):
        r = algo.train()
        print(f"iter {i}: return={r['episode_return_mean']:.1f}")
    algo.stop()
    ray_tpu.shutdown()
