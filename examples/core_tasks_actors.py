"""Core API tour: tasks, actors, objects, placement-aware scheduling."""
import numpy as np
import _bootstrap  # noqa: F401  (repo-checkout import shim)

import ray_tpu

ray_tpu.init()


@ray_tpu.remote
def square(x):
    return x * x


@ray_tpu.remote
class Accumulator:
    def __init__(self):
        self.total = 0

    def add(self, v):
        self.total += v
        return self.total


# parallel tasks
print("squares:", ray_tpu.get([square.remote(i) for i in range(8)]))

# zero-copy object store: the worker reads the array without a copy
big = ray_tpu.put(np.arange(1_000_000))
print("sum:", ray_tpu.get(square.options(num_returns=1).remote(2)),
      ray_tpu.get(big)[:3], "...")

# actors hold state across calls
acc = Accumulator.remote()
for i in range(5):
    acc.add.remote(i)
print("total:", ray_tpu.get(acc.add.remote(0)))

ctx = ray_tpu.get_runtime_context()
print("driver node:", ctx.get_node_id()[:12])
ray_tpu.shutdown()
