"""Operator CLI: form real multi-machine clusters and inspect them.

Reference: `python/ray/scripts/scripts.py` (`ray start/stop/status/...`)
and the state-API CLI (`ray list tasks/actors/objects`). Invoked as
`python -m ray_tpu <command>`.

A head start spawns the GCS + a raylet detached (surviving this CLI);
worker machines join with `start --address`. Daemon pids land in a
state file under the session dir so `stop` can tear the node down.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
import uuid

_DEFAULT_STATE_FILE = "/tmp/ray_tpu/cli_node.json"


def _state_file() -> str:
    """Node-state file path. `RAY_TPU_CLI_STATE_FILE` overrides the
    machine-global default so concurrent clusters (test isolation, two
    operators on one box) track their own daemons instead of refusing
    to start over each other's state."""
    return os.environ.get("RAY_TPU_CLI_STATE_FILE", _DEFAULT_STATE_FILE)


def _spawn_daemon(args, log_path: str, ready_prefix: str) -> tuple:
    """Detached daemon spawn; returns (pid, ready_line). Shares
    node._spawn's env hygiene + ready-wait machinery."""
    from ray_tpu._private.node import _spawn

    try:
        handle = _spawn(args, log_path, ready_prefix, timeout=60.0,
                        detach=True)
    except RuntimeError as e:
        raise SystemExit(str(e))
    return handle.proc.pid, handle.ready_line


def _save_state(state: dict):
    os.makedirs(os.path.dirname(_state_file()), exist_ok=True)
    with open(_state_file(), "w") as f:
        json.dump(state, f)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def _load_state() -> dict | None:
    try:
        with open(_state_file()) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def cmd_start(args):
    prior = _load_state()
    if prior:
        # refuse to orphan a tracked node: overwriting the state file
        # would leave the previous daemons (no parent watch) running
        # with no way to stop them
        alive = [p for p in prior["pids"] if _pid_alive(p)]
        if alive:
            raise SystemExit(
                f"node already running (pids {alive}); "
                "run `ray_tpu stop` first")
    # pid+nonce in the session name: two `start`s in the same second
    # (e.g. parallel test runs) must never share a session dir
    session = (f"/tmp/ray_tpu/cli_{int(time.time())}_{os.getpid()}_"
               f"{uuid.uuid4().hex[:6]}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    pids = []
    if args.head:
        gcs_args = [sys.executable, "-m", "ray_tpu._private.gcs",
                    "--host", args.host, "--port", str(args.port),
                    "--daemonize",
                    "--log-file", f"{session}/logs/gcs.log"]
        if args.metrics_port:
            gcs_args += ["--metrics-port", str(args.metrics_port)]
        pid, ready = _spawn_daemon(gcs_args, f"{session}/logs/gcs.out",
                                   "GCS_READY")
        gcs_addr = ready.split()[1]
        pids.append(pid)
        print(f"GCS started at {gcs_addr}")
    else:
        if not args.address:
            raise SystemExit("--address required unless --head")
        gcs_addr = args.address

    raylet_args = [sys.executable, "-m", "ray_tpu._private.raylet",
                   "--gcs-addr", gcs_addr,
                   "--session-dir", session,
                   "--daemonize",
                   "--log-file", f"{session}/logs/raylet.log"]
    if args.resources:
        raylet_args += ["--resources", args.resources]
    if getattr(args, "labels", None):
        raylet_args += ["--labels", args.labels]
    if args.object_store_memory:
        raylet_args += ["--object-store-memory",
                        str(args.object_store_memory)]
    if args.metrics_port and not args.head:
        raylet_args += ["--metrics-port", str(args.metrics_port)]
    pid, ready = _spawn_daemon(raylet_args, f"{session}/logs/raylet.out",
                               "RAYLET_READY")
    pids.append(pid)
    print(f"raylet started: {ready.split()[1]}")
    _save_state({"gcs_addr": gcs_addr, "pids": pids, "session": session})
    print(f"\nTo connect: ray_tpu.init(address={gcs_addr!r})")
    print(f"Or: export RAY_TPU_ADDRESS={gcs_addr}")


def cmd_stop(args):
    state = _load_state()
    if state is None:
        print("no tracked node on this machine")
        return
    import signal

    for pid in state["pids"]:
        try:
            os.kill(pid, signal.SIGTERM)
            print(f"stopped pid {pid}")
        except ProcessLookupError:
            pass
    try:
        os.unlink(_state_file())
    except OSError:
        pass


def _connect(args):
    import ray_tpu

    address = args.address or (_load_state() or {}).get("gcs_addr") \
        or os.environ.get("RAY_TPU_ADDRESS")
    if not address:
        raise SystemExit("--address required (or run `start --head`)")
    ray_tpu.init(address=address)
    return ray_tpu


def cmd_status(args):
    ray_tpu = _connect(args)
    try:
        nodes = ray_tpu.nodes()
        print(f"{len([n for n in nodes if n['Alive']])} alive node(s)")
        for n in nodes:
            mark = "+" if n["Alive"] else "-"
            print(f" {mark} {n['NodeID'][:12]} {n['RayletAddr']} "
                  f"total={n['Resources']} avail={n['Available']}")
        total = ray_tpu.cluster_resources()
        avail = ray_tpu.available_resources()
        print(f"resources: total={total} available={avail}")
    finally:
        ray_tpu.shutdown()


def cmd_list(args):
    ray_tpu = _connect(args)
    from ray_tpu.util import state as state_api

    try:
        fn = {
            "tasks": state_api.list_tasks,
            "actors": state_api.list_actors,
            "objects": state_api.list_objects,
            "nodes": state_api.list_nodes,
        }[args.entity]
        for rec in fn():
            print(json.dumps(rec, default=str))
    finally:
        ray_tpu.shutdown()


def cmd_memory(args):
    """Object-store usage per node + largest objects (reference
    `ray memory`: per-process ref table; here the primary-copy view —
    what each raylet pins in shm and has spilled to disk)."""
    ray_tpu = _connect(args)
    from ray_tpu.util import state as state_api

    try:
        objs = state_api.list_objects(limit=args.limit)
        by_node = {}
        for o in objs:
            agg = by_node.setdefault(
                o["node_id"], {"shm": 0, "spilled": 0, "count": 0})
            agg[o["where"]] += o["size"]
            agg["count"] += 1
        for node_id, agg in sorted(by_node.items()):
            print(f"node {node_id[:12]}: {agg['count']} objects, "
                  f"{agg['shm'] / 1e6:.1f} MB shm, "
                  f"{agg['spilled'] / 1e6:.1f} MB spilled")
        print()
        for o in sorted(objs, key=lambda o: -o["size"])[:args.top]:
            print(f"{o['object_id'][:16]} {o['size']:>12} B "
                  f"{o['where']:8} node {o['node_id'][:12]}")
        total = sum(o["size"] for o in objs)
        print(f"\n{len(objs)} primary copies, {total / 1e6:.1f} MB total")
        for s in state_api.store_stats():
            print(f"store {s['node_id'][:12]}: "
                  f"{s.get('allocated', 0) / 1e6:.1f}"
                  f"/{s.get('capacity', 0) / 1e6:.1f} MB shm allocated, "
                  f"{s.get('num_objects', 0)} live objects")
        if len(objs) >= args.limit:
            print(f"WARNING: listing truncated at --limit {args.limit}; "
                  f"totals and top-N understate actual usage")
    finally:
        ray_tpu.shutdown()


def cmd_serve(args):
    """Operator view of a running Serve instance (reference `serve
    status` / `serve shutdown`). Pure observer: connects to the existing
    controller actor by name and never starts one."""
    ray_tpu = _connect(args)
    from ray_tpu.serve.controller import CONTROLLER_NAME

    try:
        try:
            ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
        except ValueError:  # actor-not-found; real RPC errors propagate
            print("no serve instance running")
            return
        if args.action == "status":
            deployments = ray_tpu.get(ctrl.list_deployments.remote(),
                                      timeout=30)
            print(json.dumps(deployments, indent=2, default=str))
        elif args.action == "shutdown":
            # direct call so a wedged controller FAILS loudly instead of
            # being swallowed by serve.shutdown()'s best-effort cleanup
            ray_tpu.get(ctrl.shutdown.remote(), timeout=60)
            ray_tpu.kill(ctrl)
            print("serve instance shut down")
    finally:
        ray_tpu.shutdown()


def cmd_serve_deploy(args):
    """Deploy applications from a YAML config (reference `serve deploy`
    + `serve/schema.py`). Unlike cmd_serve this may START the
    controller: deploying a config is a mutating operation."""
    ray_tpu = _connect(args)
    from ray_tpu import serve

    try:
        handles = serve.deploy_config(args.config_file)
        print(f"deployed applications: {', '.join(handles)}")
    finally:
        ray_tpu.shutdown()


def cmd_summary(args):
    ray_tpu = _connect(args)
    from ray_tpu.util import state as state_api

    try:
        for name, states in state_api.summarize_tasks().items():
            print(f"{name}: " + ", ".join(
                f"{s}={c}" for s, c in sorted(states.items())))
    finally:
        ray_tpu.shutdown()


def cmd_timeline(args):
    if getattr(args, "unified", False):
        from ray_tpu.util.timeline import unified_timeline

        # --unified without a reachable cluster still merges spans +
        # step records (offline flight-recorder view)
        include_tasks = True
        ray_tpu = None
        try:
            ray_tpu = _connect(args)
        except SystemExit:
            include_tasks = False
        try:
            events = unified_timeline(args.output,
                                      trace_dir=args.trace_dir,
                                      include_tasks=include_tasks)
            kinds = {}
            for e in events:
                k = e.get("cat") or e.get("ph")
                kinds[k] = kinds.get(k, 0) + 1
            print(f"wrote {len(events)} events to {args.output} "
                  "(tasks + spans + step records; open in "
                  "chrome://tracing or ui.perfetto.dev)")
            if kinds:
                print("  " + ", ".join(f"{k}={n}"
                                       for k, n in sorted(kinds.items())))
        finally:
            if ray_tpu is not None:
                ray_tpu.shutdown()
        return
    ray_tpu = _connect(args)
    from ray_tpu.util.timeline import timeline

    try:
        events = timeline(args.output)
        print(f"wrote {len(events)} events to {args.output} "
              "(open in chrome://tracing or ui.perfetto.dev)")
    finally:
        ray_tpu.shutdown()


def cmd_profile(args):
    """Flight-recorder view: the last-N step table (per-step MFU +
    time-attribution breakdown). Offline: reads the step-record shards
    the training processes wrote beside the tracing shards — no cluster
    connection needed."""
    from ray_tpu.util import step_profiler

    records = step_profiler.collect(args.trace_dir)
    if not records and step_profiler.recent():
        records = step_profiler.recent()  # in-process fallback
    if getattr(args, "json", False):
        for rec in records[-args.last:]:
            print(json.dumps(rec))
        return
    print(step_profiler.format_table(records, last=args.last))
    if records:
        attribution = step_profiler.attribution(records)
        total_steps = records[-1].get("step", len(records))
        print(f"\n{len(records)} records "
              f"(through step {total_steps}); "
              f"dominant phase: "
              f"{max(attribution, key=attribution.get) if attribution else '?'}")


def cmd_requests(args):
    """Request-path flight recorder, offline: merged client+engine
    records from the `requests-*.jsonl` shards the serving processes
    wrote beside the tracing shards (falls back to this process's
    in-memory ring). `--slow N` keeps the N worst by total latency."""
    from ray_tpu.util import request_recorder

    records = request_recorder.collect(args.trace_dir)
    if records:
        records = request_recorder.merge_by_request(records)
    elif request_recorder.ring().recent():
        records = [r.as_dict()
                   for r in request_recorder.ring().recent()]
    if getattr(args, "slow", 0):
        records = request_recorder.slowest(records, args.slow)
    if getattr(args, "json", False):
        for rec in records:
            print(json.dumps(rec))
        return
    print(request_recorder.format_table(records, last=args.last))


def cmd_top(args):
    """Live serving view: each tick polls the serve controller's
    replicas, folds their counters into a `util.tsdb.TSDB` (alongside a
    local+daemon metrics_text scrape), and renders req/s, TTFT/TPOT
    p50/p99, KV occupancy, and per-job token shares from the stored
    series — counter rates and quantiles come from the time-series
    plane, not from one-shot gauges."""
    ray_tpu = _connect(args)
    from ray_tpu.util import tsdb as tsdb_mod

    db = tsdb_mod.TSDB()

    def poll() -> dict:
        """One tick: controller poll -> exposition text -> db.ingest."""
        view = {"deployments": []}
        try:
            ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
            names = ray_tpu.get(ctrl.list_deployments.remote(),
                                timeout=10)
        except Exception:  # noqa: BLE001 — serve not running
            return view
        lines = []
        for name in names:
            try:
                info = ray_tpu.get(ctrl.get_replicas.remote(name),
                                   timeout=10)
                rows = [ray_tpu.get(r.get_metrics.remote(), timeout=10)
                        for r in info["replicas"]]
            except Exception:  # noqa: BLE001 — replica churn mid-poll
                continue
            dep = {"deployment": name, "replicas": rows}
            view["deployments"].append(dep)
            done = sum(r.get("requests_completed", 0) for r in rows)
            toks = sum(r.get("tokens_generated", 0) for r in rows)
            live = sum(r.get("kv_pages_live", 0) for r in rows)
            total = sum(r.get("kv_pages_total", 0) for r in rows)
            lines.append(f'serve_top_requests_completed_total'
                         f'{{deployment="{name}"}} {done}')
            lines.append(f'serve_top_tokens_generated_total'
                         f'{{deployment="{name}"}} {toks}')
            lines.append(f'serve_top_kv_pages_live'
                         f'{{deployment="{name}"}} {live}')
            lines.append(f'serve_top_kv_pages_total'
                         f'{{deployment="{name}"}} {total}')
            jobs: dict = {}
            for r in rows:
                for job, row in (r.get("tenants") or {}).items():
                    jobs[job] = jobs.get(job, 0) + row.get(
                        "tokens_generated", 0)
            for job, n in jobs.items():
                lines.append(f'serve_top_tokens_generated_total'
                             f'{{deployment="{name}",job="{job}"}} {n}')
        if lines:
            db.ingest("\n".join(lines) + "\n", source="serve")
        tsdb_mod.scrape_once(db)
        return view

    def render(view: dict) -> str:
        out = []
        if db.scrape_errors:
            # a metrics callback somewhere is throwing — the table below
            # is missing that source's series, say so up front
            out.append("DEGRADED (source="
                       + ", ".join(sorted(db.scrape_errors)) + "): "
                       + " | ".join(db.scrape_errors[s]
                                    for s in sorted(db.scrape_errors)))
        for dep in view["deployments"]:
            name = dep["deployment"]
            rows = dep["replicas"]
            # counter rates from the series plane (deltas over the
            # trailing window, robust to replica restarts)
            req_s = db.rate("serve_top_requests_completed_total",
                            {"deployment": name}, source="serve")
            tok_s = db.rate("serve_top_tokens_generated_total",
                            {"deployment": name}, source="serve")
            live = db.latest("serve_top_kv_pages_live",
                             {"deployment": name}, source="serve") or 0
            total = db.latest("serve_top_kv_pages_total",
                              {"deployment": name}, source="serve") or 0
            out.append(f"deployment {name}: {len(rows)} replicas   "
                       f"req/s={req_s if req_s is None else round(req_s, 2)}"
                       f"   tok/s={tok_s if tok_s is None else round(tok_s, 1)}"
                       f"   kv={int(live)}/{int(total)} pages"
                       + (f" ({100 * live / total:.0f}%)"
                          if total else ""))
            # latency: per-replica request-recorder summaries (avg p50,
            # worst p99 — quantiles don't merge exactly across rings)
            sums = [r["request_summary"] for r in rows
                    if r.get("request_summary")]
            for key, label in (("ttft_ms", "ttft"), ("tpot_ms", "tpot"),
                               ("total_ms", "total")):
                p50s = [s[f"{key}_p50"] for s in sums
                        if s.get(f"{key}_p50") is not None]
                p99s = [s[f"{key}_p99"] for s in sums
                        if s.get(f"{key}_p99") is not None]
                if p50s:
                    out.append(
                        f"  {label:6} p50={sum(p50s) / len(p50s):8.2f} ms"
                        f"   p99<={max(p99s):8.2f} ms")
            queue = sum(r.get("queue_depth", 0) for r in rows)
            running = sum(r.get("running", 0) for r in rows)
            out.append(f"  queue={int(queue)}  running={int(running)}")
            # per-job shares of generated tokens (multi-tenant view)
            shares = {}
            for key in db.series():
                n, litems, src = key
                ld = dict(litems)
                if (n == "serve_top_tokens_generated_total"
                        and src == "serve" and "job" in ld
                        and ld.get("deployment") == name):
                    r = db.rate(n, ld, source="serve")
                    if r:
                        shares[ld["job"]] = r
            tot = sum(shares.values())
            if tot > 0:
                out.append("  job shares: " + "  ".join(
                    f"{job}={100 * r / tot:.0f}%"
                    for job, r in sorted(shares.items())))
        if not view["deployments"]:
            out.append("no serve deployments (serve.run something)")
        out.append(f"[series={len(db.series())} "
                   f"scrapes={db.scrapes}]")
        return "\n".join(out)

    import time as time_mod
    try:
        i = 0
        while args.iterations is None or i < args.iterations:
            view = poll()
            if args.iterations is None \
                    and not getattr(args, "no_clear", False):
                print("\x1b[2J\x1b[H", end="")  # refresh in place
            print(render(view))
            i += 1
            if args.iterations is None or i < args.iterations:
                time_mod.sleep(args.interval)
    except KeyboardInterrupt:
        print()  # drop the shell prompt below the ^C echo
    finally:
        try:
            ray_tpu.shutdown()
        except KeyboardInterrupt:
            pass  # second ^C mid-teardown: exit quietly anyway


def cmd_alerts(args):
    """Evaluate the SLO alert pack against a short live scrape window
    and print every rule's state (the CLI face of `util.slo`; the
    dashboard serves the same snapshot at /api/alerts). Scrapes a few
    ticks so windowed measurements (rates, quantiles) have deltas to
    work with, then lists recent alert/health transitions from the
    structured event log."""
    ray_tpu = _connect(args)
    from ray_tpu.util import slo as slo_mod
    from ray_tpu.util import tsdb as tsdb_mod

    try:
        db = tsdb_mod.TSDB()
        evaluator = slo_mod.AlertEvaluator(db, register_metrics=False)
        ticks = max(2, args.scrapes)
        for i in range(ticks):
            tsdb_mod.scrape_once(db)
            evaluator.evaluate()
            if i + 1 < ticks:
                time.sleep(args.interval)
        snap = evaluator.snapshot()
        if args.json:
            print(json.dumps(snap, indent=2))
            return
        if db.scrape_errors:
            print("DEGRADED (source="
                  + ", ".join(sorted(db.scrape_errors)) + ")")
        for a in snap["alerts"]:
            mark = {"firing": "!", "pending": "~"}.get(a["state"], " ")
            fast = ("-" if a["fast_value"] is None
                    else f"{a['fast_value']:.4g}")
            slow = ("-" if a["slow_value"] is None
                    else f"{a['slow_value']:.4g}")
            print(f" {mark} {a['rule']:24} {a['state']:7} "
                  f"{a['metric']} {a['op']} {a['threshold']:g}   "
                  f"fast={fast} slow={slow}")
        firing = snap["firing"]
        print(f"{len(firing)} firing"
              + (": " + ", ".join(firing) if firing else "")
              + f"   ({len(snap['alerts'])} rules, "
                f"{snap['evaluations']} evaluations)")
        if args.history:
            from ray_tpu.util.events import list_events

            import datetime

            wanted = ("ALERT_FIRING", "ALERT_RESOLVED",
                      "health.stalled", "health.recovered")
            evs = [e for e in list_events()
                   if e.get("label") in wanted][-args.history:]
            for ev in evs:
                ts = datetime.datetime.fromtimestamp(
                    ev["ts"]).strftime("%H:%M:%S")
                print(f"  {ts} [{ev['severity']:7}] "
                      f"{ev['label']:15} {ev['message']}")
    finally:
        ray_tpu.shutdown()


def cmd_stack(args):
    """Cluster-wide hang diagnosis (reference: `ray stack`): pull the
    `dump_stacks` RPC from the GCS and every raylet — fanned out to
    each node's workers with --all — plus this CLI process, and render
    one annotated report: per-thread stacks, held tracked locks when
    lockdep is armed, and [STALLED] marks on threads the deadman
    watchdog has flagged."""
    ray_tpu = _connect(args)
    from ray_tpu._private import health as health_mod
    from ray_tpu._private import worker_api

    try:
        cw = worker_api._global_state.core_worker
        nodes = [n for n in ray_tpu.nodes() if n["Alive"]]
        if args.node:
            nodes = [n for n in nodes
                     if n["NodeID"].startswith(args.node)]
            if not nodes:
                raise SystemExit(f"no alive node matching {args.node!r}")

        async def collect():
            reports = []
            if not args.node:
                try:
                    reports.append(await cw.gcs.call(
                        "dump_stacks", {}, timeout=10.0))
                except Exception as e:  # noqa: BLE001 — partial report
                    reports.append({"role": "gcs", "error":
                                    f"{type(e).__name__}: {e}"})
            for n in nodes:
                try:
                    raylet = await cw._clients.get(n["RayletAddr"])
                    reports.append(await raylet.call(
                        "dump_stacks", {"workers": bool(args.all)},
                        timeout=15.0))
                except Exception as e:  # noqa: BLE001
                    reports.append({"role": "raylet",
                                    "node_id": n["NodeID"], "error":
                                    f"{type(e).__name__}: {e}"})
            return reports

        reports = cw._run_sync(collect())
        # this process too — a hang report that can't see the observer
        # is one process short of the truth
        reports.append({"pid": os.getpid(), "role": "cli",
                        "threads": health_mod.dump_stacks()})
        flat = []
        for rep in reports:
            workers = rep.pop("workers", None) if isinstance(rep, dict) \
                else None
            flat.append(rep)
            flat.extend(workers or [])
        if args.json:
            print(json.dumps(flat, indent=2))
            return
        stalled = 0
        for rep in flat:
            who = rep.get("role", "?")
            if rep.get("node_id"):
                who += f" node={rep['node_id'][:12]}"
            if rep.get("worker_id"):
                who += f" worker={rep['worker_id'][:12]}"
            if rep.get("error"):
                print(f"==== {who} pid={rep.get('pid', '?')} "
                      f"UNREACHABLE: {rep['error']} ====")
                continue
            threads = rep.get("threads", [])
            print(f"==== {who} pid={rep['pid']} "
                  f"({len(threads)} threads) ====")
            for t in threads:
                marks = ""
                if t.get("loop"):
                    marks += f" [loop={t['loop']}]"
                if t.get("stalled"):
                    marks += " [STALLED]"
                    stalled += 1
                if t.get("held_locks"):
                    marks += " [holds: " + ", ".join(
                        t["held_locks"]) + "]"
                print(f"-- {t['name']} (ident={t['ident']}, "
                      f"daemon={t['daemon']}){marks}")
                print("  " + t["stack"].rstrip().replace("\n", "\n  "))
        procs = len([r for r in flat if not r.get("error")])
        print(f"[{procs} processes, "
              f"{sum(len(r.get('threads', [])) for r in flat)} threads"
              + (f", {stalled} STALLED" if stalled else "") + "]")
    finally:
        ray_tpu.shutdown()


def cmd_client_server(args):
    import sys as _sys

    _sys.argv = ["client-server", "--address", args.address,
                 "--host", args.host, "--port", str(args.port)]
    from ray_tpu.util.client.server import main as server_main

    server_main()


def cmd_events(args):
    # offline read of the structured event shards — no cluster needed
    from ray_tpu.util.events import export_otlp, list_events

    if getattr(args, "otlp", None):
        n = export_otlp(args.otlp, source=args.source,
                        severity=args.severity, label=args.label)
        print(f"wrote {n} OTLP log records to {args.otlp}")
        return
    evs = list_events(source=args.source, severity=args.severity,
                      label=args.label)
    for ev in evs[-args.limit:]:
        import datetime

        ts = datetime.datetime.fromtimestamp(ev["ts"]).strftime(
            "%H:%M:%S")
        print(f"{ts} [{ev['severity']:7}] {ev['source']:11} "
              f"{ev['label']:18} {ev['message']}")
    print(f"({len(evs)} events total)")


def cmd_trace(args):
    # offline merge of per-process span shards — no cluster needed
    from ray_tpu.util import tracing

    spans = tracing.collect(args.trace_dir)
    tracing.to_chrome(spans, args.output)
    print(f"merged {len(spans)} spans from {args.trace_dir or tracing.trace_dir()} "
          f"-> {args.output} (open in chrome://tracing)")


def cmd_dashboard(args):
    ray_tpu = _connect(args)
    from ray_tpu.dashboard import start_dashboard

    try:
        start_dashboard(port=args.port)
        print(f"dashboard at http://127.0.0.1:{args.port} (ctrl-c to stop)")
        import signal

        signal.pause()
    except KeyboardInterrupt:
        pass
    finally:
        ray_tpu.shutdown()


def cmd_job(args):
    """`ray_tpu job submit|status|logs|stop|list` (reference:
    dashboard/modules/job/cli.py)."""
    ray_tpu = _connect(args)
    from ray_tpu.job_submission import JobSubmissionClient

    try:
        client = JobSubmissionClient()
        if args.job_command == "submit":
            job_id = client.submit_job(
                entrypoint=" ".join(args.entrypoint))
            print(job_id)
            if args.wait:
                print(client.wait_until_finished(job_id))
                print(client.get_job_logs(job_id), end="")
        elif args.job_command == "status":
            print(client.get_job_status(args.job_id))
        elif args.job_command == "logs":
            print(client.get_job_logs(args.job_id), end="")
        elif args.job_command == "stop":
            print("stopped" if client.stop_job(args.job_id)
                  else "already finished")
        elif args.job_command == "list":
            for rec in client.list_jobs():
                print(json.dumps(rec))
    finally:
        ray_tpu.shutdown()


def cmd_submit(args):
    address = args.address or (_load_state() or {}).get("gcs_addr") \
        or os.environ.get("RAY_TPU_ADDRESS")
    if not address:
        raise SystemExit("--address required")
    env = dict(os.environ)
    env["RAY_TPU_ADDRESS"] = address
    cmd = [sys.executable, args.script] + args.script_args
    raise SystemExit(subprocess.call(cmd, env=env))


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ray_tpu", description="ray_tpu cluster operator CLI")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("start", help="start node daemons")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", help="GCS address to join (worker node)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=6379)
    p.add_argument("--resources", help="JSON resources override")
    p.add_argument("--labels", help="JSON node labels (e.g. the "
                   "autoscaler's instance label on TPU-VM bootstrap)")
    p.add_argument("--object-store-memory", type=int, default=0)
    p.add_argument("--metrics-port", type=int, default=0)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop this machine's daemons")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status", help="cluster nodes + resources")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list", help="list cluster entities")
    p.add_argument("entity",
                   choices=["tasks", "actors", "objects", "nodes"])
    p.add_argument("--address")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("memory",
                       help="object-store usage per node + largest objects")
    p.add_argument("--address")
    p.add_argument("--limit", type=int, default=10000)
    p.add_argument("--top", type=int, default=10)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("serve", help="observe/stop a Serve instance")
    p.add_argument("action", choices=["status", "shutdown"])
    p.add_argument("--address")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("serve-deploy",
                       help="deploy applications from a YAML config")
    p.add_argument("config_file")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_serve_deploy)

    p = sub.add_parser("summary", help="task summary by name/state")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("submit", help="run a driver script locally")
    p.add_argument("--address")
    p.add_argument("script")
    p.add_argument("script_args", nargs="*")
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("timeline", help="dump a Chrome trace of tasks")
    p.add_argument("--address")
    p.add_argument("--output", default="timeline.json")
    p.add_argument("--unified", action="store_true",
                   help="merge task events + tracing spans + per-step "
                        "records into one trace")
    p.add_argument("--trace-dir", default=None,
                   help="span/step shard dir (default: "
                        "RAY_TPU_TRACE_DIR)")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "profile",
        help="per-step training telemetry: MFU + time attribution")
    p.add_argument("--trace-dir", default=None,
                   help="step-record shard dir (default: "
                        "RAY_TPU_TRACE_DIR)")
    p.add_argument("--last", type=int, default=20,
                   help="rows to print (default 20)")
    p.add_argument("--json", action="store_true",
                   help="raw JSONL records instead of the table")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser(
        "requests",
        help="per-request serving telemetry: phase split + TTFT/TPOT")
    p.add_argument("--trace-dir", default=None,
                   help="request-record shard dir (default: "
                        "RAY_TPU_TRACE_DIR)")
    p.add_argument("--slow", type=int, default=0, metavar="N",
                   help="only the N slowest requests by total latency")
    p.add_argument("--last", type=int, default=20,
                   help="rows to print (default 20)")
    p.add_argument("--json", action="store_true",
                   help="raw JSONL records instead of the table")
    p.set_defaults(fn=cmd_requests)

    p = sub.add_parser(
        "top",
        help="live serving table: req/s, TTFT/TPOT, KV occupancy, "
             "per-job shares")
    p.add_argument("--address")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between refreshes (default 2)")
    p.add_argument("--iterations", type=int, default=None,
                   help="stop after N refreshes (default: until ^C)")
    p.add_argument("--no-clear", action="store_true",
                   help="append output instead of redrawing the screen")
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser(
        "alerts",
        help="evaluate the SLO alert rules over a live scrape window")
    p.add_argument("--address")
    p.add_argument("--scrapes", type=int, default=3,
                   help="scrape ticks to evaluate over (default 3)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between scrape ticks (default 2)")
    p.add_argument("--history", type=int, default=10,
                   help="recent alert/health events to list (0=none)")
    p.add_argument("--json", action="store_true",
                   help="raw evaluator snapshot instead of the table")
    p.set_defaults(fn=cmd_alerts)

    p = sub.add_parser(
        "stack",
        help="cluster-wide Python stack dump (hang diagnosis)")
    p.add_argument("--address")
    p.add_argument("--node", metavar="N",
                   help="only the node whose NodeID starts with N")
    p.add_argument("--all", action="store_true",
                   help="also fan out to every worker process per node")
    p.add_argument("--json", action="store_true",
                   help="raw per-process reports instead of the report")
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser(
        "client-server",
        help="serve remote 'client://' drivers against this cluster")
    p.add_argument("--address", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10001)
    p.set_defaults(fn=cmd_client_server)

    p = sub.add_parser("events", help="list structured cluster events")
    p.add_argument("--source")
    p.add_argument("--severity")
    p.add_argument("--label")
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--otlp", metavar="FILE",
                   help="export as an OTLP/JSON Logs payload instead")
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("trace",
                       help="merge tracing spans into a Chrome trace")
    p.add_argument("--trace-dir", default=None)
    p.add_argument("--output", default="trace.json")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("dashboard", help="serve the web dashboard")
    p.add_argument("--address")
    p.add_argument("--port", type=int, default=8265)
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("job", help="cluster-hosted jobs")
    p.add_argument("job_command",
                   choices=["submit", "status", "logs", "stop", "list"])
    p.add_argument("--address")
    p.add_argument("--job-id", default=None)
    p.add_argument("--wait", action="store_true")
    p.add_argument("entrypoint", nargs="*",
                   help="entrypoint command (submit)")
    p.set_defaults(fn=cmd_job)

    args = parser.parse_args(argv)
    try:
        args.fn(args)
    except KeyboardInterrupt:
        # operator ^C is a normal way to leave any live view — exit
        # with the conventional 130, never a traceback
        raise SystemExit(130)


if __name__ == "__main__":
    main()
