"""Result of a training run / trial.

Reference: `python/ray/air/result.py` — metrics + checkpoint + error +
per-trial path, plus the metrics dataframe accessor.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Optional[Dict[str, Any]] = None
    checkpoint: Optional[Checkpoint] = None
    error: Optional[Exception] = None
    path: Optional[str] = None
    metrics_history: Optional[List[Dict[str, Any]]] = None
    best_checkpoints: Optional[List[tuple]] = None

    @property
    def config(self) -> Optional[Dict[str, Any]]:
        if self.metrics is None:
            return None
        return self.metrics.get("config")

    def __repr__(self) -> str:
        keys = sorted(self.metrics.keys()) if self.metrics else []
        return (f"Result(metrics_keys={keys}, checkpoint={self.checkpoint}, "
                f"error={type(self.error).__name__ if self.error else None})")
