"""Run/scaling/failure/checkpoint configs shared by Train and Tune.

Reference: `python/ray/air/config.py` — `ScalingConfig` (:103),
`FailureConfig` (:395), `CheckpointConfig` (:445), `RunConfig` (:594).

TPU-first deltas vs the reference:
- `ScalingConfig` carries an optional `mesh_shape` / `mesh_axes` describing
  the per-worker `jax.sharding.Mesh` (DP/FSDP/TP/SP/PP/EP axes) instead of
  assuming torch DDP; `use_tpu` replaces `use_gpu`.
- Placement-group bundle construction (`as_placement_group_factory`) emits
  slice-shaped bundles: one bundle per worker with its chip count, matching
  the reference's worker-bundle layout
  (`python/ray/train/_internal/backend_executor.py:206-256`).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass
class ScalingConfig:
    """How many train workers, and what each one holds.

    num_workers: worker actors (one jax process each).
    use_tpu: give each worker TPU chips.
    resources_per_worker: explicit per-worker resources; defaults to
        ``{"CPU": 1}`` plus ``{"TPU": tpus_per_worker}`` when ``use_tpu``.
    tpus_per_worker: chips per worker (a TPU-VM host's local chips).
    mesh_axes / mesh_shape: the global device-mesh the trainer should build
        across all workers' devices, e.g. axes ``("dp", "tp")`` shape
        ``(8, 4)``. ``None`` → pure DP over all devices.
    placement_strategy: PG strategy (PACK default, like the reference).
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: int = 0
    resources_per_worker: Optional[Dict[str, float]] = None
    mesh_axes: Optional[Tuple[str, ...]] = None
    mesh_shape: Optional[Tuple[int, ...]] = None
    placement_strategy: str = "PACK"
    trainer_resources: Optional[Dict[str, float]] = None
    # TPU pod-slice topology (e.g. "v4-16"): gang-place one worker per
    # host of a single complete slice, atomically — num_workers must
    # equal the slice's host count (x num_slices for multislice). See
    # scheduling.place_slice_bundles.
    topology: Optional[str] = None
    # Multislice (SURVEY §7.1; generalizes the reference's pod
    # convention, python/ray/_private/accelerators/tpu.py:363-388):
    # place one atomic gang per slice, num_slices gangs total. Workers
    # split evenly across slices; in-slice collectives ride ICI, the
    # cross-slice data-parallel axis rides DCN
    # (parallel.mesh.build_hybrid_mesh / ShardingStrategy.dcn_dp).
    num_slices: int = 1
    # how long fit() waits for the gang placement before failing
    pg_timeout_s: float = 120.0

    def _worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        res: Dict[str, float] = {"CPU": 1.0}
        if self.use_tpu:
            res["TPU"] = float(self.tpus_per_worker or 1)
        return res

    @property
    def num_tpus_per_worker(self) -> float:
        return self._worker_resources().get("TPU", 0.0)

    @property
    def workers_per_slice(self) -> int:
        if self.num_slices <= 1:
            return self.num_workers
        if self.num_workers % self.num_slices != 0:
            raise ValueError(
                f"num_workers={self.num_workers} must divide evenly "
                f"across num_slices={self.num_slices}")
        return self.num_workers // self.num_slices

    def bundles(self) -> List[Dict[str, float]]:
        """One bundle per worker (+ a zero-CPU trainer bundle is implicit).
        For multislice this is ONE slice's worth — the executor creates
        num_slices placement groups from it."""
        n = self.workers_per_slice if self.num_slices > 1 else self.num_workers
        return [self._worker_resources() for _ in range(n)]

    def total_bundles(self) -> List[Dict[str, float]]:
        return [self._worker_resources() for _ in range(self.num_workers)]

    def total_resources(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for b in self.total_bundles():
            for k, v in b.items():
                total[k] = total.get(k, 0.0) + v
        return total


@dataclasses.dataclass
class FailureConfig:
    """Trial-level retry policy (reference `air/config.py:395`).

    max_failures: retries after a worker/trial crash. 0 = no retries,
        -1 = infinite.
    """

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    """Keep-top-K policy (reference `air/config.py:445`)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: Optional[bool] = None

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclasses.dataclass
class RunConfig:
    """Experiment-level config (reference `air/config.py:594`)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    stop: Optional[Any] = None
    verbose: int = 0
    log_to_file: bool = False
    callbacks: Optional[List[Any]] = None

    def __post_init__(self):
        if self.storage_path is None:
            self.storage_path = os.path.expanduser(
                os.environ.get("RAY_TPU_RESULTS_DIR", "~/ray_tpu_results")
            )
        if self.failure_config is None:
            self.failure_config = FailureConfig()
        if self.checkpoint_config is None:
            self.checkpoint_config = CheckpointConfig()
