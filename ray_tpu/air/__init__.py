"""Shared Train/Tune plumbing: run configs, checkpoints, results.

Reference: `python/ray/air/config.py` (ScalingConfig :103, FailureConfig
:395, CheckpointConfig :445, RunConfig :594), `python/ray/train/_checkpoint.py:56`
(Checkpoint), re-designed for JAX/TPU: ScalingConfig speaks device-mesh
axes (dp/fsdp/tp/sp/pp/ep) instead of torch process groups.
"""

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "FailureConfig",
    "Result",
    "RunConfig",
    "ScalingConfig",
]
