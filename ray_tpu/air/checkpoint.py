"""Directory-backed checkpoints.

Reference: `python/ray/train/_checkpoint.py:56` — a Checkpoint is "a
directory plus a filesystem". Here the filesystem abstraction is a plain
local path (shared-filesystem or per-node session dir); cloud filesystems
can layer in behind the same path string later. Convenience dict round-trip
helpers cover the common "small state" case; sharded-array checkpoints go
through `ray_tpu.train.array_checkpoint` (per-host shard files + index,
restorable onto a different topology).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import uuid
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional


class Checkpoint:
    """An immutable reference to a checkpoint directory."""

    _METADATA_FILE = ".metadata.json"
    _DICT_FILE = "_dict_checkpoint.pkl"

    # Lifecycle hints consumed by train/tune sessions (not user API):
    # _persisted — already in durable trial storage, pass by reference;
    # _temp_source — staged in a throwaway tempdir, delete after persist.
    _persisted = False
    _temp_source = False

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    def __repr__(self) -> str:
        return f"Checkpoint(path={self.path!r})"

    # -- construction ------------------------------------------------------

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any],
                  base_dir: Optional[str] = None) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ckpt_", dir=base_dir)
        with open(os.path.join(d, cls._DICT_FILE), "wb") as f:
            pickle.dump(data, f, protocol=pickle.HIGHEST_PROTOCOL)
        ckpt = cls(d)
        # The tempdir exists only to carry this data to a persist step;
        # sessions reclaim it after copying (session._persist_checkpoint).
        ckpt._temp_source = True
        return ckpt

    # -- access ------------------------------------------------------------

    @contextmanager
    def as_directory(self) -> Iterator[str]:
        """Yield a local directory containing the checkpoint files."""
        yield self.path

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or tempfile.mkdtemp(prefix="ckpt_copy_")
        os.makedirs(dest, exist_ok=True)
        for name in os.listdir(self.path):
            src = os.path.join(self.path, name)
            dst = os.path.join(dest, name)
            if os.path.isdir(src):
                shutil.copytree(src, dst, dirs_exist_ok=True)
            else:
                shutil.copy2(src, dst)
        return dest

    def to_dict(self) -> Dict[str, Any]:
        p = os.path.join(self.path, self._DICT_FILE)
        if not os.path.exists(p):
            raise ValueError(
                f"{self.path} was not created via Checkpoint.from_dict")
        with open(p, "rb") as f:
            return pickle.load(f)

    # -- metadata ----------------------------------------------------------

    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, self._METADATA_FILE), "w") as f:
            json.dump(metadata, f)

    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, self._METADATA_FILE)
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        merged = self.get_metadata()
        merged.update(metadata)
        self.set_metadata(merged)


def _new_checkpoint_dir(base: str, index: int) -> str:
    d = os.path.join(base, f"checkpoint_{index:06d}_{uuid.uuid4().hex[:6]}")
    os.makedirs(d, exist_ok=True)
    return d
