"""Distributed tracing: spans around task submit/execute with context
propagation through TaskSpec.

Reference: `python/ray/util/tracing/tracing_helper.py:326,450` — the
reference wraps every remote function/actor method in OpenTelemetry
spans and propagates the span context in task metadata so cross-process
traces stitch together. Same design here without the otel dependency:
spans are plain dicts written as JSONL per process (zero deps, zero
cost when disabled), trace/parent ids ride `TaskSpec.trace_ctx`, and
`collect()`/`to_chrome()` merge per-process shards into one
chrome://tracing view.

Enable with `RAY_TPU_TRACE=1` (optionally `RAY_TPU_TRACE_DIR=...`);
every process of the cluster inherits the env through the daemons.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

_current: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "ray_tpu_trace_span", default=None)

_lock = threading.Lock()
_file = None


def _reset_writer() -> None:
    """Fork safety: a child inheriting the parent's cached handle would
    append its spans to the PARENT's pid-named shard (and interleave
    writes on a shared file offset). Daemons fork workers, so the cached
    handle is dropped in the child; the next span opens the child's own
    shard. Runs in the just-forked child, which is single-threaded —
    taking the fork-inherited lock here could deadlock on a holder that
    no longer exists in the child."""
    global _file
    _file = None  # raylint: disable=lock-discipline


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_writer)


def enabled() -> bool:
    return os.environ.get("RAY_TPU_TRACE", "") in ("1", "true", "on")


def trace_dir() -> str:
    return os.environ.get("RAY_TPU_TRACE_DIR", "/tmp/ray_tpu/traces")


def _writer():
    global _file
    if _file is None:
        with _lock:
            if _file is None:
                os.makedirs(trace_dir(), exist_ok=True)
                # opened once per process at the first span; per-span
                # appends are line-buffered local writes (µs-scale), so
                # span exits inside async executors stay loop-safe
                _file = open(  # raylint: disable=async-blocking
                    os.path.join(trace_dir(), f"trace-{os.getpid()}.jsonl"),
                    "a", buffering=1)  # line-buffered: crash-safe
    return _file


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


@contextlib.contextmanager
def span(name: str, kind: str = "internal",
         parent: Optional[Dict[str, str]] = None,
         attrs: Optional[Dict[str, Any]] = None) -> Iterator[dict]:
    """Record one span; nests under the context-local current span
    unless an explicit cross-process `parent` ctx is given."""
    if not enabled():
        yield {}
        return
    cur = _current.get()
    if parent is None and cur is not None:
        parent = {"trace_id": cur["trace_id"], "span_id": cur["span_id"]}
    s = {
        "trace_id": (parent or {}).get("trace_id") or _new_id(),
        "span_id": _new_id(),
        "parent_id": (parent or {}).get("span_id"),
        "name": name,
        "kind": kind,
        "pid": os.getpid(),
        "start": time.time(),
        "attrs": dict(attrs or {}),
    }
    token = _current.set(s)
    try:
        yield s
    except Exception as e:
        s["attrs"]["error"] = type(e).__name__
        raise
    finally:
        _current.reset(token)
        s["end"] = time.time()
        try:
            _writer().write(json.dumps(s) + "\n")
        except OSError:  # tracing must never break the task path
            pass


def current_context() -> Optional[Dict[str, str]]:
    """Wire form of the current span (to stuff into a TaskSpec)."""
    cur = _current.get()
    if cur is None:
        return None
    return {"trace_id": cur["trace_id"], "span_id": cur["span_id"]}


@contextlib.contextmanager
def submit_span(task_name: str, task_type: str):
    """Producer-side span; yields the ctx dict to ship in the spec
    (None when tracing is off — zero wire overhead)."""
    if not enabled():
        yield None
        return
    with span(f"{task_name}.remote", kind="producer",
              attrs={"task_type": task_type}) as s:
        yield {"trace_id": s["trace_id"], "span_id": s["span_id"]}


@contextlib.contextmanager
def execute_span(spec) -> Iterator:
    """Consumer-side span parented on the submitter's ctx."""
    if not enabled():
        yield
        return
    parent = getattr(spec, "trace_ctx", None)
    with span(f"{spec.name}.execute", kind="consumer", parent=parent,
              attrs={"task_type": spec.task_type,
                     "task_id": spec.task_id.hex()}):
        yield


# -- aggregation ---------------------------------------------------------

def collect(path: Optional[str] = None) -> List[dict]:
    """Merge every process's span shard (sorted by start time)."""
    import glob

    spans = []
    for fn in sorted(glob.glob(os.path.join(path or trace_dir(),
                                            "trace-*.jsonl"))):
        with open(fn) as f:
            for line in f:
                line = line.strip()
                if line:
                    spans.append(json.loads(line))
    spans.sort(key=lambda s: s["start"])
    return spans


def to_chrome(spans: List[dict], filename: Optional[str] = None) -> list:
    """Chrome-trace view: one complete event per span, rows = processes,
    flow arrows producer → consumer (chrome 's'/'f' flow events).

    Two arrow mechanisms: parent/span-id links (the submit→execute task
    path, where the child ships the parent ctx in its TaskSpec), and
    explicit ``flow_id`` attrs for planes where no ctx can ride the
    wire — a channel frame has a fixed raw header, so the producer and
    consumer spans both carry ``flow_id="<channel>:<seq>"`` and the
    arrow is stitched here, at merge time, across processes."""
    events = []
    for s in spans:
        events.append({
            "name": s["name"], "cat": s["kind"], "ph": "X",
            "ts": s["start"] * 1e6,
            "dur": max(1.0, (s.get("end", s["start"]) - s["start"]) * 1e6),
            "pid": s["pid"], "tid": s["trace_id"][:8],
            "args": {k: str(v) for k, v in s.get("attrs", {}).items()},
        })
        if s.get("parent_id"):
            # flow arrow from the parent span's row
            events.append({
                "name": "flow", "cat": "trace", "ph": "f", "bp": "e",
                "id": s["parent_id"], "ts": s["start"] * 1e6,
                "pid": s["pid"], "tid": s["trace_id"][:8],
            })
        if s["kind"] == "producer":
            events.append({
                "name": "flow", "cat": "trace", "ph": "s",
                "id": s["span_id"],
                "ts": s["start"] * 1e6,
                "pid": s["pid"], "tid": s["trace_id"][:8],
            })
        flow_id = s.get("attrs", {}).get("flow_id")
        if flow_id:
            events.append({
                "name": "hop", "cat": "channel",
                "ph": "s" if s["kind"] == "producer" else "f",
                "bp": "e", "id": str(flow_id),
                "ts": s["start"] * 1e6,
                "pid": s["pid"], "tid": s["trace_id"][:8],
            })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
