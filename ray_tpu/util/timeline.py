"""Timeline export: task events → Chrome trace JSON.

Reference: `ray timeline` (`python/ray/_private/state.py:434`
`chrome_tracing_dump`) — profile events from the GCS task table rendered
for chrome://tracing / Perfetto. Each task becomes a complete ("X")
event on its owner's row, spanning SUBMITTED → FINISHED/FAILED.
"""

from __future__ import annotations

import json
from typing import Optional


def timeline(filename: Optional[str] = None) -> list:
    """Build (and optionally write) the Chrome trace for everything in
    the GCS task table. Load the file in chrome://tracing or
    ui.perfetto.dev."""
    from ray_tpu.util.state import list_tasks

    events = []
    for rec in list_tasks(limit=100_000):
        transitions = dict()
        for state, ts in rec["events"]:
            # keep the FIRST time each state was reached
            transitions.setdefault(state, ts)
        start = transitions.get("SUBMITTED")
        end = transitions.get("FINISHED", transitions.get("FAILED"))
        if start is None:
            continue
        if end is None or end < start:
            end = start
        events.append({
            "name": rec["name"],
            "cat": rec["type"],
            "ph": "X",  # complete event
            "ts": start * 1e6,  # chrome wants microseconds
            "dur": max(1.0, (end - start) * 1e6),
            "pid": "ray_tpu",
            "tid": rec["type"],
            "args": {
                "task_id": rec["task_id"],
                "state": rec["state"],
            },
        })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
