"""Timeline export: task events → Chrome trace JSON.

Reference: `ray timeline` (`python/ray/_private/state.py:434`
`chrome_tracing_dump`) — profile events from the GCS task table rendered
for chrome://tracing / Perfetto. Each task becomes a complete ("X")
event on its owner's row, spanning SUBMITTED → FINISHED/FAILED.

`unified_timeline` additionally merges the tracing plane's span shards
(submit/execute spans, channel write→read hops with cross-process flow
arrows), the flight recorder's per-step records, and the request
recorder's per-request records into ONE Chrome trace — the
`ray_tpu timeline --unified` view: task rows from the GCS, span rows
per process, a "train-step" row per training process, a
"serve-request" row per serving process (handle→replica→engine arrows
stitched by `flow_id="req:<id>"`), all on the same wall clock.
"""

from __future__ import annotations

import json
from typing import Optional


def timeline(filename: Optional[str] = None) -> list:
    """Build (and optionally write) the Chrome trace for everything in
    the GCS task table. Load the file in chrome://tracing or
    ui.perfetto.dev."""
    from ray_tpu.util.state import list_tasks

    events = []
    for rec in list_tasks(limit=100_000):
        transitions = dict()
        for state, ts in rec["events"]:
            # keep the FIRST time each state was reached
            transitions.setdefault(state, ts)
        start = transitions.get("SUBMITTED")
        end = transitions.get("FINISHED", transitions.get("FAILED"))
        if start is None:
            continue
        if end is None or end < start:
            end = start
        events.append({
            "name": rec["name"],
            "cat": rec["type"],
            "ph": "X",  # complete event
            "ts": start * 1e6,  # chrome wants microseconds
            "dur": max(1.0, (end - start) * 1e6),
            "pid": "ray_tpu",
            "tid": rec["type"],
            "args": {
                "task_id": rec["task_id"],
                "state": rec["state"],
            },
        })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def unified_timeline(filename: Optional[str] = None,
                     trace_dir: Optional[str] = None,
                     include_tasks: bool = True) -> list:
    """Merge task events + tracing spans + step records into one Chrome
    trace. Each source is optional on its own: no cluster connection
    skips the task table (`include_tasks=False` or a connection error),
    an empty trace dir contributes nothing — whatever telemetry exists
    lands in the one file."""
    from ray_tpu.util import request_recorder, step_profiler, tracing

    events: list = []
    if include_tasks:
        try:
            events.extend(timeline(None))
        except Exception:  # noqa: BLE001 — offline use: spans + steps
            pass           # still merge without a cluster
    spans = tracing.collect(trace_dir)
    events.extend(tracing.to_chrome(spans))
    steps = step_profiler.collect(trace_dir)
    events.extend(step_profiler.to_chrome(steps))
    requests = request_recorder.collect(trace_dir)
    events.extend(request_recorder.to_chrome(requests))
    events.sort(key=lambda e: e.get("ts", 0))
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
